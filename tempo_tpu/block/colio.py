"""Column blob IO: named numpy arrays in one backend object, chunked by
row group.

Layout: [chunk buffers, each independently zstd-compressed] [footer JSON]
[uint32le footer len] [magic 'VTPU'].

Every column belongs to an *axis* (span rows, trace rows, attr rows, ...)
and is stored as one compressed chunk per row group along that axis. The
footer maps column name -> dtype/shape/axis/chunk table, so a reader can
fetch the footer with two small range reads and then range-read only the
(column, row-group) chunks a query touches -- the role parquet column
chunks + pages play for the reference (vparquet block_search.go,
parquetquery), but deserializing straight into flat device-uploadable
arrays with zero transposition.
"""

from __future__ import annotations

import json
import struct
import threading
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

try:
    import zstandard
except ModuleNotFoundError:  # image without the wheel: zlib-backed shim
    from ..util import zstdshim as zstandard

MAGIC = b"VTPU"
_TAIL = struct.Struct("<I4s")

CODEC_RAW = "raw"
CODEC_ZSTD = "zstd"
# constant chunk: stored bytes are ONE row, tiled to raw_len at read.
# The structural win parquet gets from RLE/dictionary pages: absent
# optional columns (http_*, sentinel ids, unused sattr typed lanes) are
# roughly half a realistic block's raw bytes, and with this codec they
# cost one row of storage, zero compression, zero decompression, and --
# via stride-0 broadcast views on the compaction path -- zero copies.
CODEC_CONST = "const"
_MIN_COMPRESS = 128
_CONST_MIN = 64  # don't bother const-marking chunks smaller than this

# codec matrix (reference: tempodb/backend/encoding.go's nine codecs).
# zstd is the default; snappy and lz4 (block/blockcodecs.py) are the
# speed tier with native threaded batch paths next to the zstd ones and
# pure-Python fallbacks; the stdlib codecs (gzip/lzma) trade ratio/CPU
# for interop. Decode always dispatches on the chunk's recorded codec,
# so blocks written with any codec stay readable.


def is_broadcast(arr: np.ndarray) -> bool:
    """True for stride-0 first-dim views (np.broadcast_to of one row) --
    the in-memory marker for "this column is constant". The single
    definition of the convention; the compaction merge imports it."""
    return arr.ndim >= 1 and arr.size > 0 and arr.strides[0] == 0


def _gzip_c(data: bytes, level: int) -> bytes:
    import gzip

    # mtime=0 keeps output deterministic (chunk bytes are content-addressed
    # by tests and dedupe-friendly in object stores)
    return gzip.compress(data, compresslevel=min(level, 9), mtime=0)


def _gzip_d(data: bytes, raw_len: int) -> bytes:
    import zlib

    # wbits=47 auto-detects gzip (RFC1952) and zlib (RFC1950) framing:
    # blocks written before the codec emitted true gzip used zlib framing
    return zlib.decompress(data, 47)


def _lzma_c(data: bytes, level: int) -> bytes:
    import lzma

    return lzma.compress(data, preset=min(level, 6))


def _lzma_d(data: bytes, raw_len: int) -> bytes:
    import lzma

    return lzma.decompress(data)


def _snappy_c(data: bytes, level: int) -> bytes:
    from .blockcodecs import snappy_compress

    return snappy_compress(data)  # snappy has no levels


def _snappy_d(data: bytes, raw_len: int) -> bytes:
    from .blockcodecs import snappy_decompress

    return snappy_decompress(data, raw_len)


def _lz4_c(data: bytes, level: int) -> bytes:
    from .blockcodecs import lz4_compress

    return lz4_compress(data)  # lz4 block format has no levels


def _lz4_d(data: bytes, raw_len: int) -> bytes:
    from .blockcodecs import lz4_decompress

    return lz4_decompress(data, raw_len)


_EXTRA_CODECS: dict[str, tuple] = {  # name -> (compress(data, level), decompress)
    "gzip": (_gzip_c, _gzip_d),
    "lzma": (_lzma_c, _lzma_d),
    "snappy": (_snappy_c, _snappy_d),
    "lz4": (_lz4_c, _lz4_d),
}
# codecs whose chunk batches the native layer can decompress in one
# threaded ranges call (the cold pipeline's decode stage); everything
# else decodes per chunk through _EXTRA_CODECS
_NATIVE_RANGE_CODECS = frozenset({CODEC_ZSTD, "snappy", "lz4"})


class AxisChunks:
    """Row boundaries of the row groups along one axis: offsets[g] ..
    offsets[g+1] are the rows of group g."""

    def __init__(self, offsets: list[int]):
        assert len(offsets) >= 2 and offsets[0] == 0
        self.offsets = list(offsets)

    @property
    def n_groups(self) -> int:
        return len(self.offsets) - 1

    @property
    def n_rows(self) -> int:
        return self.offsets[-1]


def pack_columns_stream(
    cols: dict[str, np.ndarray],
    axes: dict[str, AxisChunks] | None = None,
    col_axis: dict[str, str] | None = None,
    level: int = 3,
    codec: str = CODEC_ZSTD,
    level_for=None,
    footer: str = "binary",
):
    """Yield the serialized pack as byte parts, ONE COLUMN AT A TIME
    (chunks of a column compress as one threaded native batch, then the
    footer+tail last). Peak memory is a single column's chunks, so the
    streamed-flush write path (backend appender) never buffers the whole
    block -- the role of the reference's incremental backend.Append
    tracker (v2/streaming_block.go:13-90)."""
    axes = axes or {}
    col_axis = col_axis or {}
    if codec not in (CODEC_ZSTD, CODEC_RAW) and codec not in _EXTRA_CODECS:
        raise ValueError(
            f"unknown codec {codec!r} (matrix: "
            f"{[CODEC_RAW, CODEC_ZSTD, *sorted(_EXTRA_CODECS)]})"
        )
    footer_tbl: dict = {"cols": {}, "axes": {k: v.offsets for k, v in axes.items()}}
    offset = 0

    from ..native import zstd_compress_from

    for name, arr in cols.items():
        # per-column override (level_for(name) -> int | "raw" | None):
        # ints pick a zstd level; "raw" stores the column uncompressed
        # (the fast-decode policy for metadata axes a cold query must
        # decode, block/builder.FAST_DECODE_PREFIXES); None keeps the
        # pack-wide level
        col_level = level
        col_raw = False
        if level_for is not None and codec == CODEC_ZSTD:
            # zstd only: the stdlib codec matrix rejects the overrides
            ov = level_for(name)
            if ov == "raw":  # store uncompressed (fast-decode policy)
                col_raw = True
            elif ov is not None:
                col_level = ov
        # stride-0 first dim = a broadcast view (read_all broadcast_const
        # / the compaction merge's const fast path): constant by
        # construction, and materializing it here would defeat the point.
        # codec == raw means "store bytes verbatim", so raw packs
        # materialize broadcast inputs instead of emitting const chunks
        # (matching the sampled detector's raw-codec skip below).
        bcast = codec != CODEC_RAW and is_broadcast(arr)
        if not bcast:
            arr = np.ascontiguousarray(arr)
        axis = col_axis.get(name)
        row_bytes = arr.dtype.itemsize * int(np.prod(arr.shape[1:], dtype=np.int64))
        if axis is not None:
            ax = axes[axis]
            if ax.n_rows != arr.shape[0]:
                raise ValueError(
                    f"column {name}: {arr.shape[0]} rows != axis {axis} ({ax.n_rows})"
                )
            bounds = [(ax.offsets[g] * row_bytes, ax.offsets[g + 1] * row_bytes)
                      for g in range(ax.n_groups)]
        else:
            bounds = [(0, arr.shape[0] * row_bytes)]

        if bcast:
            row = np.ascontiguousarray(arr[:1]).tobytes()
            recs = []
            for lo, hi in bounds:
                raw_len = hi - lo
                if raw_len == 0:
                    recs.append([offset, 0, 0, CODEC_RAW])
                    continue
                recs.append([offset, len(row), raw_len, CODEC_CONST])
                offset += len(row)
                yield row
            footer_tbl["cols"][name] = {
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "axis": axis,
                "chunks": recs,
            }
            continue

        buf = arr.reshape(-1).view(np.uint8) if arr.size else np.empty(0, np.uint8)

        # constant-chunk detection: a cheap sampled bail (rows 1 and mid
        # vs row 0 -- random data fails in nanoseconds) gates the full
        # equality check, so only genuinely constant chunks pay a read
        # pass. Skipped for raw packs (codec == raw means "store bytes
        # verbatim").
        const_rows: dict[int, bytes] = {}
        if codec != CODEC_RAW and row_bytes > 0:
            for i, (lo, hi) in enumerate(bounds):
                ln = hi - lo
                if ln < max(_CONST_MIN, 2 * row_bytes):
                    continue
                r0 = buf[lo : lo + row_bytes]
                mid = lo + ((ln // row_bytes) // 2) * row_bytes
                if not ((buf[lo + row_bytes : lo + 2 * row_bytes] == r0).all()
                        and (buf[mid : mid + row_bytes] == r0).all()):
                    continue
                if (buf[lo:hi].reshape(-1, row_bytes) == r0).all():
                    const_rows[i] = r0.tobytes()

        # compress this column's compressible chunks: zstd runs as one
        # threaded native batch STRAIGHT FROM the array's memory (no
        # per-chunk source copies, python zstd as fallback); the stdlib
        # codec matrix handles the rest per chunk
        to_compress = [i for i, (lo, hi) in enumerate(bounds)
                       if hi - lo >= _MIN_COMPRESS and codec != CODEC_RAW
                       and not col_raw and i not in const_rows]
        compressed: dict[int, bytes] = {}
        if to_compress and codec == CODEC_ZSTD:
            outs = zstd_compress_from(
                buf,
                np.asarray([bounds[i][0] for i in to_compress], np.int64),
                np.asarray([bounds[i][1] - bounds[i][0] for i in to_compress], np.int64),
                col_level,
            )
            if outs is None:
                comp = zstandard.ZstdCompressor(level=col_level)
                outs = [comp.compress(buf[bounds[i][0] : bounds[i][1]].tobytes())
                        for i in to_compress]
            compressed = dict(zip(to_compress, outs))
        elif to_compress:
            cfun = _EXTRA_CODECS[codec][0]  # unknown codec fails loudly here
            outs = None
            if codec in _NATIVE_RANGE_CODECS:
                # snappy/lz4: one threaded native batch for the column's
                # chunks, exactly like the zstd path above
                from ..native import block_compress_chunks

                outs = block_compress_chunks(
                    codec,
                    [buf[bounds[i][0] : bounds[i][1]].tobytes() for i in to_compress])
            if outs is not None:
                compressed = dict(zip(to_compress, outs))
            else:
                compressed = {
                    i: cfun(buf[bounds[i][0] : bounds[i][1]].tobytes(), col_level)
                    for i in to_compress
                }

        recs: list[list] = []
        for i, (lo, hi) in enumerate(bounds):
            raw_len = hi - lo
            row = const_rows.get(i)
            z = compressed.get(i)
            if row is not None:
                data, chunk_codec = row, CODEC_CONST
            elif z is not None and len(z) < raw_len:
                data, chunk_codec = z, codec
            else:
                data, chunk_codec = buf[lo:hi].tobytes(), CODEC_RAW
            recs.append([offset, len(data), raw_len, chunk_codec])
            offset += len(data)
            yield data
        footer_tbl["cols"][name] = {
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "axis": axis,
            "chunks": recs,
        }

    # footer="json" writes the vtpu1-era footer (block version
    # compatibility: the convert tool and mixed-version tests produce
    # genuinely old-format blocks); readers auto-detect either form
    fbytes = (_encode_footer_binary(footer_tbl) if footer == "binary"
              else json.dumps(footer_tbl, separators=(",", ":")).encode("utf-8"))
    yield fbytes
    yield _TAIL.pack(len(fbytes), MAGIC)


# Binary footer ("\x00BF1" marker; JSON can never start with NUL): the
# JSON footer cost ~0.8 ms to parse per cold block open -- a fixed tax
# on every one-shot reader. Encoding: marker, then [axes] u32 count +
# per axis (u16 name len, name utf8, u32 n_offsets, i64 offsets), then
# [cols] u32 count + per column (u16 name len, name, u8 dtype len,
# dtype str, u8 ndim, i64 dims, u8 axis len, axis, u32 n_chunks, chunks
# as (n,3) i64 [off, stored, raw] + n bytes codec indexes into the u8
# codec table emitted before [cols]). Readers accept both forms.
_BF_MARKER = b"\x00BF1"


def _encode_footer_binary(footer: dict) -> bytes:
    out = bytearray(_BF_MARKER)

    def put_str(s: str, wide: bool = False):
        b = s.encode("utf-8")
        out.extend(struct.pack("<H" if wide else "<B", len(b)))
        out.extend(b)

    axes = footer.get("axes", {})
    out.extend(struct.pack("<I", len(axes)))
    for name, offsets in axes.items():
        put_str(name, wide=True)
        arr = np.asarray(offsets, dtype=np.int64)
        out.extend(struct.pack("<I", arr.shape[0]))
        out.extend(arr.tobytes())
    codecs = sorted({rec[3] for c in footer["cols"].values() for rec in c["chunks"]})
    out.extend(struct.pack("<B", len(codecs)))
    for c in codecs:
        put_str(c)
    cidx = {c: i for i, c in enumerate(codecs)}
    cols = footer["cols"]
    out.extend(struct.pack("<I", len(cols)))
    for name, meta in cols.items():
        put_str(name, wide=True)
        body = bytearray()

        def bput_str(s: str):
            b = s.encode("utf-8")
            body.extend(struct.pack("<B", len(b)))
            body.extend(b)

        bput_str(meta["dtype"])
        shape = meta["shape"]
        body.extend(struct.pack("<B", len(shape)))
        body.extend(np.asarray(shape, dtype=np.int64).tobytes())
        bput_str(meta["axis"] or "")
        recs = meta["chunks"]
        body.extend(struct.pack("<I", len(recs)))
        tbl = np.asarray([[r[0], r[1], r[2]] for r in recs], dtype=np.int64)
        body.extend(tbl.tobytes())
        body.extend(bytes(cidx[r[3]] for r in recs))
        # body-length prefix: a reader indexes all columns by skipping
        # bodies in one hop each, decoding only the columns it touches
        out.extend(struct.pack("<I", len(body)))
        out.extend(body)
    return bytes(out)


class _LazyFooterCols(dict):
    """Footer column table decoding each column's chunk records on first
    access: a cold query touches ~a dozen of the pack's ~90 columns, so
    eagerly building every chunk list cost more than the whole footer
    read. Maps name -> meta dict; undecoded entries hold their body's
    byte range in the footer buffer."""

    def __init__(self, data: bytes, codecs: list[str], index: dict[str, tuple[int, int]]):
        super().__init__()
        self._data = data
        self._codecs = codecs
        self._index = index
        for name in index:
            dict.__setitem__(self, name, None)

    def _decode(self, name: str) -> dict:
        data, pos = self._data, self._index[name][0]
        (dlen,) = struct.unpack_from("<B", data, pos)
        pos += 1
        dtype = data[pos : pos + dlen].decode("utf-8")
        pos += dlen
        (ndim,) = struct.unpack_from("<B", data, pos)
        pos += 1
        shape = np.frombuffer(data, dtype=np.int64, count=ndim, offset=pos).tolist()
        pos += 8 * ndim
        (alen,) = struct.unpack_from("<B", data, pos)
        pos += 1
        axis = data[pos : pos + alen].decode("utf-8") or None
        pos += alen
        (n_chunks,) = struct.unpack_from("<I", data, pos)
        pos += 4
        tbl = np.frombuffer(data, dtype=np.int64, count=3 * n_chunks, offset=pos)
        pos += 24 * n_chunks
        ci = data[pos : pos + n_chunks]
        codecs = self._codecs
        meta = {
            "dtype": dtype,
            "shape": shape,
            "axis": axis,
            "chunks": [[o, s, r, codecs[c]]
                       for (o, s, r), c in zip(tbl.reshape(-1, 3).tolist(), ci)],
        }
        dict.__setitem__(self, name, meta)
        return meta

    def __getitem__(self, name: str) -> dict:
        v = dict.__getitem__(self, name)
        return self._decode(name) if v is None else v

    def get(self, name, default=None):
        return self[name] if name in self else default

    def items(self):
        return [(k, self[k]) for k in self.keys()]

    def values(self):
        return [self[k] for k in self.keys()]


def _decode_footer_binary(data: bytes) -> dict:
    pos = len(_BF_MARKER)

    def get(fmt):
        nonlocal pos
        vals = struct.unpack_from(fmt, data, pos)
        pos += struct.calcsize(fmt)
        return vals

    def get_str(wide: bool = False) -> str:
        nonlocal pos
        (ln,) = get("<H" if wide else "<B")
        s = data[pos : pos + ln].decode("utf-8")
        pos += ln
        return s

    axes = {}
    (n_axes,) = get("<I")
    for _ in range(n_axes):
        name = get_str(wide=True)
        (n_off,) = get("<I")
        offs = np.frombuffer(data, dtype=np.int64, count=n_off, offset=pos)
        pos += 8 * n_off
        axes[name] = offs.tolist()
    (n_codecs,) = get("<B")
    codecs = [get_str() for _ in range(n_codecs)]
    index: dict[str, tuple[int, int]] = {}
    (n_cols,) = get("<I")
    for _ in range(n_cols):
        name = get_str(wide=True)
        (blen,) = get("<I")
        index[name] = (pos, blen)
        pos += blen
    return {"cols": _LazyFooterCols(data, codecs, index), "axes": axes}


def pack_columns(
    cols: dict[str, np.ndarray],
    axes: dict[str, AxisChunks] | None = None,
    col_axis: dict[str, str] | None = None,
    level: int = 3,
    codec: str = CODEC_ZSTD,
) -> bytes:
    """Serialize columns. Columns named in col_axis are chunked along the
    given axis' row groups; others are stored as a single chunk."""
    return b"".join(pack_columns_stream(cols, axes, col_axis, level, codec))


_DCTX_LOCAL = threading.local()  # per-thread zstd contexts (see _dctx)


@dataclass
class ColumnFetch:
    """One planned cold read (ColumnPack.plan_fetch): the state the
    fetch and decode phases share. The byte estimates feed the stream
    pipeline's admission budget BEFORE any IO happens."""

    pack: "ColumnPack"
    full: list  # (name, meta, dst start) full-column wants
    recs: list  # (chunk rec, dst_pos >= 0 | -1 for chunk-cache-only)
    cached: list  # (raw bytes, dst_pos, raw_len) chunk-cache hits
    runs: list  # coalesced (file off, end, members) ranged reads
    raw_bytes: int  # full-column decode output (dst buffer size)
    stored_bytes: int  # compressed bytes the fetch phase will read
    bufs: list | None = None  # fetch output (run buffers)
    src_pos: dict | None = None  # chunk file off -> offset in joined src

    @property
    def est_bytes(self) -> int:
        """Peak host RAM of running this plan: fetched compressed bytes
        + every decode destination."""
        sliced = sum(r[2] for r, d in self.recs if d < 0)
        return self.stored_bytes + self.raw_bytes + sliced


class ColumnPack:
    """Lazy chunked-column reader over a backend object via range reads."""

    # decompressed-chunk LRU budget, shared per pack: the host-RAM analog
    # of the OS page cache the reference's parquet reader leans on --
    # random trace materialization re-touches the same row-group chunks
    CHUNK_CACHE_BYTES = 256 << 20

    def __init__(self, read_range, total_size: int):
        """read_range(offset, length) -> bytes."""
        self._read_range = read_range
        self._size = total_size
        tail = self._read_range(total_size - _TAIL.size, _TAIL.size)
        flen, magic = _TAIL.unpack(tail)
        if magic != MAGIC:
            raise ValueError("not a vtpu column pack (bad magic)")
        fbytes = self._read_range(total_size - _TAIL.size - flen, flen)
        footer = (_decode_footer_binary(fbytes)
                  if fbytes[:4] == _BF_MARKER else json.loads(fbytes))
        self._cols: dict[str, dict] = footer["cols"]
        self.axes: dict[str, AxisChunks] = {
            k: AxisChunks(v) for k, v in footer.get("axes", {}).items()
        }
        self.bytes_read = _TAIL.size + flen  # inspected-bytes accounting
        self._io_lock = threading.Lock()  # bytes_read is read-modify-write
        self._cache: OrderedDict[int, bytes] = OrderedDict()  # chunk offset -> raw
        self._cache_bytes = 0
        self._cache_lock = threading.Lock()
        # assembled full-column LRU (name -> readonly ndarray): repeat
        # full-column readers (the host search engine, trace_index) skip
        # the per-chunk join + frombuffer copy entirely; chunks decode
        # straight into the final buffer (native batch) on first touch
        self._arrays: OrderedDict[str, np.ndarray] = OrderedDict()
        self._arrays_bytes = 0

    def _count_read(self, n: int) -> None:
        with self._io_lock:
            self.bytes_read += n

    def preload(self) -> None:
        """Fetch the WHOLE pack with one ranged read and serve later
        reads from memory. For small blocks (compaction inputs, the
        many-tiny-blocks shape) this replaces dozens of per-chunk
        backend reads/opens with one. Idempotent: the compaction
        pipeline's prefetch stage may run it before the merge stage
        calls it again; the second call must not re-copy the pack."""
        if getattr(self, "_preloaded", False):
            return
        data = self._read_range(0, self._size)
        self._count_read(len(data))
        self._read_range = lambda off, ln: data[off : off + ln]
        self._count_read = lambda n: None  # already counted in full
        self._preloaded = True

    @staticmethod
    def _dctx() -> "zstandard.ZstdDecompressor":
        """zstd contexts are NOT thread-safe: concurrent decompress on a
        shared context intermittently fails with "data corruption
        detected" (readers run in IO pools). One context per THREAD,
        shared across every pack (contexts are stateless between calls)."""
        d = getattr(_DCTX_LOCAL, "d", None)
        if d is None:
            d = _DCTX_LOCAL.d = zstandard.ZstdDecompressor()
        return d

    def _zstd_one(self, data: bytes, raw_len: int) -> bytes:
        """Decode ONE zstd chunk, native first: on wheel-less images the
        python fallback is the zlib shim, which can't read the real zstd
        frames the native compressor writes -- and vice versa, the
        native decoder refuses shim (zlib) bytes, so each side's output
        always finds its decoder."""
        from ..native import block_decompress_chunks

        outs = block_decompress_chunks("zstd", [data], [raw_len])
        if outs is not None:
            return outs[0]
        return self._dctx().decompress(data, max_output_size=raw_len)

    @classmethod
    def from_bytes(cls, data: bytes) -> "ColumnPack":
        return cls(lambda off, ln: data[off : off + ln], len(data))

    def names(self) -> list[str]:
        return list(self._cols)

    def has(self, name: str) -> bool:
        return name in self._cols

    def n_rows_of(self, name: str) -> int:
        """Row count of a column from footer metadata alone -- no chunk
        IO (pre-read budget estimates)."""
        meta = self._cols.get(name)
        return int(meta["shape"][0]) if meta else 0

    def _cache_get(self, off: int) -> bytes | None:
        with self._cache_lock:
            hit = self._cache.get(off)
            if hit is not None:
                self._cache.move_to_end(off)
            return hit

    def _cache_put(self, off: int, raw: bytes) -> None:
        if len(raw) > self.CHUNK_CACHE_BYTES // 4:
            return  # one huge chunk must not wipe the whole cache
        with self._cache_lock:
            if off in self._cache:
                return
            self._cache[off] = raw
            self._cache_bytes += len(raw)
            while self._cache_bytes > self.CHUNK_CACHE_BYTES and self._cache:
                _, old = self._cache.popitem(last=False)
                self._cache_bytes -= len(old)

    def _chunk(self, rec: list) -> bytes:
        off, stored_len, raw_len, codec = rec
        if raw_len == 0 and stored_len == 0:
            # zero-length chunks share the byte offset of the NEXT chunk
            # (writer advances offset by stored size) -- never cache them
            # under that offset or they poison the real chunk's entry
            return b""
        hit = self._cache_get(off)
        if hit is not None:
            return hit
        data = self._read_range(off, stored_len)
        self._count_read(stored_len)
        if codec == CODEC_ZSTD:
            data = self._zstd_one(data, raw_len)
        elif codec == CODEC_CONST:
            data = data * (raw_len // stored_len)  # tile the stored row
        elif codec != CODEC_RAW:
            data = _EXTRA_CODECS[codec][1](data, raw_len)  # codec matrix
        self._cache_put(off, data)
        return data

    def _chunks(self, recs: list[list]) -> bytes:
        """Fetch + decode many chunks; zstd chunks decompress as one
        threaded native batch when >1 (native/vtpu_native.cc)."""
        parts: list[bytes | None] = [
            b"" if (rec[1] == 0 and rec[2] == 0) else self._cache_get(rec[0])
            for rec in recs
        ]
        miss = [i for i, p in enumerate(parts) if p is None]
        zst = [i for i in miss if recs[i][3] == CODEC_ZSTD]
        if len(zst) > 1:
            from ..native import available, zstd_decompress_chunks

            if available():
                outs = zstd_decompress_chunks(
                    [self._read_range(recs[i][0], recs[i][1]) for i in zst],
                    [recs[i][2] for i in zst],
                )
                if outs is not None:
                    self._count_read(sum(recs[i][1] for i in zst))
                    for i, raw in zip(zst, outs):
                        parts[i] = raw
                        self._cache_put(recs[i][0], raw)
        for i in miss:
            if parts[i] is None:
                parts[i] = self._chunk(recs[i])
        return b"".join(parts)

    def chunk_codecs(self) -> set[str]:
        """Every chunk codec present in the pack -- footer metadata
        only, no IO (the compaction passthrough's codec-match gate)."""
        return {r[3] for meta in self._cols.values() for r in meta["chunks"]}

    def has_cached_array(self, name: str) -> bool:
        """True when a full-column read of `name` is a cache hit (used by
        the search engine's host-vs-device cost estimate)."""
        with self._cache_lock:
            return name in self._arrays

    def _arrays_get(self, name: str) -> np.ndarray | None:
        with self._cache_lock:
            hit = self._arrays.get(name)
            if hit is not None:
                self._arrays.move_to_end(name)
            return hit

    def _arrays_put(self, name: str, arr: np.ndarray) -> None:
        # shares the chunk cache's byte budget (the two caches together
        # are the pack's RAM footprint). Over-budget eviction drops
        # chunk bytes first (the array holds the same data assembled),
        # then other arrays LRU -- never the entry just inserted, so a
        # single large column always stays cached for its repeat readers
        if arr.nbytes > self.CHUNK_CACHE_BYTES:
            return
        with self._cache_lock:
            if name in self._arrays:
                return
            self._arrays[name] = arr
            self._arrays_bytes += arr.nbytes
            while (self._arrays_bytes + self._cache_bytes > self.CHUNK_CACHE_BYTES
                   and self._cache):
                _, old = self._cache.popitem(last=False)
                self._cache_bytes -= len(old)
            while (self._arrays_bytes + self._cache_bytes > self.CHUNK_CACHE_BYTES
                   and len(self._arrays) > 1):
                n, old = next(iter(self._arrays.items()))
                if n == name:
                    break
                del self._arrays[n]
                self._arrays_bytes -= old.nbytes

    def _read_column_into(self, meta: dict) -> np.ndarray | None:
        """Decode a whole column straight into its final buffer. A
        column's chunks sit ADJACENT in the pack, so every run of
        uncached zstd chunks is fetched with ONE ranged read and
        decompressed from that buffer in place -- no per-chunk bytes
        objects, no joins, no per-chunk file opens. None -> caller falls
        back to the chunk-join path."""
        from ..native import available, zstd_decompress_ranges

        if not available():
            return None
        recs = [r for r in meta["chunks"] if r[2] > 0]
        dst = np.empty(int(sum(r[2] for r in recs)), dtype=np.uint8)
        # classify chunks, then coalesce stored-adjacent zstd misses
        z_miss: list[tuple[int, int, int, int]] = []  # (off, stored, raw, dst_pos)
        other: list[tuple[list, int]] = []  # (rec, dst_pos)
        pos = 0
        for rec in recs:
            off, stored, raw_len, codec = rec
            hit = self._cache_get(off)
            if hit is not None:
                dst[pos : pos + raw_len] = np.frombuffer(hit, dtype=np.uint8)
            elif codec == CODEC_ZSTD:
                z_miss.append((off, stored, raw_len, pos))
            else:
                other.append((rec, pos))
            pos += raw_len
        counted = 0
        if z_miss:
            in_offs = np.empty(len(z_miss), np.int64)
            in_lens = np.empty(len(z_miss), np.int64)
            out_offs = np.empty(len(z_miss), np.int64)
            out_lens = np.empty(len(z_miss), np.int64)
            runs: list[tuple[int, int, int]] = []  # (file_off, length, first_idx)
            for i, (off, stored, raw_len, dpos) in enumerate(z_miss):
                in_lens[i] = stored
                out_offs[i] = dpos
                out_lens[i] = raw_len
                if runs and runs[-1][0] + runs[-1][1] == off:
                    fo, ln, fi = runs[-1]
                    runs[-1] = (fo, ln + stored, fi)
                else:
                    runs.append((off, stored, i))
                in_offs[i] = off - runs[-1][0]  # provisional, rebased below
            bufs = []
            base = 0
            for fo, ln, fi in runs:
                bufs.append(self._read_range(fo, ln))
                counted += ln
                # rebase this run's chunk offsets to the joined buffer
                hi = fi
                while hi < len(z_miss) and z_miss[hi][0] >= fo and z_miss[hi][0] < fo + ln:
                    in_offs[hi] = base + (z_miss[hi][0] - fo)
                    hi += 1
                base += ln
            src = (np.frombuffer(bufs[0], dtype=np.uint8) if len(bufs) == 1
                   else np.frombuffer(b"".join(bufs), dtype=np.uint8))
            if not zstd_decompress_ranges(src, in_offs, in_lens, dst, out_offs, out_lens):
                # the ranged reads above really happened: account them
                # before falling back (the fallback counts only its own)
                self._count_read(counted)
                return None
        for (off, stored, raw_len, codec), dpos in other:
            data = self._read_range(off, stored)
            counted += stored
            if codec == CODEC_CONST:
                # tile the one stored row across the chunk, in place
                dst[dpos : dpos + raw_len].reshape(-1, stored)[:] = (
                    np.frombuffer(data, dtype=np.uint8))
                continue
            if codec != CODEC_RAW:
                data = _EXTRA_CODECS[codec][1](data, raw_len)
            dst[dpos : dpos + raw_len] = np.frombuffer(data, dtype=np.uint8)
        self._count_read(counted)
        out = dst.view(np.dtype(meta["dtype"])).reshape(meta["shape"])
        out.flags.writeable = False  # cached entries are shared across readers
        return out

    def read(self, name: str) -> np.ndarray:
        meta = self._cols[name]
        hit = self._arrays_get(name)
        if hit is not None:
            return hit
        arr = self._read_column_into(meta)
        if arr is None:
            # fallback already populated the CHUNK cache (old behavior);
            # caching the assembled array too would charge the same bytes
            # to the shared budget twice
            raw = self._chunks(meta["chunks"])
            return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])).reshape(meta["shape"])
        self._arrays_put(name, arr)
        return arr

    def read_groups(self, name: str, groups: list[int]) -> np.ndarray:
        """Concatenated rows of the given row groups (in the given order).
        Column must be axis-chunked."""
        meta = self._cols[name]
        if meta["axis"] is None:
            raise ValueError(f"column {name} is not axis-chunked")
        full = self._arrays_get(name)
        if full is not None:
            # a full-column read already paid for these rows: slice the
            # cached array instead of re-fetching chunks from the backend
            offs = self.axes[meta["axis"]].offsets
            parts = [full[offs[g] : offs[g + 1]] for g in groups]
            return parts[0] if len(parts) == 1 else np.concatenate(parts)
        raw = self._chunks([meta["chunks"][g] for g in groups])
        shape = [-1] + meta["shape"][1:]
        return np.frombuffer(raw, dtype=np.dtype(meta["dtype"])).reshape(shape)

    def read_many(self, names: list[str]) -> dict[str, np.ndarray]:
        # read() decodes each full column natively into its final buffer
        # (and caches the array), so no chunk-level warm pass is needed
        return {n: self.read(n) for n in names if n in self._cols}

    def read_groups_many(
        self, wants: list[tuple[str, list[int] | None]]
    ) -> dict[str, np.ndarray]:
        """Batched multi-column read: (name, groups|None for all). ALL
        columns' missing chunks decompress as ONE native threaded batch,
        so a trace materialization that touches 20 columns pays one
        parallel decode instead of 20 serial ones."""
        wants = [(n, g) for n, g in wants if n in self._cols]
        # full-column wants decode natively inside read(); only the
        # row-group-sliced wants benefit from the chunk-level warm batch
        self.warm([(n, g) for n, g in wants if g is not None])
        out: dict[str, np.ndarray] = {}
        for name, groups in wants:
            out[name] = self.read(name) if groups is None else self.read_groups(name, groups)
        return out

    def warm(self, wants: list[tuple[str, list[int] | None]]) -> None:
        """Prefetch + batch-decompress every missing chunk of the wanted
        (column, groups) set (full columns land in the array cache,
        group slices in the chunk cache)."""
        self._run_plan(self.plan_fetch(wants))

    def warm_columns(self, names: list[str], gap_bytes: int = 256 << 10) -> None:
        """Cold-read accelerator: fetch EVERY missing chunk of the named
        columns with a few coalesced ranged reads (runs split only at
        gaps > gap_bytes, so interleaved unwanted columns aren't pulled
        wholesale), decompress ALL of them in one batch (threaded native
        when available) straight into one destination buffer, and cache
        the assembled per-column arrays. A cold query touching 12 small
        columns pays ~2 fixed IO costs instead of 12."""
        self._run_plan(self.plan_fetch([(n, None) for n in names],
                                       gap_bytes=gap_bytes))

    def _run_plan(self, cf: "ColumnFetch | None") -> None:
        """Run a fetch plan inline with the pipeline's per-stage
        kerneltel timings -- the serial (no-overlap) form of the stream
        stages, so EVERY cold ranged read shows up under
        tempo_stream_stage_seconds whichever path issued it. The window
        records as its own run: inline stage-seconds then contribute
        matching wall-seconds, so overlap_ratio stays ~1 (honestly
        sequential) for workloads that never pipeline, instead of
        inflating the numerator against someone else's wall."""
        if cf is None:
            return
        import time as _time

        from ..util.kerneltel import TEL

        t_run = _time.perf_counter()
        t0 = t_run
        self.fetch_ranges(cf)
        TEL.record_stream_stage("fetch", _time.perf_counter() - t0)
        t0 = _time.perf_counter()
        self.decode_fetched(cf)
        TEL.record_stream_stage("decompress", _time.perf_counter() - t0)
        TEL.record_stream_run(_time.perf_counter() - t_run)

    # ------------------------------------------------- staged cold reads
    # The cold-read pipeline's unit of work: plan (footer metadata only)
    # -> fetch (the ranged IO) -> decode (decompress + assemble). The
    # streaming pipeline (ops/stream.py) runs the phases of DIFFERENT
    # blocks concurrently -- block N decodes while block N+1's ranged
    # reads are in flight; warm/warm_columns run them back to back.

    def plan_fetch(self, wants: list[tuple[str, list[int] | None]],
                   gap_bytes: int = 256 << 10) -> "ColumnFetch | None":
        """Build the fetch/decode plan for (column, groups|None) wants
        from footer metadata + cache state alone -- no IO. None when
        every want is already cached (nothing to do)."""
        full: list[tuple[str, dict, int]] = []  # (name, meta, dst start)
        recs: list[tuple[list, int]] = []  # (chunk rec, dst_pos; -1 = cache-only)
        cached: list[tuple[bytes, int, int]] = []  # dst copies of cache hits
        pos = 0
        seen: set[str] = set()
        for name, groups in wants:
            meta = self._cols.get(name)
            if meta is None or self.has_cached_array(name):
                continue  # read/read_groups serve it from the array cache
            if groups is None:
                if name in seen:
                    continue  # dedupe; call sites overlap
                seen.add(name)
                pos = (pos + 15) & ~15  # dtype-aligned column starts
                full.append((name, meta, pos))
                for r in meta["chunks"]:
                    if r[2] <= 0:
                        continue
                    hit = self._cache_get(r[0])
                    if hit is not None:
                        # already decoded (e.g. a prior find-by-id's
                        # read_groups): copy into dst, no refetch
                        cached.append((hit, pos, r[2]))
                    else:
                        recs.append((r, pos))
                    pos += r[2]
            else:
                chunks = meta["chunks"]
                for g in groups:
                    r = chunks[g]
                    if r[2] > 0 and self._cache_get(r[0]) is None:
                        recs.append((r, -1))
        if not full and not recs:
            return None
        # coalesce missing chunks into gap-bounded file runs
        by_off = sorted(recs, key=lambda t: t[0][0])
        runs: list[tuple[int, int, list]] = []  # (off, end, members)
        for r, dpos in by_off:
            if runs and r[0] - runs[-1][1] <= gap_bytes and r[0] >= runs[-1][0]:
                off, end, members = runs[-1]
                runs[-1] = (off, max(end, r[0] + r[1]), members + [(r, dpos)])
            else:
                runs.append((r[0], r[0] + r[1], [(r, dpos)]))
        return ColumnFetch(self, full, recs, cached, runs, pos,
                           sum(r[1] for r, _ in recs))

    def fetch_ranges(self, cf: "ColumnFetch") -> None:
        """The IO phase: issue the plan's coalesced ranged reads.
        Idempotent; counts inspected bytes as it reads."""
        if cf.bufs is not None:
            return
        src_parts: list[bytes] = []
        src_pos: dict[int, int] = {}  # chunk file off -> offset in joined src
        base = 0
        counted = 0
        for off, end, members in cf.runs:
            data = self._read_range(off, end - off)
            src_parts.append(data)
            counted += sum(m[0][1] for m in members)
            for r, _ in members:
                src_pos[r[0]] = base + (r[0] - off)
            base += len(data)
        self._count_read(counted)
        cf.bufs = src_parts
        cf.src_pos = src_pos

    def decode_fetched(self, cf: "ColumnFetch") -> None:
        """The decode phase: decompress every fetched chunk (native
        threaded batch per codec when available, per-chunk Python
        otherwise), assemble full-column wants into the array cache and
        sliced wants into the chunk cache."""
        if cf.bufs is None:
            raise ValueError("decode_fetched before fetch_ranges")
        src_pos = cf.src_pos or {}
        src = (np.frombuffer(cf.bufs[0], np.uint8) if len(cf.bufs) == 1
               else np.frombuffer(b"".join(cf.bufs), np.uint8)
               ) if cf.bufs else np.empty(0, np.uint8)
        dst = np.empty(cf.raw_bytes, np.uint8)
        for raw, dpos, raw_len in cf.cached:
            dst[dpos : dpos + raw_len] = np.frombuffer(raw, np.uint8)
        # full-column chunks decode straight into dst; sliced (cache-only)
        # chunks decode into a scratch tail appended after dst's columns
        into_dst = [(r, d) for r, d in cf.recs if d >= 0]
        sliced = [r for r, d in cf.recs if d < 0]
        scratch = np.empty(sum(r[2] for r in sliced), np.uint8)
        placed: list[tuple[list, np.ndarray, int]] = []  # (rec, buf, pos)
        spos = 0
        for r in sliced:
            placed.append((r, scratch, spos))
            spos += r[2]
        for r, d in into_dst:
            placed.append((r, dst, d))
        # batch the native-range codecs per codec group; everything else
        # (const/raw/gzip/lzma, or native refusal) decodes per chunk
        from ..native import block_decompress_ranges

        leftovers: list[tuple[list, np.ndarray, int]] = []
        by_codec: dict[str, list[tuple[list, np.ndarray, int]]] = {}
        for item in placed:
            codec = item[0][3]
            if codec in _NATIVE_RANGE_CODECS:
                by_codec.setdefault(codec, []).append(item)
            else:
                leftovers.append(item)
        for codec, items in by_codec.items():
            # dst and scratch are distinct buffers: one ranges call per
            # (codec, destination) pair
            for buf in (dst, scratch):
                part = [(r, p) for r, b, p in items if b is buf]
                if not part:
                    continue
                ok = block_decompress_ranges(
                    codec, src,
                    np.asarray([src_pos[r[0]] for r, _ in part], np.int64),
                    np.asarray([r[1] for r, _ in part], np.int64),
                    buf,
                    np.asarray([p for _, p in part], np.int64),
                    np.asarray([r[2] for r, _ in part], np.int64),
                )
                if not ok:
                    leftovers.extend((r, buf, p) for r, p in part)
        for r, buf, p in leftovers:
            chunk = src[src_pos[r[0]] : src_pos[r[0]] + r[1]]
            if r[3] == CODEC_CONST:
                buf[p : p + r[2]].reshape(-1, r[1])[:] = chunk
            elif r[3] == CODEC_RAW:
                buf[p : p + r[2]] = chunk
            elif r[3] == CODEC_ZSTD:
                dec = self._zstd_one(chunk.tobytes(), r[2])
                buf[p : p + r[2]] = np.frombuffer(dec, np.uint8)
            else:
                dec = _EXTRA_CODECS[r[3]][1](chunk.tobytes(), r[2])
                buf[p : p + r[2]] = np.frombuffer(dec, np.uint8)
        # sliced chunks land in the chunk cache for read_groups
        for r, buf, p in placed:
            if buf is scratch:
                self._cache_put(r[0], buf[p : p + r[2]].tobytes())
        # COPY each column out of the shared buffer: cached views over
        # one big base would pin the whole buffer for as long as any one
        # entry lives, making LRU eviction free nothing (the copy is a
        # fraction of the decompress cost just paid)
        for name, meta, start in cf.full:
            n_bytes = sum(r[2] for r in meta["chunks"] if r[2] > 0)
            out = dst[start : start + n_bytes].copy().view(np.dtype(meta["dtype"]))
            out = out.reshape(meta["shape"])
            out.flags.writeable = False
            self._arrays_put(name, out)
        cf.bufs = None  # free the fetched bytes; decode is one-shot

    def column_stats(self) -> list[dict]:
        """Per-column layout summary (name, dtype, rows, chunks, stored/
        raw bytes, codecs) -- defined beside the footer format so layout
        knowledge never leaks to callers."""
        out = []
        for name, meta in self._cols.items():
            out.append({
                "name": name,
                "dtype": meta["dtype"],
                "rows": meta["shape"][0],
                "chunks": len(meta["chunks"]),
                "stored": sum(rec[1] for rec in meta["chunks"]),
                "raw": sum(rec[2] for rec in meta["chunks"]),
                "codecs": sorted({rec[3] for rec in meta["chunks"]}),
            })
        return out

    def _broadcast_const_cols(self) -> dict[str, np.ndarray]:
        """Columns whose every chunk is const with one identical row,
        as stride-0 broadcast views (zero decode, zero memory)."""
        out: dict[str, np.ndarray] = {}
        for name, meta in self._cols.items():
            chs = [c for c in meta["chunks"] if c[2] > 0]
            if not chs or any(c[3] != CODEC_CONST for c in chs):
                continue
            rows = {self._read_range(c[0], c[1]) for c in chs}  # tiny reads
            self._count_read(sum(c[1] for c in chs))
            if len(rows) != 1:
                continue
            dt = np.dtype(meta["dtype"])
            shape = tuple(meta["shape"])
            rv = np.frombuffer(next(iter(rows)), dtype=dt).reshape(shape[1:])
            out[name] = np.broadcast_to(rv, shape)
        return out

    def read_all(self, broadcast_const: bool = False,
                 independent: bool = False) -> dict[str, np.ndarray]:
        """Every column, zero-copy: ONE destination buffer laid out
        column-after-column, every zstd chunk decompressed straight into
        its final position (native batch), raw chunks memcpy'd, then each
        column is a frombuffer VIEW of the buffer. The bulk-read path
        compaction uses -- no chunk cache round trips, no joins.

        broadcast_const=True returns fully-constant columns as stride-0
        np.broadcast_to views instead of materialized tiles (the
        compaction merge's const fast path); such views are read-only
        and NOT contiguous -- callers that hand pointers to native code
        must np.ascontiguousarray first.

        independent=True copies each column out of the shared buffer
        (one extra memcpy pass) so a caller can FREE columns one by one
        -- views over one base would pin the whole buffer for as long
        as any single column lives (the compaction merge's
        consume-as-you-go path)."""
        from ..native import available, zstd_decompress_into

        bc = self._broadcast_const_cols() if broadcast_const else {}

        def _fallback():
            # honor independent on the fallback paths too: read() hands
            # back arrays pinned in the pack's LRU cache, which would
            # silently void the caller's free-one-by-one contract
            self.warm([(n, None) for n in self._cols if n not in bc])
            return {
                n: bc[n] if n in bc
                else (self.read(n).copy() if independent else self.read(n))
                for n in self._cols
            }

        if not available():
            return _fallback()

        col_base: dict[str, int] = {}
        z_chunks: list[bytes] = []
        z_offs: list[int] = []
        z_lens: list[int] = []
        raw_parts: list[tuple[int, bytes]] = []
        const_parts: list[tuple[int, bytes, int]] = []  # (pos, row, raw_len)
        counted = 0  # this attempt's IO accounting, for relative rollback
        pos = 0
        for name, meta in self._cols.items():
            if name in bc:
                continue
            pos = (pos + 15) & ~15  # keep every column view 16B-aligned
            col_base[name] = pos
            for off, stored, raw_len, codec in meta["chunks"]:
                if raw_len == 0:
                    continue
                data = self._read_range(off, stored)
                self._count_read(stored)
                counted += stored
                if codec == CODEC_ZSTD:
                    z_chunks.append(data)
                    z_offs.append(pos)
                    z_lens.append(raw_len)
                elif codec == CODEC_CONST:
                    const_parts.append((pos, data, raw_len))
                else:
                    if codec != CODEC_RAW:
                        data = _EXTRA_CODECS[codec][1](data, raw_len)
                    raw_parts.append((pos, data))
                pos += raw_len
        dst = np.empty(pos, dtype=np.uint8)
        if z_chunks and not zstd_decompress_into(
            z_chunks, dst, np.asarray(z_offs), np.asarray(z_lens)
        ):
            # native refused mid-flight: fall back wholesale (and undo
            # this attempt's IO accounting -- the fallback re-counts).
            # Relative subtraction under the lock: a plain reset would
            # clobber concurrent readers' increments.
            self._count_read(-counted)
            return _fallback()
        for p, data in raw_parts:
            dst[p : p + len(data)] = np.frombuffer(data, dtype=np.uint8)
        for p, row, raw_len in const_parts:
            dst[p : p + raw_len].reshape(-1, len(row))[:] = np.frombuffer(
                row, dtype=np.uint8)
        out: dict[str, np.ndarray] = {}
        for name, meta in self._cols.items():
            if name in bc:
                out[name] = bc[name]
                continue
            dt = np.dtype(meta["dtype"])
            n_bytes = int(np.prod(meta["shape"], dtype=np.int64)) * dt.itemsize
            base = col_base[name]
            col = dst[base : base + n_bytes]
            if independent:
                col = col.copy()
            out[name] = col.view(dt).reshape(meta["shape"])
        return out
