"""vtpu block builder: sorted (trace_id, Trace) stream -> columnar block.

The write-side analog of vparquet's create.go:37-67 (WAL iterator ->
rows -> row-group cuts -> backend), but producing the span-major SoA
layout of schema.py. Traces MUST be added in ascending trace-id order
(the WAL iterator and compaction merge both yield sorted streams), which
makes `trace.id_codes` sorted => device lookup is a searchsorted.
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np

from ..backend.base import RawBackend
from ..wire.model import Trace
from ..wire.otlp_json import _value_from_json, _value_to_json
from . import schema as S
from .bloom import ShardedBloom
from .colio import AxisChunks, pack_columns_stream
from .dictionary import DictBuilder, Dictionary, apply_remap
from .meta import BlockMeta, RowGroupStats

DATA_NAME = "data.vtpu"
DICT_NAME = "dict.vtpu"
BLOOM_PREFIX = "bloom-"


def _cut_kernels():
    """The device block-cut kernel module (ops/blockcut) when the cut
    router picks the device engine, else None -- the host code inline
    below IS each kernel's registered twin, so both paths are
    bit-identical. Lazy so block/ imports without jax."""
    try:
        from ..ops import blockcut

        if blockcut.cut_engine() == "device":
            return blockcut
    except Exception:
        pass
    return None


def _attr_row(dictb: DictBuilder, value) -> tuple[int, int, int, float, int, float]:
    """-> (vtype, str_id, int32, f32, int64, f64)."""
    if isinstance(value, bool):
        return S.VT_BOOL, -1, int(value), 0.0, int(value), 0.0
    if isinstance(value, str):
        return S.VT_STR, dictb.code(value), 0, 0.0, 0, 0.0
    if isinstance(value, int):
        i32 = int(np.clip(value, -(2**31), 2**31 - 1))
        return S.VT_INT, -1, i32, float(value), value, 0.0
    if isinstance(value, float):
        return S.VT_FLOAT, -1, 0, np.float32(value).item(), 0, value
    # bytes / lists / anything else: exact OTLP-JSON payload in the dict
    payload = json.dumps(_value_to_json(value), separators=(",", ":"), sort_keys=True)
    return S.VT_COMPLEX, dictb.code(payload), 0, 0.0, 0, 0.0


def decode_attr_value(vtype: int, str_id: int, i32: int, i64: int, f64: float, d: Dictionary):
    if vtype == S.VT_STR:
        return d.string(str_id)
    if vtype == S.VT_INT:
        return int(i64)
    if vtype == S.VT_FLOAT:
        return float(f64)
    if vtype == S.VT_BOOL:
        return bool(i32)
    return _value_from_json(json.loads(d.string(str_id)))


class _AttrTable:
    """CSR attribute accumulator: one row per attr with an owner index."""

    def __init__(self):
        self.owner: list[int] = []
        self.key_id: list[int] = []
        self.vtype: list[int] = []
        self.str_id: list[int] = []
        self.i32: list[int] = []
        self.f32: list[float] = []
        self.i64: list[int] = []
        self.f64: list[float] = []

    def add(self, dictb: DictBuilder, owner: int, key: str, value) -> None:
        vt, sid, i32, f32, i64, f64 = _attr_row(dictb, value)
        self.owner.append(owner)
        self.key_id.append(dictb.code(key))
        self.vtype.append(vt)
        self.str_id.append(sid)
        self.i32.append(i32)
        self.f32.append(f32)
        self.i64.append(i64)
        self.f64.append(f64)

    def columns(self, prefix: str, owner_col: str) -> dict[str, np.ndarray]:
        return {
            f"{prefix}.{owner_col}": np.asarray(self.owner, dtype=np.int32),
            f"{prefix}.key_id": np.asarray(self.key_id, dtype=np.int32),
            f"{prefix}.vtype": np.asarray(self.vtype, dtype=np.int32),
            f"{prefix}.str_id": np.asarray(self.str_id, dtype=np.int32),
            f"{prefix}.int32": np.asarray(self.i32, dtype=np.int32),
            f"{prefix}.f32": np.asarray(self.f32, dtype=np.float32),
            f"{prefix}.int64": np.asarray(self.i64, dtype=np.int64),
            f"{prefix}.f64": np.asarray(self.f64, dtype=np.float64),
        }


@dataclass
class FinalizedBlock:
    meta: BlockMeta
    cols: dict[str, np.ndarray]
    axes: dict[str, AxisChunks]
    col_axis: dict[str, str]
    dictionary: Dictionary
    bloom: ShardedBloom


class BlockBuilder:
    def __init__(
        self,
        tenant: str,
        block_id: str | None = None,
        row_group_spans: int = S.DEFAULT_ROW_GROUP_SPANS,
        estimated_traces: int = 0,
        compaction_level: int = 0,
        replication_factor: int = 1,
    ):
        self.meta = BlockMeta.new(tenant, block_id)
        self.meta.compaction_level = compaction_level
        self.meta.replication_factor = replication_factor
        self.row_group_spans = row_group_spans
        self.estimated_traces = estimated_traces
        self.dictb = DictBuilder()
        self.dictb.code("")  # code 0 is always the empty string

        # span accumulators
        self.sp_trace_sid: list[int] = []
        self.sp_name: list[int] = []
        self.sp_service: list[int] = []
        self.sp_kind: list[int] = []
        self.sp_status: list[int] = []
        self.sp_start_ns: list[int] = []
        self.sp_end_ns: list[int] = []
        self.sp_http_status: list[int] = []
        self.sp_http_method: list[int] = []
        self.sp_http_url: list[int] = []
        self.sp_res_idx: list[int] = []
        self.sp_scope_idx: list[int] = []
        self.sp_id: list[bytes] = []
        self.sp_parent_id: list[bytes] = []
        self.sp_parent_idx: list[int] = []  # block row of the parent, -1 = root
        self.sp_trace_state: list[int] = []
        self.sp_status_msg: list[int] = []
        self.sp_dropped: list[int] = []
        self.sattr = _AttrTable()

        # trace accumulators
        self.tr_ids: list[bytes] = []
        self.tr_span_off: list[int] = [0]
        self.tr_start_ns: list[int] = []
        self.tr_end_ns: list[int] = []
        self.tr_root_service: list[int] = []
        self.tr_root_name: list[int] = []

        # resource / scope tables
        self.res_dedicated: dict[str, list[int]] = {
            col: [] for col in sorted(set(S.WELL_KNOWN_RES_ATTRS.values()))
        }
        self.rattr = _AttrTable()
        self.scope_key_to_idx: dict[tuple[int, int], int] = {}
        self.scope_name: list[int] = []
        self.scope_version: list[int] = []

        # events / links
        self.ev_span: list[int] = []
        self.ev_time_ns: list[int] = []
        self.ev_name: list[int] = []
        self.ev_dropped: list[int] = []
        self.evattr = _AttrTable()
        self.ln_span: list[int] = []
        self.ln_trace_id: list[bytes] = []
        self.ln_span_id: list[bytes] = []
        self.ln_state: list[int] = []
        self.lnattr = _AttrTable()

    # ------------------------------------------------------------------
    def add_trace(self, trace_id: bytes, trace: Trace) -> None:
        tid = trace_id.rjust(16, b"\x00")
        if self.tr_ids and tid <= self.tr_ids[-1]:
            raise ValueError("traces must be added in ascending unique id order")
        sid = len(self.tr_ids)
        self.tr_ids.append(tid)

        t_start, t_end = None, 0
        root_service, root_name = None, None
        first_service, first_name = None, None
        code = self.dictb.code

        # collect (start, ...) rows then sort spans within the trace by start
        rows = []
        for rs in trace.resource_spans:
            res_idx = len(self.res_dedicated["res.service_id"])
            # dedicated resource columns + generic rattr rows
            for col in self.res_dedicated:
                self.res_dedicated[col].append(-1)
            for k, v in rs.resource.attrs.items():
                ded = S.WELL_KNOWN_RES_ATTRS.get(k)
                if ded is not None and isinstance(v, str):
                    self.res_dedicated[ded][res_idx] = code(v)
                else:
                    self.rattr.add(self.dictb, res_idx, k, v)
            service = rs.resource.service_name
            svc_code = code(service) if service else -1
            self.res_dedicated["res.service_id"][res_idx] = svc_code

            for ss in rs.scope_spans:
                skey = (code(ss.scope.name), code(ss.scope.version))
                scope_idx = self.scope_key_to_idx.get(skey)
                if scope_idx is None:
                    scope_idx = len(self.scope_name)
                    self.scope_key_to_idx[skey] = scope_idx
                    self.scope_name.append(skey[0])
                    self.scope_version.append(skey[1])
                for sp in ss.spans:
                    rows.append((sp.start_unix_nano, res_idx, scope_idx, svc_code, sp))

        rows.sort(key=lambda r: (r[0], r[4].span_id))
        # parent ROW index within the block (span.parent_idx): parents
        # resolve within the trace, so one pass over the sorted rows
        # suffices; -1 = root / parent span not in this trace. Backs the
        # device/host structural operators (> >> ~) as exact gather /
        # segment ops (ops/filter 'struct' nodes) -- the reference
        # evaluates these relations row-by-row in its engine instead
        # (pkg/traceql/enum_operators.go OpSpansetChild/Descendant/Sibling).
        base = len(self.sp_trace_sid)
        local_of = {r[4].span_id: j for j, r in enumerate(rows) if r[4].span_id}
        # -1 = root (no parent id); -2 = ORPHAN (parent id set but that
        # span is absent from the trace -- dropped/partial ingest). The
        # distinction keeps the sibling operator exact-able: orphans can
        # still be siblings by shared parent ID, which the row-index
        # kernels over-match and host verification settles.
        for start_ns, res_idx, scope_idx, svc_code, sp in rows:
            pid = sp.parent_span_id
            has_pid = bool(pid and pid.strip(b"\x00"))
            j = local_of.get(pid) if has_pid else None
            self.sp_parent_idx.append(
                base + j if j is not None else (-2 if has_pid else -1))
        for start_ns, res_idx, scope_idx, svc_code, sp in rows:
            row = len(self.sp_trace_sid)
            self.sp_trace_sid.append(sid)
            self.sp_name.append(code(sp.name))
            self.sp_service.append(svc_code)
            self.sp_kind.append(int(sp.kind))
            self.sp_status.append(int(sp.status_code))
            self.sp_start_ns.append(sp.start_unix_nano)
            self.sp_end_ns.append(sp.end_unix_nano)
            self.sp_res_idx.append(res_idx)
            self.sp_scope_idx.append(scope_idx)
            self.sp_id.append(sp.span_id.ljust(8, b"\x00")[:8])
            self.sp_parent_id.append(sp.parent_span_id.ljust(8, b"\x00")[:8])
            self.sp_trace_state.append(code(sp.trace_state))
            self.sp_status_msg.append(code(sp.status_message))
            self.sp_dropped.append(sp.dropped_attributes_count)

            http_status, http_method, http_url = -1, -1, -1
            for k, v in sp.attrs.items():
                if k == "http.status_code" and isinstance(v, int) and not isinstance(v, bool):
                    http_status = int(np.clip(v, -(2**31), 2**31 - 1))
                elif k == "http.method" and isinstance(v, str):
                    http_method = code(v)
                elif k == "http.url" and isinstance(v, str):
                    http_url = code(v)
                self.sattr.add(self.dictb, row, k, v)
            self.sp_http_status.append(http_status)
            self.sp_http_method.append(http_method)
            self.sp_http_url.append(http_url)

            for e in sp.events:
                ev = len(self.ev_span)
                self.ev_span.append(row)
                self.ev_time_ns.append(e.time_unix_nano)
                self.ev_name.append(code(e.name))
                self.ev_dropped.append(e.dropped_attributes_count)
                for k, v in e.attrs.items():
                    self.evattr.add(self.dictb, ev, k, v)
            for l in sp.links:
                ln = len(self.ln_span)
                self.ln_span.append(row)
                self.ln_trace_id.append(l.trace_id.rjust(16, b"\x00")[:16])
                self.ln_span_id.append(l.span_id.ljust(8, b"\x00")[:8])
                self.ln_state.append(code(l.trace_state))
                for k, v in l.attrs.items():
                    self.lnattr.add(self.dictb, ln, k, v)

            if t_start is None or start_ns < t_start:
                t_start = start_ns
            t_end = max(t_end, sp.end_unix_nano)
            if first_service is None:
                first_service, first_name = svc_code, code(sp.name)
            if root_service is None and not sp.parent_span_id.strip(b"\x00"):
                root_service, root_name = svc_code, code(sp.name)

        self.tr_span_off.append(len(self.sp_trace_sid))
        self.tr_start_ns.append(t_start or 0)
        self.tr_end_ns.append(t_end)
        self.tr_root_service.append(root_service if root_service is not None else (first_service or 0))
        self.tr_root_name.append(root_name if root_name is not None else (first_name or 0))

    # ------------------------------------------------------------------
    def finalize(self, bloom: ShardedBloom | None = None) -> FinalizedBlock:
        """Assemble columns + meta. `bloom` (optional) is a precomputed
        filter covering every added trace id — compaction passes the
        device OR-union of the input blocks' filters (ops/bloom_ops.py)
        instead of re-inserting every id, the analog of the reference
        rebuilding blooms during merge (vparquet/compactor.go:61-80)."""
        n_spans = len(self.sp_trace_sid)
        n_traces = len(self.tr_ids)
        dictionary, remap = self.dictb.finalize()
        kern = _cut_kernels()
        rm_arr = kern.remap_codes_device if kern is not None else apply_remap
        rm = lambda lst: rm_arr(np.asarray(lst, dtype=np.int32), remap)  # noqa: E731

        start_ns = np.asarray(self.sp_start_ns, dtype=np.uint64)
        end_ns = np.asarray(self.sp_end_ns, dtype=np.uint64)
        base_ns = int(start_ns.min()) if n_spans else 0
        start_ms = ((start_ns.astype(np.int64) - base_ns) // 1_000_000).astype(np.int32)
        dur_ns_full = np.maximum(end_ns.astype(np.int64) - start_ns.astype(np.int64), 0)
        dur_us = np.clip(dur_ns_full // 1_000, 0, 2**31 - 1).astype(np.int32)
        # ns remainder: (dur_us, dur_lo) compare == exact ns compare on device
        dur_lo = (dur_ns_full % 1_000).astype(np.int32)

        tr_start_ns = np.asarray(self.tr_start_ns, dtype=np.uint64)
        tr_end_ns = np.asarray(self.tr_end_ns, dtype=np.uint64)
        tr_start_ms = ((tr_start_ns.astype(np.int64) - base_ns) // 1_000_000).astype(np.int32)
        tr_end_ms = ((tr_end_ns.astype(np.int64) - base_ns) // 1_000_000).astype(np.int32)
        tr_dur_full = np.maximum(tr_end_ns.astype(np.int64) - tr_start_ns.astype(np.int64), 0)
        tr_dur_us = np.clip(tr_dur_full // 1_000, 0, 2**31 - 1).astype(np.int32)
        tr_dur_lo = (tr_dur_full % 1_000).astype(np.int32)

        id_codes = np.asarray(
            [S.trace_id_to_codes(t) for t in self.tr_ids], dtype=np.int32
        ).reshape(n_traces, 4)

        cols: dict[str, np.ndarray] = {
            "span.trace_sid": np.asarray(self.sp_trace_sid, dtype=np.int32),
            "span.name_id": rm(self.sp_name),
            "span.service_id": rm(self.sp_service),
            "span.kind": np.asarray(self.sp_kind, dtype=np.int32),
            "span.status": np.asarray(self.sp_status, dtype=np.int32),
            "span.start_ms": start_ms,
            "span.dur_us": dur_us,
            "span.dur_lo": dur_lo,
            "span.http_status": np.asarray(self.sp_http_status, dtype=np.int32),
            "span.http_method_id": rm(self.sp_http_method),
            "span.http_url_id": rm(self.sp_http_url),
            "span.res_idx": np.asarray(self.sp_res_idx, dtype=np.int32),
            "span.start_ns": start_ns,
            "span.end_ns": end_ns,
            "span.id": np.frombuffer(b"".join(self.sp_id) or b"", dtype=np.uint8).reshape(n_spans, 8),
            "span.parent_id": np.frombuffer(b"".join(self.sp_parent_id) or b"", dtype=np.uint8).reshape(n_spans, 8),
            "span.parent_idx": np.asarray(self.sp_parent_idx, dtype=np.int32),
            "span.trace_state_id": rm(self.sp_trace_state),
            "span.status_msg_id": rm(self.sp_status_msg),
            "span.dropped_attrs": np.asarray(self.sp_dropped, dtype=np.int32),
            "span.scope_idx": np.asarray(self.sp_scope_idx, dtype=np.int32),
            "trace.id": np.frombuffer(b"".join(self.tr_ids) or b"", dtype=np.uint8).reshape(n_traces, 16),
            "trace.id_codes": id_codes,
            "trace.span_off": np.asarray(self.tr_span_off, dtype=np.int32),
            "trace.start_ms": tr_start_ms,
            "trace.end_ms": tr_end_ms,
            "trace.dur_us": tr_dur_us,
            "trace.dur_lo": tr_dur_lo,
            "trace.root_service_id": rm(self.tr_root_service),
            "trace.root_name_id": rm(self.tr_root_name),
            "trace.start_ns": tr_start_ns,
            "trace.end_ns": tr_end_ns,
            "scope.name_id": rm(self.scope_name),
            "scope.version_id": rm(self.scope_version),
            "ev.span": np.asarray(self.ev_span, dtype=np.int32),
            "ev.time_ns": np.asarray(self.ev_time_ns, dtype=np.uint64),
            "ev.name_id": rm(self.ev_name),
            "ev.dropped": np.asarray(self.ev_dropped, dtype=np.int32),
            "ln.span": np.asarray(self.ln_span, dtype=np.int32),
            "ln.trace_id": np.frombuffer(b"".join(self.ln_trace_id) or b"", dtype=np.uint8).reshape(len(self.ln_span), 16),
            "ln.span_id": np.frombuffer(b"".join(self.ln_span_id) or b"", dtype=np.uint8).reshape(len(self.ln_span), 8),
            "ln.state_id": rm(self.ln_state),
        }
        for col, vals in self.res_dedicated.items():
            cols[col] = rm(vals)

        # trace-resource membership summary (tres axis): one row per
        # (trace, resource) pair with the span count, offsets per trace.
        # Res-scoped queries (service.name etc., the dominant search
        # shape) evaluate over ~resources-per-trace rows instead of the
        # full span axis -- a ~10x smaller cold decode than span.res_idx.
        # No reference analog: vparquet nests spans under ResourceSpans so
        # its res predicates skip span pages for free (schema.go:75-172);
        # this is the SoA equivalent of that skip.
        cols.update(build_tres(cols["span.trace_sid"], cols["span.res_idx"], n_traces))
        for table, prefix, owner in (
            (self.sattr, "sattr", "span"),
            (self.rattr, "rattr", "res"),
            (self.evattr, "evattr", "ev"),
            (self.lnattr, "lnattr", "ln"),
        ):
            tcols = table.columns(prefix, owner)
            tcols[f"{prefix}.key_id"] = rm_arr(tcols[f"{prefix}.key_id"], remap)
            tcols[f"{prefix}.str_id"] = rm_arr(tcols[f"{prefix}.str_id"], remap)
            cols.update(tcols)

        axes, col_axis, row_groups = self._compute_row_groups(cols, start_ms, dur_us, kern)

        m = self.meta
        m.total_traces = n_traces
        m.total_spans = n_spans
        m.min_id = self.tr_ids[0].hex() if self.tr_ids else ""
        m.max_id = self.tr_ids[-1].hex() if self.tr_ids else ""
        m.start_time_unix_nano = base_ns
        m.end_time_unix_nano = int(end_ns.max()) if n_spans else 0
        m.dict_size = len(dictionary)
        m.row_groups = row_groups

        if bloom is None:
            if self.estimated_traces:
                bloom = ShardedBloom.for_estimated_items(max(self.estimated_traces, n_traces))
            else:
                bloom = ShardedBloom.for_estimated_items(max(n_traces, 1))
            if kern is not None and self.tr_ids:
                bloom.words = kern.bloom_bits_device(bloom.words, self.tr_ids,
                                                     bloom.shard_bits)
            else:
                bloom.add_many(self.tr_ids)
        m.bloom_shards = bloom.n_shards
        m.bloom_shard_bits = bloom.shard_bits

        return FinalizedBlock(m, cols, axes, col_axis, dictionary, bloom)

    def _compute_row_groups(self, cols, start_ms, dur_us, kernels=None):
        return compute_row_groups(cols, start_ms, dur_us, self.row_group_spans,
                                  kernels=kernels)


def build_tres(trace_sid: np.ndarray, res_idx: np.ndarray, n_traces: int) -> dict[str, np.ndarray]:
    """tres columns from the span axis: unique (trace, res) pairs with
    span counts, plus per-trace offsets. Vectorized: one 64-bit
    composite-key unique."""
    if len(trace_sid) == 0:
        return {
            "tres.res": np.empty(0, np.int32),
            "tres.nspans": np.empty(0, np.int32),
            "trace.tres_off": np.zeros(n_traces + 1, np.int32),
        }
    key = (trace_sid.astype(np.int64) << 32) | (
        res_idx.astype(np.int64) & 0xFFFFFFFF
    )
    uniq, counts = np.unique(key, return_counts=True)
    tres_sid = (uniq >> 32).astype(np.int32)
    tres_res = (uniq & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    off = np.searchsorted(tres_sid, np.arange(n_traces + 1, dtype=np.int64)).astype(np.int32)
    return {
        "tres.res": np.ascontiguousarray(tres_res),
        "tres.nspans": counts.astype(np.int32),
        "trace.tres_off": off,
    }


def compute_row_groups(cols, start_ms, dur_us, row_group_spans, kernels=None):
    """Row-group boundaries + per-group pruning stats from assembled
    columns (shared by the builder and the columnar compactor).
    `kernels` (ops/blockcut, optional) runs the per-group min/max as one
    device segmented reduce; stats are identical either way."""
    n_spans = len(cols["span.trace_sid"])
    bounds = list(range(0, n_spans, row_group_spans)) + [n_spans]
    if len(bounds) < 2:
        bounds = [0, 0]
    span_ax = AxisChunks(bounds)

    def child_axis(owner: np.ndarray) -> AxisChunks:
        offs = np.searchsorted(owner, bounds, side="left")
        offs[0], offs[-1] = 0, len(owner)
        return AxisChunks([int(x) for x in offs])

    axes = {
        S.AX_SPAN: span_ax,
        S.AX_SATTR: child_axis(cols["sattr.span"]),
        S.AX_EVENT: child_axis(cols["ev.span"]),
        S.AX_LINK: child_axis(cols["ln.span"]),
    }
    axes[S.AX_EVATTR] = AxisChunks(
        [int(x) for x in np.searchsorted(cols["evattr.ev"], axes[S.AX_EVENT].offsets)]
    )
    axes[S.AX_LNATTR] = AxisChunks(
        [int(x) for x in np.searchsorted(cols["lnattr.ln"], axes[S.AX_LINK].offsets)]
    )

    col_axis: dict[str, str] = {}
    for name in cols:
        pref = name.split(".", 1)[0]
        ax = {
            "span": S.AX_SPAN,
            "sattr": S.AX_SATTR,
            "ev": S.AX_EVENT,
            "evattr": S.AX_EVATTR,
            "ln": S.AX_LINK,
            "lnattr": S.AX_LNATTR,
        }.get(pref)
        if ax is not None:
            col_axis[name] = ax

    trace_sid = cols["span.trace_sid"]
    # with any spans at all, every bounds group is non-empty, so the
    # segmented reduce covers all of them
    mm = (kernels.rowgroup_minmax_device(start_ms, dur_us, bounds)
          if kernels is not None and n_spans > 0 else None)
    row_groups = []
    for g in range(span_ax.n_groups):
        lo, hi = bounds[g], bounds[g + 1]
        if hi <= lo:
            row_groups.append(RowGroupStats(lo, hi, 0, 0, 0, 0, 0))
            continue
        row_groups.append(
            RowGroupStats(
                span_lo=lo,
                span_hi=hi,
                trace_lo=int(trace_sid[lo]),
                trace_hi=int(trace_sid[hi - 1]) + 1,
                start_ms_min=int(mm[0][g] if mm else start_ms[lo:hi].min()),
                start_ms_max=int(mm[1][g] if mm else start_ms[lo:hi].max()),
                dur_us_max=int(mm[2][g] if mm else dur_us[lo:hi].max()),
            )
        )
    return axes, col_axis, row_groups


# metadata axes every COLD query must decode before it can do anything
# (tres plan columns, trace candidate/result columns, res and scope
# tables): stored UNCOMPRESSED so a cold open's critical path is pure
# IO -- they are a few percent of pack bytes, so the block grows ~2-3%
# while cold queries skip their entire decompress step. (The const-chunk
# codec still applies, so absent optional columns stay one row.) The
# span/attr payload keeps the ratio-optimal zstd level.
FAST_DECODE_PREFIXES = ("trace.", "tres.", "res.", "scope.")


def _column_level(name: str):
    return "raw" if name.startswith(FAST_DECODE_PREFIXES) else None


def write_block(backend: RawBackend, fin: FinalizedBlock, level: int = 3,
                codec: str = "zstd", version: str | None = None,
                defer_meta: bool = False) -> BlockMeta:
    """Write all block objects; meta.json last so pollers never see a
    partial block (reference writes meta last for the same reason).
    codec selects the chunk compression (colio codec matrix); readers
    dispatch per chunk, so mixed-codec backends are fine.

    version: block encoding version to WRITE (default: the registry's
    CURRENT_VERSION). "vtpu1" emits the JSON pack footer that pre-binary
    readers parse; "vtpu2" the binary footer. The convert tool and
    mixed-version tests are the down-level writers.

    defer_meta=True holds back the meta.json write -- the block stays
    INVISIBLE to pollers until publish_block_meta. The compaction
    pipeline uses this to commit a multi-output job atomically: every
    output's data is durable before the first meta appears, so a crash
    between outputs leaves nothing half-visible."""
    from .versioned import CURRENT_VERSION

    m = fin.meta
    m.version = version or CURRENT_VERSION
    footer_kind = "json" if m.version == "vtpu1" else "binary"
    app = backend.open_append(m.tenant_id, m.block_id, DATA_NAME)
    try:
        # pipelined writer: append() blocks on disk writeback (the write
        # syscall drops the GIL), so a single ordered writer thread
        # overlaps IO stalls with the next chunk's compression -- on the
        # one-core compactor box this hides most of the write wall time
        import queue as _queue
        import threading as _threading

        # compression emits chunks in per-column batch bursts; the queue
        # must absorb a burst (~one column's chunks) or the producer
        # blocks on put() instead of compressing the next column. The
        # bound is BYTES, not parts: a slow disk must not let hundreds
        # of MB of compressed chunks pile up in memory.
        q: _queue.Queue = _queue.Queue()
        cond = _threading.Condition()
        pending = [0]  # bytes queued but not yet written
        budget_bytes = 32 << 20
        werr: list[BaseException] = []

        def _writer():
            # keep draining after a failure so the producer never
            # deadlocks waiting for budget; the error surfaces after join
            while True:
                part = q.get()
                if part is None:
                    return
                if not werr:
                    try:
                        app.append(part)
                    except BaseException as e:
                        werr.append(e)
                with cond:
                    pending[0] -= len(part)
                    cond.notify()

        wt = _threading.Thread(target=_writer, name="block-writer", daemon=True)
        wt.start()
        try:
            for part in pack_columns_stream(fin.cols, fin.axes, fin.col_axis,
                                            level=level, codec=codec,
                                            level_for=_column_level,
                                            footer=footer_kind):
                if werr:
                    break
                with cond:
                    # an oversized single part passes when the queue is
                    # empty rather than deadlocking on the budget
                    while pending[0] > 0 and pending[0] + len(part) > budget_bytes:
                        cond.wait()
                    pending[0] += len(part)
                q.put(part)
        finally:
            q.put(None)
            wt.join()
        if werr:
            raise werr[0]
        app.close()
    except BaseException:
        app.abort()
        raise
    backend.write(m.tenant_id, m.block_id, DICT_NAME, fin.dictionary.to_bytes())
    for i in range(fin.bloom.n_shards):
        backend.write(m.tenant_id, m.block_id, f"{BLOOM_PREFIX}{i}", fin.bloom.shard_bytes(i))
    m.size_bytes = app.bytes_written
    if not defer_meta:
        backend.write(m.tenant_id, m.block_id, "meta.json", m.to_json())
    return m


def publish_block_meta(backend: RawBackend, meta: BlockMeta) -> None:
    """Commit a block written with defer_meta=True: the meta.json write
    is the visibility point for pollers."""
    backend.write(meta.tenant_id, meta.block_id, "meta.json", meta.to_json())


def build_block_from_traces(
    backend: RawBackend,
    tenant: str,
    traces: list[tuple[bytes, Trace]],
    block_id: str | None = None,
    row_group_spans: int = S.DEFAULT_ROW_GROUP_SPANS,
    compaction_level: int = 0,
    codec: str = "zstd",
) -> BlockMeta:
    b = BlockBuilder(tenant, block_id, row_group_spans, compaction_level=compaction_level)
    for tid, t in sorted(traces, key=lambda p: p[0]):
        b.add_trace(tid, t)
    return write_block(backend, b.finalize(), codec=codec)
