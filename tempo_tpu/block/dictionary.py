"""Per-block string dictionary.

All strings in a vtpu block (service names, span names, attribute keys
and string values, URLs, ...) live in ONE sorted dictionary; every
string column is an int32 code column. This is the core trick that makes
trace data TPU-friendly: string predicates become integer compares on
device, with the string->code mapping resolved host-side per query
(a miss prunes the whole block). Sorting at finalize means codes are
ordered lexicographically, so device kernels can do range/prefix
predicates as integer range checks.

Serialized form: zstd( uvarint count | repeated (uvarint len | utf8) ).
"""

from __future__ import annotations

import bisect

import numpy as np
import zstandard

from ..wire import pbwire as w

NO_CODE = np.int32(-1)  # "absent" sentinel in every code column


class DictBuilder:
    def __init__(self):
        self._codes: dict[str, int] = {}

    def code(self, s: str) -> int:
        c = self._codes.get(s)
        if c is None:
            c = len(self._codes)
            self._codes[s] = c
        return c

    def __len__(self) -> int:
        return len(self._codes)

    def finalize(self) -> tuple["Dictionary", np.ndarray]:
        """Sort strings; return (dictionary, remap) where remap[old_code]
        -> sorted code. Apply remap to every code column before writing."""
        strings = sorted(self._codes)
        remap = np.empty(len(strings), dtype=np.int32)
        for new_code, s in enumerate(strings):
            remap[self._codes[s]] = new_code
        return Dictionary(strings), remap


def apply_remap(col: np.ndarray, remap: np.ndarray) -> np.ndarray:
    """Remap a code column, passing through NO_CODE sentinels."""
    out = np.where(col >= 0, remap[np.maximum(col, 0)], col)
    return out.astype(np.int32)


class Dictionary:
    def __init__(self, strings: list[str]):
        self.strings = strings

    def __len__(self) -> int:
        return len(self.strings)

    def lookup(self, s: str) -> int:
        """Code for s, or -1 if absent (prunes the block)."""
        i = bisect.bisect_left(self.strings, s)
        if i < len(self.strings) and self.strings[i] == s:
            return i
        return -1

    def prefix_range(self, prefix: str) -> tuple[int, int]:
        """[lo, hi) code range of strings with the given prefix."""
        lo = bisect.bisect_left(self.strings, prefix)
        hi = bisect.bisect_left(self.strings, prefix + "￿")
        return lo, hi

    def string(self, code: int) -> str:
        if 0 <= code < len(self.strings):
            return self.strings[code]
        return ""

    def to_bytes(self) -> bytes:
        buf = bytearray()
        w.write_varint(buf, len(self.strings))
        for s in self.strings:
            b = s.encode("utf-8")
            w.write_varint(buf, len(b))
            buf.extend(b)
        return zstandard.ZstdCompressor(level=3).compress(bytes(buf))

    @classmethod
    def from_bytes(cls, data: bytes) -> "Dictionary":
        raw = zstandard.ZstdDecompressor().decompress(data)
        count, pos = w.read_varint(raw, 0)
        strings = []
        for _ in range(count):
            ln, pos = w.read_varint(raw, pos)
            strings.append(raw[pos : pos + ln].decode("utf-8"))
            pos += ln
        return cls(strings)
