"""Per-block string dictionary.

All strings in a vtpu block (service names, span names, attribute keys
and string values, URLs, ...) live in ONE sorted dictionary; every
string column is an int32 code column. This is the core trick that makes
trace data TPU-friendly: string predicates become integer compares on
device, with the string->code mapping resolved host-side per query
(a miss prunes the whole block). Sorting at finalize means codes are
ordered lexicographically, so device kernels can do range/prefix
predicates as integer range checks. (utf-8 byte order equals unicode
codepoint order, so byte-level and str-level comparisons agree.)

Serialized form ("DIC2"): magic | zstd( u32 count | u32 offsets[count+1]
| utf8 blob ) -- two frombuffer calls to load, NO per-string parse, and
the loaded form stays as (blob, offsets) with per-string decode deferred
until somebody actually asks for the text. Block open cost is O(bytes),
not O(strings): the dominant cost of the old uvarint stream was half a
million Python-level varint reads per compaction. The legacy varint
form is still readable.
"""

from __future__ import annotations

import struct

import numpy as np
try:
    import zstandard
except ModuleNotFoundError:  # image without the wheel: zlib-backed shim
    from ..util import zstdshim as zstandard

from ..wire import pbwire as w

NO_CODE = np.int32(-1)  # "absent" sentinel in every code column

_MAGIC = b"DIC2"


class DictBuilder:
    def __init__(self):
        self._codes: dict[str, int] = {}

    def code(self, s: str) -> int:
        c = self._codes.get(s)
        if c is None:
            c = len(self._codes)
            self._codes[s] = c
        return c

    def __len__(self) -> int:
        return len(self._codes)

    def finalize(self) -> tuple["Dictionary", np.ndarray]:
        """Sort strings; return (dictionary, remap) where remap[old_code]
        -> sorted code. Apply remap to every code column before writing."""
        strings = sorted(self._codes)
        remap = np.empty(len(strings), dtype=np.int32)
        for new_code, s in enumerate(strings):
            remap[self._codes[s]] = new_code
        return Dictionary(strings), remap


def apply_remap(col: np.ndarray, remap: np.ndarray) -> np.ndarray:
    """Remap a code column, passing through NO_CODE sentinels."""
    out = np.where(col >= 0, remap[np.maximum(col, 0)], col)
    return out.astype(np.int32)


def _incr_str(s: str) -> str | None:
    """Smallest string strictly greater than every string with prefix s
    (None = unbounded: s is all U+10FFFF). The codepoint-level twin of
    _incr_bytes; both are exact bounds, so bisecting on either yields
    the same index."""
    cps = list(s)
    while cps:
        if ord(cps[-1]) != 0x10FFFF:
            cps[-1] = chr(ord(cps[-1]) + 1)
            return "".join(cps)
        cps.pop()
    return None


def _incr_bytes(b: bytes) -> bytes | None:
    """Smallest byte string strictly greater than every string with
    prefix b (None = no upper bound: b is all 0xff)."""
    arr = bytearray(b)
    while arr:
        if arr[-1] != 0xFF:
            arr[-1] += 1
            return bytes(arr)
        arr.pop()
    return None


class Dictionary:
    """Sorted string table. Two interchangeable representations:
    eager (list[str], from the builder) and lazy ((blob, offsets) from
    disk, strings decoded on demand and memoized)."""

    def __init__(self, strings: list[str] | None = None,
                 blob: bytes | None = None, offsets: np.ndarray | None = None):
        self._strings = strings
        self._blob = blob
        self._offsets = offsets
        self._decoded: dict[int, str] = {}

    @classmethod
    def from_raw(cls, blob: bytes, offsets: np.ndarray) -> "Dictionary":
        return cls(blob=blob, offsets=offsets)

    def __len__(self) -> int:
        if self._strings is not None:
            return len(self._strings)
        return len(self._offsets) - 1

    # ------------------------------------------------------- raw access
    def raw(self) -> tuple[bytes, np.ndarray]:
        """(utf8 blob, u32 offsets[count+1]) -- the union/merge unit."""
        if self._blob is None:
            bs = [s.encode("utf-8") for s in self._strings]
            offs = np.zeros(len(bs) + 1, dtype=np.uint32)
            np.cumsum([len(b) for b in bs], out=offs[1:])
            self._blob, self._offsets = b"".join(bs), offs
        return self._blob, self._offsets

    def _bytes_at(self, i: int) -> bytes:
        return self._blob[int(self._offsets[i]) : int(self._offsets[i + 1])]

    @property
    def strings(self) -> list[str]:
        """Full decoded table (materialized once, then cached)."""
        if self._strings is None:
            blob, offs = self._blob, self._offsets
            text = blob.decode("utf-8", errors="surrogateescape")
            # one whole-blob decode + zero-copy-ish slicing beats half a
            # million per-string decodes; offsets are byte offsets, which
            # equal str offsets only for ascii blobs -- fall back per
            # string when multibyte chars are present
            if len(text) == len(blob):
                o = offs.tolist()
                self._strings = [text[o[i] : o[i + 1]] for i in range(len(o) - 1)]
            else:
                self._strings = [
                    self._bytes_at(i).decode("utf-8") for i in range(len(offs) - 1)
                ]
        return self._strings

    # ----------------------------------------------------------- lookup
    def _bisect_bytes(self, needle: bytes) -> int:
        lo, hi = 0, len(self._offsets) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self._bytes_at(mid) < needle:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def lookup(self, s: str) -> int:
        """Code for s, or -1 if absent (prunes the block)."""
        import bisect

        if self._strings is not None:
            i = bisect.bisect_left(self._strings, s)
            if i < len(self._strings) and self._strings[i] == s:
                return i
            return -1
        needle = s.encode("utf-8")
        i = self._bisect_bytes(needle)
        if i < len(self) and self._bytes_at(i) == needle:
            return i
        return -1

    def prefix_range(self, prefix: str) -> tuple[int, int]:
        """[lo, hi) code range of strings with the given prefix. Both
        representations compute the EXACT bound (first index whose
        string does not start with prefix), so the answer cannot depend
        on whether .strings happens to be materialized."""
        import bisect

        if self._strings is not None:
            lo = bisect.bisect_left(self._strings, prefix)
            up = _incr_str(prefix)
            hi = (bisect.bisect_left(self._strings, up) if up is not None
                  else len(self._strings))
            return lo, hi
        p = prefix.encode("utf-8")
        lo = self._bisect_bytes(p)
        up = _incr_bytes(p)
        hi = self._bisect_bytes(up) if up is not None else len(self)
        return lo, hi

    def string(self, code: int) -> str:
        code = int(code)
        if not 0 <= code < len(self):
            return ""
        if self._strings is not None:
            return self._strings[code]
        s = self._decoded.get(code)
        if s is None:
            s = self._decoded[code] = self._bytes_at(code).decode("utf-8")
        return s

    # -------------------------------------------------------------- io
    def to_bytes(self) -> bytes:
        blob, offs = self.raw()
        payload = (
            struct.pack("<I", len(offs) - 1)
            + np.ascontiguousarray(offs, dtype=np.uint32).tobytes()
            + blob
        )
        return _MAGIC + zstandard.ZstdCompressor(level=3).compress(payload)

    @classmethod
    def from_bytes(cls, data: bytes) -> "Dictionary":
        if data[:4] == _MAGIC:
            raw = zstandard.ZstdDecompressor().decompress(data[4:])
            (count,) = struct.unpack_from("<I", raw, 0)
            offs = np.frombuffer(raw, dtype=np.uint32, count=count + 1, offset=4)
            blob = raw[4 + (count + 1) * 4 :]
            return cls.from_raw(blob, offs)
        # legacy uvarint stream
        raw = zstandard.ZstdDecompressor().decompress(data)
        count, pos = w.read_varint(raw, 0)
        strings = []
        for _ in range(count):
            ln, pos = w.read_varint(raw, pos)
            strings.append(raw[pos : pos + ln].decode("utf-8"))
            pos += ln
        return cls(strings)
