"""vtpu block schema: the column set and its device/host split.

Design (TPU-first rethink of vparquet's one-row-per-trace nested schema,
tempodb/encoding/vparquet/schema.go:75-172):

* span-major structure-of-arrays: every span is a row across flat,
  fixed-dtype columns; traces are contiguous runs bounded by
  `trace.span_off` (a segment-offsets array). Dremel rep/def levels are
  never needed -- hierarchy is explicit segment ids, so trace-level
  aggregation is a segmented reduce and "structural" joins are masks.
* all strings are int32 codes into one sorted per-block dictionary
  (dictionary.py); string predicates become integer compares on device.
* every DEVICE column is int32/float32 and uploads with zero
  transposition. Quantities that don't fit (u64 nanos, 128-bit ids,
  byte blobs) keep an exact HOST column for verification +
  materialization, and a *conservative* int32 device encoding for
  filtering: device filters may over-match (like a bloom), never
  under-match; the host re-checks survivors exactly.

Time encoding: span start is milliseconds relative to the block's start
(int32: +-24 days), duration is microseconds clamped to int32
(~35 min); the planner rounds thresholds outward so clamping stays
conservative.

Attribute tables are CSR-style: one row per attribute with an owner-row
column (`sattr.span`, `rattr.res`, ...), so device predicate hits
scatter back to spans with one segment-max.
"""

from __future__ import annotations

import numpy as np

INT32_MAX = np.int32(2**31 - 1)

# attribute value types
VT_STR = 0
VT_INT = 1
VT_FLOAT = 2
VT_BOOL = 3
VT_COMPLEX = 4  # arrays/bytes/kvlists, stored as OTLP-JSON in the dict

# axes (row-group chunking dimensions in the column pack)
AX_SPAN = "span"
AX_TRACE = "trace"
AX_SATTR = "sattr"
AX_RATTR = "rattr"
AX_RES = "res"
AX_EVENT = "ev"
AX_EVATTR = "evattr"
AX_LINK = "ln"
AX_LNATTR = "lnattr"

# columns shipped to the device for filtering (all int32/float32)
DEVICE_SPAN_COLS = [
    "span.trace_sid",
    "span.name_id",
    "span.service_id",
    "span.kind",
    "span.status",
    "span.start_ms",
    "span.dur_us",
    "span.http_status",
    "span.http_method_id",
    "span.http_url_id",
    "span.res_idx",
    "span.parent_idx",  # parent's block row (-1 root): structural ops
]
DEVICE_SATTR_COLS = [
    "sattr.span",
    "sattr.key_id",
    "sattr.vtype",
    "sattr.str_id",
    "sattr.int32",
    "sattr.f32",
]
DEVICE_RATTR_COLS = [
    "rattr.res",
    "rattr.key_id",
    "rattr.vtype",
    "rattr.str_id",
    "rattr.int32",
    "rattr.f32",
]

# host-exact span columns (materialization + exact verify)
HOST_SPAN_COLS = [
    "span.start_ns",
    "span.end_ns",
    "span.id",
    "span.parent_id",
    "span.trace_state_id",
    "span.status_msg_id",
    "span.dropped_attrs",
    "span.scope_idx",
]

TRACE_COLS = [
    "trace.id",  # (n,16) u8, sorted
    "trace.id_codes",  # (n,4) i32 order-preserving codes
    "trace.span_off",  # (n+1,) i32 segment offsets into span rows
    "trace.start_ms",
    "trace.end_ms",
    "trace.dur_us",
    "trace.root_service_id",
    "trace.root_name_id",
    "trace.start_ns",  # u64 exact
    "trace.end_ns",
]

WELL_KNOWN_SPAN_ATTRS = {
    # attr key -> dedicated device column (vparquet's dedicated-column idea)
    "http.status_code": "span.http_status",
    "http.method": "span.http_method_id",
    "http.url": "span.http_url_id",
}
WELL_KNOWN_RES_ATTRS = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
    "cluster": "res.cluster_id2",
    "namespace": "res.namespace_id2",
    "pod": "res.pod_id2",
    "container": "res.container_id2",
}

DEFAULT_ROW_GROUP_SPANS = 1 << 16  # 64Ki span rows per group


def trace_id_to_codes(tid: bytes) -> tuple[int, int, int, int]:
    """16-byte id -> 4 order-preserving int32 codes: big-endian u32 words
    XOR 0x80000000, so signed int32 comparison == unsigned byte order."""
    t = tid.rjust(16, b"\x00")
    return tuple(
        int.from_bytes(t[i : i + 4], "big") - 0x80000000 for i in (0, 4, 8, 12)
    )


def codes_to_trace_id(codes) -> bytes:
    return b"".join(int(int(c) + 0x80000000).to_bytes(4, "big") for c in codes)


def codes_to_id_bytes(codes: np.ndarray) -> np.ndarray:
    """Vectorized codes_to_trace_id: (Q,4) int32 lanes -> (Q,16) u8."""
    u = (codes.astype(np.int64) + 0x80000000).astype(np.uint32)
    return np.ascontiguousarray(u).astype(">u4").view(np.uint8).reshape(-1, 16)


def ns_to_rel_ms(ns: int, base_ns: int) -> int:
    """Conservative int32 millisecond offset (floor), clamped."""
    v = (int(ns) - int(base_ns)) // 1_000_000
    return int(np.clip(v, -(2**31), 2**31 - 1))


def ns_to_dur_us(dur_ns: int) -> int:
    return int(min(max(0, int(dur_ns)) // 1_000, 2**31 - 1))
