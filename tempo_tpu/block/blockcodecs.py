"""Snappy + lz4 block codecs: native-first, pure-Python fallback.

The codec matrix's speed tier (reference: tempodb/backend/encoding.go
ships both next to zstd/gzip). The native layer (native/vtpu_native.cc)
carries real hash-matching compressors and full-format decompressors
with threaded batch entry points; this module provides the pure-Python
halves so blocks written with either codec stay readable (and writable)
on images without the shared library:

  * decompressors implement the COMPLETE public formats (snappy raw
    block framing; lz4 block format) -- any conformant producer's chunks
    decode here, including the native compressor's hash-matched output.
  * compressors emit format-valid output built from vectorized
    byte-run detection: long runs become offset-1 copies (the RLE
    subset of each format), everything else is literals. Column chunks
    are dominated by constant/sparse lanes, so the runs carry most of
    the win at numpy speed; entropy-heavy chunks come out as literals
    and the pack layer's "store raw when not smaller" rule keeps them
    honest.

Framing note: both are BLOCK formats (no container framing); the chunk
table's raw_len provides the decompressed size out of band, exactly as
it does for zstd chunks.
"""

from __future__ import annotations

import numpy as np

_RUN_MIN = 32  # shorter equal-byte runs stay literal (copy op overhead)


def _byte_runs(data: bytes) -> tuple[np.ndarray, np.ndarray]:
    """(starts, ends) of maximal equal-byte runs >= _RUN_MIN, vectorized
    (the Python fallback compressors' only scan)."""
    a = np.frombuffer(data, np.uint8)
    if a.size < _RUN_MIN:
        z = np.empty(0, np.int64)
        return z, z
    change = np.nonzero(np.diff(a))[0] + 1
    starts = np.concatenate(([0], change))
    ends = np.concatenate((change, [a.size]))
    keep = (ends - starts) >= _RUN_MIN
    return starts[keep], ends[keep]


# ------------------------------------------------------------------ snappy


def _sn_emit_literal(out: bytearray, data: bytes, lo: int, hi: int) -> None:
    while lo < hi:
        l = min(hi - lo, 65536)
        n1 = l - 1
        if n1 < 60:
            out.append(n1 << 2)
        elif n1 < 256:
            out += bytes((60 << 2, n1))
        else:
            out += bytes((61 << 2, n1 & 0xFF, n1 >> 8))
        out += data[lo : lo + l]
        lo += l


def _sn_emit_copy1(out: bytearray, length: int) -> None:
    """Offset-1 copies (the RLE op) in <=64-byte elements (type 10)."""
    while length:
        l = min(length, 64)
        out += bytes((((l - 1) << 2) | 2, 1, 0))
        length -= l


def snappy_compress(data: bytes) -> bytes:
    from ..native import block_compress_chunks

    outs = block_compress_chunks("snappy", [data])
    if outs is not None:
        return outs[0]
    n = len(data)
    out = bytearray()
    v = n
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    starts, ends = _byte_runs(data)
    pos = 0
    for s, e in zip(starts.tolist(), ends.tolist()):
        # literal through the run's FIRST byte: the copy needs a source
        _sn_emit_literal(out, data, pos, s + 1)
        _sn_emit_copy1(out, e - s - 1)
        pos = e
    _sn_emit_literal(out, data, pos, n)
    return bytes(out)


def snappy_decompress(data: bytes, raw_len: int) -> bytes:
    from ..native import block_decompress_chunks

    outs = block_decompress_chunks("snappy", [data], [raw_len])
    if outs is not None:
        return outs[0]
    n = len(data)
    pos = 0
    length = 0
    shift = 0
    while True:
        if pos >= n or shift > 35:
            raise ValueError("snappy: bad preamble")
        b = data[pos]
        pos += 1
        length |= (b & 0x7F) << shift
        if not (b & 0x80):
            break
        shift += 7
    if length != raw_len:
        raise ValueError("snappy: length mismatch")
    dst = bytearray(raw_len)
    d = 0
    while pos < n:
        tag = data[pos]
        pos += 1
        typ = tag & 3
        if typ == 0:
            l = (tag >> 2) + 1
            if l > 60:
                extra = l - 60  # 1..4 little-endian length bytes
                if pos + extra > n:
                    raise ValueError("snappy: truncated literal length")
                l = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + l > n or d + l > raw_len:
                raise ValueError("snappy: literal overrun")
            dst[d : d + l] = data[pos : pos + l]
            pos += l
            d += l
            continue
        if typ == 1:
            if pos >= n:
                raise ValueError("snappy: truncated copy")
            l = ((tag >> 2) & 7) + 4
            off = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif typ == 2:
            if pos + 2 > n:
                raise ValueError("snappy: truncated copy")
            l = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 2], "little")
            pos += 2
        else:
            if pos + 4 > n:
                raise ValueError("snappy: truncated copy")
            l = (tag >> 2) + 1
            off = int.from_bytes(data[pos : pos + 4], "little")
            pos += 4
        if off == 0 or off > d or d + l > raw_len:
            raise ValueError("snappy: bad copy")
        if off >= l:
            dst[d : d + l] = dst[d - off : d - off + l]
        else:  # overlapped copy repeats the trailing pattern
            for k in range(l):
                dst[d + k] = dst[d - off + k]
        d += l
    if d != raw_len:
        raise ValueError("snappy: short output")
    return bytes(dst)


# --------------------------------------------------------------------- lz4


def _lz4_seq(out: bytearray, data: bytes, lo: int, hi: int,
             match_len: int | None) -> None:
    """One sequence: literals data[lo:hi], then (unless final) an
    offset-1 match of match_len (>= 4)."""
    ll = hi - lo
    tok_idx = len(out)
    out.append(0)
    if ll >= 15:
        out[tok_idx] = 0xF0
        r = ll - 15
        while r >= 255:
            out.append(255)
            r -= 255
        out.append(r)
    else:
        out[tok_idx] = ll << 4
    out += data[lo:hi]
    if match_len is None:
        return
    out += b"\x01\x00"  # offset 1
    ml = match_len - 4
    if ml >= 15:
        out[tok_idx] |= 0x0F
        r = ml - 15
        while r >= 255:
            out.append(255)
            r -= 255
        out.append(r)
    else:
        out[tok_idx] |= ml


def lz4_compress(data: bytes) -> bytes:
    from ..native import block_compress_chunks

    outs = block_compress_chunks("lz4", [data])
    if outs is not None:
        return outs[0]
    n = len(data)
    out = bytearray()
    pos = 0
    if n > 16:
        starts, ends = _byte_runs(data)
        for s, e in zip(starts.tolist(), ends.tolist()):
            # end-of-block rules: the match starts at s+1 (offset-1 RLE),
            # must start >= 12 bytes before the end and never cover the
            # last 5 bytes
            if s + 1 > n - 12:
                break
            mlen = min(e, n - 5) - (s + 1)
            if mlen < 4:
                continue
            _lz4_seq(out, data, pos, s + 1, mlen)
            pos = s + 1 + mlen
    _lz4_seq(out, data, pos, n, None)  # final literals-only sequence
    return bytes(out)


def lz4_decompress(data: bytes, raw_len: int) -> bytes:
    from ..native import block_decompress_chunks

    outs = block_decompress_chunks("lz4", [data], [raw_len])
    if outs is not None:
        return outs[0]
    n = len(data)
    if n == 0:
        if raw_len:
            raise ValueError("lz4: empty input")
        return b""
    dst = bytearray(raw_len)
    pos = 0
    d = 0
    while pos < n:
        tok = data[pos]
        pos += 1
        ll = tok >> 4
        if ll == 15:
            while True:
                if pos >= n:
                    raise ValueError("lz4: truncated literal length")
                b = data[pos]
                pos += 1
                ll += b
                if b != 255:
                    break
        if pos + ll > n or d + ll > raw_len:
            raise ValueError("lz4: literal overrun")
        dst[d : d + ll] = data[pos : pos + ll]
        pos += ll
        d += ll
        if pos == n:
            break  # final literals-only sequence
        if pos + 2 > n:
            raise ValueError("lz4: truncated offset")
        off = int.from_bytes(data[pos : pos + 2], "little")
        pos += 2
        ml = tok & 15
        if ml == 15:
            while True:
                if pos >= n:
                    raise ValueError("lz4: truncated match length")
                b = data[pos]
                pos += 1
                ml += b
                if b != 255:
                    break
        ml += 4
        if off == 0 or off > d or d + ml > raw_len:
            raise ValueError("lz4: bad match")
        if off >= ml:
            dst[d : d + ml] = dst[d - off : d - off + ml]
        else:
            for k in range(ml):
                dst[d + k] = dst[d - off + k]
        d += ml
    if d != raw_len:
        raise ValueError("lz4: short output")
    return bytes(dst)
