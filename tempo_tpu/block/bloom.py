"""Sharded bloom filter over trace IDs.

Same role as the reference's ShardedBloomFilter (common/bloom.go:20-93):
the find-by-ID fast path tests ONE shard (selected by a hash of the
trace id) so a lookup fetches bloom_shard_size bytes, not the whole
filter. Bits live in a flat uint32 array -> the filter is directly a
device array; membership test is a gather+AND kernel and compaction's
filter union is a single elementwise OR (ops/bloom_ops.py), the
"pmap'd sketch union" of the north star (BASELINE.json).

Shard count is derived from the expected item count and target false
positive rate, like the reference sizes shards from fp+shard size.
"""

from __future__ import annotations

import math

import numpy as np

from ..util.hashing import bloom_hashes, fnv1a_32

WORD_BITS = 32
DEFAULT_FP_RATE = 0.01
SHARD_SIZE_BYTES = 100 * 1024  # reference default bloom shard size ~100KiB
_K = 7  # hash count; ~optimal for 10 bits/item


def shard_for_trace_id(trace_id: bytes, n_shards: int) -> int:
    return fnv1a_32(trace_id) % n_shards


def shard_count(expected_items: int, fp_rate: float = DEFAULT_FP_RATE) -> int:
    """Shards so that each holds <= SHARD_SIZE_BYTES of bits at ~10 bits/item."""
    if expected_items <= 0:
        return 1
    bits_per_item = max(1.0, -math.log(max(fp_rate, 1e-9)) / (math.log(2) ** 2))
    total_bits = expected_items * bits_per_item
    return max(1, math.ceil(total_bits / (SHARD_SIZE_BYTES * 8)))


class ShardedBloom:
    def __init__(self, n_shards: int, shard_bits: int = SHARD_SIZE_BYTES * 8):
        # power-of-two bits per shard keeps device-side modulo a mask
        self.shard_bits = 1 << (shard_bits - 1).bit_length()
        self.n_shards = n_shards
        self.words = np.zeros((n_shards, self.shard_bits // WORD_BITS), dtype=np.uint32)

    @classmethod
    def for_estimated_items(cls, n: int, fp_rate: float = DEFAULT_FP_RATE) -> "ShardedBloom":
        shards = shard_count(n, fp_rate)
        per_shard = max(1, n // shards)
        bits_per_item = max(1.0, -math.log(max(fp_rate, 1e-9)) / (math.log(2) ** 2))
        bits = max(1024, int(per_shard * bits_per_item))
        return cls(shards, bits)

    def add(self, trace_id: bytes) -> None:
        shard = shard_for_trace_id(trace_id, self.n_shards)
        for pos in bloom_hashes(trace_id, _K, self.shard_bits):
            self.words[shard, pos // WORD_BITS] |= np.uint32(1 << (pos % WORD_BITS))

    def add_many(self, trace_ids: list[bytes]) -> None:
        # native batch insert (native/vtpu_native.cc) when every id is the
        # canonical 16 bytes; bit-identical to the Python loop
        if trace_ids and all(len(t) == 16 for t in trace_ids):
            from ..native import bloom_add_batch

            if bloom_add_batch(self, trace_ids, _K):
                return
        for tid in trace_ids:
            self.add(tid)

    def add_array(self, ids: np.ndarray) -> None:
        """Insert a (n, 16) uint8 id array without materializing per-id
        bytes objects (the per-row .tobytes() loop costs more than the
        insertion itself at compaction scale)."""
        from ..native import bloom_add_ids_array

        ids = np.ascontiguousarray(ids, dtype=np.uint8)
        if ids.size and not bloom_add_ids_array(self, ids, _K):
            self.add_many([ids[i].tobytes() for i in range(ids.shape[0])])

    def test(self, trace_id: bytes) -> bool:
        shard = shard_for_trace_id(trace_id, self.n_shards)
        return self.test_shard(self.words[shard], trace_id)

    def test_shard(self, shard_words: np.ndarray, trace_id: bytes) -> bool:
        for pos in bloom_hashes(trace_id, _K, self.shard_bits):
            if not (int(shard_words[pos // WORD_BITS]) >> (pos % WORD_BITS)) & 1:
                return False
        return True

    # ---- serialization: one object per shard, like the reference's
    # bloom-0..bloom-N block objects
    def shard_bytes(self, shard: int) -> bytes:
        return self.words[shard].tobytes()

    @classmethod
    def shard_from_bytes(cls, data: bytes) -> np.ndarray:
        return np.frombuffer(data, dtype=np.uint32)

    @staticmethod
    def positions(trace_id: bytes, shard_bits: int) -> list[int]:
        return bloom_hashes(trace_id, _K, shard_bits)


def union_shards(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Host-side union; the compaction hot path uses ops.bloom_ops.union
    on device instead."""
    return np.bitwise_or(a, b)
