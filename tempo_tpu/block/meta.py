"""Block metadata: the poller/blocklist currency.

Role of the reference's backend.BlockMeta (tempodb/backend), extended
with vtpu row-group stats so the query planner can prune row groups
host-side (the control-plane half of predicate pushdown) before any
bytes ship to the device.
"""

from __future__ import annotations

import json
import uuid
from dataclasses import asdict, dataclass, field


@dataclass
class RowGroupStats:
    span_lo: int = 0
    span_hi: int = 0
    trace_lo: int = 0
    trace_hi: int = 0  # exclusive; last group may share a trace boundary exactly
    start_ms_min: int = 0
    start_ms_max: int = 0
    dur_us_max: int = 0


@dataclass
class BlockMeta:
    version: str = "vtpu1"
    block_id: str = ""
    tenant_id: str = ""
    min_id: str = ""  # hex trace ids
    max_id: str = ""
    start_time_unix_nano: int = 0  # block time range
    end_time_unix_nano: int = 0
    total_traces: int = 0
    total_spans: int = 0
    size_bytes: int = 0
    compaction_level: int = 0
    bloom_shards: int = 0
    bloom_shard_bits: int = 0
    dict_size: int = 0
    row_groups: list[RowGroupStats] = field(default_factory=list)
    # replication/dedupe bookkeeping used by the ingester
    replication_factor: int = 1
    # stamped into meta.compacted.json at MARK time (reference:
    # backend.CompactedBlockMeta.CompactedTime); compacted-retention runs
    # off this, never off the data's own time window
    compacted_at_unix: float = 0.0

    @staticmethod
    def new(tenant: str, block_id: str | None = None) -> "BlockMeta":
        return BlockMeta(block_id=block_id or str(uuid.uuid4()), tenant_id=tenant)

    def to_json(self) -> bytes:
        d = asdict(self)
        return json.dumps(d, separators=(",", ":")).encode("utf-8")

    @classmethod
    def from_json(cls, data: bytes) -> "BlockMeta":
        d = json.loads(data)
        rgs = [RowGroupStats(**rg) for rg in d.pop("row_groups", [])]
        known = {f for f in cls.__dataclass_fields__}  # tolerate future fields
        m = cls(**{k: v for k, v in d.items() if k in known and k != "row_groups"})
        m.row_groups = rgs
        return m

    # ---- id-range pruning (reference: includeBlock, tempodb/tempodb.go:483-502)
    def may_contain_id(self, trace_id_hex: str) -> bool:
        if not self.min_id or not self.max_id:
            return False
        return self.min_id <= trace_id_hex <= self.max_id

    def overlaps_time(self, start_unix: int, end_unix: int) -> bool:
        """[start,end] in unix seconds vs the block's nano range."""
        if end_unix <= 0:
            return True
        return not (
            self.end_time_unix_nano < start_unix * 1_000_000_000
            or self.start_time_unix_nano > end_unix * 1_000_000_000
        )
