"""Versioned block-encoding registry: the format-evolution seam.

Reference: tempodb/encoding/versioned.go:17-46 -- every complete block
carries its encoding version in meta.json; readers dispatch through a
registry (FromVersion/OpenBlock) so new formats can ship while old
blocks stay readable, and an unknown version fails loudly instead of
misparsing bytes.

Two real versions coexist: `vtpu1` (JSON pack footer) and the current
`vtpu2` (binary lazy-decode footer; colio._BF_MARKER). The column/chunk
layout is shared, so one reader class serves both -- but the VERSION
field is the compatibility contract: a vtpu1-only reader must reject a
vtpu2 block through UnknownVersion, never hit the NUL-prefixed footer
and die in a JSON parser. Compaction OUTPUT always writes the latest
version, which is how old formats age out of a backend, same as the
reference's compactors; `tempo-cli convert-block` rewrites one block
across versions (reference: cmd/tempo-cli/cmd-convert-block.go).
"""

from __future__ import annotations

from ..backend.base import RawBackend
from .meta import BlockMeta

CURRENT_VERSION = "vtpu2"


class UnknownVersion(Exception):
    def __init__(self, version: str):
        super().__init__(
            f"unknown block encoding version {version!r} "
            f"(supported: {sorted(_ENCODINGS)}); refusing to misparse"
        )
        self.version = version


_ENCODINGS: dict[str, object] = {}


def register_encoding(version: str, opener) -> None:
    """opener(backend, meta) -> block reader object."""
    _ENCODINGS[version] = opener


def open_block_versioned(backend: RawBackend, meta: BlockMeta):
    """The FromVersion dispatch: meta.version selects the reader."""
    opener = _ENCODINGS.get(meta.version or CURRENT_VERSION)
    if opener is None:
        raise UnknownVersion(meta.version)
    return opener(backend, meta)


def supported_versions() -> list[str]:
    return sorted(_ENCODINGS)


def _open_vtpu1(backend: RawBackend, meta: BlockMeta):
    from .reader import BackendBlock

    return BackendBlock(backend, meta)


def _open_vtpu2(backend: RawBackend, meta: BlockMeta):
    # same reader: ColumnPack dispatches on the footer marker; the
    # version field exists so DOWN-LEVEL readers reject these blocks
    # loudly instead of misparsing the binary footer
    from .reader import BackendBlock

    return BackendBlock(backend, meta)


register_encoding("vtpu1", _open_vtpu1)
register_encoding("vtpu2", _open_vtpu2)
