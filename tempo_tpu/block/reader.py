"""BackendBlock: the read side of a vtpu block.

Find-by-ID pipeline (analog of vparquet/block_findtracebyid.go:56-203):
bloom shard test -> binary search sorted trace.id -> span range from
trace.span_off -> range-read ONLY the row-group chunks covering that
span range -> materialize the trace back to the wire model. All host
control-plane; the batched/device lookup path lives in ops/find.py and
the search path in db/search.py.

All child tables (attrs, events, links and their attrs) have sorted
owner columns, so per-span slices are searchsorted ranges, not scans.
"""

from __future__ import annotations

import bisect
from functools import cached_property

import numpy as np

from ..backend.base import RawBackend
from ..wire.model import Event, Link, Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace
from . import schema as S
from .bloom import ShardedBloom, shard_for_trace_id
from .builder import BLOOM_PREFIX, DATA_NAME, DICT_NAME, decode_attr_value
from .colio import ColumnPack
from .dictionary import Dictionary
from .meta import BlockMeta

_MAT_SPAN_COLS = [
    "span.trace_sid",
    "span.name_id",
    "span.kind",
    "span.status",
    "span.start_ns",
    "span.end_ns",
    "span.id",
    "span.parent_id",
    "span.trace_state_id",
    "span.status_msg_id",
    "span.dropped_attrs",
    "span.res_idx",
    "span.scope_idx",
]

_ATTR_FIELDS = ("key_id", "vtype", "str_id", "int32", "int64", "f64")


class _ChildRows:
    """Rows of a child table belonging to a contiguous global owner range,
    loaded from the row-group chunks covering it."""

    def __init__(self, pack: ColumnPack, prefix: str, owner_col: str, axis: str,
                 groups: list[int], fields: tuple[str, ...]):
        ax = pack.axes[axis]
        self.global_base = ax.offsets[groups[0]] if ax.n_rows else 0
        names = [f"{prefix}.{owner_col}"] + [f"{prefix}.{f}" for f in fields]
        if ax.n_rows == 0:
            self.owner = np.empty(0, dtype=np.int32)
            self.cols = {n: np.empty(0) for n in names}
        else:
            self.cols = {n: pack.read_groups(n, groups) for n in names}
            self.owner = self.cols[f"{prefix}.{owner_col}"]
        self.prefix = prefix

    def ranges(self, owner_rows: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized per-owner [lo, hi) ranges: two batched searchsorted
        calls for ALL owner rows instead of two scalar calls per row."""
        lo = np.searchsorted(self.owner, owner_rows, side="left")
        hi = np.searchsorted(self.owner, owner_rows, side="right")
        return lo, hi

    def field(self, name: str, j: int):
        return self.cols[f"{self.prefix}.{name}"][j]

    def global_row(self, j: int) -> int:
        return self.global_base + j


def _attrs_from(child: _ChildRows, jlo: int, jhi: int, d: Dictionary) -> dict:
    out = {}
    for j in range(jlo, jhi):
        out[d.string(int(child.field("key_id", j)))] = decode_attr_value(
            int(child.field("vtype", j)),
            int(child.field("str_id", j)),
            int(child.field("int32", j)),
            int(child.field("int64", j)),
            float(child.field("f64", j)),
            d,
        )
    return out


class BackendBlock:
    def __init__(self, backend: RawBackend, meta: BlockMeta):
        self.backend = backend
        self.meta = meta
        self._data_size = meta.size_bytes
        self._pack: ColumnPack | None = None
        self.bytes_read = 0

    # ------------------------------------------------------------- IO
    @property
    def pack(self) -> ColumnPack:
        if self._pack is None:
            t, b = self.meta.tenant_id, self.meta.block_id
            size = self._data_size
            if not size:
                size = len(self.backend.read(t, b, DATA_NAME))  # fallback: full read
            self._pack = ColumnPack(
                lambda off, ln: self.backend.read_range(t, b, DATA_NAME, off, ln), size
            )
        return self._pack

    @cached_property
    def dictionary(self) -> Dictionary:
        return Dictionary.from_bytes(
            self.backend.read(self.meta.tenant_id, self.meta.block_id, DICT_NAME)
        )

    def bloom_shard(self, shard: int) -> np.ndarray:
        cache = getattr(self, "_bloom_cache", None)
        if cache is None:
            cache = self._bloom_cache = {}
        hit = cache.get(shard)
        if hit is not None:
            return hit
        data = self.backend.read(self.meta.tenant_id, self.meta.block_id, f"{BLOOM_PREFIX}{shard}")
        self.bytes_read += len(data)
        words = ShardedBloom.shard_from_bytes(data)
        cache[shard] = words  # blocks are immutable; shards are ~100 KiB
        return words

    @cached_property
    def trace_index(self) -> dict[str, np.ndarray]:
        """Trace-level columns (small; cached for the block's lifetime)."""
        return self.pack.read_many(
            [
                "trace.id",
                "trace.id_codes",
                "trace.span_off",
                "trace.start_ns",
                "trace.end_ns",
                "trace.root_service_id",
                "trace.root_name_id",
                "trace.dur_us",
            ]
        )

    # the contract with db/search._candidates/_materialize: every
    # trace-axis column they index. Extend HERE when they read more.
    SEARCH_TRACE_COLS = (
        "trace.id",
        "trace.start_ns",
        "trace.end_ns",
        "trace.root_service_id",
        "trace.root_name_id",
    )

    @cached_property
    def search_index(self) -> dict[str, np.ndarray]:
        """The trace_index subset search-result building touches
        (SEARCH_TRACE_COLS). Cold one-shot readers decode ~45% fewer
        trace-axis bytes than the full index (id_codes/span_off/dur_us
        are find-path columns)."""
        return self.pack.read_many(list(self.SEARCH_TRACE_COLS))

    # ------------------------------------------------------ find by id
    def bloom_test(self, trace_id: bytes) -> bool:
        if not self.meta.bloom_shards:
            return True
        shard = shard_for_trace_id(trace_id, self.meta.bloom_shards)
        words = self.bloom_shard(shard)
        for pos in ShardedBloom.positions(trace_id, self.meta.bloom_shard_bits):
            if not (int(words[pos // 32]) >> (pos % 32)) & 1:
                return False
        return True

    def find_trace_sid(self, trace_id: bytes) -> int:
        """Binary search the sorted trace-id index; -1 if absent.
        Shares the cached void16 view with the batched host engine
        (ops/find.lookup_ids_blocks_host)."""
        from ..ops.find import _ids_void

        iv = _ids_void(self)
        n = iv.shape[0]
        padded = trace_id.rjust(16, b"\x00")
        if n == 0 or len(padded) != 16:  # oversize ids can match nothing
            return -1
        tid = np.frombuffer(padded, dtype=np.uint8).view("V16")
        pos = int(np.searchsorted(iv, tid[0]))
        if pos < n and iv[pos] == tid[0]:
            return pos
        return -1

    def find_trace_by_id(self, trace_id: bytes) -> Trace | None:
        if not self.meta.may_contain_id(trace_id.rjust(16, b"\x00").hex()):
            return None
        if not self.bloom_test(trace_id):
            return None
        sid = self.find_trace_sid(trace_id)
        if sid < 0:
            return None
        return self.materialize_traces([sid])[0]

    # --------------------------------------------------- materialization
    def _groups_for_span_range(self, lo: int, hi: int) -> list[int]:
        offs = self.pack.axes[S.AX_SPAN].offsets
        g_lo = bisect.bisect_right(offs, lo) - 1
        g_hi = bisect.bisect_left(offs, hi)
        return list(range(max(0, g_lo), max(g_lo + 1, g_hi)))

    @cached_property
    def _res_tables(self):
        d_cols = sorted(set(S.WELL_KNOWN_RES_ATTRS.values()))
        res_ded = {c: self.pack.read(c) for c in d_cols if self.pack.has(c)}
        ded_key = {}
        for key, col in S.WELL_KNOWN_RES_ATTRS.items():
            ded_key.setdefault(col, key)
        rattr = self.pack.read_many(
            ["rattr.res"] + [f"rattr.{f}" for f in _ATTR_FIELDS]
        )
        scope_name = self.pack.read("scope.name_id")
        scope_version = self.pack.read("scope.version_id")
        return res_ded, ded_key, rattr, scope_name, scope_version

    def _resource_attrs(self, res_idx: int, d: Dictionary,
                        rrange: tuple[int, int] | None = None) -> dict:
        res_ded, ded_key, rattr, _, _ = self._res_tables
        attrs: dict = {}
        for col, arr in res_ded.items():
            code = int(arr[res_idx])
            if code >= 0:
                attrs[ded_key[col]] = d.string(code)
        owner = rattr.get("rattr.res")
        if owner is not None and len(owner):
            if rrange is not None:
                lo, hi = rrange
            else:
                lo = int(np.searchsorted(owner, res_idx, side="left"))
                hi = int(np.searchsorted(owner, res_idx, side="right"))
            for j in range(lo, hi):
                attrs[d.string(int(rattr["rattr.key_id"][j]))] = decode_attr_value(
                    int(rattr["rattr.vtype"][j]),
                    int(rattr["rattr.str_id"][j]),
                    int(rattr["rattr.int32"][j]),
                    int(rattr["rattr.int64"][j]),
                    float(rattr["rattr.f64"][j]),
                    d,
                )
        return attrs

    def materialize_traces(self, sids: list[int]) -> list[Trace]:
        """Reconstruct full wire traces for the given trace indexes,
        reading only the row-group chunks that cover their span rows."""
        span_off = self.trace_index["trace.span_off"]
        d = self.dictionary
        _, _, _, scope_name, scope_version = self._res_tables
        # global-attr tables for events/links (owner = global ev/ln row)
        evattr_all = self.pack.read_many(["evattr.ev"] + [f"evattr.{f}" for f in _ATTR_FIELDS])
        lnattr_all = self.pack.read_many(["lnattr.ln"] + [f"lnattr.{f}" for f in _ATTR_FIELDS])

        def global_attrs(table: dict, owner_name: str, global_row: int) -> dict:
            owner = table.get(owner_name)
            out: dict = {}
            if owner is None or not len(owner):
                return out
            lo = int(np.searchsorted(owner, global_row, side="left"))
            hi = int(np.searchsorted(owner, global_row, side="right"))
            pre = owner_name.split(".")[0]
            for j in range(lo, hi):
                out[d.string(int(table[f"{pre}.key_id"][j]))] = decode_attr_value(
                    int(table[f"{pre}.vtype"][j]),
                    int(table[f"{pre}.str_id"][j]),
                    int(table[f"{pre}.int32"][j]),
                    int(table[f"{pre}.int64"][j]),
                    float(table[f"{pre}.f64"][j]),
                    d,
                )
            return out

        out: list[Trace] = []
        for sid in sids:
            lo, hi = int(span_off[sid]), int(span_off[sid + 1])
            groups = self._groups_for_span_range(lo, hi)
            base = self.pack.axes[S.AX_SPAN].offsets[groups[0]]
            sl = slice(lo - base, hi - base)
            # one threaded decode for EVERY chunk this trace touches
            # (span cols + child tables); the reads below then hit the
            # pack's decompressed-chunk cache
            wants = [(c, groups) for c in _MAT_SPAN_COLS]
            for pre, fields in (("sattr", ("span",) + _ATTR_FIELDS),
                                ("ev", ("span", "time_ns", "name_id", "dropped")),
                                ("ln", ("span", "trace_id", "span_id", "state_id"))):
                wants += [(f"{pre}.{f}", groups) for f in fields]
            self.pack.warm(wants)
            sp_cols = {c: self.pack.read_groups(c, groups)[sl] for c in _MAT_SPAN_COLS}

            sat = _ChildRows(self.pack, "sattr", "span", S.AX_SATTR, groups, _ATTR_FIELDS)
            evs = _ChildRows(self.pack, "ev", "span", S.AX_EVENT, groups, ("time_ns", "name_id", "dropped"))
            lns = _ChildRows(self.pack, "ln", "span", S.AX_LINK, groups, ("trace_id", "span_id", "state_id"))

            # batched child-table ranges: one searchsorted pair per table
            # for the whole trace, not per span
            rows = np.arange(lo, hi, dtype=np.int64)
            sat_lo, sat_hi = sat.ranges(rows)
            ev_lo, ev_hi = evs.ranges(rows)
            ln_lo, ln_hi = lns.ranges(rows)
            res_u = np.unique(sp_cols["span.res_idx"])
            rowner = self._res_tables[2].get("rattr.res")
            if rowner is not None and len(rowner):
                r_lo = np.searchsorted(rowner, res_u, side="left")
                r_hi = np.searchsorted(rowner, res_u, side="right")
                res_ranges = {int(r): (int(a), int(b)) for r, a, b in zip(res_u, r_lo, r_hi)}
            else:
                res_ranges = {int(r): (0, 0) for r in res_u}

            tid_bytes = self.trace_index["trace.id"][sid].tobytes()
            t = Trace()
            batches: dict[int, ResourceSpans] = {}
            scopes: dict[tuple[int, int], ScopeSpans] = {}
            for i in range(hi - lo):
                res_idx = int(sp_cols["span.res_idx"][i])
                scope_idx = int(sp_cols["span.scope_idx"][i])
                rs = batches.get(res_idx)
                if rs is None:
                    rs = ResourceSpans(resource=Resource(attrs=self._resource_attrs(
                        res_idx, d, res_ranges.get(res_idx))))
                    batches[res_idx] = rs
                    t.resource_spans.append(rs)
                skey = (res_idx, scope_idx)
                ss = scopes.get(skey)
                if ss is None:
                    ss = ScopeSpans(
                        scope=Scope(
                            name=d.string(int(scope_name[scope_idx])),
                            version=d.string(int(scope_version[scope_idx])),
                        )
                    )
                    scopes[skey] = ss
                    rs.scope_spans.append(ss)

                parent = sp_cols["span.parent_id"][i].tobytes()
                sp = Span(
                    trace_id=tid_bytes,
                    span_id=sp_cols["span.id"][i].tobytes(),
                    parent_span_id=b"" if parent == b"\x00" * 8 else parent,
                    trace_state=d.string(int(sp_cols["span.trace_state_id"][i])),
                    name=d.string(int(sp_cols["span.name_id"][i])),
                    kind=int(sp_cols["span.kind"][i]),
                    start_unix_nano=int(sp_cols["span.start_ns"][i]),
                    end_unix_nano=int(sp_cols["span.end_ns"][i]),
                    status_code=int(sp_cols["span.status"][i]),
                    status_message=d.string(int(sp_cols["span.status_msg_id"][i])),
                    dropped_attributes_count=int(sp_cols["span.dropped_attrs"][i]),
                    attrs=_attrs_from(sat, int(sat_lo[i]), int(sat_hi[i]), d),
                )
                for j in range(int(ev_lo[i]), int(ev_hi[i])):
                    e = Event(
                        time_unix_nano=int(evs.field("time_ns", j)),
                        name=d.string(int(evs.field("name_id", j))),
                        dropped_attributes_count=int(evs.field("dropped", j)),
                        attrs=global_attrs(evattr_all, "evattr.ev", evs.global_row(j)),
                    )
                    sp.events.append(e)
                for j in range(int(ln_lo[i]), int(ln_hi[i])):
                    link = Link(
                        trace_id=lns.field("trace_id", j).tobytes(),
                        span_id=lns.field("span_id", j).tobytes(),
                        trace_state=d.string(int(lns.field("state_id", j))),
                        attrs=global_attrs(lnattr_all, "lnattr.ln", lns.global_row(j)),
                    )
                    sp.links.append(link)
                ss.spans.append(sp)
            out.append(t)
        self.bytes_read = self.pack.bytes_read
        return out


def open_block(backend: RawBackend, tenant: str, block_id: str) -> BackendBlock:
    meta = BlockMeta.from_json(backend.read(tenant, block_id, "meta.json"))
    from .versioned import open_block_versioned

    return open_block_versioned(backend, meta)
