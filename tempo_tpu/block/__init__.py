from .meta import BlockMeta, RowGroupStats
from .builder import BlockBuilder, FinalizedBlock, build_block_from_traces, write_block
from .reader import BackendBlock, open_block
from .bloom import ShardedBloom
from .dictionary import Dictionary

from .versioned import CURRENT_VERSION as VERSION
