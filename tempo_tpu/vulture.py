"""tempo-vulture equivalent: black-box write/read consistency checker.

The reference's vulture (cmd/tempo-vulture) runs beside a cluster,
pushes known traces, reads them back by id and via search, and emits
404 / missing-span metrics that alerting watches (SURVEY.md 2.1, 4.7).

Run: python -m tempo_tpu.vulture --push-url http://host:3200 \
        --query-url http://host:3200 --cycles 10 --interval 5

Alert thresholds (what the reference's vulture dashboards page on):
  - notfound_byid > 0 over 10m     -> CRITICAL: written traces are not
    readable by id (ingest loss or find-path regression).
  - missing_spans > 0 over 10m     -> CRITICAL: partial traces returned
    (combiner/replication bug, not just a slow leg).
  - notfound_search / requests > 0.01 over 30m -> WARNING: fresh traces
    absent from search results (blocklist poll lag or search-path bug;
    tolerate brief ingest->searchable delay).
  - error rate (HTTP failures / requests) > 0.05 over 5m -> WARNING:
    availability, usually ring/frontend health rather than data loss.
"""

from __future__ import annotations

import argparse
import json
import random
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

from .util.testdata import make_trace, make_trace_id
from .wire import otlp_json


@dataclass
class VultureMetrics:
    requests: int = 0
    notfound_byid: int = 0
    missing_spans: int = 0
    notfound_search: int = 0
    errors: int = 0

    def lines(self) -> list[str]:
        return [
            f"tempo_vulture_trace_total {self.requests}",
            f"tempo_vulture_notfound_byid_total {self.notfound_byid}",
            f"tempo_vulture_missing_spans_total {self.missing_spans}",
            f"tempo_vulture_notfound_search_total {self.notfound_search}",
            f"tempo_vulture_error_total {self.errors}",
        ]


class Vulture:
    def __init__(self, push_url: str, query_url: str, tenant_header: str | None = None,
                 read_back_delay_s: float = 1.0, seed: int | None = None):
        self.push_url = push_url.rstrip("/")
        self.query_url = query_url.rstrip("/")
        self.tenant_header = tenant_header
        self.read_back_delay_s = read_back_delay_s
        self.rng = random.Random(seed)
        self.metrics = VultureMetrics()

    def _headers(self):
        h = {"Content-Type": "application/json"}
        if self.tenant_header:
            h["X-Scope-OrgID"] = self.tenant_header
        return h

    def cycle(self) -> bool:
        """One write->read->search round. True if fully consistent."""
        self.metrics.requests += 1
        tid = make_trace_id(self.rng)
        tr = make_trace(self.rng, trace_id=tid, n_spans=4,
                        base_time_ns=time.time_ns())
        ok = True
        try:
            req = urllib.request.Request(
                self.push_url + "/v1/traces",
                data=otlp_json.dumps(tr).encode(), headers=self._headers(),
            )
            urllib.request.urlopen(req, timeout=10)
        except (urllib.error.URLError, OSError):
            self.metrics.errors += 1
            return False

        time.sleep(self.read_back_delay_s)

        try:
            with urllib.request.urlopen(
                urllib.request.Request(
                    f"{self.query_url}/api/traces/{tid.hex()}", headers=self._headers()
                ),
                timeout=10,
            ) as r:
                got = otlp_json.loads(r.read())
            if got.span_count() < tr.span_count():
                self.metrics.missing_spans += tr.span_count() - got.span_count()
                ok = False
        except urllib.error.HTTPError as e:
            if e.code == 404:
                self.metrics.notfound_byid += 1
                ok = False
            else:
                self.metrics.errors += 1
                return False
        except (urllib.error.URLError, OSError):
            self.metrics.errors += 1
            return False

        # search leg: the trace must be findable by its root service name
        svc = next(iter(tr.all_spans()))[0].service_name
        try:
            q = urllib.parse.quote(f"service.name={svc}")
            with urllib.request.urlopen(
                urllib.request.Request(
                    f"{self.query_url}/api/search?tags={q}&limit=200", headers=self._headers()
                ),
                timeout=10,
            ) as r:
                hits = {t["traceID"] for t in json.loads(r.read())["traces"]}
            if tid.hex() not in hits:
                self.metrics.notfound_search += 1
                ok = False
        except (urllib.error.URLError, OSError):
            self.metrics.errors += 1
            return False
        return ok


def main(argv=None):
    ap = argparse.ArgumentParser(prog="tempo-tpu-vulture")
    ap.add_argument("--push-url", default="http://127.0.0.1:3200")
    ap.add_argument("--query-url", default="http://127.0.0.1:3200")
    ap.add_argument("--tenant", default="")
    ap.add_argument("--cycles", type=int, default=0, help="0 = forever")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--read-back-delay", type=float, default=1.0)
    args = ap.parse_args(argv)
    v = Vulture(args.push_url, args.query_url, args.tenant or None,
                read_back_delay_s=args.read_back_delay)
    n = 0
    while args.cycles == 0 or n < args.cycles:
        v.cycle()
        n += 1
        print("\n".join(v.metrics.lines()), flush=True)
        if args.cycles == 0 or n < args.cycles:
            time.sleep(args.interval)


if __name__ == "__main__":
    main()
