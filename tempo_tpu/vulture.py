"""tempo-vulture equivalent: a continuous verification plane.

The reference ships a black-box prober (cmd/tempo-vulture) that pushes
known traces and reads them back; alerting watches its metrics
(SURVEY.md 2.1, 4.7). This port grows that into a long-running
continuous-verification service whose probe families cover every read
path the system has:

  push          OTLP ingest of a known trace set (unique service name
                per cycle, deterministic span content).
  find_by_id    retry-until-visible GET /api/traces/{id} BEFORE any
                flush -- the live-head by-id path -- with bit-level
                span comparison against what was pushed; the retry lag
                is the write->live-visible freshness histogram.
  find_batched  K concurrent by-id reads of the cycle's trace set: the
                cross-query batching executor's find path (PR 3) must
                demux every trace bit-identically.
  search        retry-until-visible blocking /api/search by the unique
                service tag; the retry lag is the write->searchable
                freshness histogram.
  live_head     time-windowed recent search (start=now-60s) before
                cut/flush: the shape the live-head device engine
                serves from staged columnar tails.
  search_stream /api/search?stream=true: partial events must be
                well-ordered (done=false, jobsCompleted monotone) and
                the final event must equal the blocking response.
  query_range   TraceQL metrics count_over_time over the cycle's
                service: the expected per-bucket series is computed
                from the pushed spans' timestamps and compared
                exactly.
  cold_read     POST /flush, then read the trace back cold -- through
                a FRESH TempoDB reader over the backend path when one
                is configured (self-hosted / sidecar mode: every byte
                off disk), over HTTP otherwise; the lag is the
                flush->cold-readable freshness histogram. Flushed ids
                enter the durability ledger.
  durability    a sample of previously-flushed trace ids re-probed by
                id each cycle, across compactions, against their
                recorded content digest -- data loss detection long
                after the write.
  span_metrics  the metrics-generator's RED series: expected
                traces_spanmetrics_calls_total per (service, name,
                kind, status) computed client-side from the spans just
                pushed and compared exactly against the target's
                /metrics; the retry lag is the push->series-visible
                freshness histogram (the generator freshness SLO).
  service_graph a dedicated client/server span pair across two derived
                services: exactly one service-graph edge (request,
                failed, server-latency count) must materialize from
                the coded edge store.

Outcomes per probe: ok | miss (data absent) | corrupt (content
mismatch) | timeout (never became visible) | error (transport/HTTP) |
shed (HTTP 429 -- the per-tenant QoS budget refusing work; counted
separately and EXCLUDED from the availability SLI). Every failed probe
captures the self-trace timeline id of the query that served it (the
/status/kernels slow-query log, PR 9) so a red probe links straight to
its query timeline.

Freshness is MEASURED as retry-until-visible lag, never assumed as a
sleep. On top sits a util/slo engine (probe availability + per-kind
freshness objectives) whose multi-window burn rates and verdicts ship
in vulture's own strict-OpenMetrics /metrics and in the summary.

Run against a live instance:
    python -m tempo_tpu.vulture --push-url http://host:3200 \
        --query-url http://host:3200 --cycles 0 --interval 5 \
        --metrics-port 8090
or fully self-hosted (spawns an in-process single binary and probes
it over HTTP -- the zero-config smoke mode tier-1 runs):
    python -m tempo_tpu.vulture --self-hosted --cycles 3
"""

from __future__ import annotations

import argparse
import hashlib
import json
import random
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

from .util import slo as slomod
from .util.metrics import Registry
from .util.testdata import make_trace_id
from .wire import otlp_json
from .wire.model import Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

OUTCOMES = ("ok", "miss", "corrupt", "timeout", "error", "shed")
BAD_OUTCOMES = ("miss", "corrupt", "timeout", "error")  # shed excluded

# retry-until-visible lag histograms want a fine low end (in-process
# visibility is sub-ms) and a top at the visibility timeout
FRESHNESS_BUCKETS = (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                     5.0, 10.0, 30.0)


class Shed(Exception):
    """HTTP 429: the per-tenant QoS budget refused the probe."""


@dataclass
class VultureConfig:
    push_url: str = "http://127.0.0.1:3200"
    query_url: str = "http://127.0.0.1:3200"
    tenant: str = ""
    timeout_s: float = 10.0
    # retry-until-visible budget; a probe family that never sees its
    # data within this window records outcome=timeout for the cycle
    visibility_timeout_s: float = 15.0
    retry_interval_s: float = 0.1
    spans_per_trace: int = 4
    batch_ids: int = 4  # traces pushed per cycle (find_batched width)
    # cold probe cadence: flush + cold read every Nth cycle (0 = never;
    # needs loopback or the internal token to reach /flush)
    flush_every: int = 1
    internal_token: str = ""
    durability_sample: int = 4
    ledger_max: int = 512
    # backend storage path for TRUE fresh-reader cold probes (every
    # byte off disk through a new TempoDB); "" = cold reads over HTTP
    backend_path: str = ""
    # metrics-generator probes (span_metrics + service_graph): read
    # generated series off the target's main /metrics endpoint
    generator_probes: bool = True
    # restrict the cycle to these read families (push always runs);
    # () = all. The fleet rolling-restart probe runs only the families
    # whose zero-miss guarantee it certifies: ("find_by_id", "search")
    families: tuple[str, ...] = ()
    # bounded retry of transient push failures (5xx / connection reset),
    # mirroring an OTLP exporter's retry-on-retryable behavior: during a
    # replica outage window the distributor may 500 one window before
    # the ring prunes the corpse; a retried-and-acked push still honors
    # the write contract, a persistent failure still records error
    push_retries: int = 2
    seed: int | None = None


@dataclass
class ProbeResult:
    family: str
    outcome: str
    lag_s: float = 0.0
    detail: str = ""
    self_trace_id: str = ""


@dataclass
class _LedgerEntry:
    tid_hex: str
    digest: str
    svc: str
    written_at: float


def canonical_spans(tr: Trace) -> frozenset:
    """Bit-level comparable form of a trace: every span with its
    resource identity, ids, kind, timestamps, status and attrs (values
    tagged with their type so 200 != "200" != 200.0). Set-shaped:
    span ORDER may legally differ across read paths; span CONTENT may
    not."""

    def val(v):
        return (type(v).__name__, repr(v))

    rows = []
    for res, _scope, sp in tr.all_spans():
        rows.append((
            res.service_name,
            tuple(sorted((k, val(v)) for k, v in res.attrs.items())),
            sp.span_id.hex(),
            sp.parent_span_id.hex(),
            sp.name,
            int(sp.kind),
            int(sp.start_unix_nano),
            int(sp.end_unix_nano),
            int(getattr(sp, "status_code", 0)),
            tuple(sorted((k, val(v)) for k, v in sp.attrs.items())),
        ))
    return frozenset(rows)


def content_digest(tr: Trace) -> str:
    return hashlib.sha256(
        repr(sorted(canonical_spans(tr))).encode()).hexdigest()


def _make_probe_trace(rng: random.Random, tid: bytes, svc: str,
                      n_spans: int, base_ns: int) -> Trace:
    """Deterministic probe content: attr values chosen to round-trip
    OTLP JSON exactly (ints, strings, bools, binary-exact floats), a
    parent chain for structure, timestamps inside the current minute
    so time-windowed probes and query_range buckets see them."""
    rs = ResourceSpans(resource=Resource(attrs={
        "service.name": svc, "vulture.probe": True}))
    ss = ScopeSpans(scope=Scope(name="tempo-vulture", version="2"))
    prev = b""
    for i in range(n_spans):
        sid = rng.getrandbits(64).to_bytes(8, "big")
        start = base_ns + i * 1_000_000
        sp = Span(
            trace_id=tid, span_id=sid, parent_span_id=prev,
            name=f"probe-op-{i}", kind=1 + (i % 5),
            start_unix_nano=start, end_unix_nano=start + 2_000_000,
            status_code=0,
            attrs={"probe.seq": i, "probe.note": f"v-{i:04d}",
                   "probe.flag": i % 2 == 0, "probe.weight": 0.25 * i},
        )
        ss.spans.append(sp)
        prev = sid
    rs.scope_spans.append(ss)
    t = Trace()
    t.resource_spans.append(rs)
    return t


def _make_graph_trace(rng: random.Random, tid: bytes, svc: str,
                      base_ns: int) -> Trace:
    """The minimal trace that must materialize exactly one service-graph
    edge: a CLIENT span in `svc`-client whose span id is the SERVER
    span's parent id over in `svc`-server. The server span carries
    status=ERROR so the failed counter is exercised too."""
    cid = rng.getrandbits(64).to_bytes(8, "big")
    sid = rng.getrandbits(64).to_bytes(8, "big")
    t = Trace()
    client_rs = ResourceSpans(resource=Resource(attrs={
        "service.name": f"{svc}-client", "vulture.probe": True}))
    css = ScopeSpans(scope=Scope(name="tempo-vulture", version="2"))
    css.spans.append(Span(
        trace_id=tid, span_id=cid, name="graph-call", kind=3,  # CLIENT
        start_unix_nano=base_ns, end_unix_nano=base_ns + 4_000_000,
        status_code=0))
    client_rs.scope_spans.append(css)
    server_rs = ResourceSpans(resource=Resource(attrs={
        "service.name": f"{svc}-server", "vulture.probe": True}))
    sss = ScopeSpans(scope=Scope(name="tempo-vulture", version="2"))
    sss.spans.append(Span(
        trace_id=tid, span_id=sid, parent_span_id=cid,
        name="graph-serve", kind=2,  # SERVER
        start_unix_nano=base_ns + 1_000_000,
        end_unix_nano=base_ns + 3_000_000,
        status_code=2))  # ERROR -> one failed edge expected
    server_rs.scope_spans.append(sss)
    t.resource_spans += [client_rs, server_rs]
    return t


class Vulture:
    """The continuous-verification prober. One instance owns the probe
    loop, the metric registry, the durability ledger and the SLO
    engine; `cycle()` runs every probe family once."""

    def __init__(self, cfg: VultureConfig, app=None):
        self.cfg = cfg
        self.app = app  # in-process App in --self-hosted mode (or None)
        self.push_url = cfg.push_url.rstrip("/")
        self.query_url = cfg.query_url.rstrip("/")
        # /flush is loopback-trusted only (or token-gated): against a
        # remote target without a token the cold-read probe would 401
        # every flush cycle and page on a healthy cluster -- disable it
        # here so every caller (CLI, soak sidecar) gets the guard
        if cfg.flush_every and not cfg.internal_token:
            host = urllib.parse.urlparse(self.push_url).hostname or ""
            if host not in ("127.0.0.1", "::1", "localhost"):
                from .util.log import get_logger

                get_logger("vulture").warning(
                    "cold-read probes disabled (remote target, "
                    "no --internal-token for /flush)")
                cfg.flush_every = 0
        # generator probes read generated series off the TARGET's main
        # /metrics endpoint; a remote topology may host its generators
        # on other ring members (or run generator-less), so -- same
        # stance as the /flush guard -- only loopback targets keep them
        # on by default
        if cfg.generator_probes:
            host = urllib.parse.urlparse(self.query_url).hostname or ""
            if host not in ("127.0.0.1", "::1", "localhost"):
                from .util.log import get_logger

                get_logger("vulture").warning(
                    "generator probes disabled (remote target: generated "
                    "series may live on another ring member)")
                cfg.generator_probes = False
        self.rng = random.Random(cfg.seed)
        self.run_id = f"{self.rng.getrandbits(32):08x}"
        self.seq = 0
        self.cycles = 0
        self._lock = threading.Lock()
        self.ledger: deque[_LedgerEntry] = deque(maxlen=cfg.ledger_max)
        self.failures: deque[dict] = deque(maxlen=64)
        # raw lag samples (bounded) for summary percentiles
        self._lags: dict[str, deque] = {
            k: deque(maxlen=2048)
            for k in ("live_visible", "searchable", "cold_readable",
                      "series_visible")}

        # ------------------------------ metrics (util/metrics Registry)
        self.registry = Registry()
        self.probes = self.registry.counter(
            "tempo_vulture_probes_total",
            help="verification probes by family and outcome")
        self.freshness = self.registry.histogram(
            "tempo_vulture_freshness_seconds", buckets=FRESHNESS_BUCKETS,
            help="measured retry-until-visible lag by kind "
                 "(live_visible / searchable / cold_readable)")
        self.probe_duration = self.registry.histogram(
            "tempo_vulture_probe_duration_seconds",
            help="wall time of one probe family run")
        self.cycles_total = self.registry.counter(
            "tempo_vulture_cycles_total",
            help="completed verification cycles")
        self.last_cycle_gauge = self.registry.gauge(
            "tempo_vulture_last_cycle_unix",
            help="wall-clock time the last cycle finished")
        self.ledger_gauge = self.registry.gauge(
            "tempo_vulture_ledger_entries",
            help="trace ids tracked by the durability ledger")

        # ------------------------------------------- SLO engine on top
        self.slo = slomod.SLOEngine(name_prefix="tempo_vulture_slo")
        self.slo.register(slomod.Objective(
            name="probe-availability", kind="availability", target=0.999,
            sli=slomod.counter_sli(
                self.probes,
                good=lambda l: 'outcome="ok"' in l,
                bad=lambda l: any(f'outcome="{o}"' in l
                                  for o in BAD_OUTCOMES)),
            description="probes succeeding across every family "
                        "(QoS sheds excluded)"))
        for kind, thr, tgt in (("live_visible", 2.5, 0.99),
                               ("searchable", 5.0, 0.99),
                               ("cold_readable", 10.0, 0.99),
                               ("series_visible", 2.5, 0.99)):
            self.slo.register(slomod.Objective(
                name=f"freshness-{kind}", kind="freshness", target=tgt,
                sli=slomod.histogram_sli(
                    self.freshness, thr,
                    labels_pred=lambda l, _k=kind: f'kind="{_k}"' in l),
                description=f"writes {kind.replace('_', '-')} within "
                            f"{thr:g}s"))
        self._http_server = None
        self._cold_wal: str | None = None  # shared fresh-reader WAL dir

    # ------------------------------------------------------------- http
    def _headers(self, ctype: str = "") -> dict:
        h = {}
        if ctype:
            h["Content-Type"] = ctype
        if self.cfg.tenant:
            h["X-Scope-OrgID"] = self.cfg.tenant
        return h

    def _request(self, url: str, data: bytes | None = None,
                 ctype: str = "", extra: dict | None = None) -> bytes:
        """One HTTP round trip. Raises Shed on 429 (the QoS budget
        refusing the probe -- a distinct outcome, not an error),
        re-raises HTTPError otherwise."""
        h = self._headers(ctype)
        if extra:
            h.update(extra)
        req = urllib.request.Request(url, data=data, headers=h)
        try:
            with urllib.request.urlopen(req, timeout=self.cfg.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise Shed(str(e)) from e
            raise

    def _push(self, tr: Trace) -> None:
        data = otlp_json.dumps(tr).encode()
        for attempt in range(self.cfg.push_retries + 1):
            try:
                self._request(self.push_url + "/v1/traces", data=data,
                              ctype="application/json")
                return
            except Shed:
                raise  # 429 is the QoS budget, never retried
            except (urllib.error.HTTPError, urllib.error.URLError,
                    ConnectionError, TimeoutError):
                if attempt >= self.cfg.push_retries:
                    raise
                time.sleep(0.25 * (attempt + 1))

    def _get_trace(self, tid_hex: str) -> Trace | None:
        try:
            return otlp_json.loads(
                self._request(f"{self.query_url}/api/traces/{tid_hex}"))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def _search_body(self, params: dict) -> dict:
        qs = urllib.parse.urlencode(params)
        return json.loads(self._request(f"{self.query_url}/api/search?{qs}"))

    # ------------------------------------------------------- accounting
    def _record(self, res: ProbeResult) -> None:
        self.probes.inc(
            labels=f'family="{res.family}",outcome="{res.outcome}"')
        # only REAL failures enter the bounded failure log: sheds are
        # the QoS budget working, and letting them rotate out a
        # durability miss would sever the probe->self-trace link
        # exactly when an operator needs it
        if res.outcome in BAD_OUTCOMES:
            res.self_trace_id = self._self_trace_id(res.detail)
            with self._lock:
                self.failures.append({
                    "family": res.family, "outcome": res.outcome,
                    "detail": res.detail[:300],
                    "self_trace_id": res.self_trace_id,
                    "at_unix": round(time.time(), 3)})

    def _freshness(self, kind: str, lag_s: float) -> None:
        self.freshness.observe(lag_s, labels=f'kind="{kind}"')
        with self._lock:
            self._lags[kind].append(lag_s)

    def _self_trace_id(self, marker: str) -> str:
        """Best-effort: the self-trace timeline id of the query that
        served (or failed) this probe, from the slow-query log -- a red
        probe links straight to `tempo-tpu-cli self-trace <id>`."""
        try:
            with urllib.request.urlopen(
                    self.query_url + "/status/kernels",
                    timeout=self.cfg.timeout_s) as r:
                status = json.load(r)
            probe_key = marker.split(" ", 1)[0] if marker else ""
            best = ("", -1.0)
            for q in status.get("slow_queries", []):
                if not q.get("self_trace_id"):
                    continue
                if probe_key and probe_key not in q.get("detail", ""):
                    continue
                if q.get("at_unix", 0) > best[1]:
                    best = (q["self_trace_id"], q.get("at_unix", 0))
            return best[0]
        except Exception:
            return ""

    def _await(self, check, timeout_s: float | None = None):
        """Retry-until-visible: poll `check` (None/False = not yet)
        until it returns truthy or the visibility budget runs out.
        Returns (value_or_None, lag_seconds). Shed aborts immediately
        (retrying into a closed budget just burns it further)."""
        deadline = time.perf_counter() + (timeout_s
                                          or self.cfg.visibility_timeout_s)
        t0 = time.perf_counter()
        while True:
            v = check()
            if v:
                return v, time.perf_counter() - t0
            if time.perf_counter() >= deadline:
                return None, time.perf_counter() - t0
            time.sleep(self.cfg.retry_interval_s)

    def _run_family(self, family: str, fn, detail: str) -> ProbeResult:
        """Execute one probe family with outcome classification and
        duration accounting. `fn` returns a ProbeResult (or raises)."""
        t0 = time.perf_counter()
        try:
            res = fn()
        except Shed as e:
            res = ProbeResult(family, "shed", detail=f"{detail}: {e}")
        except urllib.error.HTTPError as e:
            res = ProbeResult(family, "error",
                              detail=f"{detail}: HTTP {e.code}")
        except Exception as e:  # transport errors + probe logic bugs alike
            res = ProbeResult(family, "error",
                              detail=f"{detail}: {type(e).__name__}: {e}")
        self.probe_duration.observe(time.perf_counter() - t0,
                                    labels=f'family="{family}"')
        self._record(res)
        return res

    # ---------------------------------------------------------- probes
    def cycle(self) -> list[ProbeResult]:
        """One full verification round across every probe family.
        Returns the per-family results (self.ok(results) says whether
        the serving path held)."""
        self.seq += 1
        svc = f"vulture-{self.run_id}-{self.seq}"
        base_ns = time.time_ns()
        traces: list[tuple[bytes, Trace]] = []
        for i in range(max(1, self.cfg.batch_ids)):
            tid = make_trace_id(self.rng)
            traces.append((tid, _make_probe_trace(
                self.rng, tid, svc, self.cfg.spans_per_trace,
                base_ns + i * 10_000_000)))
        want = {tid.hex(): canonical_spans(tr) for tid, tr in traces}
        results: list[ProbeResult] = []

        sel = set(self.cfg.families)

        def run(family, fn, detail):
            if sel and family != "push" and family not in sel:
                # family filter: skipped families record nothing at all
                # (a non-probe must not dilute ok()/miss statistics)
                return ProbeResult(family, "ok", detail="skipped")
            results.append(self._run_family(family, fn, detail))
            return results[-1]

        # -- push: all of the cycle's traces in (a push failure makes
        # every read family below meaningless -- stop the cycle)
        def push_fn():
            for _tid, tr in traces:
                self._push(tr)
            return ProbeResult("push", "ok")

        if run("push", push_fn, svc).outcome != "ok":
            self._close_cycle()
            return results

        lead_hex = traces[0][0].hex()

        # -- find_by_id: retry-until-visible + bit-level comparison;
        # the lag IS the write->live-visible freshness sample
        def byid_fn():
            got, lag = self._await(lambda: self._get_trace(lead_hex))
            if got is None:
                return ProbeResult("find_by_id", "timeout", lag,
                                   f"{svc} id={lead_hex} never visible")
            self._freshness("live_visible", lag)
            if canonical_spans(got) != want[lead_hex]:
                return ProbeResult("find_by_id", "corrupt", lag,
                                   f"{svc} id={lead_hex} span mismatch")
            return ProbeResult("find_by_id", "ok", lag)

        run("find_by_id", byid_fn, svc)

        # -- find_batched: K concurrent by-id reads (the PR-3 batched
        # find path) -- every demuxed result must be bit-identical
        def batched_fn():
            with ThreadPoolExecutor(len(traces)) as ex:
                got = list(ex.map(
                    lambda th: (th, self._get_trace(th)), list(want)))
            missing = [th for th, tr in got if tr is None]
            if missing:
                return ProbeResult(
                    "find_batched", "miss",
                    detail=f"{svc} {len(missing)}/{len(got)} ids absent "
                           f"(first {missing[0]})")
            bad = [th for th, tr in got if canonical_spans(tr) != want[th]]
            if bad:
                return ProbeResult(
                    "find_batched", "corrupt",
                    detail=f"{svc} {len(bad)} ids mismatched "
                           f"(first {bad[0]})")
            return ProbeResult("find_batched", "ok")

        run("find_batched", batched_fn, svc)

        # -- search: retry-until-visible by the unique service tag; the
        # lag is the write->searchable freshness sample
        tags = f"service.name={svc}"

        def search_hits() -> dict | None:
            body = self._search_body({"tags": tags, "limit": 50})
            hits = {t["traceID"]: t for t in body.get("traces", [])}
            return hits if lead_hex in hits else None

        def search_fn():
            hits, lag = self._await(search_hits)
            if hits is None:
                return ProbeResult("search", "timeout", lag,
                                   f"{svc} not searchable")
            self._freshness("searchable", lag)
            hit = hits[lead_hex]
            if hit.get("rootServiceName") not in ("", svc):
                return ProbeResult(
                    "search", "corrupt", lag,
                    f"{svc} summary rootServiceName="
                    f"{hit.get('rootServiceName')!r}")
            return ProbeResult("search", "ok", lag)

        run("search", search_fn, svc)

        # -- live_head: the recent-window shape (start=now-60s) the
        # live-head device engine serves from staged columnar tails --
        # queried BEFORE any cut/flush of this cycle's traces
        def live_head_fn():
            now = int(time.time())
            got, lag = self._await(lambda: self._search_body({
                "tags": tags, "limit": 50,
                "start": str(now - 60), "end": str(now + 5),
            }).get("traces") or None)
            if got is None:
                return ProbeResult("live_head", "timeout", lag,
                                   f"{svc} absent from recent window")
            if lead_hex not in {t["traceID"] for t in got}:
                return ProbeResult("live_head", "miss", lag,
                                   f"{svc} lead id absent from window hits")
            return ProbeResult("live_head", "ok", lag)

        run("live_head", live_head_fn, svc)

        # -- search_stream: progressive delivery ordering + final ==
        # blocking invariants
        run("search_stream", lambda: self._stream_probe(svc, tags), svc)

        # -- query_range: expected per-bucket series computed from the
        # pushed spans' timestamps
        run("query_range",
            lambda: self._query_range_probe(svc, traces, base_ns), svc)

        # -- generated series: client-side expected RED counts + the
        # dedicated service-graph edge against the target's /metrics
        if self.cfg.generator_probes:
            run("span_metrics",
                lambda: self._span_metrics_probe(svc, traces), svc)
            run("service_graph",
                lambda: self._service_graph_probe(svc), svc)

        # -- cached_vs_fresh: the tiered result-cache contract (repeat
        # hit, bit-equality, mutation invalidation). Runs after the
        # series probes: its mutation push would otherwise perturb
        # their client-side expected counts for this service.
        run("cached_vs_fresh",
            lambda: self._cached_vs_fresh_probe(svc, tags), svc)

        # -- cold_read + durability ledger maintenance
        if self.cfg.flush_every and self.seq % self.cfg.flush_every == 0:
            run("cold_read",
                lambda: self._cold_probe(svc, traces, want), svc)
        if self.ledger:
            run("durability", self._durability_probe, "ledger")

        self._close_cycle()
        return results

    def _close_cycle(self) -> None:
        self.cycles += 1
        self.cycles_total.inc()
        self.last_cycle_gauge.set(time.time())
        self.ledger_gauge.set(len(self.ledger))
        try:
            self.slo.evaluate()
        except Exception:
            pass

    @staticmethod
    def ok(results: list[ProbeResult]) -> bool:
        return all(r.outcome in ("ok", "shed") for r in results)

    # ------------------------------------------------- stream probe
    def _stream_probe(self, svc: str, tags: str) -> ProbeResult:
        """stream=true invariants: every partial has done=false with
        monotone jobsCompleted <= jobsTotal, exactly one final with
        done=true, and the final body equals the blocking response for
        the same request (PR 8's final==blocking contract)."""
        qs = urllib.parse.urlencode(
            {"tags": tags, "limit": 50, "stream": "true"})
        req = urllib.request.Request(
            f"{self.query_url}/api/search?{qs}", headers=self._headers())
        events = []
        try:
            with urllib.request.urlopen(req, timeout=self.cfg.timeout_s) as r:
                for line in r:
                    line = line.strip()
                    if line:
                        events.append(json.loads(line))
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise Shed(str(e)) from e
            raise
        if not events:
            return ProbeResult("search_stream", "miss",
                               detail=f"{svc} stream yielded no events")
        last_jobs = -1
        for ev in events[:-1]:
            if ev.get("done"):
                return ProbeResult(
                    "search_stream", "corrupt",
                    detail=f"{svc} done=true before the final event")
            jc = ev.get("jobsCompleted", 0)
            if jc < last_jobs or jc > ev.get("jobsTotal", 0):
                return ProbeResult(
                    "search_stream", "corrupt",
                    detail=f"{svc} jobsCompleted not monotone "
                           f"({last_jobs} -> {jc})")
            last_jobs = jc
        final = events[-1]
        if not final.get("done"):
            return ProbeResult("search_stream", "corrupt",
                               detail=f"{svc} final event missing done=true")
        blocking = self._search_body({"tags": tags, "limit": 50})
        if final.get("traces") != blocking.get("traces"):
            return ProbeResult(
                "search_stream", "corrupt",
                detail=f"{svc} final stream body != blocking body "
                       f"({len(final.get('traces', []))} vs "
                       f"{len(blocking.get('traces', []))} traces)")
        return ProbeResult("search_stream", "ok")

    # --------------------------------------- cached_vs_fresh probe
    def _search_with_header(self, params: dict) -> tuple[dict, str]:
        """Like _search_body but also returns the X-Tempo-Cache
        response header ("hit"/"extend"/"miss", or "" when the result
        cache is disabled or the target predates it)."""
        qs = urllib.parse.urlencode(params)
        req = urllib.request.Request(
            f"{self.query_url}/api/search?{qs}", headers=self._headers())
        try:
            with urllib.request.urlopen(req, timeout=self.cfg.timeout_s) as r:
                return json.loads(r.read()), r.headers.get("X-Tempo-Cache", "")
        except urllib.error.HTTPError as e:
            if e.code == 429:
                raise Shed(str(e)) from e
            raise

    def _result_cache_live_wired(self) -> bool:
        """Whether the target can cache live-touching ranges (needs a
        local ingester generation feed -- /status/kernels reports it).
        Without it a now-edge repeat legitimately misses."""
        try:
            with urllib.request.urlopen(
                    self.query_url + "/status/kernels",
                    timeout=self.cfg.timeout_s) as r:
                status = json.load(r)
            return bool(status.get("caching", {})
                        .get("result_cache", {}).get("live_gen_wired"))
        except Exception:
            return False

    def _cached_vs_fresh_probe(self, svc: str, tags: str) -> ProbeResult:
        """The tiered result-cache contract: (a) the same query twice
        answers bit-identically, (b) the repeat is served from the
        cache (X-Tempo-Cache: hit/extend) when the target can cache
        the range, and (c) a corpus mutation under the entry yields
        fresh data -- a stale cached body here is a correctness bug,
        not a performance one."""
        now = int(time.time())
        params = {"tags": tags, "limit": 50,
                  "start": str(now - 300), "end": str(now + 5)}
        fresh, h1 = self._search_with_header(params)
        if not h1:
            return ProbeResult("cached_vs_fresh", "ok",
                               detail=f"{svc} result cache disabled")
        cached, h2 = fresh, h1
        for _ in range(3):  # a concurrent write may invalidate between reads
            cached, h2 = self._search_with_header(params)
            if h2 in ("hit", "extend"):
                break
        if cached.get("traces") != fresh.get("traces"):
            return ProbeResult(
                "cached_vs_fresh", "corrupt",
                detail=f"{svc} cached body != fresh body (outcome {h2!r})")
        if h2 not in ("hit", "extend") and self._result_cache_live_wired():
            return ProbeResult(
                "cached_vs_fresh", "miss",
                detail=f"{svc} repeat read outcome {h2!r}, "
                       f"expected hit/extend")
        # corpus mutation: one more trace under the same tag must
        # invalidate the entry -- if it doesn't, the stale body keeps
        # being served and the new id never appears
        tid = make_trace_id(self.rng)
        self._push(_make_probe_trace(self.rng, tid, svc, 1, time.time_ns()))

        def see_new() -> dict | None:
            body, _h = self._search_with_header(params)
            ids = {t["traceID"] for t in body.get("traces", [])}
            return body if tid.hex() in ids else None

        body, lag = self._await(see_new)
        if body is None:
            return ProbeResult(
                "cached_vs_fresh", "corrupt", lag,
                f"{svc} stale cache: id {tid.hex()} never appeared "
                f"after corpus mutation")
        # and the post-mutation cached read must match the fresh one
        again, _h3 = self._search_with_header(params)
        if again.get("traces") != body.get("traces"):
            return ProbeResult(
                "cached_vs_fresh", "corrupt", lag,
                f"{svc} post-mutation cached body != fresh body")
        return ProbeResult("cached_vs_fresh", "ok", lag)

    # -------------------------------------------- query_range probe
    def _query_range_probe(self, svc: str, traces, base_ns: int) -> ProbeResult:
        """count_over_time over the probe service: expected series
        computed client-side from the pushed spans (the server aligns
        start/end onto the step grid exactly like align_params, so the
        bucket map is reproducible)."""
        step = 5
        start_s = base_ns // 1_000_000_000 - step
        end_s = time.time() + step
        expect: dict[float, int] = {}
        for _tid, tr in traces:
            for _res, _sc, sp in tr.all_spans():
                b = (sp.start_unix_nano // 1_000_000
                     // (step * 1000)) * step
                expect[float(b)] = expect.get(float(b), 0) + 1
        q = urllib.parse.quote(
            f'{{ resource.service.name = "{svc}" }} | count_over_time()')

        def sample() -> dict | None:
            body = json.loads(self._request(
                f"{self.query_url}/api/metrics/query_range?q={q}"
                f"&start={start_s}&end={end_s}&step={step}"))
            got: dict[float, int] = {}
            for series in body.get("data", {}).get("result", []):
                for ts, v in series.get("values", []):
                    if float(v):
                        got[float(ts)] = got.get(float(ts), 0) + int(float(v))
            return got if got == expect else None

        got, _lag = self._await(sample)
        if got is None:
            # distinguish "never arrived" from "arrived wrong": one
            # last unconditional read for the detail line
            try:
                body = json.loads(self._request(
                    f"{self.query_url}/api/metrics/query_range?q={q}"
                    f"&start={start_s}&end={end_s}&step={step}"))
                n = sum(
                    int(float(v)) for series in
                    body.get("data", {}).get("result", [])
                    for _ts, v in series.get("values", []))
            except Exception:
                n = -1
            want_n = sum(expect.values())
            # n==0: series never arrived (freshness); n<0: the
            # confirming read itself failed (transport, NOT content);
            # n>0: arrived with the wrong shape (real corruption)
            outcome = ("timeout" if n == 0
                       else "error" if n < 0 else "corrupt")
            return ProbeResult(
                "query_range", outcome,
                detail=f"{svc} expected {want_n} spans across "
                       f"{len(expect)} buckets, got {n}")
        return ProbeResult("query_range", "ok")

    # ---------------------------------------- generated-series probes
    def _metrics_lines(self) -> list[str]:
        return self._request(
            self.query_url + "/metrics").decode().splitlines()

    def _span_metrics_probe(self, svc: str, traces) -> ProbeResult:
        """Expected RED counts computed client-side from the spans just
        pushed -- one traces_spanmetrics_calls_total series per unique
        (service, span name, kind, status) with an exact call count --
        compared against the generated series on the target's main
        /metrics. The retry lag is the push->series-visible freshness
        sample: the generator freshness SLO measured end to end."""
        from .services.remotewrite import parse_exposition
        from .wire.model import SpanKind, StatusCode

        expect: dict[tuple, int] = {}
        for _tid, tr in traces:
            for res, _sc, sp in tr.all_spans():
                k = (res.service_name, sp.name,
                     SpanKind(int(sp.kind)).name,
                     StatusCode(int(sp.status_code)).name)
                expect[k] = expect.get(k, 0) + 1

        def read() -> dict[tuple, int]:
            got: dict[tuple, int] = {}
            for lab, v in parse_exposition(self._metrics_lines()):
                if (lab.get("__name__") == "traces_spanmetrics_calls_total"
                        and lab.get("service") == svc):
                    k = (svc, lab.get("span_name", ""),
                         lab.get("span_kind", ""),
                         lab.get("status_code", ""))
                    got[k] = got.get(k, 0) + int(v)
            return got

        got, lag = self._await(lambda: (read() == expect) or None)
        if got is None:
            final = read()
            # nothing, or a strict UNDER-count of expected series only:
            # the window's fold is still in flight (freshness collapse,
            # not corruption). Unexpected series or over-counts can't
            # come from lag: that's corruption.
            partial = final and all(
                k in expect and v <= expect[k] for k, v in final.items())
            outcome = "timeout" if (not final or partial) else "corrupt"
            return ProbeResult(
                "span_metrics", outcome, lag,
                f"{svc} expected {len(expect)} RED series "
                f"(calls {sum(expect.values())}), got {len(final)} "
                f"(calls {sum(final.values())})")
        self._freshness("series_visible", lag)
        return ProbeResult("span_metrics", "ok", lag)

    def _service_graph_probe(self, svc: str) -> ProbeResult:
        """One dedicated client/server pair -> exactly one generated
        edge: request_total 1, request_failed_total 1 (the server span
        carries status=ERROR), server latency count 1. The edge only
        exists if the coded edge store paired the two spans on
        (trace id, span id / parent id) codes across the two pushed
        resource blocks."""
        from .services.remotewrite import parse_exposition

        tid = make_trace_id(self.rng)
        tr = _make_graph_trace(self.rng, tid, svc, time.time_ns())
        self._push(tr)
        client, server = f"{svc}-client", f"{svc}-server"
        want = {"traces_service_graph_request_total": 1.0,
                "traces_service_graph_request_failed_total": 1.0,
                "traces_service_graph_request_server_seconds_count": 1.0}

        def read() -> dict[str, float]:
            got: dict[str, float] = {}
            for lab, v in parse_exposition(self._metrics_lines()):
                if (lab.get("__name__") in want
                        and lab.get("client") == client
                        and lab.get("server") == server):
                    got[lab["__name__"]] = got.get(lab["__name__"], 0.0) + v
            return got

        got, lag = self._await(lambda: (read() == want) or None)
        if got is None:
            final = read()
            # same partial-vs-corrupt split as span_metrics: an edge
            # whose series under-count `want` is a fold in flight
            partial = final and all(
                k in want and v <= want[k] for k, v in final.items())
            outcome = "timeout" if (not final or partial) else "corrupt"
            return ProbeResult(
                "service_graph", outcome, lag,
                f"{svc} edge {client}->{server} expected {want}, "
                f"got {final or 'nothing'}")
        self._freshness("series_visible", lag)
        return ProbeResult("service_graph", "ok", lag)

    # ------------------------------------------------- cold probe
    def _cold_probe(self, svc: str, traces, want) -> ProbeResult:
        """Flush the live head, then prove the cycle's traces are
        readable COLD: through a fresh TempoDB reader over the backend
        path when configured (fresh readers pay every byte from disk),
        over HTTP otherwise. The lag from flush to first successful
        cold read is the flush->cold-readable freshness sample.
        Flushed ids enter the durability ledger."""
        t_flush = time.perf_counter()
        self._request(self.push_url + "/flush", data=b"",
                      extra={"X-Tempo-Internal-Token":
                             self.cfg.internal_token}
                      if self.cfg.internal_token else None)
        lead_tid, lead_tr = traces[0]
        lead_hex = lead_tid.hex()

        if self.cfg.backend_path:
            got, _ = self._await(
                lambda: self._cold_read_fresh(lead_tid))
        else:
            got, _ = self._await(lambda: self._get_trace(lead_hex))
        lag = time.perf_counter() - t_flush
        if got is None:
            return ProbeResult("cold_read", "timeout", lag,
                               f"{svc} id={lead_hex} not cold-readable")
        self._freshness("cold_readable", lag)
        if canonical_spans(got) != want[lead_hex]:
            return ProbeResult("cold_read", "corrupt", lag,
                               f"{svc} id={lead_hex} cold span mismatch")
        now = time.time()
        with self._lock:
            for tid, tr in traces:
                self.ledger.append(_LedgerEntry(
                    tid.hex(), content_digest(tr), svc, now))
        return ProbeResult("cold_read", "ok", lag)

    def _cold_read_fresh(self, tid: bytes):
        """A brand-new TempoDB over the backend path: fresh blocklist
        poll, fresh readers, zero shared caches -- the strongest form
        of "the flushed block is durable and complete". The scratch
        WAL dir is allocated ONCE per prober and reused: this path
        retries sub-second inside a long-running service, and a
        per-attempt mkdtemp would leak a directory per poll forever."""
        from .db.tempodb import TempoDB, TempoDBConfig

        if self._cold_wal is None:
            import tempfile

            self._cold_wal = tempfile.mkdtemp(prefix="vulture-cold-wal-")
        db = TempoDB(TempoDBConfig(
            backend={"backend": "local", "path": self.cfg.backend_path},
            wal_path=self._cold_wal))
        try:
            db.poll_now()
            return db.find_trace_by_id(
                self.cfg.tenant or "single-tenant", tid)
        finally:
            db.close()

    # --------------------------------------------- durability probe
    def _durability_probe(self) -> ProbeResult:
        """Re-probe a sample of previously-flushed trace ids against
        their recorded content digests -- the check that survives
        compactions, retention bugs and backend bit rot."""
        with self._lock:
            entries = list(self.ledger)
        sample = self.rng.sample(
            entries, min(self.cfg.durability_sample, len(entries)))
        gone: list[_LedgerEntry] = []
        changed: list[_LedgerEntry] = []
        for ent in sample:
            # verify the WHOLE sample (no early return): partial loss
            # must burn proportionally, not read as one bad probe. An
            # HTTP 5xx on one id means THAT id is unreadable (a deleted
            # block object 500s the find path) -- count it lost and
            # keep scanning; transport failures abort the family.
            try:
                got = self._get_trace(ent.tid_hex)
            except Shed:
                raise
            except urllib.error.HTTPError:
                got = None
            if got is None:
                gone.append(ent)
            elif content_digest(got) != ent.digest:
                changed.append(ent)
        if gone:
            ent = gone[0]
            return ProbeResult(
                "durability", "miss",
                detail=f"{len(gone)}/{len(sample)} ledger ids unreadable "
                       f"(first: {ent.svc} id={ent.tid_hex}, written "
                       f"{time.time() - ent.written_at:.0f}s ago)")
        if changed:
            ent = changed[0]
            return ProbeResult(
                "durability", "corrupt",
                detail=f"{len(changed)}/{len(sample)} ledger ids changed "
                       f"content (first: {ent.svc} id={ent.tid_hex})")
        return ProbeResult("durability", "ok",
                           detail=f"{len(sample)} ids re-verified")

    # ------------------------------------------------------ exposition
    def exposition(self) -> str:
        """Vulture's own /metrics: registry instruments + SLO gauges
        rendered as strict OpenMetrics (with EOF marker)."""
        helps = dict(self.slo.help_entries())
        return self.registry.render(
            extra_lines=self.slo.metrics_lines(),
            extra_helps=helps) + "# EOF\n"

    def _pct(self, xs, p: float) -> float:
        if not xs:
            return 0.0
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * p))]

    def status(self) -> dict:
        with self._lock:
            lags = {k: list(v) for k, v in self._lags.items()}
            failures = list(self.failures)
        outcomes: dict[str, dict[str, int]] = {}
        for labels, v in self.probes.snapshot().items():
            fam = labels.split('family="', 1)[1].split('"', 1)[0]
            out = labels.split('outcome="', 1)[1].split('"', 1)[0]
            outcomes.setdefault(fam, {})[out] = int(v)
        return {
            "cycles": self.cycles,
            "outcomes": outcomes,
            "freshness": {
                k: {"p50_ms": round(self._pct(v, 0.5) * 1e3, 2),
                    "p99_ms": round(self._pct(v, 0.99) * 1e3, 2),
                    "n": len(v)}
                for k, v in lags.items()},
            "ledger_entries": len(self.ledger),
            "failures": failures,
            "slo": self.slo.status(),
        }

    def serve_metrics(self, port: int, host: str = "127.0.0.1"):
        """Expose /metrics (strict OpenMetrics) + /status (JSON) --
        vulture is itself a scrape target whose verdicts alerting
        watches."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        vulture = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/metrics":
                    return self._send(
                        200, vulture.exposition().encode(),
                        "application/openmetrics-text; version=1.0.0; "
                        "charset=utf-8")
                if self.path == "/status":
                    return self._send(
                        200, json.dumps(vulture.status(), indent=2).encode(),
                        "application/json")
                return self._send(404, b'{"error":"no route"}',
                                  "application/json")

        self._http_server = ThreadingHTTPServer((host, port), Handler)
        t = threading.Thread(target=self._http_server.serve_forever,
                             daemon=True, name="vulture-metrics")
        t.start()
        return self._http_server

    def close(self) -> None:
        if self._http_server is not None:
            self._http_server.shutdown()
            self._http_server = None
        if self._cold_wal is not None:
            import shutil

            shutil.rmtree(self._cold_wal, ignore_errors=True)
            self._cold_wal = None


def _self_hosted_app(storage: str, compaction_cycle_s: float = 5.0):
    """An in-process single binary on an ephemeral port for
    --self-hosted mode: short compaction cycle so the durability
    ledger actually crosses compactions within a short run."""
    import socket

    from .services.app import App, AppConfig
    from .services.ingester import IngesterConfig

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    cfg = AppConfig(
        storage_path=storage, http_port=port,
        compaction_cycle_s=compaction_cycle_s,
        ingester=IngesterConfig(flush_check_period_s=1.0),
    )
    app = App(cfg)
    app.start()
    app.serve_http(background=True)
    return app, f"http://127.0.0.1:{port}"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tempo-tpu-vulture")
    ap.add_argument("--push-url", default="http://127.0.0.1:3200")
    ap.add_argument("--query-url", default="")
    ap.add_argument("--tenant", default="")
    ap.add_argument("--cycles", type=int, default=0, help="0 = forever")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--visibility-timeout", type=float, default=15.0)
    ap.add_argument("--flush-every", type=int, default=1,
                    help="cold-read probe cadence in cycles (0 = never "
                         "flush; needs loopback or --internal-token)")
    ap.add_argument("--internal-token", default="")
    ap.add_argument("--backend-path", default="",
                    help="backend storage path for fresh-reader cold "
                         "probes (every byte off disk)")
    ap.add_argument("--no-generator-probes", action="store_true",
                    help="skip the span_metrics / service_graph probes "
                         "(generated-series verification)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve vulture's own /metrics + /status here")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--self-hosted", action="store_true",
                    help="spawn an in-process single binary and probe it")
    args = ap.parse_args(argv)

    app = None
    push_url, query_url = args.push_url, args.query_url or args.push_url
    backend_path = args.backend_path
    if args.self_hosted:
        import tempfile

        storage = tempfile.mkdtemp(prefix="vulture-store-")
        app, base = _self_hosted_app(storage)
        push_url = query_url = base
        backend_path = backend_path or storage

    cfg = VultureConfig(
        push_url=push_url, query_url=query_url, tenant=args.tenant,
        visibility_timeout_s=args.visibility_timeout,
        flush_every=args.flush_every, internal_token=args.internal_token,
        backend_path=backend_path,
        generator_probes=not args.no_generator_probes, seed=args.seed,
    )
    v = Vulture(cfg, app=app)
    if args.metrics_port:
        v.serve_metrics(args.metrics_port)
        print(f"vulture metrics on :{args.metrics_port}", flush=True)

    all_ok = True
    try:
        n = 0
        while args.cycles == 0 or n < args.cycles:
            results = v.cycle()
            all_ok = all_ok and Vulture.ok(results)
            print(json.dumps({
                "cycle": v.cycles,
                "ok": Vulture.ok(results),
                "results": [{"family": r.family, "outcome": r.outcome,
                             "lag_ms": round(r.lag_s * 1e3, 1),
                             **({"detail": r.detail} if r.outcome != "ok"
                                else {}),
                             **({"self_trace_id": r.self_trace_id}
                                if r.self_trace_id else {})}
                            for r in results],
            }), flush=True)
            n += 1
            if args.cycles == 0 or n < args.cycles:
                time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        print(json.dumps({"summary": v.status()}, indent=2), flush=True)
        v.close()
        if app is not None:
            app.stop()
    return 0 if all_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
