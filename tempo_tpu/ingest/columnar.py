"""Batched columnar decode for the write path.

One OTLP push window decodes ONCE into flat coded features -- span
names and (attr key, lowered value) pairs as codes in the never-
remapping LiveDict, plus the segment's span-time bounds -- instead of
each consumer (live-search staging, WAL feature checkpoints, search
indexes) re-running the per-span Python object walk. The decode is
keyed by SEGMENT OBJECT IDENTITY: the ingester keeps one bytes object
per segment across the live/cut/flushing lifecycle, so the cache ref
IS the aliasing guard (holding the segment pins its id; an entry can
never be shadowed by a recycled id while it exists).

Lock order: callers may hold the livestage tail lock when computing
features (LiveStager._stage_trace_locked -> features_for); the cache
lock here is a leaf and never calls out while held.
"""

from __future__ import annotations

import threading
import time
from typing import NamedTuple

import numpy as np

from ..wire.segment import segment_to_trace


class LiveDict:
    """Append-only string<->code dictionary: codes are assigned in
    arrival order and NEVER remap (unlike block dictionaries, which
    sort+remap at finalize), so rows staged in earlier generations stay
    valid forever. Misses on lookup are exact prunes: a string absent
    here is provably absent from every staged row."""

    def __init__(self):
        self._lock = threading.Lock()
        self._code: dict[str, int] = {"": 0}
        self._strings: list[str] = [""]

    def code(self, s: str) -> int:
        with self._lock:
            c = self._code.get(s)
            if c is None:
                c = self._code[s] = len(self._strings)
                self._strings.append(s)
            return c

    def lookup(self, s: str) -> int:
        with self._lock:
            return self._code.get(s, -1)

    def string(self, code: int) -> str:
        with self._lock:
            return self._strings[code] if 0 <= code < len(self._strings) else ""

    def __len__(self) -> int:
        with self._lock:
            return len(self._strings)


def kv_pair_key(key: str, value: str) -> str:
    """Dictionary key for one (attr key, lowered value) membership pair
    -- a single code per pair keeps the tag test one equality on
    device. NUL can't appear in either half (attr keys and stringified
    values), so the join is collision-free."""
    return key + "\x00" + value


_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3
_U64 = (1 << 64) - 1


def _fnv1a_64(data: bytes, seed: int = _FNV64_OFFSET) -> int:
    """64-bit FNV-1a over raw bytes: the coded edge-store key hash.
    Python-side (runs inside the one-time decode walk); 64 bits keep
    accidental (trace, span) key collisions out of reach."""
    h = seed
    for b in data:
        h = ((h ^ b) * _FNV64_PRIME) & _U64
    return h


def edge_key_client(trace_id: bytes, span_id: bytes) -> int:
    """Coded pairing key for a CLIENT span: hash(trace_id || span_id).
    The matching SERVER span hashes (trace_id || parent_span_id) to the
    SAME integer, so client/server pairing is one dict probe on an int
    instead of a byte-tuple key. 0 is reserved for "no edge role"."""
    return _fnv1a_64(span_id, _fnv1a_64(trace_id)) or 1


class SpanColumns(NamedTuple):
    """Per-span coded columns for the streaming metrics-generator,
    filled inside the SAME decode that codes the search features. All
    arrays share span (document) order:

      svc_code/name_code  int32 LiveDict codes (resource service.name,
                          span name -- never remap, so series keys
                          assembled from them stay stable forever)
      kind/status         int32 raw enum values
      dur_s               float32 max(0, duration_nanos)/1e9 (exactly
                          the legacy processors' duration definition)
      edge_key            uint64 service-graph pairing key: CLIENT
                          spans hash (trace_id, span_id), SERVER spans
                          hash (trace_id, parent_span_id), others 0
      tid_hex             the segment's trace id (exemplars)
    """

    svc_code: np.ndarray
    name_code: np.ndarray
    kind: np.ndarray
    status: np.ndarray
    dur_s: np.ndarray
    edge_key: np.ndarray
    tid_hex: str


class SegFeatures(NamedTuple):
    """One segment's coded contribution to its trace's staged features.
    EXACTLY the per-span extraction services/ingester._SearchEntry.build
    performs, coded through the LiveDict: the union over a trace's
    segments is a conservative superset of the entry built from the
    combined trace (combine_traces dedupes by (span_id, start, name),
    so dropped duplicates only SHRINK the combined sets). lo/hi None =
    the segment carried no spans.

    `spans` (per-span generator columns) is optional: WAL replay seeds
    features from checkpointed strings WITHOUT a proto decode, and the
    generator tap only consumes freshly-pushed windows -- so replayed
    entries legitimately carry None here."""

    kv_codes: tuple[int, ...]
    name_codes: tuple[int, ...]
    lo_ns: int | None
    hi_ns: int | None
    spans: SpanColumns | None = None


# SpanKind values with a service-graph edge role (wire/model.py:
# SERVER=2, CLIENT=3)
_KIND_SERVER = 2
_KIND_CLIENT = 3


def span_columns_from_trace(tr, code) -> SpanColumns:
    """Per-span generator columns from an already-decoded Trace; `code`
    is a LiveDict.code bound method. Shared by compute_features (the
    write-path single decode) and the remote-generator push path (which
    receives decoded traces over /internal/genpush)."""
    svc: list[int] = []
    name: list[int] = []
    kind: list[int] = []
    status: list[int] = []
    dur: list[float] = []
    ekey: list[int] = []
    tid_hex = ""
    for res, _, sp in tr.all_spans():
        svc.append(code(res.service_name))
        name.append(code(sp.name))
        k = int(sp.kind)
        kind.append(k)
        status.append(int(sp.status_code))
        dur.append(max(0, sp.duration_nanos) / 1e9)
        if k == _KIND_CLIENT:
            ekey.append(edge_key_client(sp.trace_id, sp.span_id))
        elif k == _KIND_SERVER:
            ekey.append(edge_key_client(sp.trace_id, sp.parent_span_id))
        else:
            ekey.append(0)
        if not tid_hex and sp.trace_id:
            tid_hex = sp.trace_id.hex()
    return SpanColumns(
        np.asarray(svc, np.int32), np.asarray(name, np.int32),
        np.asarray(kind, np.int32), np.asarray(status, np.int32),
        np.asarray(dur, np.float32), np.asarray(ekey, np.uint64), tid_hex)


def compute_features(seg: bytes, ldict: LiveDict) -> SegFeatures:
    """Decode one segment's proto and code its features (first-seen
    order, deduped within the segment). The generator's per-span
    columns ride the same walk -- one decode serves search staging,
    WAL checkpoints AND the streaming metrics-generator."""
    tr = segment_to_trace(seg)
    code = ldict.code
    kv_codes: list[int] = []
    kv_seen: set[int] = set()
    name_codes: list[int] = []
    name_seen: set[int] = set()
    lo = hi = None
    for res, _, sp in tr.all_spans():
        c = code(sp.name)
        if c not in name_seen:
            name_seen.add(c)
            name_codes.append(c)
        for attrs in (sp.attrs, res.attrs):
            for k, v in attrs.items():
                c = code(kv_pair_key(k, str(v).lower()))
                if c not in kv_seen:
                    kv_seen.add(c)
                    kv_codes.append(c)
        if lo is None or sp.start_unix_nano < lo:
            lo = sp.start_unix_nano
        if hi is None or sp.end_unix_nano > hi:
            hi = sp.end_unix_nano
    return SegFeatures(tuple(kv_codes), tuple(name_codes), lo, hi,
                       span_columns_from_trace(tr, code))


class ColumnarIngest:
    """Per-instance columnar decode plane: one LiveDict shared by
    live-search staging and the WAL's feature checkpoints, plus the
    identity-keyed feature cache that makes 'decode once' true across
    consumers. Thread-safe; the internal lock is a leaf."""

    # cache ceiling (segments). Overflow evicts oldest-inserted half --
    # evicted entries recompute on next touch, so the cap only bounds
    # memory, never correctness.
    MAX_ENTRIES = 1 << 16

    def __init__(self, dictionary: LiveDict | None = None):
        self.dict = dictionary if dictionary is not None else LiveDict()
        self._lock = threading.Lock()
        # id(seg) -> (seg, SegFeatures); the held seg ref pins the id
        self._feats: dict[int, tuple[bytes, SegFeatures]] = {}
        self.decodes = 0  # proto decodes actually performed
        self.seeded = 0  # features installed without a decode (replay)

    # ------------------------------------------------------------ decode
    def features_for(self, seg: bytes) -> SegFeatures:
        """The segment's features, computing (and caching) on miss.
        This IS the batched-decode chokepoint: staging, WAL feature
        flushes and replay all read through here."""
        key = id(seg)
        with self._lock:
            ent = self._feats.get(key)
            if ent is not None:
                return ent[1]
        t0 = time.perf_counter()
        feat = compute_features(seg, self.dict)
        dt = time.perf_counter() - t0
        try:
            from ..util.kerneltel import TEL

            TEL.record_ingest_stage("decode", dt)
        except Exception:
            pass
        with self._lock:
            self.decodes += 1
            self._install_locked(key, seg, feat)
        return feat

    def decode_window(self, batch: list[tuple[bytes, int, int, bytes]]) -> list[SegFeatures]:
        """Eager decode of one push window's segments
        ([(tid, start_s, end_s, seg)]), returned in order."""
        return [self.features_for(seg) for _, _, _, seg in batch]

    def cached(self, seg: bytes) -> SegFeatures | None:
        """Cache-only lookup (never decodes): the WAL feature flush uses
        this so checkpointing never ADDS decode work to the write path."""
        with self._lock:
            ent = self._feats.get(id(seg))
            return ent[1] if ent is not None else None

    # ------------------------------------------------------------ replay
    def seed_strings(self, seg: bytes, kv: tuple[str, ...],
                     names: tuple[str, ...], lo_ns: int | None,
                     hi_ns: int | None) -> None:
        """Install replayed WAL feature strings as this instance's codes
        -- the no-proto-decode replay path. kv strings are the joined
        kv_pair_key form, exactly as the dictionary stores them."""
        feat = SegFeatures(tuple(self.dict.code(s) for s in kv),
                           tuple(self.dict.code(n) for n in names),
                           lo_ns, hi_ns)
        with self._lock:
            self.seeded += 1
            self._install_locked(id(seg), seg, feat)

    # ---------------------------------------------------------- lifecycle
    def discard(self, segs: list[bytes]) -> None:
        """Drop cache entries for segments leaving the live window (a
        flushed block landed, or the WAL head was cleared)."""
        with self._lock:
            for seg in segs:
                self._feats.pop(id(seg), None)

    def _install_locked(self, key: int, seg: bytes, feat: SegFeatures) -> None:
        if len(self._feats) >= self.MAX_ENTRIES:
            for k in list(self._feats)[: self.MAX_ENTRIES // 2]:
                del self._feats[k]
        self._feats[key] = (seg, feat)

    def stats(self) -> dict:
        with self._lock:
            return {"cached": len(self._feats), "decodes": self.decodes,
                    "seeded": self.seeded, "dict_size": len(self.dict)}
