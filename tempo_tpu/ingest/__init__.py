"""Device-native ingest: the write path's columnar subsystem.

Three planes (ISSUE 16): OTLP push windows append to the WAL as single
windowed records with per-record CRC (walcodec, the "w2" format db/wal
writes and replays); segments decode ONCE into coded features shared by
live-search staging, WAL feature checkpoints and flush-time block
assembly (columnar.ColumnarIngest over the never-remapping LiveDict);
and block cut runs its bloom bit-setting / dictionary remap / row-group
min-max work as device kernels (ops/blockcut, twins in ops/twins.py).
"""

from .columnar import (
    ColumnarIngest,
    LiveDict,
    SegFeatures,
    compute_features,
    kv_pair_key,
)
from .walcodec import WAL2_VERSION

__all__ = [
    "ColumnarIngest",
    "LiveDict",
    "SegFeatures",
    "WAL2_VERSION",
    "compute_features",
    "kv_pair_key",
]
