"""Columnar WAL v2 ("w2") record codec.

The v1 WAL frames ONE segment per record; a 20-trace OTLP export window
therefore pays 20 varint frames, 20 chaos-seam checks and 20 file
writes on the ack path, and replay re-decodes every segment's proto to
rebuild the live-search staging state. v2 keeps v1's OUTER framing
(`uvarint total_len | body`, so the native varint frame scanner and its
torn-tail detection work unchanged) but makes the body columnar:

  body    := uint32le crc32(payload) | payload
  payload := uint8 rec_type | ...

  rec_type 1 (WINDOW): one distributor push window, all traces in one
    record -- one frame, one CRC, one write per push:
      uint32le n_traces
      n_traces x ( trace_id[16] | uint32le start_s | uint32le end_s |
                   uint32le seg_len )
      concat(segment bytes)

  rec_type 2 (FEATURES): a lazy checkpoint of already-decoded segment
    features (ingest/columnar.SegFeatures) referencing earlier windows
    BY POSITION, with a file-local dictionary delta so codes are
    self-contained (multi-file replay order never matters):
      uint32le n_delta | n_delta x (uvarint len | utf8 string)
      uint32le n_entries
      n_entries x ( uint32le window_idx | uint32le trace_idx |
                    uint32le n_kv | n_kv x uint32le file_code |
                    uint32le n_names | n_names x uint32le file_code |
                    uint64le lo_ns | uint64le hi_ns )

A record whose CRC does not match (disk corruption, the chaos plane's
wal.append corrupt action) invalidates itself AND everything after it
-- the byte stream past a corruption cannot be trusted -- so readers
truncate there exactly like a torn tail. lo_ns/hi_ns use the all-ones
uint64 as the "unknown" sentinel (a segment with no spans).
"""

from __future__ import annotations

import struct
import zlib

from ..wire import pbwire as w

WAL2_VERSION = "w2"
REC_WINDOW = 1
REC_FEATURES = 2

NS_UNKNOWN = 0xFFFFFFFFFFFFFFFF  # lo/hi sentinel: no spans in segment

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_WIN_ENT = struct.Struct("<16sIII")  # trace_id, start_s, end_s, seg_len
_MIN_BODY = _U32.size + 1  # crc + rec_type


class CodecError(ValueError):
    pass


def _frame(parts: list[bytes]) -> bytes:
    """crc-prefix `parts` (the payload) and varint-frame the body."""
    payload = b"".join(parts)
    hdr = bytearray()
    w.write_varint(hdr, _U32.size + len(payload))
    return b"".join([bytes(hdr),
                     _U32.pack(zlib.crc32(payload) & 0xFFFFFFFF), payload])


def encode_window(batch: list[tuple[bytes, int, int, bytes]]) -> bytes:
    """One framed WINDOW record for [(trace_id, start_s, end_s, seg)]."""
    parts = [bytes([REC_WINDOW]), _U32.pack(len(batch))]
    parts.extend(_WIN_ENT.pack(tid.rjust(16, b"\x00"),
                               s & 0xFFFFFFFF, e & 0xFFFFFFFF, len(seg))
                 for tid, s, e, seg in batch)
    parts.extend(seg for _, _, _, seg in batch)
    return _frame(parts)


def encode_features(delta: list[str],
                    entries: list[tuple[int, int, list[int], list[int],
                                        int | None, int | None]]) -> bytes:
    """One framed FEATURES record. `delta` holds the strings for file
    codes assigned since the previous features record, in code order;
    entries are (window_idx, trace_idx, kv_file_codes, name_file_codes,
    lo_ns, hi_ns)."""
    parts = [bytes([REC_FEATURES]), _U32.pack(len(delta))]
    for s in delta:
        b = s.encode("utf-8")
        hdr = bytearray()
        w.write_varint(hdr, len(b))
        parts.append(bytes(hdr) + b)
    parts.append(_U32.pack(len(entries)))
    for w_idx, t_idx, kv, nm, lo, hi in entries:
        parts.append(_U32.pack(w_idx) + _U32.pack(t_idx))
        parts.append(_U32.pack(len(kv)) + b"".join(_U32.pack(c) for c in kv))
        parts.append(_U32.pack(len(nm)) + b"".join(_U32.pack(c) for c in nm))
        parts.append(_U64.pack(NS_UNKNOWN if lo is None else lo))
        parts.append(_U64.pack(NS_UNKNOWN if hi is None else hi))
    return _frame(parts)


def decode_record(data: bytes, off: int, ln: int):
    """Parse one framed BODY (data[off:off+ln], outer varint already
    consumed). Returns (rec_type, parsed) or None when the CRC or the
    shape rejects the record (readers treat that as corruption and stop
    there). Window parse -> [(tid, start_s, end_s, segment)]; features
    parse -> (delta_strings, entries)."""
    if ln < _MIN_BODY:
        return None
    end = off + ln
    (crc,) = _U32.unpack_from(data, off)
    payload = data[off + _U32.size : end]
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        return None
    rtype = payload[0]
    try:
        if rtype == REC_WINDOW:
            return REC_WINDOW, _decode_window(payload)
        if rtype == REC_FEATURES:
            return REC_FEATURES, _decode_features(payload)
    except (CodecError, struct.error, ValueError, UnicodeDecodeError):
        return None
    return None  # unknown record type: written by a future version


def _decode_window(payload: bytes):
    pos = 1
    (n,) = _U32.unpack_from(payload, pos)
    pos += _U32.size
    heads = []
    for _ in range(n):
        tid, s, e, seg_len = _WIN_ENT.unpack_from(payload, pos)
        pos += _WIN_ENT.size
        heads.append((tid, s, e, seg_len))
    out = []
    for tid, s, e, seg_len in heads:
        if pos + seg_len > len(payload):
            raise CodecError("window segment overruns record")
        out.append((tid, s, e, payload[pos : pos + seg_len]))
        pos += seg_len
    if pos != len(payload):
        raise CodecError("trailing bytes in window record")
    return out


def _decode_features(payload: bytes):
    pos = 1
    (n_delta,) = _U32.unpack_from(payload, pos)
    pos += _U32.size
    delta = []
    for _ in range(n_delta):
        ln, pos = w.read_varint(payload, pos)
        if pos + ln > len(payload):
            raise CodecError("delta string overruns record")
        delta.append(payload[pos : pos + ln].decode("utf-8"))
        pos += ln
    (n_ent,) = _U32.unpack_from(payload, pos)
    pos += _U32.size
    entries = []
    for _ in range(n_ent):
        w_idx, t_idx = _U32.unpack_from(payload, pos)[0], _U32.unpack_from(payload, pos + 4)[0]
        pos += 8
        (n_kv,) = _U32.unpack_from(payload, pos)
        pos += _U32.size
        kv = list(struct.unpack_from(f"<{n_kv}I", payload, pos))
        pos += 4 * n_kv
        (n_nm,) = _U32.unpack_from(payload, pos)
        pos += _U32.size
        nm = list(struct.unpack_from(f"<{n_nm}I", payload, pos))
        pos += 4 * n_nm
        (lo,) = _U64.unpack_from(payload, pos)
        (hi,) = _U64.unpack_from(payload, pos + 8)
        pos += 16
        entries.append((w_idx, t_idx, kv, nm,
                        None if lo == NS_UNKNOWN else lo,
                        None if hi == NS_UNKNOWN else hi))
    if pos != len(payload):
        raise CodecError("trailing bytes in features record")
    return delta, entries
