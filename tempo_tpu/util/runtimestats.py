"""Runtime health gauges: the Go-runtime metrics the reference gets
for free (goroutines, GC pauses, RSS), for a CPython process.

  tempo_runtime_gc_collections_total{generation}  via gc.callbacks
  tempo_runtime_gc_pause_seconds{generation}      stop-the-world pause
  tempo_runtime_threads                           live thread count
  tempo_runtime_rss_bytes                         resident set size
  tempo_runtime_open_fds                          open file descriptors

Counters accumulate from the moment install() first runs (the app
installs at start; the /metrics chokepoint installs lazily as a
belt-and-braces). Point-in-time gauges refresh at scrape.
"""

from __future__ import annotations

import gc
import os
import threading
import time

from .metrics import Counter, Gauge, Histogram

# CPython gen-0 sweeps run sub-ms; a gen-2 pass over a large heap can
# stall tens of ms -- exactly the tail-latency blip worth a bucket edge
GC_PAUSE_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025,
                    0.05, 0.1, 0.25, 1.0)

GC_COLLECTIONS = Counter(
    "tempo_runtime_gc_collections_total",
    help="CPython garbage collections by generation")
GC_PAUSE = Histogram(
    "tempo_runtime_gc_pause_seconds", buckets=GC_PAUSE_BUCKETS,
    help="CPython GC stop-the-world pause by generation")
THREADS = Gauge("tempo_runtime_threads",
                help="live Python threads (the goroutine-count analog)")
RSS = Gauge("tempo_runtime_rss_bytes",
            help="resident set size of this process")
OPEN_FDS = Gauge("tempo_runtime_open_fds",
                 help="open file descriptors of this process")

_install_lock = threading.Lock()
_installed = False
_gc_lock = threading.Lock()
_gc_t0: dict[int, float] = {}  # generation -> collection start


def _gc_cb(phase: str, info: dict) -> None:
    try:
        gen = int(info.get("generation", 0))
        if phase == "start":
            with _gc_lock:
                _gc_t0[gen] = time.perf_counter()
            return
        with _gc_lock:
            t0 = _gc_t0.pop(gen, None)
        GC_COLLECTIONS.inc(labels=f'generation="{gen}"')
        if t0 is not None:
            GC_PAUSE.observe(time.perf_counter() - t0,
                             f'generation="{gen}"')
    except Exception:
        pass  # a GC callback must never raise into the collector


def install() -> None:
    """Register the GC callback once per process."""
    global _installed
    with _install_lock:
        if _installed:
            return
        gc.callbacks.append(_gc_cb)
        _installed = True


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:
        try:
            import resource

            # ru_maxrss is KiB on Linux: peak, not current -- still a
            # usable ceiling where /proc is absent
            return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        except Exception:
            return 0


def _open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def refresh() -> None:
    THREADS.set(threading.active_count())
    RSS.set(_rss_bytes())
    OPEN_FDS.set(_open_fds())


def metrics_lines() -> list[str]:
    install()  # lazy belt-and-braces: scrape implies counting
    refresh()
    return (GC_COLLECTIONS.text() + GC_PAUSE.text() + THREADS.text()
            + RSS.text() + OPEN_FDS.text())


def help_entries() -> dict[str, str]:
    return {
        "tempo_runtime_gc_collections": GC_COLLECTIONS.help,
        "tempo_runtime_gc_pause_seconds": GC_PAUSE.help,
        "tempo_runtime_threads": THREADS.help,
        "tempo_runtime_rss_bytes": RSS.help,
        "tempo_runtime_open_fds": OPEN_FDS.help,
    }
