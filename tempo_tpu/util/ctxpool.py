"""Context-propagating thread pool.

ThreadPoolExecutor workers run with the contextvars of whatever thread
happened to create them, so ambient query attribution -- the active
self-trace (util/kerneltel set_active_trace) and the affinity dequeue
placement -- silently vanished on every pooled leg: staged-cache probes
attributed to "none", engine spans dropped on the floor. This subclass
captures the SUBMITTING thread's context per task and runs the callable
under a copy, the same fix services/querier.py applies to its own pool.
Executor.map routes through submit, so both entry points propagate.
"""

from __future__ import annotations

import contextvars
from concurrent.futures import ThreadPoolExecutor


class ContextThreadPool(ThreadPoolExecutor):
    def submit(self, fn, /, *args, **kwargs):
        ctx = contextvars.copy_context()
        return super().submit(ctx.run, fn, *args, **kwargs)
