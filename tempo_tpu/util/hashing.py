"""Hashing primitives used for ring tokens, bloom filters and dedupe.

The reference derives ring tokens with 32-bit FNV-1 over tenant+traceID
(pkg/util/hash.go:7-16) and hashes bloom keys with xxhash via willf/bloom.
We standardise on FNV-1a (public domain algorithm) for tokens and a
splitmix64-style mix for bloom key derivation; both are reimplemented
here from the published algorithm definitions, not from reference code.
"""

from __future__ import annotations

_FNV32_OFFSET = 0x811C9DC5
_FNV32_PRIME = 0x01000193
_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x00000100000001B3
_MASK32 = 0xFFFFFFFF
_MASK64 = 0xFFFFFFFFFFFFFFFF


def fnv1a_32(data: bytes) -> int:
    h = _FNV32_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV32_PRIME) & _MASK32
    return h


def fnv1a_64(data: bytes) -> int:
    h = _FNV64_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & _MASK64
    return h


def ring_token(tenant: str, trace_id: bytes) -> int:
    """32-bit placement token for a (tenant, trace id) pair.

    Same shape as the reference's TokenFor (pkg/util/hash.go:7-16): one
    32-bit hash over tenant-then-id decides the owning ring segment.
    """
    return fnv1a_32(tenant.encode("utf-8") + trace_id)


def splitmix64(x: int) -> int:
    """One round of the splitmix64 finalizer (public domain constant set)."""
    x = (x + 0x9E3779B97F4A7C15) & _MASK64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return (z ^ (z >> 31)) & _MASK64


def bloom_hashes(key: bytes, k: int, m_bits: int) -> list[int]:
    """k bit positions for `key` in an m_bits bloom via double hashing.

    h_i = h1 + i*h2 (Kirsch-Mitzenmacher double hashing) keeps this a
    two-hash computation host-side and a pure gather on device.
    """
    h1 = fnv1a_64(key)
    h2 = splitmix64(h1) | 1  # odd => full-period stepping
    return [((h1 + i * h2) & _MASK64) % m_bits for i in range(k)]
