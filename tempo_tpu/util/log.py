"""Structured logging shim: JSON lines with level + component.

The reference leans on go-kit structured logging everywhere; this repo
had one stdlib logging call and half a dozen bare stderr prints. This
shim is the single seam they migrate onto:

  * one JSON object per line on stderr: ts, level, component, msg --
    machine-parseable by any log pipeline without a format contract;
  * the ambient self-trace id (kerneltel's active trace) is attached
    when present, so a log line from deep in a query links straight to
    its timeline (`tempo-tpu-cli self-trace <id>`);
  * rate-limited repeat suppression: the same (component, template)
    emits once per window, repeats are counted and surfaced as
    `repeats_suppressed` on the next emission -- a hot failing loop
    cannot flood stderr;
  * tempo_log_messages_total{level,component} counts every message
    that passes the level filter (suppressed repeats included: they
    happened, they just didn't print), exported through the kerneltel
    /metrics chokepoint.

Stdlib-only and import-light on purpose: the analysis CLI (stdlib-only
by contract) and the earliest startup paths use it too.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

from .metrics import Counter

LEVEL_ENV = "TEMPO_LOG_LEVEL"
REPEAT_WINDOW_S = 10.0
_REPEAT_KEYS_MAX = 512

_LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}

MESSAGES = Counter(
    "tempo_log_messages_total",
    help="structured log messages by level and component "
         "(rate-suppressed repeats included)")

_state_lock = threading.Lock()
# (component, template) -> [window_start_monotonic, suppressed_count]
_repeats: dict[tuple[str, str], list] = {}


def _threshold() -> int:
    return _LEVELS.get(os.environ.get(LEVEL_ENV, "").lower(), 20)


def _esc(v: str) -> str:
    return (v.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))


def _active_trace_hex() -> str:
    """Ambient self-trace id, if a query is executing on this thread.
    Lazy + guarded: log must work before (and without) kerneltel."""
    try:
        from .kerneltel import TEL

        t = TEL.active_trace()
        tid = getattr(t, "trace_id", None)
        return tid.hex() if tid is not None else ""
    except Exception:
        return ""


class Logger:
    __slots__ = ("component",)

    def __init__(self, component: str):
        self.component = component

    # printf-style args match the stdlib logging call sites this shim
    # replaces; keyword fields land as extra JSON keys
    def debug(self, msg: str, *args, **fields) -> None:
        self._emit("debug", msg, args, fields)

    def info(self, msg: str, *args, **fields) -> None:
        self._emit("info", msg, args, fields)

    def warning(self, msg: str, *args, **fields) -> None:
        self._emit("warning", msg, args, fields)

    def error(self, msg: str, *args, **fields) -> None:
        self._emit("error", msg, args, fields)

    def _emit(self, level: str, msg: str, args: tuple, fields: dict) -> None:
        try:
            if _LEVELS[level] < _threshold():
                return
            MESSAGES.inc(labels=f'level="{level}",'
                                f'component="{_esc(self.component)}"')
            now = time.monotonic()
            key = (self.component, msg)
            with _state_lock:
                st = _repeats.get(key)
                if st is not None and now - st[0] < REPEAT_WINDOW_S:
                    st[1] += 1  # suppressed: counted, not printed
                    return
                suppressed = st[1] if st is not None else 0
                _repeats[key] = [now, 0]
                if len(_repeats) > _REPEAT_KEYS_MAX:
                    # bounded: drop the stalest window
                    oldest = min(_repeats, key=lambda k: _repeats[k][0])
                    _repeats.pop(oldest, None)
            rec = {
                "ts": round(time.time(), 3),
                "level": level,
                "component": self.component,
                "msg": (msg % args) if args else msg,
            }
            trace_hex = _active_trace_hex()
            if trace_hex:
                rec["trace_id"] = trace_hex
            if suppressed:
                rec["repeats_suppressed"] = suppressed
            if fields:
                rec.update(fields)
            sys.stderr.write(json.dumps(rec) + "\n")
            sys.stderr.flush()
        except Exception:
            pass  # logging must never fail the caller


def get_logger(component: str) -> Logger:
    return Logger(component)


def metrics_lines() -> list[str]:
    return MESSAGES.text()


def help_entries() -> dict[str, str]:
    return {"tempo_log_messages": MESSAGES.help}
