"""Declarative SLOs evaluated as multi-window burn rates.

The role the reference's operations/tempo-mixin SLO recording rules
play, in-process: an Objective names a target ("99.9% of reads
succeed", "99% of searches under 2.5 s", "99% of pushes live-visible
within 2.5 s") over a cumulative SLI source -- an existing
util/metrics Counter or Histogram -- and the engine turns the
cumulative totals into windowed error rates by snapshotting them over
time and differencing against the window start.

Burn rate (Google SRE Workbook ch. 5): the ratio of the observed error
rate to the rate that would exactly exhaust the error budget over the
SLO period. burn == 1 means "spending budget exactly on schedule";
14.4 over both a short and a long window is the classic page-now pair
(2% of a 30-day budget gone in one hour). Multi-window evaluation
(5m/1h/6h here) keeps the signal fast AND debounced: the short window
detects, the long window confirms, and recovery resets the short
window first.

Windows shorter than the collected history evaluate against the oldest
sample (a partial window): a freshly-started process reports honest
burn from its first two samples instead of silence, which is exactly
what the injected-regression matrix in tests/test_vulture.py relies
on -- a red probe drives every window critical within one cycle.

No traffic is not an outage: a window whose good+bad delta is zero
reports burn 0.0.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable

from .metrics import Counter, Gauge, Histogram

# (label, seconds) evaluation windows, short to long
DEFAULT_WINDOWS: tuple[tuple[str, int], ...] = (
    ("5m", 300), ("1h", 3600), ("6h", 21600))

# page when BOTH the fast pair burns above this (SRE workbook: 14.4 =
# 2% of a 30-day budget in 1h)
FAST_BURN = 14.4
# warn when the slow pair burns above this (6 = 5% of the budget in 6h)
SLOW_BURN = 6.0

VERDICTS = ("ok", "warning", "critical")

# every gauge family an SLOEngine can emit, over the name_prefix values
# actually instantiated (services/app.py default + vulture.py): the
# names are built with f-strings the telemetry contract checker cannot
# see through, so the families are declared here instead
METRIC_FAMILIES = (
    "tempo_slo_burn_rate", "tempo_slo_verdict",
    "tempo_vulture_slo_burn_rate", "tempo_vulture_slo_verdict",
)


@dataclass
class Objective:
    """One declarative objective. `sli` returns CUMULATIVE (good, bad)
    event totals; the engine does the windowing. `target` is the good
    fraction promised (0.999 leaves a 0.1% error budget)."""

    name: str
    kind: str  # availability | freshness | latency
    target: float
    sli: Callable[[], tuple[float, float]]
    description: str = ""


def counter_sli(counter: Counter,
                good: Callable[[str], bool],
                bad: Callable[[str], bool]) -> Callable[[], tuple[float, float]]:
    """SLI over a labeled Counter: classify each label set as good,
    bad, or neither (excluded -- e.g. QoS sheds, which are the budget
    system working, not the serving path failing)."""

    def read() -> tuple[float, float]:
        g = b = 0.0
        for labels, v in counter.snapshot().items():
            if good(labels):
                g += v
            elif bad(labels):
                b += v
        return g, b

    return read


def histogram_sli(hist: Histogram, threshold: float,
                  labels_pred: Callable[[str], bool] | None = None
                  ) -> Callable[[], tuple[float, float]]:
    """Latency/freshness SLI over a Histogram: observations in buckets
    whose upper edge is <= threshold are good, the rest (including the
    +Inf overflow) are bad. The threshold should sit on a bucket edge;
    anything between edges rounds down to the nearest edge, so the SLI
    never claims credit the histogram can't prove."""

    def read() -> tuple[float, float]:
        g = total = 0.0
        for labels, (counts, _s, n) in hist.snapshot().items():
            if labels_pred is not None and not labels_pred(labels):
                continue
            total += n
            g += sum(c for c, edge in zip(counts, hist.buckets)
                     if edge <= threshold)
        return g, total - g

    return read


def freshness_objective(name: str, hist_fn: Callable[[], Histogram],
                        threshold: float, description: str = "",
                        target: float = 0.99) -> Objective:
    """Push->visible freshness objective over a lag histogram (live
    staging lag, generator series-visible lag). `hist_fn` resolves the
    Histogram at EVALUATION time: kerneltel's TEL.reset() (tests) swaps
    instrument objects, and binding the object at registration would
    silently freeze the SLI on the dead one. The threshold should sit
    on a bucket edge (histogram_sli's rounding rule)."""

    def sli() -> tuple[float, float]:
        return histogram_sli(hist_fn(), threshold)()

    return Objective(name=name, kind="freshness", target=target,
                     sli=sli, description=description)


class SLOEngine:
    """Evaluates registered objectives into per-window burn rates,
    verdicts, and exposition gauges.

    `name_prefix` namespaces the gauge families so the app's engine
    (tempo_slo_*) and vulture's own engine (tempo_vulture_slo_*) can
    coexist on different /metrics endpoints of one process."""

    def __init__(self, windows: tuple[tuple[str, int], ...] = DEFAULT_WINDOWS,
                 name_prefix: str = "tempo_slo"):
        self.windows = tuple(windows)
        self._objectives: dict[str, Objective] = {}
        # name -> deque[(unix, good, bad)]; bounded to the longest
        # window plus slack at the minimum sane sample cadence
        self._history: dict[str, deque] = {}
        self._lock = threading.Lock()
        self._max_age = max(w for _, w in self.windows) * 1.25
        self._history_max = 4096
        # minimum spacing between RETAINED samples: evaluate() fires
        # per scrape + per /status/slo request + from the background
        # loop, and without thinning a busy scrape cadence would
        # rotate the bounded deque below the longest window -- the
        # "6h" burn would silently difference against a younger ref.
        # Burn math reads the CURRENT cumulative totals fresh each
        # evaluation, so skipping an append loses no accuracy.
        self._min_sample_gap = self._max_age / (self._history_max / 2)
        self.burn_gauge = Gauge(
            f"{name_prefix}_burn_rate",  # families: see METRIC_FAMILIES
            help="error-budget burn rate by objective and window "
                 "(1.0 = spending the budget exactly on schedule)")
        self.verdict_gauge = Gauge(
            f"{name_prefix}_verdict",
            help="objective verdict (0 ok, 1 warning, 2 critical)")
        self._last_status: dict = {"objectives": {}, "verdict": "ok"}
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ config
    def register(self, obj: Objective) -> Objective:
        with self._lock:
            self._objectives[obj.name] = obj
            self._history[obj.name] = deque(maxlen=self._history_max)
        return obj

    def objectives(self) -> list[Objective]:
        with self._lock:
            return list(self._objectives.values())

    # ---------------------------------------------------------- evaluate
    @staticmethod
    def _verdict(burns: dict[str, float]) -> str:
        """Multi-window verdict: fast pair (shortest two windows) both
        over FAST_BURN pages; slow pair (longest two) both over
        SLOW_BURN warns. Partial windows fall back to the oldest
        sample, so early in a process's life the pairs agree and a
        hard failure still pages immediately."""
        vals = list(burns.values())
        if len(vals) >= 2 and vals[0] > FAST_BURN and vals[1] > FAST_BURN:
            return "critical"
        if len(vals) >= 2 and vals[-2] > SLOW_BURN and vals[-1] > SLOW_BURN:
            return "warning"
        return "ok"

    def evaluate(self, now: float | None = None) -> dict:
        """Snapshot every objective's SLI, difference against each
        window, publish gauges, and return the /status/slo payload.
        `now` is injectable for tests."""
        now = time.time() if now is None else float(now)
        with self._lock:
            objs = list(self._objectives.values())
        out: dict[str, dict] = {}
        worst = "ok"
        for obj in objs:
            try:
                good, bad = obj.sli()
            except Exception as e:  # an SLI source must not kill the plane
                out[obj.name] = {"error": f"{type(e).__name__}: {e}"}
                continue
            with self._lock:
                hist = self._history[obj.name]
                if not hist or now - hist[-1][0] >= self._min_sample_gap:
                    hist.append((now, float(good), float(bad)))
                while hist and hist[0][0] < now - self._max_age:
                    hist.popleft()
                samples = list(hist)
            burns: dict[str, float] = {}
            for wname, wsec in self.windows:
                ref = samples[0]
                for s in samples:
                    if s[0] <= now - wsec:
                        ref = s
                    else:
                        break
                dg, db = good - ref[1], bad - ref[2]
                total = dg + db
                err_rate = (db / total) if total > 0 else 0.0
                burn = err_rate / max(1e-9, 1.0 - obj.target)
                burns[wname] = round(burn, 4)
                self.burn_gauge.set(
                    burn, labels=f'objective="{obj.name}",window="{wname}"')
            verdict = self._verdict(burns)
            self.verdict_gauge.set(VERDICTS.index(verdict),
                                   labels=f'objective="{obj.name}"')
            if VERDICTS.index(verdict) > VERDICTS.index(worst):
                worst = verdict
            out[obj.name] = {
                "kind": obj.kind,
                "target": obj.target,
                "description": obj.description,
                "good_total": round(float(good), 3),
                "bad_total": round(float(bad), 3),
                "burn_rates": burns,
                "verdict": verdict,
            }
        status = {"objectives": out, "verdict": worst,
                  "windows": dict(self.windows),
                  "evaluated_at_unix": round(now, 3)}
        with self._lock:
            self._last_status = status
        return status

    def status(self) -> dict:
        """Most recent evaluation (without re-evaluating)."""
        with self._lock:
            return self._last_status

    # -------------------------------------------------------- exposition
    def metrics_lines(self) -> list[str]:
        return self.burn_gauge.text() + self.verdict_gauge.text()

    def help_entries(self) -> dict[str, str]:
        return {self.burn_gauge.name: self.burn_gauge.help,
                self.verdict_gauge.name: self.verdict_gauge.help}

    # --------------------------------------------------------- lifecycle
    def start(self, interval_s: float = 15.0) -> None:
        """Background evaluator so gauges stay fresh for scrapes even
        when nobody hits /status/slo."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.evaluate()
                except Exception:  # noqa: BLE001 - evaluator must survive
                    pass

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="slo-evaluator")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
