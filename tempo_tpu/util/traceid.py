"""Trace-ID helpers.

Mirrors the reference's hex parse/pad semantics (pkg/util/traceid.go):
IDs are 128-bit, hex strings may arrive shorter (Jaeger 64-bit ids) and
are left-padded with zeros to 16 bytes.
"""

from __future__ import annotations

TRACE_ID_LEN = 16
SPAN_ID_LEN = 8


class InvalidTraceID(ValueError):
    pass


def parse_trace_id(hex_id: str) -> bytes:
    s = hex_id.strip().lower()
    if s.startswith("0x"):
        s = s[2:]
    if not s or len(s) > 2 * TRACE_ID_LEN:
        raise InvalidTraceID(f"trace id must be 1-32 hex chars, got {hex_id!r}")
    try:
        raw = bytes.fromhex(s.zfill(2 * TRACE_ID_LEN))
    except ValueError as e:
        raise InvalidTraceID(f"invalid hex in trace id {hex_id!r}") from e
    return raw


def pad_trace_id(tid: bytes) -> bytes:
    if len(tid) > TRACE_ID_LEN:
        raise InvalidTraceID(f"trace id longer than 16 bytes: {len(tid)}")
    return tid.rjust(TRACE_ID_LEN, b"\x00")


def trace_id_to_hex(tid: bytes) -> str:
    return pad_trace_id(tid).hex()
