from .hashing import fnv1a_32, fnv1a_64, ring_token
from .traceid import parse_trace_id, trace_id_to_hex, pad_trace_id
