"""Device cost observability: program cost analysis, collective comm
accounting, and the HBM ledger.

kerneltel (PR 2) says how long each kernel RAN; this module says what
each kernel COSTS and whether the time was well spent:

  * **Program cost analysis** -- on every new compile (the
    TEL.record_launch chokepoint passes a capture thunk), a background
    worker lowers the same program against abstract avals and records
    XLA's own `cost_analysis()` (FLOPs, bytes accessed) and
    `memory_analysis()` (argument/output/temp/code bytes) per
    (op, shape-bucket). Paired with kerneltel's measured wall-time
    histograms this yields achieved-vs-roofline utilization per kernel
    in /status/cost. Capture happens OFF the query path: the hot path
    only enqueues ShapeDtypeStructs (never live device arrays).

  * **Collective comm accounting** -- for mesh programs the capture
    also traces a jaxpr and statically walks it for collectives
    (all_gather / psum / pmax / pmin / psum_scatter / reduce_scatter /
    all_to_all / ppermute), pricing each with the standard ring-
    algorithm model (see collective_comm_bytes) times the number of
    independent device groups. Per-launch bytes x launch counts feed
    `tempo_mesh_comm_bytes_total{collective,op}` -- ROADMAP item 2(a)'s
    "how big IS the struct-op all_gather" made a first-class series.

  * **HBM ledger** -- one device-memory view unifying the staged
    block-column cache (ops/stage), live-head staging tails
    (ops/livestage) and the compiled-program footprint, cross-checked
    against device.memory_stats() where the backend provides it, with
    watermark gauges feeding the TempoHBMPressure alert.

  * **Persistent compilation cache** -- TEMPO_COMPILE_CACHE_DIR (env or
    --compile-cache.dir) turns on jax's disk compilation cache so
    restarts stop paying the first-compile storm;
    `tempo_kernel_compile_disk_total{outcome}` (fed by jax.monitoring
    events) splits disk-cache hits from fresh XLA compiles, the
    complement of kerneltel's in-process jit-cache-hit counter.

Kill switches: TEMPO_COSTMODEL=0 disables capture entirely (launch
counting stays, it is two dict increments); TEMPO_COSTMODEL_MEMORY=0
skips the background `compile()` that memory_analysis needs, keeping
capture to trace+lower. Everything here is advisory: no method may
raise into the query path.
"""

from __future__ import annotations

import os
import threading
import time

# peak HBM bandwidth per chip for the roofline denominator (v5e: 819
# GB/s; axon is the tunneled TPU platform the dev boxes expose).
# Unknown platforms (cpu) report utilization 0.0 = "no roofline".
HBM_PEAK_BPS = {"tpu": 819e9, "axon": 819e9}

# collectives the comm walker prices (jaxpr primitive names)
_COLLECTIVES = ("all_gather", "psum", "pmax", "pmin", "psum_scatter",
                "reduce_scatter", "all_to_all", "ppermute")


def _aval_bytes(aval) -> int:
    try:
        return int(aval.size) * int(aval.dtype.itemsize)
    except Exception:
        return 0


def _axis_group_size(params, mesh_axis_sizes: dict[str, int]) -> int:
    """Number of devices participating in one collective group: the
    product of the collective's named axes' sizes."""
    axes = params.get("axis_name", params.get("axes", ()))
    if isinstance(axes, str):
        axes = (axes,)
    k = 1
    for a in axes or ():
        k *= int(mesh_axis_sizes.get(a, 1))
    return max(k, 1)


def _sub_jaxprs(params):
    for v in params.values():
        if hasattr(v, "eqns"):
            yield v
        elif hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
            yield v.jaxpr
        elif isinstance(v, (list, tuple)):
            for b in v:
                if hasattr(b, "eqns"):
                    yield b
                elif hasattr(b, "jaxpr") and hasattr(b.jaxpr, "eqns"):
                    yield b.jaxpr


def ring_wire_bytes(name: str, in_bytes: int, out_bytes: int, k: int) -> int:
    """Wire bytes one collective moves for ONE group of k devices under
    the standard ring algorithms (the walker's pricing model, exported
    so tests and the mesh-batch bench can hand-compute the expected
    totals and cross-check the jaxpr walk):
      all_gather      out_bytes x (k-1)   (each of k receives the
                                           (k-1)/k it lacks)
      psum/pmax/pmin  2 x in_bytes x (k-1)  (ring all-reduce)
      ppermute        in_bytes x k          (every shard moves)
      psum_scatter / reduce_scatter /
      all_to_all      in_bytes x (k-1)"""
    if name == "all_gather":
        return out_bytes * (k - 1)
    if name in ("psum", "pmax", "pmin"):
        return 2 * in_bytes * (k - 1)
    if name == "ppermute":
        return in_bytes * k
    return in_bytes * (k - 1)  # psum_scatter / reduce_scatter / all_to_all


def collective_comm_bytes(jaxpr, mesh_axis_sizes: dict[str, int],
                          total_devices: int) -> dict[str, int]:
    """Statically price every collective in a jaxpr: fleet-wide wire
    bytes per program execution, by collective name.

    Model: ring_wire_bytes (k = devices in one collective group) times
    g = total_devices / k independent groups running the collective.
    Shapes inside shard_map are PER-SHARD; in/out bytes are the
    eqn's own aval bytes, so the model needs no sharding inference.
    Recursion: sub-jaxprs (pjit/shard_map/custom calls) count once,
    `scan` bodies multiply by the trip count, `cond` branches take the
    max (conservative for routing, never an undercount of the worst
    branch)."""
    out: dict[str, int] = {}

    def add(dst: dict[str, int], src: dict[str, int], mul: int = 1) -> None:
        for kk, vv in src.items():
            dst[kk] = dst.get(kk, 0) + vv * mul

    def walk(jx) -> dict[str, int]:
        acc: dict[str, int] = {}
        for eqn in jx.eqns:
            name = eqn.primitive.name
            if name in _COLLECTIVES:
                k = _axis_group_size(eqn.params, mesh_axis_sizes)
                groups = max(1, total_devices // k)
                in_b = sum(_aval_bytes(v.aval) for v in eqn.invars
                           if hasattr(v, "aval"))
                out_b = sum(_aval_bytes(v.aval) for v in eqn.outvars)
                wire = ring_wire_bytes(name, in_b, out_b, k)
                acc[name] = acc.get(name, 0) + wire * groups
            if name == "cond":
                branches = [walk(b.jaxpr if hasattr(b, "jaxpr") else b)
                            for b in eqn.params.get("branches", ())]
                if branches:
                    worst: dict[str, int] = {}
                    for b in branches:
                        for kk in set(worst) | set(b):
                            worst[kk] = max(worst.get(kk, 0), b.get(kk, 0))
                    add(acc, worst)
                continue
            mul = int(eqn.params.get("length", 1)) if name == "scan" else 1
            for sub in _sub_jaxprs(eqn.params):
                add(acc, walk(sub), mul)
        return acc

    add(out, walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr))
    return out


# --------------------------------------------------------- program specs


class ProgramSpec:
    """Everything the background worker needs to re-derive one compiled
    program's costs: the jitted callable plus ABSTRACT argument avals
    (built eagerly at the call site, so the spec never pins live device
    arrays), and the mesh shape for comm pricing (None = single-device
    program, no jaxpr walk)."""

    __slots__ = ("fn", "args", "kwargs", "mesh_axis_sizes", "mesh_devices")

    def __init__(self, fn, args, kwargs, mesh_axis_sizes, mesh_devices):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.mesh_axis_sizes = mesh_axis_sizes
        self.mesh_devices = mesh_devices


def spec(fn, *args, mesh=None, **kwargs) -> ProgramSpec:
    """Build a capture spec at a launch site. Array-likes (anything with
    a dtype) become ShapeDtypeStructs; python ints/bools/strings pass
    through untouched so static args still key the lowering."""
    import jax

    def absify(x):
        if hasattr(x, "dtype") and hasattr(x, "shape"):
            import numpy as np

            return jax.ShapeDtypeStruct(tuple(x.shape), np.dtype(x.dtype))
        return x

    a_args = jax.tree_util.tree_map(absify, args)
    a_kwargs = jax.tree_util.tree_map(absify, kwargs)
    axis_sizes = dict(mesh.shape) if mesh is not None else None
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    return ProgramSpec(fn, a_args, a_kwargs, axis_sizes, n_dev)


# ------------------------------------------------------------ cost model


class CostModel:
    """Process-wide capture store + background analysis worker."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: list[tuple[str, str, ProgramSpec]] = []
        self._pending = 0
        self._worker: threading.Thread | None = None
        # (op, bucket) -> analysis row (last capture wins; one row per
        # shape bucket is the granularity the kernel table also uses)
        self._programs: dict[tuple[str, str], dict] = {}
        self._launches: dict[tuple[str, str], int] = {}
        self._captures = 0
        self._capture_errors = 0
        self._hbm_peak = 0

    # ------------------------------------------------------------ config
    @staticmethod
    def enabled() -> bool:
        return os.environ.get("TEMPO_COSTMODEL", "1") != "0"

    @staticmethod
    def _memory_enabled() -> bool:
        return os.environ.get("TEMPO_COSTMODEL_MEMORY", "1") != "0"

    # ----------------------------------------------------------- capture
    def note_launch(self, op: str, bucket_label: str) -> None:
        """Every kernel launch (compile or cache hit) lands here from
        record_launch: launch counts turn per-program comm bytes into
        the tempo_mesh_comm_bytes_total counter."""
        with self._lock:
            key = (op, bucket_label)
            self._launches[key] = self._launches.get(key, 0) + 1

    def enqueue(self, op: str, bucket_label: str, program: ProgramSpec) -> None:
        """Queue one new program for background analysis."""
        if not self.enabled():
            return
        with self._cv:
            self._queue.append((op, bucket_label, program))
            self._pending += 1
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._run, daemon=True, name="costmodel")
                self._worker.start()
            self._cv.notify_all()

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait for queued captures to finish (tests, /status/cost)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue:
                    self._cv.wait()
                op, blab, program = self._queue.pop(0)
            try:
                entry = self._analyze(program)
            except Exception as e:  # the worker must outlive any one capture
                entry = {"flops": 0.0, "bytes_accessed": 0.0,
                         "argument_bytes": 0, "output_bytes": 0,
                         "peak_temp_bytes": 0, "generated_code_bytes": 0,
                         "mesh_devices": 1, "comm": {},
                         "error": f"{type(e).__name__}: {e}",
                         "captured_at_unix": round(time.time(), 3)}
            with self._cv:
                self._programs[(op, blab)] = entry
                self._captures += 1
                if entry.get("error"):
                    self._capture_errors += 1
                self._pending -= 1
                self._cv.notify_all()

    def _analyze(self, program: ProgramSpec) -> dict:
        entry: dict = {
            "flops": 0.0, "bytes_accessed": 0.0,
            "argument_bytes": 0, "output_bytes": 0,
            "peak_temp_bytes": 0, "generated_code_bytes": 0,
            "mesh_devices": getattr(program, "mesh_devices", 1),
            "comm": {}, "error": "",
            "captured_at_unix": round(time.time(), 3),
        }
        try:
            import jax

            lowered = program.fn.lower(*program.args, **program.kwargs)
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                entry["flops"] = float(ca.get("flops", 0.0))
                entry["bytes_accessed"] = float(ca.get("bytes accessed", 0.0))
            if self._memory_enabled():
                mem = lowered.compile().memory_analysis()
                if mem is not None:
                    entry["argument_bytes"] = int(
                        getattr(mem, "argument_size_in_bytes", 0))
                    entry["output_bytes"] = int(
                        getattr(mem, "output_size_in_bytes", 0))
                    entry["peak_temp_bytes"] = int(
                        getattr(mem, "temp_size_in_bytes", 0))
                    entry["generated_code_bytes"] = int(
                        getattr(mem, "generated_code_size_in_bytes", 0))
            if program.mesh_axis_sizes:
                jaxpr = jax.make_jaxpr(program.fn)(
                    *program.args, **program.kwargs)
                entry["comm"] = collective_comm_bytes(
                    jaxpr, program.mesh_axis_sizes, program.mesh_devices)
        except Exception as e:  # capture is advisory; record why it failed
            entry["error"] = f"{type(e).__name__}: {e}"
        return entry

    # ------------------------------------------------------------ readout
    def program_table(self) -> dict[tuple[str, str], dict]:
        with self._lock:
            return {k: {**dict(v), "launches": self._launches.get(k, 0),
                        "comm": dict(v["comm"])}
                    for k, v in self._programs.items()}

    def comm_for(self, op: str, bucket_label: str) -> dict[str, int]:
        with self._lock:
            e = self._programs.get((op, bucket_label))
            return dict(e["comm"]) if e else {}

    def comm_totals(self) -> dict[tuple[str, str], int]:
        """(op, collective) -> fleet wire bytes = per-launch bytes x
        launches of that program's bucket."""
        out: dict[tuple[str, str], int] = {}
        with self._lock:
            for (op, blab), e in self._programs.items():
                n = self._launches.get((op, blab), 0)
                for coll, b in e["comm"].items():
                    k = (op, coll)
                    out[k] = out.get(k, 0) + b * n
        return out

    # --------------------------------------------------------- HBM ledger
    def hbm_snapshot(self) -> dict:
        """One device-memory accounting view. Components are the
        accountable residents this process manages; `device` carries the
        backend's own memory_stats() where it exposes one (TPU runtimes
        do, CPU does not) as the cross-check -- device.bytes_in_use
        should be >= the accounted total, the gap being XLA runtime
        overhead plus anything staged outside these caches."""
        comps: dict[str, dict] = {}
        staged_bytes = live_bytes = code_bytes = 0
        try:
            from ..ops.stage import staged_cache_stats

            st = staged_cache_stats(max_entries=1)
            staged_bytes = int(st["bytes"])
            comps["staged_cache"] = {
                "bytes": staged_bytes, "entries": int(st["entries"]),
                "budget_bytes": int(st["budget_bytes"]),
            }
        except Exception:
            comps["staged_cache"] = {"bytes": 0, "error": "unavailable"}
        try:
            from ..ops.livestage import stager_device_bytes

            live_bytes, n_stagers = stager_device_bytes()
            comps["livestage"] = {"bytes": int(live_bytes),
                                  "stagers": int(n_stagers)}
        except Exception:
            comps["livestage"] = {"bytes": 0, "error": "unavailable"}
        with self._lock:
            code_bytes = sum(e["generated_code_bytes"]
                             for e in self._programs.values())
            peak_temp = max(
                (e["peak_temp_bytes"] for e in self._programs.values()),
                default=0)
            n_prog = len(self._programs)
        comps["compiled_programs"] = {
            "bytes": int(code_bytes), "programs": n_prog,
            "max_peak_temp_bytes": int(peak_temp),
        }
        total = staged_bytes + live_bytes + code_bytes
        with self._lock:
            if total > self._hbm_peak:
                self._hbm_peak = total
            peak = self._hbm_peak
        device = None
        try:
            import jax

            device = jax.devices()[0].memory_stats()
        except Exception:
            device = None
        snap = {
            "components": comps,
            "accounted_bytes": int(total),
            "accounted_peak_bytes": int(peak),
            "device_memory_stats": device,
        }
        if isinstance(device, dict) and "bytes_in_use" in device:
            snap["unaccounted_bytes"] = max(
                0, int(device["bytes_in_use"]) - int(total))
        return snap

    # ----------------------------------------------------------- metrics
    def metrics_lines(self) -> list[str]:
        """Exposition samples for /metrics (rendered through the app's
        strict-OpenMetrics pass like every kerneltel instrument)."""
        out: list[str] = []
        try:
            table = self.program_table()
            for (op, blab) in sorted(table):
                e = table[(op, blab)]
                lbl = f'op="{op}",bucket="{blab}"'
                out.append(f"tempo_program_flops{{{lbl}}} {e['flops']:g}")
                out.append(
                    f"tempo_program_bytes_accessed{{{lbl}}} "
                    f"{e['bytes_accessed']:g}")
                out.append(
                    f"tempo_program_peak_temp_bytes{{{lbl}}} "
                    f"{e['peak_temp_bytes']:g}")
            for (op, coll), b in sorted(self.comm_totals().items()):
                out.append(
                    f'tempo_mesh_comm_bytes_total{{collective="{coll}",'
                    f'op="{op}"}} {b:g}')
            hbm = self.hbm_snapshot()
            for comp, row in sorted(hbm["components"].items()):
                out.append(
                    f'tempo_hbm_bytes{{component="{comp}"}} '
                    f"{row.get('bytes', 0):g}")
            out.append(f"tempo_hbm_peak_bytes {hbm['accounted_peak_bytes']:g}")
            budget = hbm["components"].get("staged_cache", {}).get(
                "budget_bytes")
            if budget is not None:
                out.append(f"tempo_hbm_staged_budget_bytes {budget:g}")
            out += _DISK_CACHE_EVENTS.text()
        except Exception:
            pass  # observability must never take /metrics down
        return out

    @staticmethod
    def help_entries() -> dict[str, str]:
        return {
            "tempo_program_flops":
                "XLA cost-analysis FLOPs per execution by op and shape bucket",
            "tempo_program_bytes_accessed":
                "XLA cost-analysis bytes accessed per execution by op/bucket",
            "tempo_program_peak_temp_bytes":
                "XLA peak temp allocation per execution by op/bucket",
            "tempo_mesh_comm_bytes":
                "static collective wire bytes x launches by collective and op",
            "tempo_hbm_bytes":
                "accounted device memory by component (staged_cache/"
                "livestage/compiled_programs)",
            "tempo_hbm_peak_bytes":
                "high-water mark of accounted device memory",
            "tempo_hbm_staged_budget_bytes":
                "device budget for the staged block-column cache",
            "tempo_kernel_compile_disk":
                "persistent compilation cache outcomes (hit = executable "
                "deserialized from disk, miss = fresh XLA compile)",
        }

    # ------------------------------------------------------------- status
    def status_snapshot(self, drain_timeout: float = 1.0) -> dict:
        """The /status/cost payload: per-(op,bucket) static costs joined
        with kerneltel's measured wall times into achieved-vs-roofline
        utilization, per-collective comm bytes, the HBM ledger, the
        crossover ledger, and compile-cache state."""
        self.drain(drain_timeout)
        from .kerneltel import TEL

        kern = {(k["op"], k["bucket"]): k for k in TEL.snapshot(slow_k=0)["kernels"]}
        peak_bps = 0.0
        platform = ""
        try:
            import jax

            platform = jax.devices()[0].platform
            peak_bps = HBM_PEAK_BPS.get(platform, 0.0)
        except Exception:
            pass
        programs = []
        table = self.program_table()
        for (op, blab) in sorted(table):
            e = table[(op, blab)]
            row = {"op": op, "bucket": blab, **{k: v for k, v in e.items()
                                               if k != "comm"}}
            krow = kern.get((op, blab))
            calls = krow["calls"] if krow else 0
            dev_s = krow["device_seconds"] if krow else 0.0
            if calls and dev_s > 0:
                per_call = dev_s / calls
                row["measured_calls"] = calls
                row["measured_s_per_call"] = round(per_call, 9)
                row["achieved_flops_per_s"] = round(e["flops"] / per_call, 1)
                row["achieved_bytes_per_s"] = round(
                    e["bytes_accessed"] / per_call, 1)
                row["hbm_utilization"] = (
                    round(e["bytes_accessed"] / per_call / peak_bps, 6)
                    if peak_bps else 0.0)
            comm = e["comm"]
            if comm:
                row["comm_bytes_per_launch"] = dict(sorted(comm.items()))
            programs.append(row)
        comm_rows = [
            {"op": op, "collective": coll, "bytes_total": b}
            for (op, coll), b in sorted(self.comm_totals().items())
        ]
        from .costledger import ledger

        with self._lock:
            meta = {"captures": self._captures,
                    "capture_errors": self._capture_errors,
                    "pending": self._pending,
                    "enabled": self.enabled()}
        return {
            "platform": platform,
            "roofline_hbm_bytes_per_s": peak_bps,
            "programs": programs,
            "comm": comm_rows,
            "hbm": self.hbm_snapshot(),
            "ledger": ledger().to_dict(),
            "compile_cache": compile_cache_stats(),
            "capture": meta,
        }

    def reset(self) -> None:
        """Fresh state (tests). The worker thread survives; in-flight
        captures may still land rows after a reset -- tests drain first."""
        with self._cv:
            # discarded queue items will never reach the worker's
            # decrement: release their pending counts here or drain()
            # waits its full timeout forever after
            self._pending -= len(self._queue)
            self._queue.clear()
            self._programs.clear()
            self._launches.clear()
            self._captures = 0
            self._capture_errors = 0
            self._hbm_peak = 0
            self._cv.notify_all()


COST = CostModel()


# ------------------------------------------------ persistent compile cache

COMPILE_CACHE_ENV = "TEMPO_COMPILE_CACHE_DIR"

from .metrics import Counter as _Counter  # noqa: E402

_DISK_CACHE_EVENTS = _Counter(
    "tempo_kernel_compile_disk_total",
    help="persistent compilation cache outcomes by event")

_cc_lock = threading.Lock()
_cc_state = {"enabled": False, "dir": "", "listener": False}


def _on_jax_event(name: str, **kw) -> None:
    if name.endswith("/compilation_cache/cache_hits"):
        _DISK_CACHE_EVENTS.inc(labels='outcome="hit"')
    elif name.endswith("/compilation_cache/cache_misses"):
        _DISK_CACHE_EVENTS.inc(labels='outcome="miss"')


def enable_compile_cache(cache_dir: str) -> bool:
    """Turn on jax's persistent (disk) compilation cache so a restarted
    process deserializes yesterday's executables instead of re-paying
    the first-compile storm (ROADMAP item 5). Registers a
    jax.monitoring listener so disk hits vs fresh compiles are counted
    (tempo_kernel_compile_disk_total) -- kerneltel's compile counter
    cannot tell them apart (both look like a new program key). Must run
    before the first compile to cover it; later calls still cover every
    compile after them. Returns True when the cache is active."""
    if not cache_dir:
        return False
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        with _cc_lock:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            # cache everything: the padded-bucket discipline keeps the
            # program population small, so entry-size/compile-time floors
            # would only punch holes in warm restarts
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            try:
                jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            except Exception:
                pass  # knob not present on this jax version
            try:
                # the cache object latches its (possibly empty) dir on
                # first compile: a process that compiled anything before
                # this call must rebuild it or the new dir is ignored
                from jax._src import compilation_cache as _jcc

                _jcc.reset_cache()
            except Exception:
                pass  # private API drift: pre-first-compile enables still work
            if not _cc_state["listener"]:
                jax.monitoring.register_event_listener(_on_jax_event)
                _cc_state["listener"] = True
            _cc_state["enabled"] = True
            _cc_state["dir"] = cache_dir
        return True
    except Exception as e:
        from .log import get_logger

        get_logger("costmodel").warning(
            "persistent compile cache at %r unavailable: %s", cache_dir, e)
        return False


def disable_compile_cache() -> None:
    """Turn the persistent cache back off (tests that enabled it at a
    throwaway dir must not leave the process reading a deleted path)."""
    try:
        import jax

        with _cc_lock:
            jax.config.update("jax_compilation_cache_dir", None)
            _cc_state["enabled"] = False
            _cc_state["dir"] = ""
        from jax._src import compilation_cache as _jcc

        _jcc.reset_cache()
    except Exception:
        pass


def maybe_enable_compile_cache_from_env() -> bool:
    """The env hook every jax-touching entry point runs once at import
    (ops/device.py): TEMPO_COMPILE_CACHE_DIR set => cache on."""
    with _cc_lock:
        if _cc_state["enabled"]:
            return True
    return enable_compile_cache(os.environ.get(COMPILE_CACHE_ENV, ""))


def compile_cache_stats() -> dict:
    with _cc_lock:
        st = dict(_cc_state)
    st.pop("listener", None)
    st["disk_hits"] = int(_DISK_CACHE_EVENTS.get(labels='outcome="hit"'))
    st["disk_misses"] = int(_DISK_CACHE_EVENTS.get(labels='outcome="miss"'))
    return st
