"""Measured host<->device link cost, shared by every engine-choice
site (search's host-vs-staged decision, the generator's reduce).

On a datacenter TPU the round trip is sub-millisecond and device
execution wins from the first megabyte; through a high-latency tunnel
(~100 ms/sync) host execution wins for anything the host can scan
faster than one round trip. Measure once per process, don't assume."""

from __future__ import annotations

import threading

import numpy as np

_LINK_RTT_MS: float | None = None
_rtt_lock = threading.Lock()


def link_rtt_ms() -> float:
    """One tiny put+compute+fetch round trip, measured at first use
    (first rep absorbs backend init + the +1 kernel compile). The lock
    keeps concurrent first callers from racing duplicate probes (and
    double-paying the backend-init rep)."""
    global _LINK_RTT_MS
    if _LINK_RTT_MS is None:
        with _rtt_lock:
            if _LINK_RTT_MS is None:
                try:
                    import time as _time

                    import jax.numpy as jnp

                    probe = np.zeros(8, np.int32)
                    best = float("inf")
                    for _ in range(3):
                        t0 = _time.perf_counter()
                        np.asarray(jnp.asarray(probe) + 1)
                        best = min(best, _time.perf_counter() - t0)
                    _LINK_RTT_MS = best * 1e3
                except Exception:
                    _LINK_RTT_MS = 0.0
    return _LINK_RTT_MS
