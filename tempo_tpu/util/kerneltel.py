"""Kernel telemetry: device-execution observability for the read path.

The HTTP layer says how long a query took; this subsystem says WHY --
recompile storm, host fallback, padding waste, or transfer stall. One
process-wide registry (TEL) collects, from every device entry point in
ops/ and parallel/:

  * compile vs jit-cache-hit counters keyed by (op, shape-bucket): the
    ops pad every axis to a power-of-two bucket (ops/device.bucket), so
    the (op, bucket-signature) pair IS the XLA program key. The model
    tracks OUR cache key, not XLA's internals, so an lru_cache eviction
    that forces a silent re-trace undercounts -- acceptable for an
    operational signal (evictions mean 256+ live program shapes).
  * per-op device wall-time histograms. When sync timing is on the
    observer calls block_until_ready, so the histogram records true
    device time rather than Python dispatch; on a high-latency link that
    sync would cost a full RTT per kernel, so the default follows the
    measured link (util/linkcost): sync when RTT <= SYNC_RTT_MS,
    dispatch-only otherwise. TEMPO_KERNELTEL_SYNC=0|1 overrides.
  * host->device transfer bytes + padding-waste rows per staging call
    (ops/stage), plus staged-cache hit/miss counters.
  * engine routing decisions WITH reasons (cold block, pre-IO budget
    exceeded, lossy/unplannable plan, mesh fallback, ...) from
    db/search, db/metrics_exec and db/metrics_mesh.
  * a bounded recent-query log (slowest first in /status/kernels), each
    entry carrying its self-trace id so a slow query links straight to
    its flame view.

Self-trace plumbing: the frontend parks the active SelfTracer trace in
a contextvar (set_active_trace) around local job execution; engine code
deep in db/ attaches per-block child spans with kernel attrs
(engine=device|host, bucket=..., compile=true) through child_span()
without any signature threading. Everything here is advisory -- no
method may raise into the query path.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from collections import OrderedDict, deque

from ..chaos import plane as _chaos
from .metrics import Counter, Gauge, Histogram

# device kernels run sub-ms to ~seconds: a finer low end than the
# request-latency default buckets
DEVICE_TIME_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005,
                       0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

# compaction pipeline stages span sub-ms (tiny-block fetch) to tens of
# seconds (a big level-1 merge)
COMPACT_STAGE_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                         0.25, 0.5, 1.0, 2.5, 5.0, 15.0, 60.0)

QUERY_LOG_SIZE = 64  # recent queries kept for the slow-query log
SYNC_RTT_MS = 2.0  # block_until_ready timing only below this link RTT
# bound on remembered compile signatures: full query structures key the
# set, so an unbounded set would grow forever in a long-lived querier.
# LRU eviction mirrors what the jitted functions' lru_caches do anyway
# (an evicted program recompiles on next use, and we count it again).
SEEN_SIGNATURES_MAX = 4096

_active_trace: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_selftrace", default=None)

# placement the current job was dequeued under (own/steal/unowned, "" =
# no affinity context): the frontend/worker parks it around execution so
# ops/stage can attribute staged-cache hits to owner-vs-stolen routing
_affinity_placement: contextvars.ContextVar = contextvars.ContextVar(
    "tempo_affinity_placement", default="")

QOS_SHED_TENANTS_MAX = 128  # per-tenant shed rows kept before _overflow


def _esc_label(v: str) -> str:
    """Prometheus label-value escaping; delegates to the shared
    util/metrics.escape_label (kept as a module-local name because the
    call sites predate the public helper)."""
    from .metrics import escape_label

    return escape_label(v)


class KernelTelemetry:
    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._sync: bool | None = None
        self.compiles = Counter(
            "tempo_kernel_compiles_total",
            help="XLA program compiles by op and shape bucket")
        self.cache_hits = Counter(
            "tempo_kernel_cache_hits_total",
            help="jit-cache hits by op and shape bucket")
        self.device_time = Histogram(
            "tempo_kernel_device_seconds", buckets=DEVICE_TIME_BUCKETS,
            help="per-op device wall time (block_until_ready when the "
                 "link is fast; dispatch time otherwise)")
        self.transfer_bytes = Counter(
            "tempo_stage_transfer_bytes_total",
            help="host->device bytes uploaded by block staging")
        self.staged_rows_real = Counter(
            "tempo_stage_rows_real_total",
            help="real (pre-padding) rows staged to device")
        self.staged_rows_padded = Counter(
            "tempo_stage_rows_padded_total",
            help="rows staged to device after bucket padding")
        self.staged_cache_hits = Counter(
            "tempo_stage_cache_hits_total",
            help="staged-column device cache hits")
        self.staged_cache_misses = Counter(
            "tempo_stage_cache_misses_total",
            help="staged-column device cache misses (uploads)")
        self.routing = Counter(
            "tempo_engine_routing_total",
            help="engine routing decisions by layer, engine and reason")
        # cross-query batching executor (db/batchexec): fused launches
        self.batch_groups = Counter(
            "tempo_batch_groups_total",
            help="fused batch launches by executor")
        self.batch_queries = Counter(
            "tempo_batch_queries_total",
            help="queries admitted into the batching executor")
        self.batch_occupancy = Histogram(
            "tempo_batch_occupancy_queries",
            buckets=(1, 2, 4, 8, 16, 32, 64),
            help="queries coalesced per fused launch group")
        self.batch_window_wait = Histogram(
            "tempo_batch_window_wait_seconds",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1),
            help="admission-window wait paid by each batch leader")
        self.batch_demux = Counter(
            "tempo_batch_demux_total",
            help="per-query results demultiplexed out of fused launches")
        self._batches: dict[str, dict] = {}
        # mesh-batched serving (parallel/multiquery): one admission
        # window lowered to a single Q-programs x sharded-rows launch
        # across every chip -- launches and per-launch occupancy
        self.mesh_batch_launches = Counter(
            "tempo_mesh_batch_launches_total",
            help="batched multi-query mesh launches (one admission "
                 "window -> all chips)")
        self.mesh_batch_queries = Counter(
            "tempo_mesh_batch_queries_total",
            help="queries fused into batched mesh launches")
        self.mesh_batch_occupancy = Histogram(
            "tempo_mesh_batch_occupancy_queries",
            buckets=(1, 2, 4, 8, 16, 32, 64),
            help="queries per batched mesh launch")
        self._mesh_batches: dict = {"launches": 0, "queries": 0,
                                    "max_occupancy": 0}
        # compaction pipeline (db/compact_pipeline): per-stage wall
        # times, admission-gate occupancy, prefetch effectiveness
        self.compact_stage_time = Histogram(
            "tempo_compaction_stage_seconds", buckets=COMPACT_STAGE_BUCKETS,
            help="per-stage wall time of compaction pipeline jobs")
        self.compact_jobs = Counter(
            "tempo_compaction_jobs_total",
            help="compaction jobs executed by the pipeline by outcome")
        self.compact_input_bytes = Counter(
            "tempo_compaction_input_bytes_total",
            help="compaction input bytes consumed by completed jobs")
        self.compact_prefetch = Counter(
            "tempo_compaction_prefetch_total",
            help="pipeline input-prefetch outcomes by kind (hit/miss/waste)")
        self.compact_jobs_inflight = Gauge(
            "tempo_compaction_jobs_inflight",
            help="compaction jobs currently admitted into the pipeline")
        self.compact_bytes_inflight = Gauge(
            "tempo_compaction_bytes_inflight",
            help="estimated peak host-RAM bytes of admitted compaction jobs")
        self.compact_queue_depth = Gauge(
            "tempo_compaction_queue_depth",
            help="compaction jobs waiting at the pipeline admission gate")
        self._compaction: dict = {
            "runs": 0, "wall_seconds": 0.0, "stage_seconds": {},
            "jobs": 0, "errors": 0, "input_bytes": 0,
            "prefetch": {"hit": 0, "miss": 0, "waste": 0},
            "max_jobs_inflight": 0,  # process lifetime
            "run_max_jobs_inflight": 0,  # current/most-recent pipeline run
        }
        self.compact_passthrough_bytes = Counter(
            "tempo_compaction_passthrough_bytes_total",
            help="compressed bytes compaction copied through verbatim "
                 "(chunk passthrough + concat part copies) instead of "
                 "decompress->recompress")
        # cold-read streaming pipeline (ops/stream): per-stage wall
        # times, admission-gate bytes, unit outcomes
        self.stream_stage_time = Histogram(
            "tempo_stream_stage_seconds", buckets=COMPACT_STAGE_BUCKETS,
            help="per-stage wall time of cold-read stream pipeline units "
                 "(fetch/decompress/assemble/upload)")
        self.stream_units = Counter(
            "tempo_stream_units_total",
            help="cold-read stream pipeline units completed by outcome")
        self.stream_bytes_inflight = Gauge(
            "tempo_stream_bytes_inflight",
            help="estimated host bytes of admitted stream pipeline units")
        self._stream: dict = {
            "runs": 0, "wall_seconds": 0.0, "stage_seconds": {},
            "units": 0, "errors": 0, "cancelled": 0,
        }
        # cache-affinity scheduling (services/frontend): dequeue
        # placement outcomes, per-tenant QoS sheds, and staged-cache
        # lookups attributed by the dequeue placement of the job that
        # made them (owner-vs-stolen hit-rate attribution)
        self.affinity_jobs = Counter(
            "tempo_affinity_jobs_total",
            help="frontend dequeue placement outcomes (own/steal/unowned)")
        self.qos_shed = Counter(
            "tempo_qos_shed_total",
            help="queries shed with 429 by per-tenant read QoS budgets")
        self.staged_placement = Counter(
            "tempo_stage_cache_placement_total",
            help="staged-cache lookups by job placement (own/steal/"
                 "unowned/none) and result")
        self._affinity: dict[str, int] = {}
        self._qos_sheds: dict[str, dict[str, int]] = {}
        self._staged_by_placement: dict[str, list[int]] = {}
        # live-head staging (ops/livestage): slot/row occupancy by
        # lifecycle state, delta-upload volume, push->device-visible lag
        self.livestage_rows = Gauge(
            "tempo_livestage_rows",
            help="live-head staged slots by lifecycle state "
                 "(live/cut/flushing/dead) and membership rows (rows)")
        self.livestage_delta_bytes = Counter(
            "tempo_livestage_delta_bytes_total",
            help="host->device bytes uploaded by live-head staging "
                 "refreshes (delta appends + slot columns)")
        self.livestage_lag = Histogram(
            "tempo_livestage_lag_seconds",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
            help="staging lag: push acknowledged -> segment visible to "
                 "the device live engine")
        self._livestage: dict = {
            "slots": {}, "rows": 0, "generation": 0,
            "uploads": 0, "full_uploads": 0, "delta_bytes": 0,
            "delta_rows": 0, "lag_count": 0, "lag_sum": 0.0, "lag_max": 0.0,
        }
        # device-native ingest (tempo_tpu/ingest): per-stage write-path
        # seconds (decode / wal_append / stage_delta / cut / flush),
        # window/feature-checkpoint volume, replay outcomes
        self.ingest_stage_time = Histogram(
            "tempo_ingest_stage_seconds",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
            help="write-path stage wall seconds by stage "
                 "(decode/wal_append/stage_delta/cut/flush)")
        self._ingest: dict = {
            "stages": {}, "windows": 0, "window_traces": 0,
            "window_bytes": 0, "feature_entries": 0,
            "replays": {"records": 0, "features": 0, "torn": 0},
        }
        # streaming metrics-generator (services/generator): per-stage
        # fold seconds, push->series-visible freshness, per-tenant
        # series-limit sheds, window/pairing volume
        self.generator_stage_time = Histogram(
            "tempo_generator_stage_seconds",
            buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0),
            help="streaming generator fold wall seconds by stage "
                 "(span-metrics/service-graphs)")
        self.generator_freshness = Histogram(
            "tempo_generator_freshness_seconds",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
            help="push received -> generated series visible to the "
                 "next exposition scrape")
        self.generator_shed = Counter(
            "tempo_generator_series_shed_total",
            help="spans shed by the per-tenant max-active-series limit")
        self._generator: dict = {
            "stages": {}, "windows": 0, "window_spans": 0,
            "edges_completed": 0, "unpaired": 0, "expired": 0,
            "shed": {}, "freshness_count": 0, "freshness_sum": 0.0,
            "freshness_max": 0.0,
        }
        # self-tracing pipeline health (services/selftrace): spans
        # shipped vs whole traces dropped at the bounded in-flight queue
        self.selftrace_spans = Counter(
            "tempo_selftrace_spans_total",
            help="self-trace spans by outcome (shipped / dropped with "
                 "their trace at the bounded in-flight queue)")
        self._selftrace: dict[str, int] = {}
        # per-query cost attribution (selftrace root spans): per-tenant
        # totals of device ms, staged bytes, compiles, verified rows
        self.query_cost = Counter(
            "tempo_query_cost_total",
            help="per-tenant query cost totals by resource (device_ms, "
                 "staged_bytes, bytes_scanned, compiles, rows_verified)")
        self._query_costs: dict[str, dict[str, float]] = {}
        # per-query-class outcomes (ok / error / shed) recorded by the
        # frontend at every query exit: the availability SLI the SLO
        # engine (util/slo) evaluates. Sheds are a separate outcome --
        # a per-tenant QoS budget refusing work is the admission system
        # functioning, not the serving path failing, so the
        # availability objective excludes them.
        self.query_outcomes = Counter(
            "tempo_query_outcomes_total",
            help="frontend queries by op and outcome (ok/error/shed)")
        # resilience plane (PR 14): hedge outcomes (win = the hedge
        # twin finished first; lose = the original won after the twin
        # started; unneeded = the original won before the twin ran),
        # and per-query retry-budget consumption (retry = a shard
        # retry was granted; budget_exhausted = a retryable failure
        # was refused because the query's budget ran dry)
        self.hedge_total = Counter(
            "tempo_hedge_total",
            help="frontend hedged jobs by outcome (win/lose/unneeded)")
        self.retry_total = Counter(
            "tempo_retry_total",
            help="frontend shard retries by outcome "
                 "(retry/budget_exhausted)")
        self._hedges: dict[str, int] = {}
        self._retries: dict[str, int] = {}
        # tiered cache plane (PR 20): Tier A frontend result cache
        # (services/resultcache) and Tier B host-RAM compressed
        # column-chunk pool under the HBM staged cache (ops/chunkpool)
        self.result_cache_hits = Counter(
            "tempo_result_cache_hits_total",
            help="frontend result-cache hits served without touching "
                 "QoS budgets, the queue, or a device")
        self.result_cache_misses = Counter(
            "tempo_result_cache_misses_total",
            help="frontend result-cache misses (full execution)")
        self.result_cache_extensions = Counter(
            "tempo_result_cache_extensions_total",
            help="now-edge queries answered by extending a cached "
                 "immutable prefix with a tail-only execution")
        self.result_cache_invalidations = Counter(
            "tempo_result_cache_invalidations_total",
            help="result-cache entries invalidated by a blocklist or "
                 "live-head generation change")
        self.result_cache_bytes = Gauge(
            "tempo_result_cache_bytes",
            help="bytes held by the frontend result cache")
        self.chunk_cache_hits = Counter(
            "tempo_chunk_cache_hits_total",
            help="staged-column restages served from the host-RAM "
                 "compressed demote pool (no backend read)")
        self.chunk_cache_misses = Counter(
            "tempo_chunk_cache_misses_total",
            help="demote-pool probes that fell through to the backend")
        self.chunk_cache_demotions = Counter(
            "tempo_chunk_cache_demotions_total",
            help="staged-column entries demoted (recompressed) into the "
                 "host pool on HBM eviction instead of discarded")
        self.chunk_cache_evictions = Counter(
            "tempo_chunk_cache_evictions_total",
            help="demote-pool entries evicted by the host-RAM budget")
        self.chunk_cache_bytes = Gauge(
            "tempo_chunk_cache_bytes",
            help="compressed bytes held by the demote pool")
        # every instrument exported through /metrics -- ONE list shared
        # by metrics_lines() and help_entries() so an instrument can't
        # ship samples without its HELP (or vice versa)
        self._instruments = (
            self.compiles, self.cache_hits, self.device_time,
            self.transfer_bytes, self.staged_rows_real,
            self.staged_rows_padded, self.staged_cache_hits,
            self.staged_cache_misses, self.routing,
            self.batch_groups, self.batch_queries,
            self.batch_occupancy, self.batch_window_wait,
            self.batch_demux, self.mesh_batch_launches,
            self.mesh_batch_queries, self.mesh_batch_occupancy,
            self.compact_stage_time,
            self.compact_jobs, self.compact_input_bytes,
            self.compact_prefetch, self.compact_jobs_inflight,
            self.compact_bytes_inflight, self.compact_queue_depth,
            self.compact_passthrough_bytes, self.stream_stage_time,
            self.stream_units, self.stream_bytes_inflight,
            self.affinity_jobs, self.qos_shed, self.staged_placement,
            self.livestage_rows, self.livestage_delta_bytes,
            self.livestage_lag, self.ingest_stage_time,
            self.generator_stage_time, self.generator_freshness,
            self.generator_shed,
            self.selftrace_spans, self.query_cost,
            self.query_outcomes, self.hedge_total, self.retry_total,
            self.result_cache_hits, self.result_cache_misses,
            self.result_cache_extensions, self.result_cache_invalidations,
            self.result_cache_bytes, self.chunk_cache_hits,
            self.chunk_cache_misses, self.chunk_cache_demotions,
            self.chunk_cache_evictions, self.chunk_cache_bytes,
        )
        # full compile-key signatures, LRU-bounded (SEEN_SIGNATURES_MAX)
        self._seen: OrderedDict = OrderedDict()
        # (op, bucket-label) -> aggregate row for /status/kernels
        self._kernels: dict[tuple[str, str], dict] = {}
        self._routing: dict[tuple[str, str, str], int] = {}
        self._queries: deque = deque(maxlen=QUERY_LOG_SIZE)

    # ------------------------------------------------------------ config
    def sync_timing(self) -> bool:
        """Whether device timers block_until_ready (true device time) or
        record dispatch time only. Resolved once per process."""
        if self._sync is None:
            env = os.environ.get("TEMPO_KERNELTEL_SYNC", "")
            if env in ("0", "1"):
                self._sync = env == "1"
            else:
                try:
                    from .linkcost import link_rtt_ms

                    self._sync = link_rtt_ms() <= SYNC_RTT_MS
                except Exception:
                    self._sync = False
        return self._sync

    # ----------------------------------------------------------- kernels
    def record_launch(self, op: str, key, bucket, cost=None) -> bool:
        """Note one kernel launch. `key` is the full compile signature
        (everything that keys the jitted program: tree/cond structure +
        every padded axis bucket); `bucket` is the primary shape bucket
        used as the metric label. Returns True on a new compile.

        `cost`: zero-arg callable returning a costmodel.ProgramSpec --
        invoked only on a NEW compile, so the program's XLA cost
        analysis (and, for mesh programs, its collective comm bytes)
        is captured once in the costmodel's background worker. Every
        launch (new or cached) also ticks the costmodel's launch
        counter, which turns static per-program comm bytes into the
        tempo_mesh_comm_bytes_total series."""
        if _chaos.is_active():
            # chaos launch shim (ops/device.launch_tap): deliberately
            # OUTSIDE the swallow-everything block below -- an injected
            # compile failure / device OOM must reach the caller like a
            # real one would
            from ..ops.device import launch_tap

            launch_tap(op)
        blab = str(bucket)
        try:
            with self._lock:
                new = key not in self._seen
                if new:
                    self._seen[key] = True
                    while len(self._seen) > SEEN_SIGNATURES_MAX:
                        self._seen.popitem(last=False)
                else:
                    self._seen.move_to_end(key)
                k = self._kernels.get((op, blab))
                if k is None:
                    k = self._kernels[(op, blab)] = {
                        "compiles": 0, "cache_hits": 0, "calls": 0,
                        "device_seconds": 0.0, "last_compile_unix": 0.0,
                    }
                if new:
                    k["compiles"] += 1
                    k["last_compile_unix"] = time.time()
                else:
                    k["cache_hits"] += 1
            labels = f'op="{op}",bucket="{blab}"'
            (self.compiles if new else self.cache_hits).inc(labels=labels)
            self._tls.last = (op, blab, new)
            self.add_query_cost("compiles" if new else "cache_hits", 1)
            try:
                from .costmodel import COST

                COST.note_launch(op, blab)
                if new and cost is not None:
                    COST.enqueue(op, blab, cost())
            except Exception:
                pass  # cost capture must not flip the compile verdict
            if new:
                try:
                    # AOT warmup corpus: every first compile of an (op,
                    # bucket) pair is remembered in the CostLedger so a
                    # restarted process can pre-compile it (--warmup.shapes)
                    from .warmup import note_compile

                    note_compile(op, blab)
                except Exception:
                    pass
            return new
        except Exception:
            return False

    def last_launch(self) -> tuple[str, str, bool] | None:
        """(op, bucket, compiled) of this thread's most recent launch --
        lets the search layer stamp compile=true on the block's
        self-trace span without threading flags through every return."""
        return getattr(self._tls, "last", None)

    def observe_device(self, op: str, bucket, t0: float, out=None):
        """Close a device timing window opened at perf_counter() t0.
        With sync timing on and device outputs given, waits for them
        first so the window covers device execution, not just dispatch.
        Returns `out` for call-site chaining."""
        try:
            if out is not None and self.sync_timing():
                import jax

                jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            self.device_time.observe(dt, f'op="{op}"',
                                     exemplar=self._exemplar_tid())
            self.add_query_cost("device_ms", dt * 1e3)
            with self._lock:
                k = self._kernels.get((op, str(bucket)))
                if k is not None:
                    k["calls"] += 1
                    k["device_seconds"] += dt
        except Exception:
            pass
        return out

    def credit_device(self, op: str, bucket, seconds: float) -> None:
        """Credit a kernel-table row with one call and a share of a
        batch's timing window WITHOUT a histogram observation -- for
        call sites that launch several per-bucket programs under one
        measured window (the batched find loop)."""
        try:
            with self._lock:
                k = self._kernels.get((op, str(bucket)))
                if k is not None:
                    k["calls"] += 1
                    k["device_seconds"] += seconds
        except Exception:
            pass

    # ----------------------------------------------------------- staging
    def record_transfer(self, nbytes: int, rows_real: int, rows_padded: int) -> None:
        self.transfer_bytes.inc(nbytes)
        self.staged_rows_real.inc(rows_real)
        self.staged_rows_padded.inc(rows_padded)
        self.add_query_cost("staged_bytes", nbytes)

    # ----------------------------------------------------------- routing
    def record_routing(self, layer: str, engine: str, reason: str, n: int = 1) -> None:
        """One engine decision: which engine ran (or why the fast path
        fell back) and the reason the router chose it."""
        self.routing.inc(
            n, labels=f'layer="{layer}",engine="{engine}",reason="{reason}"')
        with self._lock:
            key = (layer, engine, reason)
            self._routing[key] = self._routing.get(key, 0) + n

    def routing_counts(self) -> dict[tuple[str, str, str], int]:
        with self._lock:
            return dict(self._routing)

    # ---------------------------------------------------------- batching
    def record_batch(self, name: str, occupancy: int, window_wait_s: float) -> None:
        """One fused batch group executed: its occupancy (queries per
        launch group) and the admission-window wait its leader paid."""
        try:
            labels = f'exec="{name}"'
            self.batch_groups.inc(labels=labels)
            self.batch_queries.inc(occupancy, labels=labels)
            self.batch_occupancy.observe(float(occupancy), labels)
            self.batch_window_wait.observe(float(window_wait_s), labels,
                                           exemplar=self._exemplar_tid())
            with self._lock:
                b = self._batches.setdefault(
                    name, {"groups": 0, "queries": 0, "max_occupancy": 0})
                b["groups"] += 1
                b["queries"] += int(occupancy)
                b["max_occupancy"] = max(b["max_occupancy"], int(occupancy))
        except Exception:
            pass

    def record_mesh_batch(self, occupancy: int) -> None:
        """One batched mesh launch executed: the whole window ran as a
        single Q-programs x sharded-rows program across every chip."""
        try:
            self.mesh_batch_launches.inc()
            self.mesh_batch_queries.inc(occupancy)
            self.mesh_batch_occupancy.observe(float(occupancy))
            with self._lock:
                mb = self._mesh_batches
                mb["launches"] += 1
                mb["queries"] += int(occupancy)
                mb["max_occupancy"] = max(mb["max_occupancy"], int(occupancy))
        except Exception:
            pass

    def mesh_batch_stats(self) -> dict:
        """Mesh-batch aggregates for /status/kernels and the bench row:
        occupancy = queries per mesh launch (1.0 = no amortization)."""
        with self._lock:
            mb = dict(self._mesh_batches)
        mb["occupancy"] = round(
            mb["queries"] / mb["launches"], 3) if mb["launches"] else 0.0
        return mb

    def record_demux(self, name: str, n: int = 1) -> None:
        try:
            self.batch_demux.inc(n, labels=f'exec="{name}"')
        except Exception:
            pass

    def batch_stats(self) -> dict:
        """Per-executor batching aggregates for /status/kernels.
        coalesce_ratio = queries per fused group (1.0 = no coalescing)."""
        with self._lock:
            out = {}
            for name, b in self._batches.items():
                out[name] = dict(b)
                out[name]["coalesce_ratio"] = round(
                    b["queries"] / b["groups"], 3) if b["groups"] else 0.0
            return out

    # --------------------------------------------------------- compaction
    def record_compact_stage(self, stage: str, seconds: float) -> None:
        """One pipeline stage (fetch/merge/assemble/write) finished for
        one job: observe its wall time."""
        try:
            self.compact_stage_time.observe(float(seconds), f'stage="{stage}"')
            with self._lock:
                ss = self._compaction["stage_seconds"]
                ss[stage] = ss.get(stage, 0.0) + float(seconds)
        except Exception:
            pass

    def record_compact_job(self, input_bytes: int, ok: bool = True) -> None:
        try:
            self.compact_jobs.inc(
                labels=f'outcome="{"ok" if ok else "error"}"')
            with self._lock:
                if ok:
                    self._compaction["jobs"] += 1
                    self._compaction["input_bytes"] += int(input_bytes)
                else:
                    self._compaction["errors"] += 1
            if ok:
                self.compact_input_bytes.inc(int(input_bytes))
        except Exception:
            pass

    def record_compact_prefetch(self, kind: str, n: int = 1) -> None:
        """Prefetch outcome: hit (worker found its inputs preloaded),
        miss (worker fetched them itself), waste (a prefetch attempt
        failed mid-IO and its work was thrown away -- the worker
        refetched from scratch)."""
        try:
            self.compact_prefetch.inc(n, labels=f'kind="{kind}"')
            with self._lock:
                p = self._compaction["prefetch"]
                p[kind] = p.get(kind, 0) + n
        except Exception:
            pass

    def compact_inflight(self, jobs: int, est_bytes: int, queued: int) -> None:
        """Point-in-time pipeline occupancy from the admission gate."""
        try:
            self.compact_jobs_inflight.set(jobs)
            self.compact_bytes_inflight.set(est_bytes)
            self.compact_queue_depth.set(queued)
            with self._lock:
                if jobs > self._compaction["max_jobs_inflight"]:
                    self._compaction["max_jobs_inflight"] = jobs
                if jobs > self._compaction["run_max_jobs_inflight"]:
                    self._compaction["run_max_jobs_inflight"] = jobs
        except Exception:
            pass

    def begin_compact_run(self) -> None:
        """Open one pipeline run: resets the run-scoped occupancy peak
        (the lifetime max stays monotonic)."""
        try:
            with self._lock:
                self._compaction["run_max_jobs_inflight"] = 0
        except Exception:
            pass

    def record_compact_run(self, wall_seconds: float) -> None:
        """Close one pipeline run (a whole admitted job set)."""
        try:
            with self._lock:
                self._compaction["runs"] += 1
                self._compaction["wall_seconds"] += float(wall_seconds)
        except Exception:
            pass

    def compaction_stats(self) -> dict:
        """Pipeline aggregates for /status/kernels and the bench rows.
        overlap_ratio = total stage seconds / run wall seconds: 1.0 means
        strictly sequential execution, >1 means stages (or jobs) actually
        overlapped in time."""
        with self._lock:
            c = {k: v for k, v in self._compaction.items()
                 if k not in ("stage_seconds", "prefetch")}
            c["stage_seconds"] = {
                k: round(v, 6)
                for k, v in self._compaction["stage_seconds"].items()}
            c["prefetch"] = dict(self._compaction["prefetch"])
        wall = c["wall_seconds"]
        stage_total = sum(c["stage_seconds"].values())
        c["overlap_ratio"] = round(stage_total / wall, 3) if wall > 0 else 0.0
        c["wall_seconds"] = round(wall, 6)
        c["jobs_inflight"] = int(self.compact_jobs_inflight.get())
        c["bytes_inflight"] = int(self.compact_bytes_inflight.get())
        c["queue_depth"] = int(self.compact_queue_depth.get())
        return c

    # ------------------------------------------------- cold-read streaming
    # stages that emit timeline spans from this chokepoint; "upload"
    # spans come from ops/stage.upload_stage (which knows the bytes and
    # also covers warm staging uploads outside the stream pipeline)
    _STREAM_SPAN_STAGES = ("fetch", "decompress", "assemble")

    def record_stream_stage(self, stage: str, seconds: float) -> None:
        """One stream-pipeline stage (fetch/decompress/assemble/upload)
        finished for one unit: observe its wall time, and attach a
        timeline span to the active self-trace -- this is the single
        chokepoint every cold ranged read passes (colio._run_plan and
        ops/stream._run_stages both land here)."""
        try:
            self.stream_stage_time.observe(float(seconds), f'stage="{stage}"',
                                           exemplar=self._exemplar_tid())
            with self._lock:
                ss = self._stream["stage_seconds"]
                ss[stage] = ss.get(stage, 0.0) + float(seconds)
            if stage in self._STREAM_SPAN_STAGES:
                t1 = time.time()
                self.child_span(f"stream:{stage}", t1 - float(seconds), t1)
        except Exception:
            pass

    def record_stream_unit(self, outcome: str = "ok") -> None:
        """One pipeline unit reached a terminal state (ok / error /
        cancelled)."""
        try:
            self.stream_units.inc(labels=f'outcome="{outcome}"')
            with self._lock:
                if outcome == "ok":
                    self._stream["units"] += 1
                elif outcome == "cancelled":
                    self._stream["cancelled"] += 1
                else:
                    self._stream["errors"] += 1
        except Exception:
            pass

    def stream_inflight(self, est_bytes: int) -> None:
        try:
            self.stream_bytes_inflight.set(est_bytes)
        except Exception:
            pass

    def record_stream_run(self, wall_seconds: float) -> None:
        """Close one pipeline run (one streamed iterator drained)."""
        try:
            with self._lock:
                self._stream["runs"] += 1
                self._stream["wall_seconds"] += float(wall_seconds)
        except Exception:
            pass

    def stream_stats(self) -> dict:
        """Stream-pipeline aggregates for /status/kernels and the cold
        bench rows. overlap_ratio = total stage seconds / run wall
        seconds: <=1.0 means effectively sequential, >1 means stages of
        different units genuinely overlapped in time."""
        with self._lock:
            c = {k: v for k, v in self._stream.items() if k != "stage_seconds"}
            c["stage_seconds"] = {
                k: round(v, 6) for k, v in self._stream["stage_seconds"].items()}
        wall = c["wall_seconds"]
        stage_total = sum(c["stage_seconds"].values())
        c["overlap_ratio"] = round(stage_total / wall, 3) if wall > 0 else 0.0
        c["wall_seconds"] = round(wall, 6)
        c["bytes_inflight"] = int(self.stream_bytes_inflight.get())
        return c

    # ------------------------------------------------- affinity scheduling
    def record_affinity(self, outcome: str, n: int = 1) -> None:
        """One frontend dequeue under affinity routing: the job went to
        its owner ("own"), was taken past the steal timeout ("steal"),
        or carried no block affinity at all ("unowned")."""
        try:
            self.affinity_jobs.inc(n, labels=f'outcome="{outcome}"')
            with self._lock:
                self._affinity[outcome] = self._affinity.get(outcome, 0) + n
        except Exception:
            pass

    def record_shed(self, tenant: str, budget: str) -> None:
        """One query refused with 429 by a per-tenant QoS budget
        ("concurrency" or "bytes")."""
        try:
            tenant = tenant[:128]  # header-sourced: bound label size
            with self._lock:
                key = (tenant if (tenant in self._qos_sheds
                                  or len(self._qos_sheds) < QOS_SHED_TENANTS_MAX)
                       else "_overflow")
                t = self._qos_sheds.setdefault(key, {})
                t[budget] = t.get(budget, 0) + 1
            self.qos_shed.inc(
                labels=f'tenant="{_esc_label(key)}",budget="{budget}"')
        except Exception:
            pass

    def set_affinity_placement(self, placement: str):
        """Park the current job's dequeue placement for this execution
        context; returns a token for reset_affinity_placement."""
        return _affinity_placement.set(placement or "")

    def reset_affinity_placement(self, token) -> None:
        try:
            _affinity_placement.reset(token)
        except Exception:
            pass

    def affinity_placement(self) -> str:
        return _affinity_placement.get()

    def record_staged_lookup(self, hit: bool) -> None:
        """One staged-cache probe, attributed to the ambient dequeue
        placement -- the owner-vs-stolen hit-rate split that says
        whether affinity routing is actually landing jobs on warm
        caches."""
        try:
            p = _affinity_placement.get() or "none"
            self.staged_placement.inc(
                labels=f'placement="{p}",result="{"hit" if hit else "miss"}"')
            with self._lock:
                row = self._staged_by_placement.setdefault(p, [0, 0])
                row[0 if hit else 1] += 1
        except Exception:
            pass

    def affinity_stats(self) -> dict:
        """Affinity + QoS aggregates for /status/kernels and the bench
        differential row."""
        with self._lock:
            staged = {
                p: {"hits": h, "misses": m,
                    "hit_rate": round(h / (h + m), 4) if h + m else 0.0}
                for p, (h, m) in sorted(self._staged_by_placement.items())
            }
            return {"jobs": dict(self._affinity),
                    "staged_by_placement": staged,
                    "qos_sheds": {t: dict(v)
                                  for t, v in sorted(self._qos_sheds.items())}}

    # ------------------------------------------------- live-head staging
    def set_livestage_rows(self, states: dict[str, int], rows: int,
                           generation: int) -> None:
        """Point-in-time occupancy after one staging refresh: slots by
        lifecycle state plus total membership rows."""
        try:
            with self._lock:
                gone = set(self._livestage["slots"]) - set(states)
                self._livestage["slots"] = dict(states)
                self._livestage["rows"] = int(rows)
                self._livestage["generation"] = int(generation)
            for state, n in states.items():
                self.livestage_rows.set(n, labels=f'state="{state}"')
            for state in gone:  # a drained state must read 0, not stale
                self.livestage_rows.set(0, labels=f'state="{state}"')
            self.livestage_rows.set(rows, labels='state="rows"')
        except Exception:
            pass

    def record_livestage_upload(self, nbytes: int, rows: int,
                                full: bool) -> None:
        """One refresh moved bytes over the host->device link (a delta
        append, or a full re-upload on bucket growth/compaction)."""
        try:
            self.livestage_delta_bytes.inc(nbytes)
            with self._lock:
                self._livestage["uploads"] += 1
                if full:
                    self._livestage["full_uploads"] += 1
                self._livestage["delta_bytes"] += int(nbytes)
                self._livestage["delta_rows"] += int(rows)
        except Exception:
            pass

    def record_staging_lag(self, seconds: float) -> None:
        """Push acknowledged -> segment staged (device-visible)."""
        try:
            self.livestage_lag.observe(float(seconds))
            with self._lock:
                ls = self._livestage
                ls["lag_count"] += 1
                ls["lag_sum"] += float(seconds)
                ls["lag_max"] = max(ls["lag_max"], float(seconds))
        except Exception:
            pass

    def livestage_stats(self) -> dict:
        """Live-head staging aggregates for /status/kernels, including
        the live-vs-host engine routing split."""
        with self._lock:
            out = dict(self._livestage)
            out["slots"] = dict(self._livestage["slots"])
            routing = {
                f"{layer}:{engine}:{reason}": n
                for (layer, engine, reason), n in sorted(self._routing.items())
                if layer in ("search_live", "find_live")
            }
        out["lag_avg_s"] = round(
            out["lag_sum"] / out["lag_count"], 6) if out["lag_count"] else 0.0
        out["lag_max_s"] = round(out.pop("lag_max"), 6)
        out.pop("lag_sum", None)
        out["routing"] = routing
        return out

    # ----------------------------------------------------------- ingest
    def record_ingest_stage(self, stage: str, seconds: float) -> None:
        """One write-path stage interval: decode / wal_append /
        stage_delta / cut / flush (tempo_tpu/ingest)."""
        try:
            self.ingest_stage_time.observe(float(seconds),
                                           labels=f'stage="{stage}"')
            with self._lock:
                st = self._ingest["stages"].setdefault(
                    stage, {"count": 0, "seconds": 0.0})
                st["count"] += 1
                st["seconds"] += float(seconds)
        except Exception:
            pass

    def record_ingest_window(self, traces: int, nbytes: int) -> None:
        """One push window appended to the columnar WAL."""
        try:
            with self._lock:
                self._ingest["windows"] += 1
                self._ingest["window_traces"] += int(traces)
                self._ingest["window_bytes"] += int(nbytes)
        except Exception:
            pass

    def record_ingest_features(self, entries: int) -> None:
        """Segment features checkpointed into the WAL."""
        try:
            with self._lock:
                self._ingest["feature_entries"] += int(entries)
        except Exception:
            pass

    def record_ingest_replay(self, records: int, features: int,
                             torn: bool = False) -> None:
        """One WAL file replayed at startup."""
        try:
            with self._lock:
                rp = self._ingest["replays"]
                rp["records"] += int(records)
                rp["features"] += int(features)
                if torn:
                    rp["torn"] += 1
        except Exception:
            pass

    def ingest_stats(self) -> dict:
        """Write-path aggregates for /status/kernels."""
        with self._lock:
            out = dict(self._ingest)
            out["stages"] = {k: dict(v) for k, v in self._ingest["stages"].items()}
            out["replays"] = dict(self._ingest["replays"])
        for st in out["stages"].values():
            st["seconds"] = round(st["seconds"], 6)
        return out

    # -------------------------------------------------------- generator
    def record_generator_stage(self, stage: str, seconds: float) -> None:
        """One streaming-generator fold interval (span-metrics /
        service-graphs) on the tap worker."""
        try:
            self.generator_stage_time.observe(float(seconds),
                                              labels=f'stage="{stage}"')
            with self._lock:
                st = self._generator["stages"].setdefault(
                    stage, {"count": 0, "seconds": 0.0})
                st["count"] += 1
                st["seconds"] += float(seconds)
        except Exception:
            pass

    def record_generator_window(self, spans: int, edges: int,
                                unpaired: int = 0, expired: int = 0) -> None:
        """One push window folded: spans aggregated, service-graph
        edges completed, plus the edge store's current unpaired depth
        and cumulative expiries."""
        try:
            with self._lock:
                g = self._generator
                g["windows"] += 1
                g["window_spans"] += int(spans)
                g["edges_completed"] += int(edges)
                g["unpaired"] = int(unpaired)
                g["expired"] = int(expired)
        except Exception:
            pass

    def record_generator_shed(self, tenant: str, n: int) -> None:
        """Spans refused a new series by max-active-series."""
        try:
            self.generator_shed.inc(
                int(n), labels=f'tenant="{_esc_label(tenant)}"')
            with self._lock:
                sh = self._generator["shed"]
                sh[tenant] = sh.get(tenant, 0) + int(n)
        except Exception:
            pass

    def record_generator_freshness(self, seconds: float) -> None:
        """Push receive -> series visible, one window."""
        try:
            self.generator_freshness.observe(float(seconds))
            with self._lock:
                g = self._generator
                g["freshness_count"] += 1
                g["freshness_sum"] += float(seconds)
                g["freshness_max"] = max(g["freshness_max"], float(seconds))
        except Exception:
            pass

    def generator_stats(self) -> dict:
        """Streaming-generator aggregates for /status/kernels."""
        with self._lock:
            out = dict(self._generator)
            out["stages"] = {k: dict(v)
                             for k, v in self._generator["stages"].items()}
            out["shed"] = dict(self._generator["shed"])
        for st in out["stages"].values():
            st["seconds"] = round(st["seconds"], 6)
        out["freshness_avg_s"] = round(
            out["freshness_sum"] / out["freshness_count"],
            6) if out["freshness_count"] else 0.0
        out["freshness_max_s"] = round(out.pop("freshness_max"), 6)
        out.pop("freshness_sum", None)
        return out

    def record_passthrough(self, nbytes: int) -> None:
        """Compressed bytes a compaction output inherited verbatim."""
        try:
            self.compact_passthrough_bytes.inc(int(nbytes))
        except Exception:
            pass

    # --------------------------------------------------------- hedging
    def record_hedge(self, outcome: str) -> None:
        """One hedged job resolved: win / lose / unneeded."""
        try:
            self.hedge_total.inc(labels=f'outcome="{outcome}"')
            with self._lock:
                self._hedges[outcome] = self._hedges.get(outcome, 0) + 1
        except Exception:
            pass

    def record_retry(self, outcome: str) -> None:
        """One retry decision: retry (granted) / budget_exhausted."""
        try:
            self.retry_total.inc(labels=f'outcome="{outcome}"')
            with self._lock:
                self._retries[outcome] = self._retries.get(outcome, 0) + 1
        except Exception:
            pass

    def hedge_stats(self) -> dict:
        with self._lock:
            return dict(self._hedges)

    def retry_stats(self) -> dict:
        with self._lock:
            return dict(self._retries)

    # --------------------------------------------------------- query log
    def record_query(self, op: str, seconds: float, trace_id: str = "",
                     detail: str = "", outcome: str = "ok") -> None:
        try:
            self.query_outcomes.inc(
                labels=f'op="{op}",outcome="{outcome}"')
        except Exception:
            pass
        artifact = ""
        try:
            # slow-query auto-capture (util/profiler): latency past the
            # query class's SLO p99 threshold snapshots the sampler
            # ring into a folded artifact whose id rides the log entry
            # beside the self-trace id -- page -> /status/slo ->
            # slow-query log -> timeline + profile
            from .profiler import PROF

            if PROF.sampling:
                artifact = PROF.capture_slow_query(op, float(seconds),
                                                   trace_id)
        except Exception:
            artifact = ""
        with self._lock:
            self._queries.append({
                "op": op,
                "seconds": round(float(seconds), 6),
                "self_trace_id": trace_id,
                "profile_artifact_id": artifact,
                "detail": detail[:200],
                "outcome": outcome,
                "at_unix": round(time.time(), 3),
            })

    def slow_queries(self, k: int = 10) -> list[dict]:
        with self._lock:
            recent = list(self._queries)
        return sorted(recent, key=lambda q: -q["seconds"])[:k]

    # --------------------------------------------------- query cost record
    def add_query_cost(self, key: str, value: float) -> None:
        """Accumulate one cost dimension onto the ACTIVE self-trace (a
        no-op when no trace is parked): device ms, staged bytes,
        compiles, verified rows. Totals become `cost.*` root attrs at
        trace finish and fold into per-tenant counters here."""
        try:
            t = _active_trace.get()
            if t is not None:
                t.add_cost(key, value)
        except Exception:
            pass

    def record_query_cost(self, tenant: str, cost: dict) -> None:
        """Fold one finished query's cost record into the per-tenant
        aggregates (bounded tenant cardinality, like QoS sheds)."""
        try:
            tenant = (tenant or "_unknown")[:128]
            with self._lock:
                key = (tenant if (tenant in self._query_costs
                                  or len(self._query_costs) < QOS_SHED_TENANTS_MAX)
                       else "_overflow")
                t = self._query_costs.setdefault(key, {"queries": 0})
                t["queries"] += 1
                for k, v in cost.items():
                    t[k] = round(t.get(k, 0) + float(v), 3)
            esc = _esc_label(key)
            self.query_cost.inc(1, labels=f'tenant="{esc}",resource="queries"')
            for k, v in cost.items():
                self.query_cost.inc(
                    float(v), labels=f'tenant="{esc}",resource="{k}"')
        except Exception:
            pass

    def query_cost_stats(self) -> dict:
        with self._lock:
            return {t: dict(v) for t, v in sorted(self._query_costs.items())}

    # --------------------------------------------------------- self-trace
    def record_selftrace(self, outcome: str, n_spans: int) -> None:
        """Self-trace shipping outcome: `shipped` spans reached the
        distributor, `dropped` spans died with their trace at the
        bounded in-flight queue (TempoSelfTraceDropped alert feed)."""
        try:
            self.selftrace_spans.inc(n_spans, labels=f'outcome="{outcome}"')
            with self._lock:
                self._selftrace[outcome] = (
                    self._selftrace.get(outcome, 0) + n_spans)
        except Exception:
            pass

    def selftrace_stats(self) -> dict:
        with self._lock:
            return dict(self._selftrace)

    def _exemplar_tid(self) -> str | None:
        """The active self-trace's id for OpenMetrics exemplars (None
        when no trace is parked -- the histogram keeps its last one)."""
        try:
            t = _active_trace.get()
            tid = getattr(t, "trace_id", None)
            return tid.hex() if tid is not None else None
        except Exception:
            return None

    @staticmethod
    def _note_profiler_thread(trace) -> None:
        """Mirror the active trace into the profiler's thread registry
        (set/reset run ON the executing thread) so background samples
        attribute to the query. One attribute check when sampling is
        off -- the profiling-off path stays effectively free."""
        try:
            from .profiler import PROF

            if PROF.sampling:
                PROF.note_thread_trace(threading.get_ident(),
                                       getattr(trace, "trace_id", None))
        except Exception:
            pass

    def set_active_trace(self, trace):
        """Park the active SelfTracer trace for this execution context;
        returns a token for reset_active_trace."""
        token = _active_trace.set(trace)
        self._note_profiler_thread(trace)
        return token

    def reset_active_trace(self, token) -> None:
        try:
            _active_trace.reset(token)
        except Exception:
            pass
        # restore the registry to whatever the context now holds
        # (nested set/reset pairs land back on the outer trace)
        self._note_profiler_thread(_active_trace.get())

    def active_trace(self):
        return _active_trace.get()

    def child_span(self, name: str, t0: float, t1: float,
                   attrs: dict | None = None) -> None:
        """Attach one child span (wall-clock seconds) to the active
        self-trace, if any. Engine code calls this per block."""
        t = _active_trace.get()
        if t is not None:
            try:
                t.child(name, t0, t1, attrs or {})
            except Exception:
                pass  # observability must never fail a query

    # ----------------------------------------------------------- readout
    def jit_cache_size(self) -> int:
        with self._lock:
            return len(self._seen)

    def totals(self) -> tuple[int, float]:
        """(total compiles, total device seconds) -- bench deltas."""
        with self._lock:
            return (sum(k["compiles"] for k in self._kernels.values()),
                    sum(k["device_seconds"] for k in self._kernels.values()))

    def launch_count(self) -> int:
        """Total device-kernel launches recorded (compiles + jit-cache
        hits across every op) -- the batching tests and the concurrent
        bench measure launches-per-query as deltas of this."""
        with self._lock:
            return sum(k["compiles"] + k["cache_hits"]
                       for k in self._kernels.values())

    def snapshot(self, slow_k: int = 10) -> dict:
        """The /status/kernels payload."""
        with self._lock:
            kernels = [
                {"op": op, "bucket": b, **dict(stats)}
                for (op, b), stats in sorted(self._kernels.items())
            ]
            rows_real = self.staged_rows_real.get()
            rows_padded = self.staged_rows_padded.get()
            routing = [
                {"layer": l, "engine": e, "reason": r, "count": n}
                for (l, e, r), n in sorted(self._routing.items())
            ]
        return {
            "jit_cache": {
                "entries": self.jit_cache_size(),
                "compiles_total": sum(k["compiles"] for k in kernels),
                "cache_hits_total": sum(k["cache_hits"] for k in kernels),
            },
            "kernels": kernels,
            "staging": {
                "transfer_bytes_total": int(self.transfer_bytes.get()),
                "rows_real_total": int(rows_real),
                "rows_padded_total": int(rows_padded),
                "padding_waste_ratio": round(
                    rows_padded / rows_real, 4) if rows_real else 0.0,
                "cache_hits": int(self.staged_cache_hits.get()),
                "cache_misses": int(self.staged_cache_misses.get()),
            },
            "routing": routing,
            "hedging": self.hedge_stats(),
            "retries": self.retry_stats(),
            "affinity": self.affinity_stats(),
            "query_costs": self.query_cost_stats(),
            "selftrace": self.selftrace_stats(),
            "batching": self.batch_stats(),
            "mesh_batch": self.mesh_batch_stats(),
            "compaction": self.compaction_stats(),
            "stream": self.stream_stats(),
            "livestage": self.livestage_stats(),
            "ingest": self.ingest_stats(),
            "generator": self.generator_stats(),
            "slow_queries": self.slow_queries(slow_k),
        }

    def metrics_lines(self) -> list[str]:
        """Exposition sample lines for /metrics (kerneltel instruments
        plus the costmodel's program/comm/HBM families -- one
        chokepoint so /metrics can't ship one plane without the
        other)."""
        out: list[str] = []
        for inst in self._instruments:
            out += inst.text()
        try:
            from .costmodel import COST

            out += COST.metrics_lines()
        except Exception:
            pass
        # chaos + circuit-breaker planes ride the same exposition
        # chokepoint so /metrics can't ship one plane without the other
        try:
            out += _chaos.metrics_lines()
        except Exception:
            pass
        try:
            from . import breaker as _breaker

            out += _breaker.metrics_lines()
        except Exception:
            pass
        # continuous-profiling plane: sampler/lock-wait/log/runtime
        # families ride the same chokepoint, so every /metrics surface
        # (app, vulture sidecars) ships them with the rest
        try:
            from . import profiler as _profiler

            out += _profiler.metrics_lines()
        except Exception:
            pass
        try:
            from . import log as _log

            out += _log.metrics_lines()
        except Exception:
            pass
        try:
            from . import runtimestats as _rt

            out += _rt.metrics_lines()
        except Exception:
            pass
        return out

    def help_entries(self) -> dict[str, str]:
        """family -> help for the exposition renderer."""
        out = {}
        for inst in self._instruments:
            fam = inst.name[:-6] if inst.name.endswith("_total") else inst.name
            out[fam] = inst.help
        try:
            from .costmodel import COST

            out.update(COST.help_entries())
        except Exception:
            pass
        try:
            out.update(_chaos.help_entries())
        except Exception:
            pass
        try:
            from . import breaker as _breaker

            out.update(_breaker.help_entries())
        except Exception:
            pass
        try:
            from . import profiler as _profiler

            out.update(_profiler.help_entries())
        except Exception:
            pass
        try:
            from . import log as _log

            out.update(_log.help_entries())
        except Exception:
            pass
        try:
            from . import runtimestats as _rt

            out.update(_rt.help_entries())
        except Exception:
            pass
        return out

    def reset(self) -> None:
        """Fresh state (tests). Callers must reference instruments via
        TEL attributes, never cache them across a reset. The costmodel's
        launch/program tables reset with the kernel table they mirror."""
        self.__init__()
        try:
            from .costmodel import COST

            COST.reset()
        except Exception:
            pass


TEL = KernelTelemetry()
