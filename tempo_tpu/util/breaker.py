"""Circuit breakers + per-query retry budgets (the read-path armor the
chaos plane forces).

CircuitBreaker: error-rate tripping with half-open probes. A leg
(backend block jobs, a remote ingester client) records each outcome into a
sliding time window; once volume and error rate cross the thresholds
the breaker opens and `allow()` sheds callers fast instead of letting
every query pay the full failure (timeout, retries, hedges) against a
dying dependency. After `open_s` it half-opens: a bounded number of
probe calls go through; all-success closes it, any failure re-opens.
Sheds land on the EXISTING per-class failure policy: a shed search
shard degrades coverage (partial results, query still 200), while
find/metrics queries -- whose shard-loss rule forbids silent partials
-- fail FAST with the breaker open instead of timing out against the
dead dependency. Either way no call pays the failing leg's latency.

RetryBudget: one counter per query capping TOTAL retries across all of
its shard jobs. Per-job retry caps compose multiplicatively with shard
fan-out -- a dying backend used to be able to trigger jobs x retries
extra load exactly when it could least afford it. The budget makes the
worst case additive.

Registry: breakers are process-wide singletons by leg name (like the
kerneltel registry) so the frontend, querier legs, /status surfaces and
/metrics all see one state. Defaults come from TEMPO_BREAKER_* env vars
read at creation time.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from .metrics import Counter, Gauge

STATES = ("closed", "half_open", "open")

# env-tunable creation defaults
ENV_DEFAULTS = {
    "window_s": ("TEMPO_BREAKER_WINDOW_S", 30.0),
    "min_volume": ("TEMPO_BREAKER_MIN_VOLUME", 8),
    "error_rate": ("TEMPO_BREAKER_ERROR_RATE", 0.5),
    "open_s": ("TEMPO_BREAKER_OPEN_S", 5.0),
    "probes": ("TEMPO_BREAKER_PROBES", 2),
    "probe_timeout_s": ("TEMPO_BREAKER_PROBE_TIMEOUT_S", 30.0),
}

STATE_GAUGE = Gauge(
    "tempo_circuit_breaker_state",
    help="breaker state by leg (0 closed, 1 half-open, 2 open)")
TRANSITIONS = Counter(
    "tempo_circuit_breaker_transitions_total",
    help="breaker state transitions by leg and destination state")
SHEDS = Counter(
    "tempo_circuit_breaker_sheds_total",
    help="calls refused fast by an open breaker, by leg")


class CircuitOpen(Exception):
    """Raised/recorded when a breaker sheds a call. Deliberately NOT an
    OSError: a shed must not be retried into the same open breaker."""


def _env_num(name: str, default):
    try:
        raw = os.environ.get(name, "")
        return type(default)(raw) if raw else default
    except ValueError:
        return default


class CircuitBreaker:
    def __init__(self, name: str, window_s: float | None = None,
                 min_volume: int | None = None,
                 error_rate: float | None = None,
                 open_s: float | None = None, probes: int | None = None,
                 probe_timeout_s: float | None = None):
        env = {k: _env_num(e, d) for k, (e, d) in ENV_DEFAULTS.items()}
        self.name = name
        self.window_s = window_s if window_s is not None else env["window_s"]
        self.min_volume = (min_volume if min_volume is not None
                           else env["min_volume"])
        self.error_rate = (error_rate if error_rate is not None
                           else env["error_rate"])
        self.open_s = open_s if open_s is not None else env["open_s"]
        self.probes = probes if probes is not None else env["probes"]
        self.probe_timeout_s = (probe_timeout_s if probe_timeout_s is not None
                                else env["probe_timeout_s"])
        # cataloged hot lock: every guarded call crosses allow()/record()
        # here (TEMPO_LOCK_PROFILE arms contention timing)
        from .profiler import timed_lock

        self._lock = timed_lock("breaker")
        self.state = "closed"
        self._window: deque = deque()  # (monotonic, ok)
        self._opened_at = 0.0
        # half-open probe slots: grant timestamps, so a slot whose call
        # never comes back (dead worker, expired lease -- paths that
        # allow() without a matching record()) is reclaimed after
        # probe_timeout_s instead of wedging the breaker half-open
        self._probe_slots: list[float] = []
        self._probe_successes = 0
        self.transitions: list[tuple[float, str]] = []  # (unix, to-state)
        self._publish_locked()

    # ------------------------------------------------------------ state
    def _to_locked(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        self.transitions.append((time.time(), state))
        del self.transitions[:-64]
        TRANSITIONS.inc(labels=f'leg="{self.name}",to="{state}"')
        self._publish_locked()

    def _publish_locked(self) -> None:
        STATE_GAUGE.set(STATES.index(self.state),
                        labels=f'leg="{self.name}"')

    def allow(self) -> bool:
        """May a call proceed on this leg right now? False = shed."""
        with self._lock:
            now = time.monotonic()
            if self.state == "open":
                if now - self._opened_at >= self.open_s:
                    self._to_locked("half_open")
                    self._probe_slots = []
                    self._probe_successes = 0
                else:
                    SHEDS.inc(labels=f'leg="{self.name}"')
                    return False
            if self.state == "half_open":
                # reclaim slots whose call never reported back
                cutoff = now - self.probe_timeout_s
                self._probe_slots = [t for t in self._probe_slots
                                     if t >= cutoff]
                if len(self._probe_slots) < self.probes:
                    self._probe_slots.append(now)
                    return True
                SHEDS.inc(labels=f'leg="{self.name}"')
                return False
            return True

    def record(self, ok: bool) -> None:
        with self._lock:
            now = time.monotonic()
            if self.state == "half_open":
                if self._probe_slots:
                    self._probe_slots.pop(0)
                if not ok:
                    self._opened_at = now
                    self._to_locked("open")
                    return
                self._probe_successes += 1
                if self._probe_successes >= self.probes:
                    self._window.clear()
                    self._to_locked("closed")
                return
            self._window.append((now, ok))
            cutoff = now - self.window_s
            while self._window and self._window[0][0] < cutoff:
                self._window.popleft()
            if self.state == "closed":
                vol = len(self._window)
                errs = sum(1 for _, o in self._window if not o)
                if vol >= self.min_volume and errs / vol >= self.error_rate:
                    self._opened_at = now
                    self._to_locked("open")

    def snapshot(self) -> dict:
        with self._lock:
            vol = len(self._window)
            errs = sum(1 for _, o in self._window if not o)
            return {
                "state": self.state,
                "window_volume": vol,
                "window_errors": errs,
                "error_rate": round(errs / vol, 4) if vol else 0.0,
                "transitions": [
                    {"at_unix": round(t, 3), "to": s}
                    for t, s in self.transitions[-8:]],
            }


class RetryBudget:
    """Total-retry cap shared by all shard jobs of one query."""

    def __init__(self, total: int):
        self.total = max(0, int(total))
        self.used = 0
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self.used < self.total:
                self.used += 1
                return True
            return False


# ------------------------------------------------------------ registry
_breakers: dict[str, CircuitBreaker] = {}
_registry_lock = threading.Lock()


def get_breaker(name: str, **params) -> CircuitBreaker:
    with _registry_lock:
        br = _breakers.get(name)
        if br is None:
            br = _breakers[name] = CircuitBreaker(name, **params)
        return br


def breakers_snapshot() -> dict:
    with _registry_lock:
        legs = list(_breakers.items())
    return {name: br.snapshot() for name, br in legs}


def reset_for_tests() -> None:
    with _registry_lock:
        _breakers.clear()


def metrics_lines() -> list[str]:
    return STATE_GAUGE.text() + TRANSITIONS.text() + SHEDS.text()


def help_entries() -> dict[str, str]:
    return {
        STATE_GAUGE.name: STATE_GAUGE.help,
        "tempo_circuit_breaker_transitions": TRANSITIONS.help,
        "tempo_circuit_breaker_sheds": SHEDS.help,
    }
