"""CostLedger: persisted measured-crossover store for engine routing.

Every engine router in this tree weighs "host arithmetic, zero device
round trips" against "device kernel, ~fixed dispatch cost". Until now
each router learned that trade from scratch every process start
(live_engine's EMA), assumed it (ops/find's single-chip-means-host
rule), or seeded it from a hardcoded constant (db/search's host-rate
EMA). The ledger makes those measurements durable: a small JSON
artifact, atomically published (tmp file + os.replace) so readers never
see a torn write, loaded once at startup and consulted by:

  * ops/find -- the `auto` find policy routes host-vs-device from the
    measured race `tempo-tpu-cli calibrate` (or the bench's
    find_auto_crossover_rows row) committed under key "find";
  * db/live_engine -- seeds its host-s/row and device-fixed-s EMAs from
    key "live_search" instead of the TEMPO_LIVE_CROSSOVER_ROWS guess
    (the env var still wins when set);
  * db/search -- seeds the cold-scan host-rate EMA from key
    "block_scan" instead of the DDR-ish constant.

Resolution order for the artifact path: explicit configure() (the app
wires <storage.path>/cost_ledger.json), else the TEMPO_COST_LEDGER env
var, else no persistence (an in-memory ledger: updates work, publish is
a no-op -- bench/CLI runs against throwaway stores stay self-contained).

A corrupt or unreadable artifact must never take routing down: load
falls back to an empty ledger, remembers the error (surfaced in
/status/cost), and the next publish rewrites the artifact whole.
"""

from __future__ import annotations

import json
import os
import threading
import time

LEDGER_ENV = "TEMPO_COST_LEDGER"
SCHEMA_VERSION = 1

# routing keys with committed meaning (callers may add more; these are
# the ones the shipped routers consult)
KEY_FIND = "find"
KEY_LIVE_SEARCH = "live_search"
KEY_BLOCK_SCAN = "block_scan"


class CostLedger:
    """One JSON artifact of measured crossovers. Thread-safe; reads
    return copies so callers can't mutate shared state."""

    def __init__(self, path: str = ""):
        self.path = path or ""
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        self.load_error = ""
        if self.path:
            self._load()

    # -------------------------------------------------------------- load
    def _load(self) -> None:
        try:
            with open(self.path, "rb") as f:
                data = json.loads(f.read())
            if not isinstance(data, dict) or not isinstance(
                    data.get("entries"), dict):
                raise ValueError("ledger root must be "
                                 '{"version": int, "entries": {...}}')
            self._entries = {
                str(k): dict(v) for k, v in data["entries"].items()
                if isinstance(v, dict)
            }
        except FileNotFoundError:
            pass  # first run: publish() creates it
        except Exception as e:  # corrupt artifact: degrade loudly, keep serving
            self.load_error = f"{type(e).__name__}: {e}"
            self._entries = {}
            from .log import get_logger

            get_logger("costledger").error(
                "cost ledger %s unreadable (%s); starting from an "
                "empty ledger", self.path, self.load_error)

    # ------------------------------------------------------------- access
    def get(self, key: str) -> dict | None:
        with self._lock:
            e = self._entries.get(key)
            return dict(e) if e is not None else None

    def entries(self) -> dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    def update(self, key: str, **fields) -> dict:
        """Merge fields into an entry (stamping measured_at_unix) and
        return the merged copy. Call publish() to persist."""
        with self._lock:
            e = self._entries.setdefault(key, {})
            e.update(fields)
            e["measured_at_unix"] = round(time.time(), 3)
            return dict(e)

    # ------------------------------------------------------------ publish
    def publish(self) -> bool:
        """Atomically write the artifact (tmp + rename). Returns True on
        a durable write, False when pathless or the write failed --
        routing never depends on persistence succeeding."""
        if not self.path:
            return False
        with self._lock:
            doc = {"version": SCHEMA_VERSION,
                   "entries": {k: dict(v) for k, v in self._entries.items()}}
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)  # atomic publish: readers see old or new
            return True
        except OSError as e:
            from .log import get_logger

            get_logger("costledger").error(
                "cost ledger publish to %s failed: %s", self.path, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def to_dict(self) -> dict:
        return {"path": self.path, "load_error": self.load_error,
                "entries": self.entries()}


# process-wide singleton: routers consult ledger() at decision time; the
# app (or a test) points it somewhere with configure()
_singleton_lock = threading.Lock()
_singleton: CostLedger | None = None


def ledger() -> CostLedger:
    global _singleton
    with _singleton_lock:
        if _singleton is None:
            _singleton = CostLedger(os.environ.get(LEDGER_ENV, ""))
        return _singleton


def configure(path: str) -> CostLedger:
    """(Re)point the process ledger at an artifact path and load it.
    The app calls this with <storage.path>/cost_ledger.json; tests call
    it with tmp paths. An explicit TEMPO_COST_LEDGER env var wins over
    the app default (the operator aimed it somewhere on purpose)."""
    global _singleton
    with _singleton_lock:
        _singleton = CostLedger(path)
        return _singleton


def reset_for_tests() -> None:
    global _singleton
    with _singleton_lock:
        _singleton = None
