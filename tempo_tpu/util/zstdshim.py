"""zlib-backed stand-in for the `zstandard` wheel.

Some images lack the zstandard wheel; importing it at module scope took
the whole block layer (and ~20 test modules) down with it. The wheel
stays the real codec wherever it exists -- import sites gate on
ModuleNotFoundError and fall back here, which implements exactly the
API surface this repo touches (ZstdCompressor(level=).compress,
ZstdDecompressor().decompress(data, max_output_size=)) over stdlib
zlib.

Compatibility contract: within one deployment the shim is
self-consistent (blocks written under it read back under it). It can
NEVER decode a real zstd frame -- attempting to read a block produced
by an environment that had the wheel fails loudly with the actual
cause instead of zlib garbage. The inverse also holds: STORAGE objects
(block chunks, dictionaries) written under the shim are readable only
by shim environments, so don't share a backend across mixed images.
The transport layer is exempt by construction -- frames._seal ships
uncompressed rather than tag shim output as zstd, so RPC stays
compatible across a mixed-image fleet.
"""

from __future__ import annotations

import zlib

_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"


class ZstdError(Exception):
    pass


class ZstdCompressor:
    def __init__(self, level: int = 3, **_kw):
        # zstd levels run 1..22, zlib 1..9: clamp rather than scale --
        # the callers only use small levels (1..6)
        self.level = max(1, min(int(level), 9))

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(bytes(data), self.level)


class ZstdDecompressor:
    def decompress(self, data: bytes, max_output_size: int = 0) -> bytes:
        if bytes(data[:4]) == _ZSTD_MAGIC:
            raise ZstdError(
                "real zstd frame but the zstandard wheel is not installed "
                "(this data was written by an environment that had it)")
        out = zlib.decompress(bytes(data))
        if max_output_size and len(out) > max_output_size:
            raise ZstdError(
                f"decompressed {len(out)} bytes > max_output_size {max_output_size}")
        return out
