"""AOT warmup: pre-compile the known (op, shape-bucket) corpus at
startup (ROADMAP item 5's leftover).

kerneltel's record_launch notes every FIRST compile of an (op, bucket)
pair into the CostLedger (key `compile_corpus`) -- the durable record
of which program shapes this deployment actually serves. A process
started with `--warmup.shapes` replays that corpus through registered
warmup builders BEFORE serving: each builder compiles a canonical
program of that op at that bucket, which (a) populates the in-process
jit caches and (b) pulls the persistent XLA compilation cache
(TEMPO_COMPILE_CACHE_DIR) off disk ahead of the first query, so the
first-query p99 stops paying the compile storm.

Builders are canonical, not exhaustive: the filter builder compiles a
single-predicate program per row bucket -- real queries with other
tree shapes still compile on first use, but the dominant storm (the
per-bucket base programs, and with the disk cache every previously
seen program) is paid before the listen socket opens. The
`first_query_compile_p99_ms` bench row carries a warmup-on leg
measuring exactly this.
"""

from __future__ import annotations

import threading
import time

from . import costledger

CORPUS_KEY = "compile_corpus"
CORPUS_MAX = 256  # distinct (op, bucket) pairs remembered

_lock = threading.Lock()
_seen: set[tuple[str, str]] = set()
_builders: dict[str, object] = {}


def register_builder(op: str, fn) -> None:
    """fn(bucket: int) compiles the canonical program of `op` at that
    row bucket (and blocks until ready)."""
    with _lock:
        _builders[op] = fn


def note_compile(op: str, bucket_label: str) -> None:
    """Record one first-compile into the ledger corpus (deduplicated,
    bounded, best-effort -- called from kerneltel.record_launch). The
    ledger read-modify-write stays under the module lock: two threads
    first-compiling different pairs concurrently would otherwise each
    publish a corpus missing the other's entry, and the in-process
    _seen gate would prevent the lost pair from ever being re-noted."""
    pair = (str(op), str(bucket_label))
    with _lock:
        if pair in _seen or len(_seen) >= CORPUS_MAX:
            return
        _seen.add(pair)
        led = costledger.ledger()
        ent = led.get(CORPUS_KEY) or {}
        pairs = {tuple(p) for p in ent.get("pairs", []) if len(p) == 2}
        if pair in pairs:
            return
        pairs.add(pair)
        led.update(CORPUS_KEY, pairs=sorted([list(p) for p in pairs]))
        led.publish()


def corpus() -> list[tuple[str, str]]:
    ent = costledger.ledger().get(CORPUS_KEY) or {}
    return [tuple(p) for p in ent.get("pairs", []) if len(p) == 2]


def reset_for_tests() -> None:
    with _lock:
        _seen.clear()


def _warm_filter(nb: int) -> None:
    """Canonical fused-filter program: one span predicate, all axes at
    the same bucket -- the base program every search compiles first."""
    import jax
    import numpy as np

    from ..ops.device import PAD_I32, pad_rows
    from ..ops.filter import Cond, Operands, T_SPAN, eval_block

    n = min(64, nb)
    cols = {
        "span.trace_sid": pad_rows(np.zeros(n, np.int32), nb, PAD_I32),
        "span.dur_us": pad_rows(np.arange(n, dtype=np.int32), nb, PAD_I32),
        "trace.span_off": pad_rows(np.asarray([0, n], np.int32), nb + 1,
                                   np.int32(n)),
    }
    conds = (Cond(target=T_SPAN, col="span.dur_us", op="ge"),)
    ops = Operands.build([(0, 10, 0, 0.0, 0.0)])
    jax.block_until_ready(
        eval_block((("cond", 0), conds), cols, ops, n, 1, nb, nb, nb))


register_builder("filter", _warm_filter)


def run_warmup() -> dict:
    """Compile the ledger corpus through the registered builders.
    Returns the report the app surfaces ({warmed, skipped, errors,
    wall_ms}); never raises -- a warmup failure must not stop serving."""
    t0 = time.perf_counter()
    with _lock:
        builders = dict(_builders)
    warmed: list[list[str]] = []
    skipped: list[list[str]] = []
    errors: list[str] = []
    for op, blab in corpus():
        fn = builders.get(op)
        if fn is None:
            skipped.append([op, blab])
            continue
        try:
            nb = int(blab)
        except ValueError:
            skipped.append([op, blab])
            continue
        try:
            fn(nb)
            warmed.append([op, blab])
        except Exception as e:  # noqa: BLE001 - warmup is best-effort
            errors.append(f"{op}@{blab}: {type(e).__name__}: {e}")
    return {
        "warmed": warmed,
        "skipped": skipped,
        "errors": errors,
        "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }
