"""Byte-bounded distinct-string collection for tag/value APIs.

Capability parity with the reference's DistinctStringCollector
(pkg/util/distinct_string_collector.go:15): collect unique strings until
a byte budget is hit, then drop further additions.
"""

from __future__ import annotations


class DistinctStringCollector:
    def __init__(self, max_bytes: int = 0):
        self._max = max_bytes
        self._size = 0
        self._values: set[str] = set()
        self.exceeded = False

    def collect(self, s: str) -> None:
        if s in self._values:
            return
        n = len(s.encode("utf-8"))
        if self._max and self._size + n > self._max:
            self.exceeded = True
            return
        self._values.add(s)
        self._size += n

    def strings(self) -> list[str]:
        return sorted(self._values)

    def __len__(self) -> int:
        return len(self._values)
