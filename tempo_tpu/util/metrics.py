"""Minimal Prometheus-style instruments.

The role promauto plays across the reference (histograms + counters on
every subsystem, e.g. modules/distributor/distributor.go:56-103,
tempodb/blocklist/poller.go:26-68), sized to this codebase: lock-free
enough for the hot paths (float adds under a small lock), rendered to
exposition text by /metrics.
"""

from __future__ import annotations

import threading
import time

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Histogram:
    """Cumulative-bucket latency histogram."""

    def __init__(self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        self.name = name
        self.buckets = buckets
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}
        self._totals: dict[str, int] = {}

    def observe(self, value: float, labels: str = "") -> None:
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * (len(self.buckets) + 1)
                self._sums[labels] = 0.0
                self._totals[labels] = 0
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[labels] += value
            self._totals[labels] += 1

    def text(self) -> list[str]:
        out = []
        with self._lock:
            for labels, counts in self._counts.items():
                sep = "," if labels else ""
                cum = 0
                for i, edge in enumerate(self.buckets):
                    cum += counts[i]
                    out.append(f'{self.name}_bucket{{{labels}{sep}le="{edge}"}} {cum}')
                cum += counts[-1]
                out.append(f'{self.name}_bucket{{{labels}{sep}le="+Inf"}} {cum}')
                out.append(f"{self.name}_sum{{{labels}}} {self._sums[labels]:.6f}")
                out.append(f"{self.name}_count{{{labels}}} {self._totals[labels]}")
        return out


class Counter:
    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._vals: dict[str, float] = {}

    def inc(self, n: float = 1, labels: str = "") -> None:
        with self._lock:
            self._vals[labels] = self._vals.get(labels, 0) + n

    def get(self, labels: str = "") -> float:
        with self._lock:
            return self._vals.get(labels, 0)

    def text(self) -> list[str]:
        with self._lock:
            return [
                f"{self.name}{{{labels}}} {v:g}" if labels else f"{self.name} {v:g}"
                for labels, v in self._vals.items()
            ]


class _Timed:
    __slots__ = ("hist", "labels", "t0")

    def __init__(self, hist, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, self.labels)
        return False


def timed(hist: Histogram, labels: str = ""):
    """Context manager: observe the block's wall time."""
    return _Timed(hist, labels)
