"""Minimal Prometheus-style instruments.

The role promauto plays across the reference (histograms + counters on
every subsystem, e.g. modules/distributor/distributor.go:56-103,
tempodb/blocklist/poller.go:26-68), sized to this codebase: lock-free
enough for the hot paths (float adds under a small lock), rendered to
exposition text by /metrics.

Exposition: instruments emit raw sample lines (`.text()`); the
/metrics endpoint runs everything through `render_openmetrics`, which
groups samples into families, synthesizes the `# TYPE` / `# HELP`
lines strict OpenMetrics parsers require, and never renders an empty
`{}` label set.
"""

from __future__ import annotations

import re
import threading
import time

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt(name: str, labels: str) -> str:
    """Sample name with labels; empty label sets render bare (OpenMetrics
    forbids `name{}`)."""
    return f"{name}{{{labels}}}" if labels else name


def escape_label(v: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline).
    Any caller-controlled string reaching a label value -- tenant names
    off the X-Scope-OrgID header above all -- must pass through here:
    one unescaped quote corrupts every subsequent /metrics scrape line.
    (The static checker's metric-label-cardinality rule enforces this
    for tenant=/key=/query= label interpolations.)"""
    return (v.replace("\\", "\\\\").replace('"', '\\"')
             .replace("\n", "\\n"))


class Histogram:
    """Cumulative-bucket latency histogram.

    Exemplars (OpenMetrics): observe(..., exemplar="<trace-id>") keeps
    the most recent exemplar per label set; text() renders it as a
    `# {trace_id="..."} value` suffix on the bucket line its value
    falls in -- so a latency histogram on /metrics links straight to a
    self-trace of a query that landed in that bucket."""

    def __init__(self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                 help: str = ""):
        self.name = name
        self.help = help
        self.buckets = buckets
        self._lock = threading.Lock()
        self._counts: dict[str, list[int]] = {}
        self._sums: dict[str, float] = {}
        self._totals: dict[str, int] = {}
        # labels -> (exemplar trace id, observed value): last one wins
        self._exemplars: dict[str, tuple[str, float]] = {}

    def observe(self, value: float, labels: str = "",
                exemplar: str | None = None) -> None:
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * (len(self.buckets) + 1)
                self._sums[labels] = 0.0
                self._totals[labels] = 0
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[labels] += value
            self._totals[labels] += 1
            if exemplar:
                self._exemplars[labels] = (exemplar, float(value))

    def snapshot(self) -> dict[str, tuple[list[int], float, int]]:
        """labels -> (per-bucket counts incl. overflow, sum, total).
        Counts are NON-cumulative (one entry per bucket edge plus the
        +Inf overflow) -- the SLI readers in util/slo threshold on
        them without re-deriving from the cumulative exposition."""
        with self._lock:
            return {labels: (list(counts), self._sums[labels],
                             self._totals[labels])
                    for labels, counts in self._counts.items()}

    def text(self) -> list[str]:
        out = []
        with self._lock:
            for labels, counts in self._counts.items():
                sep = "," if labels else ""
                ex = self._exemplars.get(labels)
                cum = 0
                for i, edge in enumerate(self.buckets):
                    cum += counts[i]
                    line = f'{self.name}_bucket{{{labels}{sep}le="{edge}"}} {cum}'
                    if ex is not None and ex[1] <= edge:
                        line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6g}'
                        ex = None  # one exemplar, on its own bucket
                    out.append(line)
                cum += counts[-1]
                line = f'{self.name}_bucket{{{labels}{sep}le="+Inf"}} {cum}'
                if ex is not None:
                    line += f' # {{trace_id="{ex[0]}"}} {ex[1]:.6g}'
                out.append(line)
                out.append(f"{_fmt(self.name + '_sum', labels)} {self._sums[labels]:.6f}")
                out.append(f"{_fmt(self.name + '_count', labels)} {self._totals[labels]}")
        return out


class Counter:
    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals: dict[str, float] = {}

    def inc(self, n: float = 1, labels: str = "") -> None:
        with self._lock:
            self._vals[labels] = self._vals.get(labels, 0) + n

    def get(self, labels: str = "") -> float:
        with self._lock:
            return self._vals.get(labels, 0)

    def snapshot(self) -> dict[str, float]:
        """labels -> cumulative value, every label set."""
        with self._lock:
            return dict(self._vals)

    def text(self) -> list[str]:
        with self._lock:
            return [f"{_fmt(self.name, labels)} {v:g}"
                    for labels, v in self._vals.items()]


class Gauge:
    """Point-in-time value (jit-cache size, blocklist length, WAL depth):
    set at scrape or event time, rendered like any other instrument."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._vals: dict[str, float] = {}

    def set(self, value: float, labels: str = "") -> None:
        with self._lock:
            self._vals[labels] = float(value)

    def inc(self, n: float = 1, labels: str = "") -> None:
        with self._lock:
            self._vals[labels] = self._vals.get(labels, 0.0) + n

    def dec(self, n: float = 1, labels: str = "") -> None:
        self.inc(-n, labels)

    def get(self, labels: str = "") -> float:
        with self._lock:
            return self._vals.get(labels, 0.0)

    def text(self) -> list[str]:
        with self._lock:
            return [f"{_fmt(self.name, labels)} {v:g}"
                    for labels, v in self._vals.items()]


class _Timed:
    __slots__ = ("hist", "labels", "t0")

    def __init__(self, hist, labels):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, self.labels)
        return False


def timed(hist: Histogram, labels: str = ""):
    """Context manager: observe the block's wall time."""
    return _Timed(hist, labels)


# ------------------------------------------------------------ exposition

_NAME_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)")
_EMPTY_BRACES_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)\{\}")


class Registry:
    """Instrument registry: one object owning a set of instruments and
    their exposition (the role promauto's default registerer plays).
    Subsystems with their own /metrics endpoint (vulture) register
    every instrument here so samples can't ship without HELP/TYPE --
    the same one-list discipline kerneltel keeps by hand."""

    def __init__(self):
        self._instruments: list = []

    def register(self, inst):
        self._instruments.append(inst)
        return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self.register(Counter(name, help=help))

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self.register(Gauge(name, help=help))

    def histogram(self, name: str, buckets: tuple[float, ...] = DEFAULT_BUCKETS,
                  help: str = "") -> Histogram:
        return self.register(Histogram(name, buckets=buckets, help=help))

    def lines(self) -> list[str]:
        out: list[str] = []
        for inst in self._instruments:
            out += inst.text()
        return out

    def helps(self) -> dict[str, str]:
        out = {}
        for inst in self._instruments:
            fam = (inst.name[:-6] if inst.name.endswith("_total")
                   else inst.name)
            out[fam] = inst.help
        return out

    def render(self, extra_lines: list[str] | None = None,
               extra_helps: dict[str, str] | None = None) -> str:
        helps = self.helps()
        if extra_helps:
            helps.update(extra_helps)
        return render_openmetrics(self.lines() + (extra_lines or []),
                                  helps=helps)


def _family_of(name: str, hist_bases: set[str]) -> tuple[str, str]:
    """Sample name -> (family, type) per OpenMetrics suffix rules."""
    if name.endswith("_bucket") and name[:-7] in hist_bases:
        return name[:-7], "histogram"
    if name.endswith("_sum") and name[:-4] in hist_bases:
        return name[:-4], "histogram"
    if name.endswith("_count") and name[:-6] in hist_bases:
        return name[:-6], "histogram"
    if name.endswith("_total"):
        return name[:-6], "counter"
    return name, "gauge"


def render_openmetrics(lines: list[str], helps: dict[str, str] | None = None) -> str:
    """Raw sample lines -> OpenMetrics exposition text (no EOF marker).

    Groups samples into metric families, synthesizes `# TYPE`/`# HELP`
    per family (type inferred from the `_total` / `_bucket`+`le=` suffix
    conventions every emitter in this repo follows), strips empty `{}`
    label sets, and drops exact-duplicate sample lines -- strict parsers
    reject duplicates and interleaved families. Sample lines themselves
    pass through verbatim (exemplar suffixes included)."""
    helps = helps or {}
    seen: set[str] = set()
    samples: list[tuple[str, str]] = []  # (name, line)
    hist_bases: set[str] = set()
    for ln in lines:
        if not ln or ln.startswith("#"):
            continue
        ln = _EMPTY_BRACES_RE.sub(r"\1", ln)
        if ln in seen:
            continue
        seen.add(ln)
        m = _NAME_RE.match(ln)
        if m is None:
            continue
        name = m.group(1)
        samples.append((name, ln))
        if name.endswith("_bucket") and 'le="' in ln:
            hist_bases.add(name[:-7])
    families: dict[str, tuple[str, list[str]]] = {}
    order: list[str] = []
    for name, ln in samples:
        fam, typ = _family_of(name, hist_bases)
        if fam not in families:
            families[fam] = (typ, [])
            order.append(fam)
        families[fam][1].append(ln)
    out: list[str] = []
    for fam in order:
        typ, fam_lines = families[fam]
        out.append(f"# HELP {fam} {helps.get(fam, f'tempo-tpu {typ} {fam}')}")
        out.append(f"# TYPE {fam} {typ}")
        out.extend(fam_lines)
    return "\n".join(out) + "\n" if out else ""
