"""Random OTLP trace generation for tests and benchmarks.

Role parity with the reference's pkg/util/test MakeTrace helpers used
throughout its test suite (SURVEY.md section 4.4). Deterministic given a
seed so golden tests are stable.
"""

from __future__ import annotations

import random

from ..wire.model import Event, Resource, ResourceSpans, Scope, ScopeSpans, Span, Trace

_SERVICES = ["api-gateway", "auth", "cart", "checkout", "db", "frontend", "payments", "search"]
_OPS = ["GET /", "GET /api", "POST /api/orders", "db.query", "cache.get", "rpc.Call", "render"]
_HTTP_METHODS = ["GET", "POST", "PUT", "DELETE"]


def make_trace_id(rng: random.Random) -> bytes:
    return rng.getrandbits(128).to_bytes(16, "big")


def make_span_id(rng: random.Random) -> bytes:
    return rng.getrandbits(64).to_bytes(8, "big")


def make_trace(
    rng: random.Random | int = 0,
    trace_id: bytes | None = None,
    n_spans: int = 8,
    base_time_ns: int = 1_700_000_000_000_000_000,
    n_batches: int = 2,
) -> Trace:
    if isinstance(rng, int):
        rng = random.Random(rng)
    tid = trace_id or make_trace_id(rng)
    t = Trace()
    span_ids: list[bytes] = []
    per_batch = max(1, n_spans // max(1, n_batches))
    remaining = n_spans
    while remaining > 0:
        n = min(per_batch, remaining)
        remaining -= n
        svc = rng.choice(_SERVICES)
        rs = ResourceSpans(
            resource=Resource(
                attrs={
                    "service.name": svc,
                    "k8s.cluster.name": "prod",
                    "k8s.namespace.name": rng.choice(["default", "apps"]),
                }
            )
        )
        ss = ScopeSpans(scope=Scope(name="test-scope", version="1"))
        for _ in range(n):
            start = base_time_ns + rng.randrange(0, 10**9)
            dur = rng.randrange(10_000, 2 * 10**9)
            sid = make_span_id(rng)
            sp = Span(
                trace_id=tid,
                span_id=sid,
                parent_span_id=rng.choice(span_ids) if span_ids and rng.random() < 0.7 else b"",
                name=rng.choice(_OPS),
                kind=rng.randrange(1, 6),
                start_unix_nano=start,
                end_unix_nano=start + dur,
                status_code=2 if rng.random() < 0.1 else 0,
                attrs={
                    "http.method": rng.choice(_HTTP_METHODS),
                    "http.status_code": rng.choice([200, 200, 200, 404, 500]),
                    "component": rng.choice(["net/http", "grpc", "sql"]),
                    "latency.weight": rng.random(),
                    "cache.hit": rng.random() < 0.5,
                },
            )
            if rng.random() < 0.3:
                sp.events.append(
                    Event(time_unix_nano=start + dur // 2, name="exception", attrs={"exception.type": "IOError"})
                )
            span_ids.append(sid)
            ss.spans.append(sp)
        rs.scope_spans.append(ss)
        t.resource_spans.append(rs)
    return t


def make_traces(
    n: int, seed: int = 0, n_spans: int = 8,
    base_time_ns: int = 1_700_000_000_000_000_000,
) -> list[tuple[bytes, Trace]]:
    """n distinct traces, sorted by trace id (block-build friendly)."""
    rng = random.Random(seed)
    out = []
    seen = set()
    while len(out) < n:
        tid = make_trace_id(rng)
        if tid in seen:
            continue
        seen.add(tid)
        out.append((tid, make_trace(rng, trace_id=tid, n_spans=n_spans, base_time_ns=base_time_ns)))
    out.sort(key=lambda p: p[0])
    return out
