"""Continuous profiling plane: where did the host CPU and lock time go.

Kerneltel (util/kerneltel) answers *which op* was slow and the
self-trace timelines (services/selftrace) answer *which stage*; this
module answers *where inside a stage* the host CPU went -- the missing
layer for tail-latency work, playing the role the reference gets for
free from Go's pprof (SURVEY.md 5.1, cmd/tempo/main.go mutex-profile
flag). Four faces, all advisory (nothing here may fail or perturb a
query; profiling off means bit-identical outputs and zero added
kernel launches):

  * an ALWAYS-ON low-rate background sampler (default ~19 Hz --
    deliberately co-prime with common 10/20/100 Hz periodic work so it
    can't alias against it; TEMPO_PROFILE_HZ, 0 = off) over
    sys._current_frames(). Each sample is attributed to a COMPONENT
    (innermost tempo_tpu frame: ops/db/frontend/ingester/...) and,
    via a thread registry maintained by kerneltel's
    set_active_trace/reset_active_trace, to the ACTIVE QUERY's
    self-trace id. Samples aggregate into a bounded folded-stack
    table (tempo_profile_samples_total{component}, /status/profile
    top stacks, flamegraph-ready folded text) and a time-bounded
    ring buffer that slow-query auto-capture snapshots.
  * ON-DEMAND captures: sample_cpu() is the /debug/profile burst
    profiler (high rate, bounded seconds, text or folded output) and
    capture_device_profile() wraps jax.profiler's trace into a
    downloadable artifact -- both publish through the ArtifactStore.
  * LOCK-CONTENTION profiling: timed_lock()/timed_rlock() factories
    return plain threading locks until TEMPO_LOCK_PROFILE=1 arms the
    TimedLock/TimedRLock wrappers (resolved at lock creation, so the
    unarmored hot path pays literally nothing). Armed wrappers record
    contended waits into tempo_lock_wait_seconds{lock} with self-trace
    exemplars; the hot locks the concurrency lint already catalogs
    (stage LRU, batchexec window, livestage tail, frontend queue,
    breaker) create through these factories.
  * SLOW-QUERY AUTO-CAPTURE: kerneltel.record_query calls
    capture_slow_query when a query's latency crosses its SLO class
    p99 threshold (the same TEMPO_SLO_<CLASS>_P99_S knobs util/slo
    reads); the sampler ring's window for that query is snapshotted
    into a folded artifact whose id lands in the slow-query log next
    to the self-trace id -- closing the loop page -> /status/slo ->
    slow-query log -> timeline + profile.

Artifacts live in a bounded directory (atomic tmp+rename publish,
oldest-first pruning); `tempo-tpu-cli profile [cpu|device|lock|
artifact]` fetches and renders them.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque

from .metrics import Counter, Histogram

PROFILE_HZ_ENV = "TEMPO_PROFILE_HZ"
PROFILE_DIR_ENV = "TEMPO_PROFILE_DIR"
LOCK_PROFILE_ENV = "TEMPO_LOCK_PROFILE"

# ~19 Hz: low enough to stay invisible (<2% on the concurrent search
# bench), prime so it can't phase-lock with 10/20/100 Hz periodic work
DEFAULT_HZ = 19.0
MAX_STACK_DEPTH = 48  # frames kept per sample (innermost wins)
MAX_STACKS = 2048     # distinct folded stacks before overflow folding
RING_SECONDS = 120.0  # how far back slow-query capture can reach
RING_MAX = 16384      # hard cap regardless of hz
CAPTURE_MIN_GAP_S = 0.25  # slow-query capture stampede guard

# lock waits run from sub-us uncontended neighborhoods to whole-second
# convoy stalls; only CONTENDED acquisitions are observed
LOCK_WAIT_BUCKETS = (1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                     5e-3, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)

# the SLO latency classes build_default_slo registers (services/app):
# op -> (env var, default p99 seconds). Unknown ops use the search
# threshold -- every frontend query class is listed here today.
SLOW_THRESHOLDS = {
    "traces": ("TEMPO_SLO_TRACES_P99_S", 1.0),
    "search": ("TEMPO_SLO_SEARCH_P99_S", 2.5),
    "search_stream": ("TEMPO_SLO_STREAM_P99_S", 5.0),
    "metrics": ("TEMPO_SLO_METRICS_P99_S", 10.0),
}


class ProfilerUnavailable(RuntimeError):
    """A capture backend (jax device profiler, artifact store) is not
    usable in this process; endpoints surface it as 503, not 500."""


def slow_threshold(op: str) -> float:
    env, default = SLOW_THRESHOLDS.get(op, SLOW_THRESHOLDS["search"])
    try:
        return float(os.environ.get(env, "") or default)
    except ValueError:
        return default


# ------------------------------------------------------------ stack walk

_PKG_MARK = f"{os.sep}tempo_tpu{os.sep}"


def _component_of_file(filename: str) -> str:
    """tempo_tpu-relative component of one frame's file: services and
    util resolve to the module (frontend, kerneltel, ...), subpackages
    to their name (ops, db, block, ...), top-level modules to their
    stem (vulture)."""
    i = filename.rfind(_PKG_MARK)
    if i < 0:
        return ""
    parts = filename[i + len(_PKG_MARK):].split(os.sep)
    if len(parts) == 1:
        stem = parts[0][:-3] if parts[0].endswith(".py") else parts[0]
        return stem or "tempo_tpu"
    if parts[0] in ("services", "util"):
        stem = parts[1][:-3] if parts[1].endswith(".py") else parts[1]
        return stem
    return parts[0]


def _walk_frame(frame, with_line: bool = False) -> tuple[str, list[str]]:
    """(component, frames outermost->innermost) for one thread's frame.
    Component = the innermost tempo_tpu frame's home; raw f_code walk
    (no traceback machinery) so the sampler stays cheap."""
    frames: list[str] = []
    component = ""
    f = frame
    depth = 0
    while f is not None and depth < MAX_STACK_DEPTH:
        code = f.f_code
        fname = code.co_filename
        short = fname.rsplit(os.sep, 1)[-1]
        if with_line:
            frames.append(f"{short}:{f.f_lineno} {code.co_name}")
        else:
            frames.append(f"{short}:{code.co_name}")
        if not component:
            component = _component_of_file(fname)
        f = f.f_back
        depth += 1
    frames.reverse()
    return component, frames


# --------------------------------------------------------- artifact store


class ArtifactStore:
    """Bounded on-disk profile-artifact store. Publish is atomic
    (tmp + os.replace: a reader never sees a torn artifact), pruning is
    oldest-first by both file count and cumulative bytes. Ids are flat
    filenames; get() rejects anything path-shaped."""

    def __init__(self, root: str, max_files: int = 64,
                 max_bytes: int = 128 << 20):
        self.root = root
        self.max_files = max(1, int(max_files))
        self.max_bytes = max(1 << 20, int(max_bytes))
        self._lock = threading.Lock()
        os.makedirs(root, exist_ok=True)

    def put(self, kind: str, data: bytes, suffix: str = ".bin") -> str:
        aid = (f"{kind}-{int(time.time() * 1000):013d}-"
               f"{os.urandom(4).hex()}{suffix}")
        tmp = os.path.join(self.root, f".tmp-{aid}")
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.root, aid))
            self._prune_locked()
        return aid

    @staticmethod
    def _valid_id(aid: str) -> bool:
        return bool(aid) and not aid.startswith(".") and all(
            c.isalnum() or c in "._-" for c in aid) and ".." not in aid

    def get(self, aid: str) -> bytes | None:
        if not self._valid_id(aid):
            return None
        p = os.path.join(self.root, aid)
        if not os.path.isfile(p):
            return None
        try:
            with open(p, "rb") as f:
                return f.read()
        except OSError:
            return None

    def list(self) -> list[dict]:
        """Newest-first artifact index for /status/profile. Only plain
        files count: under the app the store root sits inside the
        storage path, whose poller may drop tenant-index DIRECTORIES
        beside the artifacts."""
        out = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not self._valid_id(name):
                continue
            p = os.path.join(self.root, name)
            if not os.path.isfile(p):
                continue
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append({"id": name, "bytes": int(st.st_size),
                        "at_unix": round(st.st_mtime, 3)})
        out.sort(key=lambda a: -a["at_unix"])
        return out

    def _prune_locked(self) -> None:
        entries = []
        for name in os.listdir(self.root):
            p = os.path.join(self.root, name)
            if name.startswith(".tmp-"):
                # a crashed publish left a torn temp file behind
                try:
                    os.unlink(p)
                except OSError:
                    pass
                continue
            if not os.path.isfile(p):
                continue  # foreign directories are not ours to prune
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, name))
        entries.sort()  # oldest first
        total = sum(sz for _, sz, _ in entries)
        while entries and (len(entries) > self.max_files
                           or total > self.max_bytes):
            _, sz, name = entries.pop(0)
            try:
                os.unlink(os.path.join(self.root, name))
            except OSError:
                pass
            total -= sz


# ------------------------------------------------------------- profiler


class Profiler:
    """Process-wide continuous profiler (module singleton PROF)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.sampling = False  # read lock-free on kerneltel hot paths
        self._hz = 0.0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stacks: dict[tuple[str, str], int] = {}
        self._overflow = 0
        self._total = 0
        self._ring: deque = deque()  # (wall, trace_hex, component, stack)
        self._ring_max = RING_MAX
        self._thread_traces: dict[int, str] = {}
        self._missing: set[int] = set()  # two-cycle tag-prune memory
        self._store: ArtifactStore | None = None
        self._last_capture = 0.0
        self.samples = Counter(
            "tempo_profile_samples_total",
            help="background sampler thread-samples by component")
        self.slow_captures = Counter(
            "tempo_profile_slow_captures_total",
            help="slow-query profile artifacts auto-captured")

    # ------------------------------------------------------- lifecycle
    def ensure_sampler(self) -> bool:
        """Start the always-on sampler at the env-configured rate
        (TEMPO_PROFILE_HZ, default ~19; 0 = off). Idempotent -- the
        app calls this at start; with hz=0 it is a strict no-op, so
        the profiling-off differential holds trivially."""
        try:
            hz = float(os.environ.get(PROFILE_HZ_ENV, "") or DEFAULT_HZ)
        except ValueError:
            hz = DEFAULT_HZ
        if hz <= 0:
            return False
        return self.start(hz)

    def start(self, hz: float = DEFAULT_HZ) -> bool:
        with self._lock:
            if self.sampling:
                return True
            self._hz = min(max(float(hz), 0.1), 1000.0)
            self._ring_max = min(RING_MAX,
                                 max(512, int(self._hz * RING_SECONDS)))
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="tempo-profiler")
            self.sampling = True
        self._thread.start()
        return True

    def stop(self) -> None:
        with self._lock:
            if not self.sampling:
                return
            self.sampling = False
            t = self._thread
            self._thread = None
        self._stop.set()
        if t is not None:
            t.join(timeout=2.0)

    def reset(self) -> None:
        """Fresh aggregate state (tests). The artifact store and armed
        sampler survive; only counts/tables clear."""
        with self._lock:
            self._stacks = {}
            self._overflow = 0
            self._total = 0
            self._ring.clear()
            self._thread_traces = {}
            self._missing = set()
            self._last_capture = 0.0

    # ------------------------------------------------------ artifacts
    def configure_artifacts(self, root: str, max_files: int = 64,
                            max_bytes: int = 128 << 20) -> None:
        """Aim the artifact store. An explicit TEMPO_PROFILE_DIR env
        wins over programmatic defaults -- the operator aimed it."""
        root = os.environ.get(PROFILE_DIR_ENV, "") or root
        with self._lock:
            self._store = ArtifactStore(root, max_files=max_files,
                                        max_bytes=max_bytes)

    def _store_or_env(self) -> ArtifactStore | None:
        with self._lock:
            if self._store is None:
                env = os.environ.get(PROFILE_DIR_ENV, "")
                if env:
                    self._store = ArtifactStore(env)
            return self._store

    def artifact_bytes(self, aid: str) -> bytes | None:
        store = self._store_or_env()
        return store.get(aid) if store is not None else None

    def artifact_list(self) -> list[dict]:
        store = self._store_or_env()
        return store.list() if store is not None else []

    # ----------------------------------------------------- attribution
    def note_thread_trace(self, tid: int, trace_id) -> None:
        """Kerneltel parks/unparks the active self-trace for a thread
        here (set_active_trace/reset_active_trace run ON the executing
        thread, so the tid is authoritative). Empty id = unpark."""
        hexid = ""
        try:
            hexid = trace_id.hex() if trace_id else ""
        except AttributeError:
            pass
        with self._lock:
            if hexid:
                self._thread_traces[tid] = hexid
            else:
                self._thread_traces.pop(tid, None)

    # -------------------------------------------------------- sampling
    def _loop(self) -> None:
        period = 1.0 / self._hz
        me = threading.get_ident()
        while not self._stop.wait(period):
            try:
                self._sample_once(me)
            except Exception:
                pass  # the sampler must never take the process down

    def _sample_once(self, me: int) -> None:
        now = time.time()
        frames = sys._current_frames()
        rows: list[tuple[int, str, str]] = []
        for tid, frame in frames.items():
            if tid == me:
                continue
            component, stack = _walk_frame(frame)
            rows.append((tid, component, ";".join(stack)))
        per_component: dict[str, int] = {}
        with self._lock:
            tags = self._thread_traces
            # threads die with their tag still parked (rare: a trace
            # active at thread exit). Prune only after a tid is absent
            # TWO consecutive cycles: the frames snapshot above is
            # taken before the stack walk, so a thread that spawned
            # and parked its tag in between must not lose it mid-query
            for tid in list(tags):
                if tid in frames:
                    self._missing.discard(tid)
                elif tid in self._missing:
                    tags.pop(tid, None)
                    self._missing.discard(tid)
                else:
                    self._missing.add(tid)
            for tid, component, stack in rows:
                key = (component, stack)
                if key in self._stacks or len(self._stacks) < MAX_STACKS:
                    self._stacks[key] = self._stacks.get(key, 0) + 1
                else:
                    self._overflow += 1
                self._total += 1
                self._ring.append((now, tags.get(tid, ""), component, stack))
                per_component[component] = per_component.get(component, 0) + 1
            while len(self._ring) > self._ring_max:
                self._ring.popleft()
        for component, n in per_component.items():
            self.samples.inc(n, labels=f'component="{component or "other"}"')

    # --------------------------------------------------------- readout
    def folded(self, top_k: int = 0) -> str:
        """Flamegraph-collapsed text of the aggregate table: one
        `component;frame;...;frame count` line per distinct stack."""
        with self._lock:
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
        if top_k:
            items = items[:top_k]
        lines = [f"{(comp or 'other')};{stack} {n}"
                 for (comp, stack), n in items]
        return "\n".join(lines) + ("\n" if lines else "")

    def status_snapshot(self, top_k: int = 15) -> dict:
        """The /status/profile payload."""
        with self._lock:
            total = self._total
            overflow = self._overflow
            hz = self._hz
            running = self.sampling
            ring_len = len(self._ring)
            tagged = len(self._thread_traces)
            items = sorted(self._stacks.items(), key=lambda kv: -kv[1])
            distinct = len(items)
        components = {}
        for labels, v in self.samples.snapshot().items():
            # labels is 'component="x"'
            name = labels.split('"')[1] if '"' in labels else labels
            components[name] = int(v)
        top = [{
            "component": comp or "other",
            "samples": n,
            "share": round(n / total, 4) if total else 0.0,
            "stack": stack.split(";")[-8:],
        } for (comp, stack), n in items[:top_k]]
        return {
            "sampler": {
                "running": running,
                "hz": hz,
                "samples_total": total,
                "distinct_stacks": distinct,
                "overflow_samples": overflow,
                "ring_samples": ring_len,
                "tagged_threads": tagged,
                "components": components,
                "top_stacks": top,
            },
            "locks": lock_stats(),
            "slow_captures": int(self.slow_captures.get()),
            "artifacts": self.artifact_list()[:20],
        }

    # ------------------------------------------------ slow-query capture
    def capture_slow_query(self, op: str, seconds: float,
                           trace_id: str) -> str:
        """Snapshot the sampler ring's window for one just-finished slow
        query into a folded artifact; returns the artifact id ('' when
        not captured). Samples tagged with OTHER queries' traces are
        excluded; samples tagged with THIS query or untagged (pool legs
        whose contextvar never passed set_active_trace) stay."""
        if not self.sampling:
            return ""
        # threshold first: every finished query lands here when the
        # sampler is armed, and the fast-path exit must not touch the
        # profiler lock (_store_or_env) the sampler itself contends on
        threshold = slow_threshold(op)
        if threshold <= 0 or seconds < threshold:
            return ""
        store = self._store_or_env()
        if store is None:
            return ""
        now = time.time()
        with self._lock:
            if now - self._last_capture < CAPTURE_MIN_GAP_S:
                return ""
            self._last_capture = now
            cutoff = now - float(seconds) - 1.0 / max(self._hz, 0.1)
            window = [r for r in self._ring if r[0] >= cutoff]
        rows = [r for r in window if r[1] in ("", trace_id)]
        folded: dict[str, int] = {}
        matched = 0
        for _, tag, comp, stack in rows:
            line = f"{comp or 'other'};{stack}"
            folded[line] = folded.get(line, 0) + 1
            if trace_id and tag == trace_id:
                matched += 1
        body = "".join(
            f"{line} {n}\n"
            for line, n in sorted(folded.items(), key=lambda kv: -kv[1]))
        text = (
            "# tempo-tpu slow-query profile\n"
            f"# op={op} seconds={seconds:.4f} threshold={threshold:g} "
            f"self_trace_id={trace_id or '-'}\n"
            f"# captured_unix={now:.3f} window_samples={len(rows)} "
            f"query_tagged_samples={matched} hz={self._hz:g}\n"
            + body)
        try:
            aid = store.put("slowq", text.encode(), suffix=".folded")
        except OSError:
            return ""
        self.slow_captures.inc()
        return aid

    # ------------------------------------------------ on-demand capture
    def sample_cpu(self, seconds: float, hz: float = 200.0,
                   fmt: str = "text") -> str:
        """Burst statistical profile for /debug/profile: sample every
        thread's stack for `seconds` at `hz` and render the hottest
        stacks (text) or the full flamegraph-collapsed table
        (folded). The sampling thread itself is excluded."""
        seconds = min(max(float(seconds), 0.05), 30.0)
        hz = min(max(float(hz), 1.0), 1000.0)
        me = threading.get_ident()
        names = {t.ident: t.name for t in threading.enumerate()}
        counts: dict[tuple[str, str], int] = {}
        total = 0
        deadline = time.monotonic() + seconds
        period = 1.0 / hz
        with_line = fmt != "folded"
        while time.monotonic() < deadline:
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                _, stack = _walk_frame(frame, with_line=with_line)
                key = (names.get(tid, str(tid)), ";".join(stack))
                counts[key] = counts.get(key, 0) + 1
                total += 1
            time.sleep(period)
        ordered = sorted(counts.items(), key=lambda kv: -kv[1])
        if fmt == "folded":
            return "".join(f"{tname};{stack} {n}\n"
                           for (tname, stack), n in ordered)
        lines = [f"# sampling profile: {seconds:.1f}s at ~{hz:.0f} Hz, "
                 f"{total} thread-samples\n"]
        for (tname, stack), n in ordered[:25]:
            lines.append(f"\n--- {tname}: {n} samples "
                         f"({100.0 * n / max(1, total):.1f}%)\n")
            lines.extend(f"    {fr}\n" for fr in stack.split(";")[-12:])
        return "".join(lines)

    def capture_device_profile(self, seconds: float) -> tuple[str, dict]:
        """Record a jax.profiler trace for `seconds` while serving
        continues, zip the trace directory, publish it as an artifact.
        Returns (artifact_id, summary). Raises ProfilerUnavailable when
        the device profiler or the store can't run here."""
        import io
        import shutil
        import tempfile
        import zipfile

        store = self._store_or_env()
        if store is None:
            raise ProfilerUnavailable(
                "no profile artifact store configured "
                f"(set {PROFILE_DIR_ENV} or run under the app)")
        seconds = min(max(float(seconds), 0.1), 60.0)
        try:
            import jax
        except Exception as e:  # pragma: no cover - jax is baked in
            raise ProfilerUnavailable(f"jax unavailable: {e}")
        tmpd = tempfile.mkdtemp(prefix="tempo-devprof-")
        try:
            try:
                jax.profiler.start_trace(tmpd)
                time.sleep(seconds)
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
            buf = io.BytesIO()
            n_files = 0
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                for root, _dirs, files in os.walk(tmpd):
                    for name in files:
                        p = os.path.join(root, name)
                        z.write(p, os.path.relpath(p, tmpd))
                        n_files += 1
            if n_files == 0:
                raise ProfilerUnavailable(
                    "device profiler produced no trace files")
            data = buf.getvalue()
        except ProfilerUnavailable:
            raise
        except Exception as e:
            raise ProfilerUnavailable(f"device trace failed: "
                                      f"{type(e).__name__}: {e}")
        finally:
            shutil.rmtree(tmpd, ignore_errors=True)
        aid = store.put("device", data, suffix=".zip")
        return aid, {"bytes": len(data), "files": n_files,
                     "seconds": seconds}


PROF = Profiler()


# -------------------------------------------------- lock-wait profiling

LOCK_WAIT = Histogram(
    "tempo_lock_wait_seconds", buckets=LOCK_WAIT_BUCKETS,
    help="contended lock acquisition wait by lock name (armed via "
         "TEMPO_LOCK_PROFILE; exemplars carry the waiting query's "
         "self-trace id)")
LOCK_ACQ_NAME = "tempo_lock_acquisitions_total"
LOCK_ACQ_HELP = ("timed-lock acquisitions by lock name and outcome "
                 "(fast/contended)")

# per-lock stats rows: [fast, contended, wait_sum_s, wait_max_s].
# A row is mutated only while HOLDING its wrapped lock (acquirers of
# the same lock are already serialized), so armed profiling never
# funnels independent hot locks through one shared stats mutex --
# contention measured stays contention the workload caused. The
# registry (name, row) list is append-only under its own lock
# (construction-time only) and retains rows past their lock's GC so
# the exported counters stay monotonic.
_rows_lock = threading.Lock()
_lock_rows: list[tuple[str, list]] = []
_LOCK_ROWS_MAX = 4096  # runaway lock creation folds into one row
_OVERFLOW_ROW: list = [0, 0, 0.0, 0.0]


def lock_profiling_armed() -> bool:
    return os.environ.get(LOCK_PROFILE_ENV, "") not in ("", "0")


def _exemplar_tid() -> str | None:
    try:
        from .kerneltel import TEL

        return TEL._exemplar_tid()
    except Exception:
        return None


def lock_stats() -> dict[str, dict]:
    """Aggregate per-name stats (several breakers share one label).
    Rows are read without their locks: torn int/float reads skew a
    stat by one sample at worst, never corrupt it."""
    with _rows_lock:
        rows = list(_lock_rows)
        if _OVERFLOW_ROW[0] or _OVERFLOW_ROW[1]:
            rows.append(("_overflow", _OVERFLOW_ROW))
    agg: dict[str, list] = {}
    for name, row in rows:
        a = agg.setdefault(name, [0, 0, 0.0, 0.0])
        a[0] += row[0]
        a[1] += row[1]
        a[2] += row[2]
        a[3] = max(a[3], row[3])
    return {
        name: {"acquisitions": a[0] + a[1], "contended": a[1],
               "wait_sum_s": round(a[2], 6),
               "wait_max_s": round(a[3], 6)}
        for name, a in sorted(agg.items())
    }


class TimedLock:
    """threading.Lock wrapper timing CONTENDED acquisitions. The fast
    path is one non-blocking try plus an increment of the lock's OWN
    stats row (made under the lock just taken -- no extra mutex, no
    clock read). Condition-compatible (acquire/release signatures
    match)."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str):
        self.name = name
        self._lock = self._factory()
        row: list = [0, 0, 0.0, 0.0]
        with _rows_lock:
            if len(_lock_rows) < _LOCK_ROWS_MAX:
                _lock_rows.append((name, row))
            else:
                row = _OVERFLOW_ROW  # lossy shared fallback, bounded
        self._row = row

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):
            self._row[0] += 1  # holding the lock: serialized per lock
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        ok = self._lock.acquire(True, timeout)
        if ok:
            wait_s = time.perf_counter() - t0
            row = self._row
            row[1] += 1
            row[2] += wait_s
            if wait_s > row[3]:
                row[3] = wait_s
            try:
                LOCK_WAIT.observe(wait_s, f'lock="{self.name}"',
                                  exemplar=_exemplar_tid())
            except Exception:
                pass  # wait telemetry must never wedge the lock
        return ok

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "TimedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self._lock.release()
        return False

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<{type(self).__name__} {self.name!r} {self._lock!r}>"


class TimedRLock(TimedLock):
    """Reentrant variant: the owner's recursive re-acquire succeeds on
    the non-blocking fast path, so recursion is never timed as
    contention."""

    _factory = staticmethod(threading.RLock)

    def locked(self) -> bool:  # RLock has no locked(); answer truthfully
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def _is_owned(self) -> bool:
        # Condition(RLock) consults _is_owned; the fallback probe
        # (acquire(0)) would RECURSE for the owner and misreport
        return self._lock._is_owned()


def timed_lock(name: str):
    """A lock for a cataloged hot critical section: the TimedLock
    wrapper when TEMPO_LOCK_PROFILE arms contention profiling, a raw
    threading.Lock otherwise (zero overhead, bit-identical paths)."""
    return TimedLock(name) if lock_profiling_armed() else threading.Lock()


def timed_rlock(name: str):
    return TimedRLock(name) if lock_profiling_armed() else threading.RLock()


# ------------------------------------------------------------ exposition


def _lock_acq_lines() -> list[str]:
    """Acquisition counters rendered from the per-lock stats rows (the
    hot path never touches a shared Counter lock; exposition derives
    the series at scrape time)."""
    out = []
    for name, s in lock_stats().items():
        fast = s["acquisitions"] - s["contended"]
        if fast:
            out.append(f'{LOCK_ACQ_NAME}{{lock="{name}",outcome="fast"}} '
                       f"{fast}")
        if s["contended"]:
            out.append(f'{LOCK_ACQ_NAME}{{lock="{name}",'
                       f'outcome="contended"}} {s["contended"]}')
    return out


def metrics_lines() -> list[str]:
    return (PROF.samples.text() + PROF.slow_captures.text()
            + LOCK_WAIT.text() + _lock_acq_lines())


def help_entries() -> dict[str, str]:
    return {
        "tempo_profile_samples": PROF.samples.help,
        "tempo_profile_slow_captures": PROF.slow_captures.help,
        "tempo_lock_wait_seconds": LOCK_WAIT.help,
        "tempo_lock_acquisitions": LOCK_ACQ_HELP,
    }
