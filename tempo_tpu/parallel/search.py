"""Sharded predicate search: blocks over dp, span rows over sp.

The multi-chip analog of the reference's two-level search sharding --
blocks to jobs (modules/frontend/searchsharding.go:266-310) and pages
within a block (SearchOptions.StartPage/TotalPages) -- as one mesh
program: the span axis is sharded over 'sp' (each chip filters its row
slice), per-trace aggregation is a segment reduce + `psum` over 'sp'
(the combiner collective), and independent blocks ride 'dp'.

Operands are PER BLOCK: every block resolves strings through its own
dictionary, so the same query yields different int codes (and different
regex-match tables) per block. ops_i/ops_f/tables carry a leading block
axis sharded over 'dp'; condition compares broadcast the per-block
operand over that block's rows. Operand values are traced, and the mesh
programs are memoized, so different constants with the same structure
share one compiled program.

Mirrors ops/filter.py's trace-level tree semantics: span subtrees
aggregate through ('tracify', t) nodes, trace-axis conds compare
per-block (B, NT) columns.

Generic-attr conds (sattr/rattr -- the reference's first-class generic
attribute iterators, tempodb/encoding/vparquet/block_traceql.go:682-763)
run on the mesh too: attr VALUE rows shard over 'sp' exactly like span
rows, the per-owner aggregation is a local cumsum + gathers at the
(replicated) owner-offset column, and the cross-shard stitch is a
`psum_scatter` over 'sp' -- a reduce-scatter that lands each chip
precisely its own span slice of the per-span hit counts, so an
arbitrary `{ span.foo = "bar" }` costs one collective the size of the
span axis. rattr rows aggregate to the small replicated resource axis
with a plain `psum` and gather through span.res_idx. Padded attr rows
carry key_id = PAD (< 0); planner key codes are always >= 0, so
validity needs no extra operand.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.device import bucket
from ..ops.filter import (
    _ATTR_VALUE_COL,
    _VT_CODE,
    Cond,
    Operands,
    T_RATTR,
    T_RES,
    T_SATTR,
    T_SPAN,
    T_TRACE,
    normalize_tree,
)
from .mesh import smap


def _cmp_b(op: str, x, v0, v1, f0, f1, is_float: bool, table):
    """Per-block compare: x (Bl, N); v0/v1/f0/f1 (Bl,) per-block operands;
    table (Bl, L) per-block dictionary-match table."""
    a = (f0 if is_float else v0)[:, None]
    b = (f1 if is_float else v1)[:, None]
    if op == "eq":
        return x == a
    if op == "ne":
        return x != a
    if op == "ne_present":
        return (x != a) & (x >= 0)
    if op == "ne_clamped":
        return (x != a) | (x == 2**31 - 1) | (x == -(2**31) + 1)
    if op == "lt":
        return x < a
    if op == "le":
        return x <= a
    if op == "gt":
        return x > a
    if op == "ge":
        return x >= a
    if op == "range":
        return (x >= a) & (x <= b)
    if op == "exists":
        return jnp.ones_like(x, dtype=bool)
    if op in ("intable", "notintable"):
        hit = jnp.take_along_axis(table, jnp.clip(x, 0, table.shape[1] - 1), axis=1) > 0
        if op == "notintable":
            hit = ~hit
        return hit & (x >= 0)
    raise ValueError(f"unknown op {op}")


@lru_cache(maxsize=128)
def make_sharded_search(mesh, tree, conds: tuple[Cond, ...], col_names: tuple[str, ...],
                        B: int, S: int, R: int, NT: int, table_idxs: tuple[int, ...] = (),
                        pack: bool = True):
    """Jitted mesh program over stacked blocks.

    ops_i: (B, C, 3) int32, ops_f: (B, C, 2) f32, tables: (B, L) u8 --
    all sharded over dp. cols[name]: (B, S) span-axis int32
    (trace_sid included), or (B, R) res-axis, or (B, NT) trace-axis.
    n_spans: (B,). `tree` must be trace-level (normalize_tree applied).
    Returns (trace_mask (B, NT) bool, span_count (B, NT) int32),
    sharded over dp.
    """

    def local(ops_i, ops_f, n_spans_l, *arrays):
        n_tab = len(table_idxs)
        tables = dict(zip(table_idxs, arrays[:n_tab]))
        cols = dict(zip(col_names, arrays[n_tab:]))
        Sl = cols["span.trace_sid"].shape[1]
        row0 = jax.lax.axis_index("sp") * Sl
        valid = (jnp.arange(Sl, dtype=jnp.int32)[None, :] + row0) < n_spans_l[:, None]
        span_masks: list = []

        def cond_cmp(i, x):
            c = conds[i]
            return _cmp_b(c.op, x, ops_i[:, i, 1], ops_i[:, i, 2],
                          ops_f[:, i, 0], ops_f[:, i, 1], c.is_float, tables.get(i))

        def owner_counts(row_hit, off):
            """Per-owner True counts when rows are GROUPED by owner and
            sharded over 'sp': local exclusive cumsum + gathers at the
            global offsets, each shard contributing only the slice of
            every segment it holds. off: (Bl, n_seg+1) global attr rows,
            replicated along sp. Returns (Bl, n_seg) PARTIAL counts --
            the caller sums over 'sp'."""
            Al = row_hit.shape[1]
            arow0 = jax.lax.axis_index("sp") * Al
            ecs = jnp.concatenate(
                [jnp.zeros((row_hit.shape[0], 1), jnp.int32),
                 jnp.cumsum(row_hit.astype(jnp.int32), axis=1)], axis=1)
            lo = jnp.clip(off[:, :-1] - arow0, 0, Al)
            hi = jnp.clip(off[:, 1:] - arow0, 0, Al)
            return jnp.take_along_axis(ecs, hi, 1) - jnp.take_along_axis(ecs, lo, 1)

        def attr_mask(i):
            """Span-level mask for a generic-attr cond: hit rows in the
            sharded attr table, aggregated to their owner axis."""
            c = conds[i]
            pre = c.target  # 'sattr' | 'rattr'
            key_match = cols[f"{pre}.key_id"] == ops_i[:, i, 0][:, None]
            if c.col == "any":
                row_hit = key_match
            else:
                vcol = cols[f"{pre}.{_ATTR_VALUE_COL[c.col]}"]
                vt_ok = cols[f"{pre}.vtype"] == _VT_CODE[c.col]
                row_hit = key_match & vt_ok & cond_cmp(i, vcol)
            cnt = owner_counts(row_hit, cols[f"{pre}.off"])
            if pre == T_SATTR:
                # reduce-scatter: chip k receives the summed counts for
                # exactly its span columns [k*Sl, (k+1)*Sl)
                cnt = jax.lax.psum_scatter(cnt, "sp", scatter_dimension=1,
                                           tiled=True)  # (Bl, Sl)
                return (cnt > 0) & valid
            rm = jax.lax.psum(cnt, "sp") > 0  # (Bl, R) -- small, replicated
            idx = jnp.clip(cols["span.res_idx"], 0, rm.shape[1] - 1)
            rm_g = jnp.take_along_axis(rm, idx, axis=1)
            return rm_g & (cols["span.res_idx"] >= 0) & valid

        def cond_mask(i):
            c = conds[i]
            if c.target == T_SPAN:
                return cond_cmp(i, cols[c.col]) & valid
            if c.target == T_RES:
                rm = cond_cmp(i, cols[c.col])  # (Bl, R)
                idx = jnp.clip(cols["span.res_idx"], 0, rm.shape[1] - 1)
                rm_g = jnp.take_along_axis(rm, idx, axis=1)
                return rm_g & (cols["span.res_idx"] >= 0) & valid
            if c.target in (T_SATTR, T_RATTR):
                return attr_mask(i)
            raise ValueError(f"sharded search: unsupported target {c.target}")

        def gather_mask(m):
            """all_gather a boolean row mask along 'sp', bit-packed into
            uint8 lanes before the collective and unpacked after: x8
            fewer wire bytes than gathering the bool array, with an
            exact pack/unpack round trip (Sl is a power-of-two bucket,
            always 8-aligned). pack=False keeps the legacy unpacked
            gather (the before/after comm bench and the differential
            suite's byte-identity anchor)."""
            if not pack or m.shape[1] % 8:
                return jax.lax.all_gather(m, "sp", axis=1, tiled=True)
            pk = jnp.packbits(m, axis=1)  # (Bl, Sl/8) uint8
            pk_g = jax.lax.all_gather(pk, "sp", axis=1, tiled=True)
            return jnp.unpackbits(pk_g, axis=1).astype(bool)  # (Bl, S)

        hoisted: dict = {}

        def parent_tables():
            """The predicate-independent struct operands -- the parent
            index table and row validity, replicated along 'sp' --
            gathered ONCE per launch (lazily, at the first '>>' or '~'
            node) and shared by every struct node of the query: only
            the per-node lhs mask rides a per-node collective."""
            if "pid" not in hoisted:
                hoisted["pid"] = jax.lax.all_gather(
                    cols["span.parent_idx"], "sp", axis=1, tiled=True)
                hoisted["val"] = gather_mask(valid)
            return hoisted["pid"], hoisted["val"]

        def ev_struct(op, lm, rm):
            """Structural relation on the mesh. The '>' relation needs
            only the REPLICATED lhs mask (each row's parent index is in
            the local shard already), so its per-node collective is one
            bit-packed span-axis gather; '>>' and '~' additionally read
            the hoisted parent/validity tables (parent_tables, once per
            launch) and run the single-chip relation (ops/filter
            ev_struct) on the replicated (Bl, S) tables, each chip
            slicing its own span range back out to AND with the local
            rhs."""
            Sl = lm.shape[1]
            if pack and op == ">":
                lm_g = gather_mask(lm)  # lm is valid-masked at the leaves
                pid_l = cols["span.parent_idx"]
                has_p_l = (pid_l >= 0) & valid
                hit = jnp.take_along_axis(
                    lm_g, jnp.clip(pid_l, 0, lm_g.shape[1] - 1), 1)
                return rm & has_p_l & hit & valid
            lm_g = gather_mask(lm)  # (Bl, S)
            if pack:
                pid_g, val_g = parent_tables()
            else:  # legacy: every node gathers all three tables
                pid_g = jax.lax.all_gather(cols["span.parent_idx"], "sp",
                                           axis=1, tiled=True)
                val_g = jax.lax.all_gather(valid, "sp", axis=1, tiled=True)
            Sg = lm_g.shape[1]
            has_p = (pid_g >= 0) & val_g
            safe = jnp.clip(pid_g, 0, Sg - 1)
            if op == ">":
                out = has_p & jnp.take_along_axis(lm_g, safe, 1)
            elif op == ">>":
                acc = has_p & jnp.take_along_axis(lm_g, safe, 1)
                ptr = jnp.where(has_p, safe, -1)
                for _ in range(max(1, (Sg - 1).bit_length())):
                    psafe = jnp.clip(ptr, 0, Sg - 1)
                    alive = ptr >= 0
                    acc = acc | (alive & jnp.take_along_axis(acc, psafe, 1))
                    nxt = jnp.take_along_axis(ptr, psafe, 1)
                    ptr = jnp.where(alive, jnp.where(nxt >= 0, nxt, -1), -1)
                out = acc
            else:  # '~': sibling with a DIFFERENT lhs span under one parent
                lhs_child = (lm_g & has_p).astype(jnp.int32)
                owner = jnp.where(has_p & lm_g, safe, Sg)
                cnt = jax.vmap(
                    lambda o, w: jax.ops.segment_sum(w, o, num_segments=Sg + 1)[:Sg]
                )(owner, lhs_child)
                sibs = jnp.take_along_axis(cnt, safe, 1) - lhs_child
                orphan = (pid_g == -2) & val_g
                any_lhs_orphan = jnp.any(lm_g & orphan, axis=1, keepdims=True)
                out = (has_p & (sibs > 0)) | (orphan & any_lhs_orphan)
            row0_ = jax.lax.axis_index("sp") * Sl
            out_local = jax.lax.dynamic_slice_in_dim(out, row0_, Sl, axis=1)
            return rm & out_local & valid

        def ev_span(t):
            if t == ("true",):
                return valid
            if t == ("false",):
                return jnp.zeros_like(valid)
            if t[0] == "cond":
                return cond_mask(t[1])
            if t[0] == "struct":
                return ev_struct(t[1], ev_span(t[2]), ev_span(t[3]))
            ms = [ev_span(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        def seg_reduce(mask):
            if "trace.span_off" in cols:
                # grouped layout: per-shard cumsum + offset gathers, then
                # psum over 'sp' stitches traces straddling shard cuts --
                # no scatter anywhere (see ops/filter._offset_counts)
                off = cols["trace.span_off"]  # (Bl, NT+1) global span rows
                ecs = jnp.concatenate(
                    [jnp.zeros((mask.shape[0], 1), jnp.int32),
                     jnp.cumsum(mask.astype(jnp.int32), axis=1)], axis=1)
                lo = jnp.clip(off[:, :-1] - row0, 0, Sl)
                hi = jnp.clip(off[:, 1:] - row0, 0, Sl)
                local_c = jnp.take_along_axis(ecs, hi, 1) - jnp.take_along_axis(ecs, lo, 1)
            else:
                sid = jnp.clip(jnp.where(mask, cols["span.trace_sid"], NT), 0, NT)
                local_c = jax.vmap(
                    lambda m, s: jax.ops.segment_sum(m.astype(jnp.int32), s,
                                                     num_segments=NT + 1)[:NT]
                )(mask, sid)
            return jax.lax.psum(local_c, "sp")  # (Bl, NT)

        def ev_trace(t):
            if t[0] == "tracify":
                sm = ev_span(t[1])
                span_masks.append(sm)
                return seg_reduce(sm) > 0
            if t == ("true",):
                return jnp.ones((n_spans_l.shape[0], NT), dtype=bool)
            if t == ("false",):
                return jnp.zeros((n_spans_l.shape[0], NT), dtype=bool)
            if t[0] == "cond":
                return cond_cmp(t[1], cols[conds[t[1]].col])
            ms = [ev_trace(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        if tree is None:
            span_mask = valid
            count = seg_reduce(span_mask)
            trace_mask = count > 0
        else:
            trace_mask = ev_trace(tree)
            if span_masks:
                span_mask = span_masks[0]
                for m in span_masks[1:]:
                    span_mask = span_mask | m
            else:
                span_mask = valid
            count = seg_reduce(span_mask)
        return trace_mask, jnp.where(trace_mask, count, 0)

    in_specs = [P("dp"), P("dp"), P("dp")] + [P("dp")] * len(table_idxs)
    for n in col_names:
        if n.endswith(".off"):
            in_specs.append(P("dp"))  # owner offsets: replicated along sp
        elif n.startswith(("span.", "sattr.", "rattr.")):
            in_specs.append(P("dp", "sp"))  # row axes shard over sp
        else:
            in_specs.append(P("dp"))
    fn = smap(local, mesh, in_specs=tuple(in_specs), out_specs=(P("dp"), P("dp")))
    return jax.jit(fn)


def _stack_operands(operands, B: int, n_conds: int):
    """Accept one Operands (replicated to every block) or a per-block
    list (padded with zero rows to B). Returns (ints (B,C,3),
    floats (B,C,2), tables {i: (B, L) u8})."""
    if isinstance(operands, Operands):
        ints = np.broadcast_to(operands.ints[None], (B,) + operands.ints.shape).copy()
        floats = np.broadcast_to(operands.floats[None], (B,) + operands.floats.shape).copy()
        tabs = {}
        for i, t in (operands.tables or {}).items():
            t8 = np.asarray(t, dtype=np.uint8)
            tabs[i] = np.broadcast_to(t8[None], (B,) + t8.shape).copy()
        return ints, floats, tabs
    ints = np.zeros((B, n_conds, 3), dtype=np.int32)
    floats = np.zeros((B, n_conds, 2), dtype=np.float32)
    idxs = set()
    for o in operands:
        idxs.update(o.tables or {})
    tabs = {}
    for i in sorted(idxs):
        L = bucket(max(max(len(o.tables[i]) for o in operands if o.tables and i in o.tables), 1))
        tabs[i] = np.zeros((B, L), dtype=np.uint8)
    for bi, o in enumerate(operands):
        ints[bi, : o.ints.shape[0]] = o.ints
        floats[bi, : o.floats.shape[0]] = o.floats
        for i, t in (o.tables or {}).items():
            tabs[i][bi, : len(t)] = np.asarray(t, dtype=np.uint8)
    return ints, floats, tabs


def struct_pack_enabled() -> bool:
    """TEMPO_STRUCT_PACK=0 reverts struct nodes to the legacy
    per-node unpacked triple gather -- the before/after leg of the
    comm-shrink bench and the differential suite's byte-identity
    anchor. Default: hoisted + bit-packed collectives."""
    import os

    return os.environ.get("TEMPO_STRUCT_PACK", "1") not in ("0", "false")


def sharded_search(mesh, tree, conds, operands, cols: dict[str, np.ndarray],
                   n_spans: np.ndarray, nt: int | None = None):
    """Host entry. `operands`: one Operands (same codes for every block:
    the synthetic-bench path) or a list of per-block Operands (the
    service path -- per-block dictionary codes). cols must already be
    stacked/padded: span-axis (B, S) with S % sp == 0 and B % dp == 0;
    res/trace axis (B, R)/(B, NT) replicated along sp. Returns
    (trace_mask, span_count) as numpy, (B, NT)."""
    names = tuple(sorted(cols))
    NT = nt
    if NT is None and any(n.startswith("trace.") for n in names):
        NT = cols[[n for n in names if n.startswith("trace.")][0]].shape[1]
    if NT is None:
        NT = bucket(int(cols["span.trace_sid"].max(initial=0)) + 1)
    B, S = cols["span.trace_sid"].shape
    R = next((cols[n].shape[1] for n in names if n.startswith("res.")), 1)
    conds = tuple(conds)
    if tree is not None:
        tree = normalize_tree(tree, conds)
    ints, floats, tabs = _stack_operands(operands, B, len(conds))
    table_idxs = tuple(sorted(tabs))
    pack = struct_pack_enabled()
    fn = make_sharded_search(mesh, tree, conds, names, B, S, R, NT, table_idxs,
                             pack=pack)
    arrays = [jnp.asarray(tabs[i]) for i in table_idxs] + [jnp.asarray(cols[n]) for n in names]
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    ints_j = jnp.asarray(ints)
    floats_j = jnp.asarray(floats)
    nsp_j = jnp.asarray(n_spans, dtype=np.int32)
    # the legacy (unpacked) program keeps its own costmodel op label so
    # the comm-shrink bench can read both variants' walker prices
    op = "mesh_search" if pack else "mesh_search_nopack"
    TEL.record_launch(
        op, ("search", tree, conds, names, B, S, R, NT, table_idxs, pack), S,
        cost=lambda: costmodel.spec(fn, ints_j, floats_j, nsp_j, *arrays,
                                    mesh=mesh))
    t0 = _time.perf_counter()
    t0_wall = _time.time()
    from .mesh import DISPATCH_LOCK

    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        tm, sc = fn(ints_j, floats_j, nsp_j, *arrays)
        out = np.asarray(tm), np.asarray(sc)
    TEL.observe_device(op, S, t0)
    # timeline: the mesh leg with its statically-priced collective bytes
    # (costmodel comm walker; zeros until the background capture lands)
    comm = costmodel.COST.comm_for(op, str(S))
    TEL.child_span(
        "mesh:search", t0_wall, _time.time(),
        {"blocks": B, "bucket": S, "comm_bytes": int(sum(comm.values())),
         **{f"comm.{c}": int(b) for c, b in sorted(comm.items())}})
    return out
