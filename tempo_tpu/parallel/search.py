"""Sharded predicate search: blocks over dp, span rows over sp.

The multi-chip analog of the reference's two-level search sharding --
blocks to jobs (modules/frontend/searchsharding.go:266-310) and pages
within a block (SearchOptions.StartPage/TotalPages) -- as one mesh
program: the span axis is sharded over 'sp' (each chip filters its row
slice), per-trace aggregation is a segment reduce + `psum` over 'sp'
(the combiner collective), and independent blocks ride 'dp'.

Mirrors ops/filter.py's trace-level tree semantics: span subtrees
aggregate through ('tracify', t) nodes, trace-axis conds compare
replicated (B, NT) columns, dictionary tables (regex/set predicates)
ride along replicated. The generic-attr tables shard differently and
stay on the per-block path (ops/filter.py). Operand values are traced,
and the mesh programs are memoized, so different constants with the
same structure share one compiled program.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.filter import Cond, Operands, T_RES, T_SPAN, T_TRACE, _cmp, normalize_tree
from .mesh import smap


@lru_cache(maxsize=128)
def make_sharded_search(mesh, tree, conds: tuple[Cond, ...], col_names: tuple[str, ...],
                        B: int, S: int, R: int, NT: int, table_idxs: tuple[int, ...] = ()):
    """Jitted mesh program over stacked blocks.

    cols[name]: (B, S) span-axis int32 (trace_sid included), or (B, R)
    res-axis, or (B, NT) trace-axis. n_spans: (B,). `tree` must be
    trace-level (normalize_tree applied). Returns
    (trace_mask (B, NT) bool, span_count (B, NT) int32), sharded over dp.
    """

    def local(ops_i, ops_f, n_spans_l, *arrays):
        n_tab = len(table_idxs)
        tables = dict(zip(table_idxs, arrays[:n_tab]))
        cols = dict(zip(col_names, arrays[n_tab:]))
        Sl = cols["span.trace_sid"].shape[1]
        row0 = jax.lax.axis_index("sp") * Sl
        valid = (jnp.arange(Sl, dtype=jnp.int32)[None, :] + row0) < n_spans_l[:, None]
        span_masks: list = []

        def cond_mask(i):
            c = conds[i]
            v0, v1 = ops_i[i, 1], ops_i[i, 2]
            f0, f1 = ops_f[i, 0], ops_f[i, 1]
            t = tables.get(i)
            if c.target == T_SPAN:
                return _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, t) & valid
            if c.target == T_RES:
                rm = _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, t)  # (Bl, R)
                idx = jnp.clip(cols["span.res_idx"], 0, rm.shape[1] - 1)
                rm_g = jnp.take_along_axis(rm, idx, axis=1)
                return rm_g & (cols["span.res_idx"] >= 0) & valid
            raise ValueError(f"sharded search: unsupported target {c.target}")

        def ev_span(t):
            if t[0] == "cond":
                return cond_mask(t[1])
            ms = [ev_span(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        def seg_reduce(mask):
            sid = jnp.clip(jnp.where(mask, cols["span.trace_sid"], NT), 0, NT)
            local_c = jax.vmap(
                lambda m, s: jax.ops.segment_sum(m.astype(jnp.int32), s,
                                                 num_segments=NT + 1)[:NT]
            )(mask, sid)
            return jax.lax.psum(local_c, "sp")  # (Bl, NT)

        def ev_trace(t):
            if t[0] == "tracify":
                sm = ev_span(t[1])
                span_masks.append(sm)
                return seg_reduce(sm) > 0
            if t[0] == "cond":
                i = t[1]
                c = conds[i]
                return _cmp(c.op, cols[c.col], ops_i[i, 1], ops_i[i, 2],
                            ops_f[i, 0], ops_f[i, 1], c.is_float, tables.get(i))
            ms = [ev_trace(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        if tree is None:
            span_mask = valid
            count = seg_reduce(span_mask)
            trace_mask = count > 0
        else:
            trace_mask = ev_trace(tree)
            if span_masks:
                span_mask = span_masks[0]
                for m in span_masks[1:]:
                    span_mask = span_mask | m
            else:
                span_mask = valid
            count = seg_reduce(span_mask)
        return trace_mask, jnp.where(trace_mask, count, 0)

    in_specs = [P(), P(), P("dp")] + [P()] * len(table_idxs)
    for n in col_names:
        in_specs.append(P("dp", "sp") if n.startswith("span.") else P("dp"))
    fn = smap(local, mesh, in_specs=tuple(in_specs), out_specs=(P("dp"), P("dp")))
    return jax.jit(fn)


def sharded_search(mesh, tree, conds, operands: Operands, cols: dict[str, np.ndarray],
                   n_spans: np.ndarray, nt: int | None = None):
    """Host entry. cols must already be stacked/padded:
    span-axis (B, S) with S % sp == 0 and B % dp == 0; res/trace axis
    (B, R)/(B, NT) replicated along sp. Returns (trace_mask, span_count)
    as numpy, (B, NT)."""
    names = tuple(sorted(cols))
    NT = nt
    if NT is None and any(n.startswith("trace.") for n in names):
        NT = cols[[n for n in names if n.startswith("trace.")][0]].shape[1]
    if NT is None:
        NT = int(cols["span.trace_sid"].max(initial=0)) + 1
        # pad to bucket for stable jit keys
        from ..ops.device import bucket

        NT = bucket(NT)
    B, S = cols["span.trace_sid"].shape
    R = next((cols[n].shape[1] for n in names if n.startswith("res.")), 1)
    conds = tuple(conds)
    if tree is not None:
        tree = normalize_tree(tree, conds)
    tables = operands.tables or {}
    table_idxs = tuple(sorted(tables))
    fn = make_sharded_search(mesh, tree, conds, names, B, S, R, NT, table_idxs)
    table_arrays = [jnp.asarray(np.asarray(tables[i], dtype=np.uint8)) for i in table_idxs]
    arrays = table_arrays + [jnp.asarray(cols[n]) for n in names]
    tm, sc = fn(jnp.asarray(operands.ints), jnp.asarray(operands.floats),
                jnp.asarray(n_spans, dtype=np.int32), *arrays)
    return np.asarray(tm), np.asarray(sc)
