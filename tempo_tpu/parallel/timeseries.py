"""Sharded metrics fold: blocks over a 1-D 'dp' axis, combined by psum.

The multi-chip twin of ops/timeseries: stacked per-block columns shard
over 'dp' (each chip folds its slice of blocks with the same fused
filter->bucketize->segmented-fold), and the [num_groups, num_buckets]
partial accumulators combine with ONE collective -- `psum` for counts
and sums, `pmin`/`pmax` for the min/max folds. Group ids arrive already
GLOBALIZED (db/metrics_mesh unions the per-block label sets and remaps
each block's dense ids onto the global table), which is exactly what
makes the cross-chip psum correct: every chip accumulates into the same
group axis.

Operands are per block (each block's dictionary yields different codes
for the same query), carried with a leading block axis like
parallel/search. Cond targets cover the span/res/trace axes; generic
attr conds take the per-block fallback path instead (db/metrics_exec) --
they need the attr-table machinery, and a metrics query hot enough to
matter runs on dedicated res/span columns.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from ..ops.filter import Cond, T_RES, T_SPAN, T_TRACE
from .mesh import smap
from .search import _cmp_b, _stack_operands

MESH_TARGETS = (T_SPAN, T_RES, T_TRACE)


def mesh_1d(mesh) -> Mesh:
    """Flatten a (dp, sp) query mesh into the 1-D block axis this fold
    shards over (every chip folds whole blocks; rows are not split)."""
    return Mesh(mesh.devices.reshape(-1), ("dp",))


@lru_cache(maxsize=64)
def make_sharded_timeseries(mesh, tree, conds: tuple[Cond, ...],
                            col_names: tuple[str, ...], has_val: bool,
                            G_b: int, NB_b: int, NT: int,
                            table_idxs: tuple[int, ...] = ()):
    nseg = G_b * NB_b + 1

    def local(ops_i, ops_f, n_spans_l, t0_l, step, n_buckets, gid, val, pres,
              *arrays):
        n_tab = len(table_idxs)
        tables = dict(zip(table_idxs, arrays[:n_tab]))
        cols = dict(zip(col_names, arrays[n_tab:]))
        Sl = cols["span.start_ms"].shape[1]
        valid = (jnp.arange(Sl, dtype=jnp.int32)[None, :]
                 < n_spans_l[:, None])

        def cond_cmp(i, x):
            c = conds[i]
            return _cmp_b(c.op, x, ops_i[:, i, 1], ops_i[:, i, 2],
                          ops_f[:, i, 0], ops_f[:, i, 1], c.is_float,
                          tables.get(i))

        def cond_mask(i):
            c = conds[i]
            if c.target == T_SPAN:
                return cond_cmp(i, cols[c.col]) & valid
            if c.target == T_RES:
                rm = cond_cmp(i, cols[c.col])  # (Bl, R)
                idx = jnp.clip(cols["span.res_idx"], 0, rm.shape[1] - 1)
                rm_g = jnp.take_along_axis(rm, idx, axis=1)
                return rm_g & (cols["span.res_idx"] >= 0) & valid
            if c.target == T_TRACE:
                tm = cond_cmp(i, cols[c.col])  # (Bl, NT)
                sid = jnp.clip(cols["span.trace_sid"], 0, NT - 1)
                return jnp.take_along_axis(tm, sid, axis=1) & valid
            raise ValueError(f"mesh timeseries: unsupported target {c.target}")

        def ev(t):
            if t == ("true",):
                return valid
            if t == ("false",):
                return jnp.zeros_like(valid)
            if t[0] == "cond":
                return cond_mask(t[1])
            ms = [ev(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        sm = valid if tree is None else (ev(tree) & valid)
        b = (cols["span.start_ms"] - t0_l[:, None]) // step
        ok = sm & (b >= 0) & (b < n_buckets) & (gid >= 0)
        b32 = jnp.clip(b, 0, NB_b - 1)
        seg = jnp.where(ok, gid * NB_b + b32, G_b * NB_b)

        def fold_sum(weights, segs):
            per_block = jax.vmap(
                lambda w, s: jax.ops.segment_sum(w, s, num_segments=nseg)[:-1]
            )(weights, segs)
            return jax.lax.psum(per_block.sum(axis=0), "dp").reshape(G_b, NB_b)

        counts = fold_sum(ok.astype(jnp.int32), seg)
        if not has_val:
            return (counts,)
        pres2 = ok & pres
        segv = jnp.where(pres2, seg, G_b * NB_b)
        vcnt = fold_sum(pres2.astype(jnp.int32), segv)
        vsum = fold_sum(jnp.where(pres2, val, jnp.float32(0)), segv)
        vmin = jax.lax.pmin(jax.vmap(
            lambda w, s: jax.ops.segment_min(w, s, num_segments=nseg)[:-1]
        )(jnp.where(pres2, val, jnp.float32(jnp.inf)), segv).min(axis=0),
            "dp").reshape(G_b, NB_b)
        vmax = jax.lax.pmax(jax.vmap(
            lambda w, s: jax.ops.segment_max(w, s, num_segments=nseg)[:-1]
        )(jnp.where(pres2, val, jnp.float32(-jnp.inf)), segv).max(axis=0),
            "dp").reshape(G_b, NB_b)
        return counts, vcnt, vsum, vmin, vmax

    n_in = 9 + len(table_idxs) + len(col_names)
    in_specs = [P("dp"), P("dp"), P("dp"), P("dp"), P(), P(),
                P("dp"), P("dp"), P("dp")]
    in_specs += [P("dp")] * (len(table_idxs) + len(col_names))
    assert len(in_specs) == n_in
    n_out = 5 if has_val else 1
    fn = smap(local, mesh, in_specs=tuple(in_specs),
              out_specs=tuple([P()] * n_out) if n_out > 1 else (P(),))
    return jax.jit(fn)


def sharded_timeseries(mesh, tree, conds, operands, cols: dict[str, np.ndarray],
                       n_spans: np.ndarray, t0_rel: np.ndarray,
                       gid: np.ndarray, val: np.ndarray | None,
                       pres: np.ndarray | None,
                       step_ms: int, n_buckets: int, n_groups: int):
    """Host entry. cols: stacked/padded per-block arrays -- span axis
    (B, S), res axis (B, R), trace axis (B, NT); B a multiple of the
    device count. gid: (B, S) GLOBAL dense group ids (-1 drops). val /
    pres: (B, S) f32/bool or None for count folds. t0_rel: (B,) per-
    block request-origin offset in block-relative ms. Returns numpy
    accumulators clipped to (n_groups, n_buckets)."""
    from ..ops.device import bucket

    m1 = mesh_1d(mesh)
    names = tuple(sorted(cols))
    B, S = cols["span.start_ms"].shape
    NT = next((cols[n].shape[1] for n in names if n.startswith("trace.")), 1)
    conds = tuple(conds)
    ints, floats, tabs = _stack_operands(operands, B, len(conds))
    table_idxs = tuple(sorted(tabs))
    G_b, NB_b = bucket(max(n_groups, 1)), bucket(max(n_buckets, 1))
    has_val = val is not None
    fn = make_sharded_timeseries(m1, tree, conds, names, has_val,
                                 G_b, NB_b, NT, table_idxs)
    if not has_val:
        val = np.zeros((B, 1), np.float32)
        pres = np.zeros((B, 1), bool)
    arrays = [jnp.asarray(tabs[i]) for i in table_idxs]
    arrays += [jnp.asarray(cols[n]) for n in names]
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    call_args = (
        jnp.asarray(ints), jnp.asarray(floats),
        jnp.asarray(n_spans, np.int32), jnp.asarray(t0_rel, np.int32),
        jnp.asarray(np.int32(max(1, step_ms))),
        jnp.asarray(np.int32(n_buckets)),
        jnp.asarray(np.asarray(gid, np.int32)),
        jnp.asarray(np.asarray(val, np.float32)),
        jnp.asarray(np.asarray(pres, bool)), *arrays)
    TEL.record_launch(
        "mesh_timeseries",
        ("ts", tree, conds, names, has_val, G_b, NB_b, NT, B, S, table_idxs), S,
        cost=lambda: costmodel.spec(fn, *call_args, mesh=m1))
    tw = _time.perf_counter()
    from .mesh import DISPATCH_LOCK

    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        outs = fn(*call_args)
        res = tuple(np.asarray(o)[:n_groups, :n_buckets] for o in outs)
    TEL.observe_device("mesh_timeseries", S, tw)
    return res
