"""Multi-chip execution: jax.sharding.Mesh + shard_map over ICI.

The reference scales its read path with process-level fan-out (frontend
sharders + querier worker pools + intra-process goroutine pools,
SURVEY.md 2.10). Here the same axes map onto a device mesh:

  dp  -- blocks across chips (the reference's per-block job fan-out,
         modules/frontend/searchsharding.go + tempodb/pool)
  sp  -- rows *within* a block across chips (the reference's
         StartPage/TotalPages page sharding, the "sequence" axis)

XLA collectives (pmax / psum / all_gather) replace the reference's
result-merging combiners on the host.
"""

from .mesh import make_mesh
from .find import sharded_find, sharded_find_rows, stack_block_ids
from .search import sharded_search
from .bloom import sharded_bloom_union
from .step import distributed_query_step
from .multiquery import mesh_eval_multiquery

__all__ = [
    "make_mesh",
    "sharded_find",
    "sharded_find_rows",
    "stack_block_ids",
    "sharded_search",
    "sharded_bloom_union",
    "distributed_query_step",
    "mesh_eval_multiquery",
]
