"""Distributed bloom/sketch union: the compaction collective.

The north-star "pmap'd sketch union" (BASELINE.json): compacting K
blocks unions K same-geometry sharded blooms. Input filters shard over
the mesh, each chip ORs its slice locally, and an `all_gather` + OR
produces the replicated result -- one pass over ICI instead of the
reference's per-key re-insertion during merge (v2/streaming_block.go).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..block.bloom import ShardedBloom
from .mesh import smap


@lru_cache(maxsize=64)
def make_sharded_union(mesh, K: int, NS: int, W: int):
    """(K, NS, W) uint32 stacked blooms, K sharded over the whole mesh ->
    (NS, W) replicated union."""

    def local(stacked_l):
        acc = jax.lax.reduce(stacked_l, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0,))
        gathered = jax.lax.all_gather(acc, "sp")
        acc = jax.lax.reduce(gathered, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0,))
        gathered = jax.lax.all_gather(acc, "dp")
        return jax.lax.reduce(gathered, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0,))

    fn = smap(local, mesh, in_specs=(P(("dp", "sp")),), out_specs=P())
    return jax.jit(fn)


def sharded_bloom_union(mesh, blooms: list[ShardedBloom]) -> ShardedBloom:
    """Union many same-geometry blooms across the mesh."""
    first = blooms[0]
    for b in blooms[1:]:
        if b.n_shards != first.n_shards or b.shard_bits != first.shard_bits:
            raise ValueError("bloom geometry mismatch")
    n = mesh.devices.size
    K = ((len(blooms) + n - 1) // n) * n
    stacked = np.zeros((K,) + first.words.shape, dtype=np.uint32)
    for i, b in enumerate(blooms):
        stacked[i] = b.words
    fn = make_sharded_union(mesh, K, first.words.shape[0], first.words.shape[1])
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    stacked_j = jnp.asarray(stacked)
    TEL.record_launch("mesh_bloom", ("union", K, first.words.shape), K,
                      cost=lambda: costmodel.spec(fn, stacked_j, mesh=mesh))
    t0 = _time.perf_counter()
    out = ShardedBloom(first.n_shards, first.shard_bits)
    from .mesh import DISPATCH_LOCK

    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        out.words = np.asarray(fn(stacked_j))
    TEL.observe_device("mesh_bloom", K, t0)
    return out
