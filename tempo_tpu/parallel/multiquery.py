"""Mesh-batched multi-query search: one admission window -> ALL chips.

ops/multiquery.py fuses a PR-3 admission window's Q queries into one
single-chip launch; this module is the same fixed-shape predicate
program as a shard_map so the window's ONE launch also spans every
device: the staged span axis shards over the whole mesh (both axes
flattened -- a single block has no 'dp' fan-out to ride), each chip
interprets all Q packed programs against its row slice, and one psum
stitches the per-trace counts. Concurrency (the Q axis) and
chip-parallelism (the row axis) therefore multiply instead of
competing for the executor -- the ROADMAP 2c "fuse it with batching"
leg.

Bit-identity: every per-shard fold is the same cumsum + offset-gather
segment fold as the single-chip interpreter, shifted by the shard's
global row base and clipped to its slice; the psum adds exact int32
partials, so (trace_mask, counts) equal ops/multiquery.eval_multiquery
bit for bit (tests/test_mesh_batch.py holds the differential).

Launch keys are shape-only -- (ProgramShape, Q-bucket, axis buckets,
mesh) -- exactly the coalesce-key discipline of the single-chip path:
operand tables stay traced, so windows with different constants share
one compiled mesh program.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.device import PAD_I32
from ..ops.multiquery import ProgramShape, _cmp_code
from .mesh import smap


@lru_cache(maxsize=32)
def make_mesh_multiquery(mesh, shape: ProgramShape, q_b: int,
                         n_spans_b: int, n_traces_b: int):
    """Jitted Q-programs x sharded-rows program over `mesh`.

    Inputs: span_mat (n_sc, S) int32 row-sharded over every mesh axis;
    trace_mat (n_tc, NT), span_off (NT+1,), the packed program tables
    (ops/multiquery.pack_queries) and the real row counts, all
    replicated. Returns replicated (q_b, NT) (trace_mask, counts)."""
    n_sc = max(1, len(shape.span_cols))
    n_tc = max(1, len(shape.trace_cols))
    axes = tuple(mesh.axis_names)  # row axis shards over ALL mesh axes

    def local(span_mat, trace_mat, span_off, progs, n_spans, n_traces):
        Sl = span_mat.shape[1]
        shard = jax.lax.axis_index(axes[0])
        for a in axes[1:]:
            shard = shard * mesh.shape[a] + jax.lax.axis_index(a)
        row0 = shard * Sl
        valid_span = (jnp.arange(Sl, dtype=jnp.int32) + row0) < n_spans
        valid_trace = jnp.arange(n_traces_b, dtype=jnp.int32) < n_traces
        lo = jnp.clip(span_off[:-1] - row0, 0, Sl)
        hi = jnp.clip(span_off[1:] - row0, 0, Sl)

        def seg_partial(masks):
            """(G, Sl) row masks -> (G, NT) PARTIAL per-trace counts:
            local cumsum + global-offset gathers clipped to this
            shard's slice (ops/filter._offset_counts shifted by row0);
            the caller psums the partials."""
            cs = jnp.concatenate(
                [jnp.zeros((masks.shape[0], 1), jnp.int32),
                 jnp.cumsum(masks.astype(jnp.int32), axis=1)], axis=1)
            return cs[:, hi] - cs[:, lo]

        def fold_rows(p):
            """One program's span-level half on the local row slice:
            per-group and union-mask partial per-trace counts."""
            x = span_mat[jnp.clip(p["cond_col"], 0, n_sc - 1)]
            m = _cmp_code(p["cond_op"][:, None], x,
                          p["cond_v0"][:, None], p["cond_v1"][:, None])
            m = m & (~p["cond_guard"][:, None] | (x != PAD_I32))
            m = m & valid_span[None, :]
            cs = jnp.concatenate(
                [jnp.zeros((1, Sl), jnp.int32),
                 jnp.cumsum(m.astype(jnp.int32), axis=0)])
            co = p["clause_off"]
            clause_ok = (cs[co[1:]] - cs[co[:-1]]) > 0
            cs2 = jnp.concatenate(
                [jnp.zeros((1, Sl), jnp.int32),
                 jnp.cumsum(clause_ok.astype(jnp.int32), axis=0)])
            go = p["group_off"]
            n_cl = (go[1:] - go[:-1])[:, None]
            grp_ok = ((cs2[go[1:]] - cs2[go[:-1]]) == n_cl) & valid_span[None, :]
            live = (jnp.arange(grp_ok.shape[0]) < p["n_groups"])[:, None]
            union = jnp.where(p["n_groups"] > 0,
                              jnp.any(grp_ok & live, axis=0), valid_span)
            return seg_partial(jnp.concatenate([grp_ok, union[None]]))

        parts = jax.vmap(fold_rows)(progs)  # (Q, NG+1, NT) partials
        counts_all = jax.lax.psum(parts, axes)  # ONE collective per launch
        gcounts, ucounts = counts_all[:, :-1], counts_all[:, -1]

        def combine(p, gcounts_q, ucounts_q):
            """Trace-level half on the replicated psummed counts --
            identical arithmetic on every shard, so the output needs no
            further collective."""
            gmask = gcounts_q > 0
            tx = trace_mat[jnp.clip(p["tcond_col"], 0, n_tc - 1)]
            tcm = _cmp_code(p["tcond_op"][:, None], tx,
                            p["tcond_v0"][:, None], p["tcond_v1"][:, None])
            kind = p["atom_kind"]
            aval = jnp.where(
                (kind == 0)[:, None],
                gmask[jnp.clip(p["atom_idx"], 0, gmask.shape[0] - 1)],
                tcm[jnp.clip(p["atom_idx"], 0, tcm.shape[0] - 1)],
            ) & (kind >= 0)[:, None]
            cs4 = jnp.concatenate(
                [jnp.zeros((1, n_traces_b), jnp.int32),
                 jnp.cumsum(aval.astype(jnp.int32), axis=0)])
            to = p["tclause_off"]
            tcl_ok = ((cs4[to[1:]] - cs4[to[:-1]]) > 0) | (
                jnp.arange(to.shape[0] - 1) >= p["n_tclauses"])[:, None]
            tm = jnp.all(tcl_ok, axis=0) & valid_trace
            return tm, jnp.where(tm, ucounts_q, 0)

        return jax.vmap(combine)(progs, gcounts, ucounts)

    row_spec = P(None, axes)  # row axis over every device, dp-major
    in_specs = (row_spec, P(), P(), P(), P(), P())
    fn = smap(local, mesh, in_specs=in_specs, out_specs=(P(), P()))
    return jax.jit(fn)


def mesh_batch_eligible(mesh, staged) -> bool:
    """Shape guard for the mesh-batched route: every device needs a
    whole slice of the padded span axis. Power-of-two buckets (>= 1024,
    ops/device.bucket) over power-of-two meshes always pass; odd
    virtual-device counts fall back to the single-chip fused launch."""
    n_dev = int(mesh.devices.size)
    return n_dev > 1 and staged.n_spans_b % n_dev == 0


def mesh_eval_multiquery(mesh, lowered: list, staged, progs: dict):
    """Run Q packed programs against one staged block as ONE launch
    across every mesh device. Same contract as
    ops/multiquery.eval_multiquery but returns host numpy (q_b, NT)
    arrays: the demux path slices per-query rows and mixing the mesh
    program's replicated outputs with single-device staged arrays in a
    later jit would force a device-mismatch reshard anyway."""
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL
    from .mesh import DISPATCH_LOCK

    shape = lowered[0].shape
    q_b = int(progs["cond_op"].shape[0])
    fn = make_mesh_multiquery(mesh, shape, q_b, staged.n_spans_b,
                              staged.n_traces_b)
    span_mat = (jnp.stack([staged.cols[n] for n in shape.span_cols])
                if shape.span_cols
                else jnp.zeros((1, staged.n_spans_b), jnp.int32))
    trace_mat = (jnp.stack([staged.cols[n] for n in shape.trace_cols])
                 if shape.trace_cols
                 else jnp.zeros((1, staged.n_traces_b), jnp.int32))
    args = (span_mat, trace_mat, staged.cols["trace.span_off"], progs,
            np.int32(staged.n_spans), np.int32(staged.n_traces))
    TEL.record_launch(
        "mesh_multiquery",
        ("mmq", shape, q_b, staged.n_spans_b, staged.n_traces_b,
         tuple(mesh.shape.items())),
        staged.n_spans_b,
        cost=lambda: costmodel.spec(fn, *args, mesh=mesh))
    t0 = _time.perf_counter()
    t0_wall = _time.time()
    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        tm, counts = fn(*args)
        out = np.asarray(tm), np.asarray(counts)
    TEL.observe_device("mesh_multiquery", staged.n_spans_b, t0)
    TEL.record_mesh_batch(len(lowered))
    comm = costmodel.COST.comm_for("mesh_multiquery", str(staged.n_spans_b))
    TEL.child_span(
        "mesh:batch", t0_wall, _time.time(),
        {"occupancy": len(lowered), "bucket": staged.n_spans_b,
         "devices": int(mesh.devices.size),
         "comm_bytes": int(sum(comm.values()))})
    return out
