"""Sharded trace-ID lookup: the multi-chip Find.

The reference fans trace-by-ID out per candidate block over a goroutine
pool (tempodb/tempodb.go:271-352 Find + tempodb/pool) and across
queriers via trace-ID-space shards (modules/frontend/
tracebyidsharding.go). Here every chip holds a slice of the stacked
per-block sorted trace-id indexes, runs the same batched bisection
locally (ops/find.py), and a single `pmax` over the mesh merges hits --
the combiner is an ICI collective instead of a host merge loop.

A hit is the (global_block, row) pair, combined in two pmax stages:
first the mesh elects the max hit-holding block id per query, then the
winner's shard contributes the row. max() is a valid combiner because
each trace id lives in >= 1 block row and any duplicate (compaction
overlap) resolves deterministically to the highest block -- callers
treat hits as candidates to materialize + combine, same as the
reference's partial-trace combiner.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .mesh import smap
from ..ops.device import bucket, pad_rows
from ..ops.find import bisect_ids


@lru_cache(maxsize=64)
def make_sharded_find(mesh, B: int, T: int, Q: int):
    """Build the jitted mesh program for fixed (padded) shapes.

    ids: (B, T, 4) int32, blocks sharded over the flattened (dp, sp) axis;
    n_valid: (B,); queries: (Q, 4) replicated.
    Returns (Q, 2) int32 [global_block, row], (-1, -1) on miss.
    """
    n_steps = int(T).bit_length()

    def local(ids_l, n_valid_l, queries):
        # ids_l: (B/n, T, 4) — this shard's blocks
        Bl = ids_l.shape[0]
        sids = jax.vmap(lambda a, nv: bisect_ids(a, queries, nv, n_steps))(
            ids_l, n_valid_l
        )  # (Bl, Q)
        # psum(1, axis) == axis size (jax.lax.axis_size is not in this
        # jax release)
        sp_size = jax.lax.psum(1, "sp")
        shard = jax.lax.axis_index("dp") * sp_size + jax.lax.axis_index("sp")
        gblock = shard * Bl + jnp.arange(Bl, dtype=jnp.int32)[:, None]  # (Bl, 1)
        # two-stage combine, no block*T+row packing (would overflow i32):
        # 1) pmax elects the winning block id per query
        blk = jnp.where(sids >= 0, gblock, -1)  # (Bl, Q)
        best_blk = jnp.max(blk, axis=0)
        best_blk = jax.lax.pmax(jax.lax.pmax(best_blk, "sp"), "dp")  # (Q,)
        # 2) only the winner's shard contributes its row, pmax broadcasts it
        row = jnp.where(blk == best_blk[None, :], sids, -1)
        row = jnp.max(row, axis=0)
        row = jax.lax.pmax(jax.lax.pmax(row, "sp"), "dp")
        return jnp.stack([best_blk, row], axis=-1)  # (Q, 2)

    fn = smap(local, mesh,
        in_specs=(P(("dp", "sp")), P(("dp", "sp")), P()),
        out_specs=P(),
    )
    return jax.jit(fn)


@lru_cache(maxsize=64)
def make_sharded_find_rows(mesh, B: int, T: int, Q: int):
    """Like make_sharded_find but each block reports its OWN hit row:
    returns (B, Q) int32 sids (-1 miss), block axis sharded over the
    flattened mesh. This is the service-path Find: every block holding
    the id contributes a partial trace for the host combiner
    (wire/combine.py), matching the reference's Find + combiner
    (tempodb/tempodb.go:271-352) instead of electing one winner."""
    n_steps = int(T).bit_length()

    def local(ids_l, n_valid_l, queries):
        return jax.vmap(lambda a, nv: bisect_ids(a, queries, nv, n_steps))(ids_l, n_valid_l)

    fn = smap(local, mesh,
        in_specs=(P(("dp", "sp")), P(("dp", "sp")), P()),
        out_specs=P(("dp", "sp")),
    )
    return jax.jit(fn)


def sharded_find_rows(mesh, id_code_arrays: list[np.ndarray], query_codes: np.ndarray) -> np.ndarray:
    """Host entry for the per-block-rows Find. Returns (B, Q) int32
    row-in-block (-1 miss), B = len(id_code_arrays)."""
    n = mesh.devices.size
    q = query_codes.shape[0]
    if not id_code_arrays or q == 0:
        return np.full((len(id_code_arrays), q), -1, dtype=np.int32)
    ids, n_valid, T = stack_block_ids(id_code_arrays, n)
    Qb = bucket(q)
    queries = pad_rows(np.asarray(query_codes, np.int32), Qb, np.int32(-(2**31)))
    fn = make_sharded_find_rows(mesh, ids.shape[0], T, Qb)
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    ids_j, nv_j, q_j = jnp.asarray(ids), jnp.asarray(n_valid), jnp.asarray(queries)
    TEL.record_launch(
        "mesh_find", ("rows", ids.shape[0], T, Qb), T,
        cost=lambda: costmodel.spec(fn, ids_j, nv_j, q_j, mesh=mesh))
    t0 = _time.perf_counter()
    from .mesh import DISPATCH_LOCK

    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        out = np.asarray(fn(ids_j, nv_j, q_j))
    TEL.observe_device("mesh_find", T, t0)
    return out[: len(id_code_arrays), :q]


def stack_block_ids(id_code_arrays: list[np.ndarray], n_shards: int) -> tuple[np.ndarray, np.ndarray, int]:
    """Stack per-block sorted id-code arrays (Ti, 4) into (B, T, 4) padded
    for an n_shards-way mesh: T = common power-of-two bucket, B padded to a
    multiple of n_shards with empty blocks. Returns (ids, n_valid, T)."""
    B = len(id_code_arrays)
    T = bucket(max([a.shape[0] for a in id_code_arrays] + [1]))
    Bp = ((B + n_shards - 1) // n_shards) * n_shards if B else n_shards
    ids = np.full((Bp, T, 4), np.int32(2**31 - 1), dtype=np.int32)
    n_valid = np.zeros((Bp,), dtype=np.int32)
    for i, a in enumerate(id_code_arrays):
        ids[i, : a.shape[0]] = a
        n_valid[i] = a.shape[0]
    return ids, n_valid, T


def sharded_find(mesh, id_code_arrays: list[np.ndarray], query_codes: np.ndarray) -> np.ndarray:
    """Host entry: look up Q trace ids across many blocks on the mesh.
    Returns (Q, 2) int32 [block, row] (-1,-1 on miss)."""
    n = mesh.devices.size
    q = query_codes.shape[0]
    if not id_code_arrays or q == 0:
        return np.full((q, 2), -1, dtype=np.int32)
    ids, n_valid, T = stack_block_ids(id_code_arrays, n, )
    Qb = bucket(q)
    queries = pad_rows(np.asarray(query_codes, np.int32), Qb, np.int32(-(2**31)))
    fn = make_sharded_find(mesh, ids.shape[0], T, Qb)
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    ids_j, nv_j, q_j = jnp.asarray(ids), jnp.asarray(n_valid), jnp.asarray(queries)
    TEL.record_launch(
        "mesh_find", ("elect", ids.shape[0], T, Qb), T,
        cost=lambda: costmodel.spec(fn, ids_j, nv_j, q_j, mesh=mesh))
    t0 = _time.perf_counter()
    from .mesh import DISPATCH_LOCK

    with DISPATCH_LOCK:  # collective programs must not interleave enqueues
        out = np.asarray(fn(ids_j, nv_j, q_j))[:q]
    TEL.observe_device("mesh_find", T, t0)
    out = out.astype(np.int32, copy=True)
    out[out[:, 0] < 0] = -1  # normalize misses to (-1, -1)
    return out
