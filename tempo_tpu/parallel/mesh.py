"""Device mesh construction for the query/compaction axes."""

from __future__ import annotations

import threading

import jax
from jax.sharding import Mesh

# Multi-device (collective) programs dispatched concurrently from
# several host threads can interleave their per-device enqueue order --
# thread A lands program1 on device 0 first while thread B lands
# program2 on device 3 first -- and the collectives then wait on each
# other forever (observed as a hard hang in test_stress's concurrent
# searchers on the 8-device CPU mesh; the same cross-ordering hazard
# exists on real chips). Every mesh host entry point serializes its
# dispatch+fetch under this lock; single-device kernels are unaffected.
DISPATCH_LOCK = threading.Lock()


def smap(f, mesh, in_specs, out_specs):
    """shard_map with the varying-axes check off: our kernels mix
    replicated operands (queries, predicate operands) with device-varying
    shards inside fori_loops, which the strict vma check rejects."""
    try:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                             check_vma=False)
    except (TypeError, AttributeError):
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def _factor(n: int) -> tuple[int, int]:
    """(dp, sp) with dp*sp == n, dp the largest divisor <= sqrt(n)."""
    dp = 1
    d = 1
    while d * d <= n:
        if n % d == 0:
            dp = d
        d += 1
    return dp, n // dp


def make_mesh(n_devices: int | None = None, dp: int | None = None, sp: int | None = None) -> Mesh:
    """2D mesh with axes ('dp', 'sp'): dp shards blocks, sp shards rows
    within a block. Defaults to all visible devices, near-square split so
    both axes are exercised (8 devices -> 2x4)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    if dp is None and sp is None:
        dp, sp = _factor(n)
    elif dp is None:
        dp = n // sp
    elif sp is None:
        sp = n // dp
    assert dp * sp == n, f"dp*sp ({dp}*{sp}) != n_devices ({n})"
    import numpy as np

    return Mesh(np.asarray(devices[:n]).reshape(dp, sp), ("dp", "sp"))
