"""The composed multi-chip step: find + search + bloom union in ONE jit.

This is the whole read+compact hot path as a single mesh program --
what the driver's dryrun compiles, and the shape production queries run
as: sharded trace-ID lookup (dp x sp, pmax combine), sharded predicate
search (dp blocks, sp rows, psum combine), and the compaction bloom
union (all_gather + OR). One compile, three collectives, zero host
round-trips between stages.
"""

from __future__ import annotations

from functools import lru_cache

import jax

from ..ops.filter import normalize_tree
from .bloom import make_sharded_union
from .find import make_sharded_find
from .search import make_sharded_search


@lru_cache(maxsize=32)
def distributed_query_step(mesh, tree, conds, col_names: tuple[str, ...],
                           B: int, T: int, Q: int, S: int, R: int, NT: int,
                           K: int, NS: int, W: int):
    """Returns jit(fn)(ids, n_valid, queries, ops_i, ops_f, n_spans,
    col_arrays, blooms) -> (hits (Q,2) [block,row], trace_mask (B,NT),
    span_count (B,NT), bloom_union (NS,W))."""
    conds = tuple(conds)
    if tree is not None:
        tree = normalize_tree(tree, conds)
    find_fn = make_sharded_find(mesh, B, T, Q)
    search_fn = make_sharded_search(mesh, tree, conds, col_names, B, S, R, NT)
    union_fn = make_sharded_union(mesh, K, NS, W)

    def step(ids, n_valid, queries, ops_i, ops_f, n_spans, col_arrays, blooms):
        import jax.numpy as jnp

        hits = find_fn(ids, n_valid, queries)
        # search operands are per-block (B, C, ...); the composed step takes
        # one operand set and replicates it across blocks
        ops_bi = jnp.broadcast_to(ops_i[None], (B,) + ops_i.shape)
        ops_bf = jnp.broadcast_to(ops_f[None], (B,) + ops_f.shape)
        tm, sc = search_fn(ops_bi, ops_bf, n_spans, *col_arrays)
        bu = union_fn(blooms)
        return hits, tm, sc, bu

    fn = jax.jit(step)

    def launcher(ids, n_valid, queries, ops_i, ops_f, n_spans, col_arrays, blooms):
        """Thin telemetry shim over the jitted step: the driver calls
        this like the jit fn; the first call per shape also captures the
        composed program's XLA costs + collective comm bytes
        (util/costmodel -- find's pmax, search's psum, union's
        all_gather all in ONE walk)."""
        from ..util import costmodel
        from ..util.kerneltel import TEL

        args = (ids, n_valid, queries, ops_i, ops_f, n_spans, col_arrays, blooms)
        TEL.record_launch(
            "mesh_step", ("step", B, T, Q, S, R, NT, K, NS, W), S,
            cost=lambda: costmodel.spec(fn, *args, mesh=mesh))
        import time as _time

        t0 = _time.perf_counter()
        out = fn(*args)
        TEL.observe_device("mesh_step", S, t0)
        return out

    return launcher
