"""Exact host-side evaluation of TraceQL over a materialized wire-model
trace.

The device filter is allowed to over-match (clamped int32/f32 encodings,
mixed OR trees, and every construct the planner can't compile -- field
arithmetic, parent scope, childCount, pipelines); queries whose plan
sets needs_verify re-check every surviving candidate here before it
reaches the user, the same role the final proto-level Matches() check
plays in the reference (pkg/model/object_decoder.go Matches).

Evaluation is VALUE-typed (the reference's Static runtime): field
expressions produce str/int/float/bool/duration(ns int)/status/kind
values or None (missing); comparisons and arithmetic follow
pkg/traceql/ast.go execute semantics. Pipelines carry a list of span
GROUPS (by() splits, coalesce() merges, scalar filters keep groups
whose fold passes); a trace matches when some group survives every
stage non-empty.
"""

from __future__ import annotations

import re

from ..wire.model import Resource, Span, Trace
from .ast import (
    Aggregate,
    BinaryOp,
    Coalesce,
    Comparison,
    Field,
    GroupBy,
    LogicalExpr,
    Pipeline,
    ScalarFilter,
    ScalarOp,
    ScalarPipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
    UnaryOp,
)

_STATUS_NAMES = {0: "unset", 1: "ok", 2: "error"}
_KIND_NAMES = {0: "unspecified", 1: "internal", 2: "server", 3: "client", 4: "producer", 5: "consumer"}


class _Nil:
    """The nil literal's runtime value: distinct from None (missing) so
    `x = nil` can match absent attributes explicitly."""

    __slots__ = ()


_NIL = _Nil()


class _TraceCtx:
    """Per-trace evaluation context: trace intrinsics, span parent links
    and child counts (parent./childCount/parent-intrinsic support)."""

    def __init__(self, trace: Trace):
        self.trace = trace
        lo, hi = trace.time_range_nanos()
        self.spans: list[tuple[Span, Resource]] = []
        self.by_id: dict[bytes, tuple[Span, Resource]] = {}
        self.child_count: dict[bytes, int] = {}
        root = first = None
        for rs in trace.resource_spans:
            for ss in rs.scope_spans:
                for sp in ss.spans:
                    pair = (sp, rs.resource)
                    self.spans.append(pair)
                    if sp.span_id:
                        self.by_id[sp.span_id] = pair
                    if first is None:
                        first = pair
                    if root is None and not sp.parent_span_id.strip(b"\x00"):
                        root = pair
        for sp, _ in self.spans:
            p = sp.parent_span_id
            if p and p.strip(b"\x00"):
                self.child_count[p] = self.child_count.get(p, 0) + 1
        pick = root or first
        self.tvals = {
            "traceDuration": (hi or 0) - (lo or 0),
            "rootName": pick[0].name if pick else "",
            "rootServiceName": pick[1].service_name if pick else "",
        }

    def parent_of(self, sp: Span) -> tuple[Span, Resource] | None:
        p = sp.parent_span_id
        if not p or not p.strip(b"\x00"):
            return None
        return self.by_id.get(p)


# ------------------------------------------------------------- values


def _field_value(f: Field, span: Span, res: Resource, ctx: _TraceCtx):
    """Typed value of a field for one span; None = missing."""
    if f.parent:
        parent = ctx.parent_of(span)
        if parent is None:
            return None  # roots have no parent: parent.x is undefined
        span, res = parent
        f = Field(f.scope, f.name)
    if f.scope == Scope.INTRINSIC:
        n = f.name
        if n == "name":
            return span.name
        if n == "duration":
            return span.duration_nanos
        if n == "status":
            return ("status", int(span.status_code))
        if n == "kind":
            return ("kind", int(span.kind))
        if n == "childCount":
            return ctx.child_count.get(span.span_id, 0)
        if n == "parent":
            return ctx.parent_of(span)  # None for roots -> `parent = nil`
        if n in ("traceDuration", "rootName", "rootServiceName"):
            return ctx.tvals[n]
        return None
    if f.scope == Scope.SPAN:
        return span.attrs.get(f.name)
    if f.scope == Scope.RESOURCE:
        return res.attrs.get(f.name)
    # EITHER: span wins, falls back to resource (reference precedence)
    if f.name in span.attrs:
        return span.attrs[f.name]
    return res.attrs.get(f.name)


def _static_value(s: Static):
    if s.kind == "nil":
        return _NIL
    if s.kind == "status":
        return ("status", int(s.value))
    if s.kind == "kind":
        return ("kind", int(s.value))
    return s.value


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _cmp_values(op: str, actual, want) -> bool:
    """Comparison semantics over runtime values. None (missing) never
    matches except `= nil`; nil matches None and only None."""
    if op == "exists":
        return actual is not None
    if want is _NIL or actual is _NIL:
        other = actual if want is _NIL else want
        missing = other is None or other is _NIL
        return missing if op == "=" else (not missing) if op == "!=" else False
    if actual is None or want is None:
        return False
    # status/kind enums compare only against their own tag
    if isinstance(actual, tuple) or isinstance(want, tuple):
        if (isinstance(actual, tuple) and isinstance(want, tuple)
                and actual[0] == want[0]):
            if op == "=":
                return actual[1] == want[1]
            if op == "!=":
                return actual[1] != want[1]
        # number literals also compare against enums (legacy surface);
        # no int() truncation -- 1.7 must not equal status code 1
        if isinstance(actual, tuple) and _is_num(want):
            return _cmp_values(op, actual[1], want)
        if isinstance(want, tuple) and _is_num(actual):
            return _cmp_values(op, actual, want[1])
        return op == "!="
    if isinstance(want, bool) or isinstance(actual, bool):
        if not isinstance(actual, bool) or not isinstance(want, bool):
            return op == "!="
        return (actual == want) if op == "=" else (actual != want) if op == "!=" else False
    if isinstance(want, str) or isinstance(actual, str):
        if not (isinstance(actual, str) and isinstance(want, str)):
            return op == "!="
        if op == "=~":
            return re.search(want, actual) is not None
        if op == "!~":
            return re.search(want, actual) is None
        if op == "=":
            return actual == want
        if op == "!=":
            return actual != want
        return False
    if not (_is_num(actual) and _is_num(want)):
        return op == "!="
    a, w = float(actual), float(want)
    return {
        "=": a == w, "!=": a != w, "<": a < w, "<=": a <= w, ">": a > w, ">=": a >= w,
    }.get(op, False)


def _arith(op: str, a, b):
    if not (_is_num(a) and _is_num(b)):
        return None
    try:
        if op == "+":
            return a + b
        if op == "-":
            return a - b
        if op == "*":
            return a * b
        if op == "/":
            return a / b
        if op == "%":
            return a % b
        if op == "^":
            return a ** b
    except (ZeroDivisionError, OverflowError, ValueError):
        return None
    return None


def _value(expr, span: Span, res: Resource, ctx: _TraceCtx):
    """Evaluate a field expression to a runtime value (None = undefined)."""
    if isinstance(expr, Static):
        return _static_value(expr)
    if isinstance(expr, Field):
        return _field_value(expr, span, res, ctx)
    if isinstance(expr, Comparison):
        want = _static_value(expr.value)
        actual = _field_value(expr.field, span, res, ctx)
        return _cmp_values(expr.op, actual, want)
    if isinstance(expr, LogicalExpr):
        lv = _value(expr.lhs, span, res, ctx)
        rv = _value(expr.rhs, span, res, ctx)
        lb = lv is True
        rb = rv is True
        return (lb and rb) if expr.op == "&&" else (lb or rb)
    if isinstance(expr, UnaryOp):
        v = _value(expr.operand, span, res, ctx)
        if expr.op == "-":
            return -v if _is_num(v) else None
        return (not v) if isinstance(v, bool) else None
    if isinstance(expr, BinaryOp):
        a = _value(expr.lhs, span, res, ctx)
        b = _value(expr.rhs, span, res, ctx)
        if expr.op in ("+", "-", "*", "/", "%", "^"):
            return _arith(expr.op, a, b)
        return _cmp_values(expr.op, a, b)
    raise TypeError(f"cannot evaluate {expr!r}")


def _eval_expr(expr, span: Span, res: Resource, ctx: _TraceCtx) -> bool:
    """Boolean position: the expression's value must be True."""
    return _value(expr, span, res, ctx) is True


# ------------------------------------------------------------ spansets


def _matched_spans(expr, ctx: _TraceCtx) -> list[tuple[Span, Resource]]:
    """The spanset an expression selects from one trace: filter matches,
    the structural/combinator result of two spansets (expr.y
    spansetExpression semantics), or a pipeline's surviving spans."""
    if isinstance(expr, Pipeline):
        groups = _eval_pipeline_groups(expr, ctx)
        out = []
        for g in groups:
            out = _union(out, g)
        return out
    if isinstance(expr, SpansetFilter):
        if expr.expr is None:
            return list(ctx.spans)
        return [(sp, r) for sp, r in ctx.spans if _eval_expr(expr.expr, sp, r, ctx)]
    lhs = _matched_spans(expr.lhs, ctx)
    rhs = _matched_spans(expr.rhs, ctx)
    if expr.op == "&&":
        # both present: result is the union of both sides' spans
        return _union(lhs, rhs) if lhs and rhs else []
    if expr.op == "||":
        return _union(lhs, rhs)

    def _parent(sp: Span) -> bytes:
        # zero-filled parent ids mean "no parent", same rule as root
        # detection elsewhere in this module
        p = sp.parent_span_id
        return p if p and p.strip(b"\x00") else b""

    lhs_ids = {sp.span_id for sp, _ in lhs if sp.span_id}
    if expr.op == ">":
        return [(sp, r) for sp, r in rhs if _parent(sp) in lhs_ids]
    if expr.op == ">>":
        parent_of = {sp.span_id: _parent(sp) for sp, _ in ctx.spans if sp.span_id}
        out = []
        for sp, r in rhs:
            anc = _parent(sp)
            seen = set()
            while anc and anc not in seen:
                if anc in lhs_ids:
                    out.append((sp, r))
                    break
                seen.add(anc)
                anc = parent_of.get(anc, b"")
        return out
    if expr.op == "~":
        # siblings: some lhs span with the SAME parent and a DIFFERENT
        # id (pairwise, so `{x} ~ {x}` matches twin x spans)
        by_parent: dict[bytes, set] = {}
        for sp, _ in lhs:
            p = _parent(sp)
            if p:
                by_parent.setdefault(p, set()).add(sp.span_id)
        out = []
        for sp, r in rhs:
            sibs = by_parent.get(_parent(sp))
            if sibs and (sibs - {sp.span_id}):
                out.append((sp, r))
        return out
    raise TypeError(f"unknown spanset op {expr.op!r}")


def _union(a, b):
    seen = set()
    out = []
    for sp, r in a + b:
        if id(sp) not in seen:
            seen.add(id(sp))
            out.append((sp, r))
    return out


# ------------------------------------------------------------ scalars


def _scalar_value(s, group: list, ctx: _TraceCtx):
    """Value of a scalar expression over one span group (None =
    undefined: empty fold, missing fields, arithmetic on non-numbers)."""
    if isinstance(s, Static):
        v = _static_value(s)
        return v if _is_num(v) else None
    if isinstance(s, Aggregate):
        if s.fn == "count":
            return len(group)
        vals = []
        for sp, res in group:
            v = _value(s.field, sp, res, ctx)
            if _is_num(v):
                vals.append(v)
        if not vals:
            return None
        if s.fn == "avg":
            return sum(vals) / len(vals)
        if s.fn == "min":
            return min(vals)
        if s.fn == "max":
            return max(vals)
        return sum(vals)
    if isinstance(s, ScalarOp):
        return _arith(s.op, _scalar_value(s.lhs, group, ctx),
                      _scalar_value(s.rhs, group, ctx))
    if isinstance(s, ScalarPipeline):
        # wrapped pipeline: its scalar folds over the spans its OWN
        # pipeline selects from the whole trace
        sub = _matched_spans(s.filter, ctx)
        return _scalar_value(s.scalar, sub, ctx)
    raise TypeError(f"cannot evaluate scalar {s!r}")


# ----------------------------------------------------------- pipelines


def _eval_pipeline_groups(q: Pipeline, ctx: _TraceCtx) -> list[list]:
    """Run a pipeline: start from the filter's spanset as one group,
    apply stages in order; returns the surviving (non-empty) groups."""
    start = _matched_spans(q.filter, ctx)
    if not start:
        # an empty spanset never enters the pipeline (reference drops
        # empty spansets first), so `| count() = 0` matches nothing --
        # identically to the device prefilter path
        return []
    groups: list[list] = [start]
    for st in q.stages:
        if isinstance(st, (SpansetFilter, SpansetOp)):
            if isinstance(st, SpansetFilter):
                groups = [
                    [(sp, r) for sp, r in g
                     if st.expr is None or _eval_expr(st.expr, sp, r, ctx)]
                    for g in groups
                ]
            else:
                # structural stage: relations resolve against the whole
                # trace, membership restricted to the group
                sel = _matched_spans(st, ctx)
                keep = {id(sp) for sp, _ in sel}
                groups = [[(sp, r) for sp, r in g if id(sp) in keep]
                          for g in groups]
        elif isinstance(st, ScalarFilter):
            out = []
            for g in groups:
                lv = _scalar_value(st.lhs, g, ctx)
                rv = _scalar_value(st.rhs, g, ctx)
                if lv is not None and rv is not None and _cmp_values(st.op, lv, rv):
                    out.append(g)
            groups = out
        elif isinstance(st, GroupBy):
            regrouped: dict = {}
            for g in groups:
                for sp, r in g:
                    k = _value(st.expr, sp, r, ctx)
                    if k is None:
                        continue  # nil group keys drop the span
                    if (isinstance(k, tuple) and len(k) == 2
                            and isinstance(k[0], Span)):
                        k = ("span", k[0].span_id)  # by(parent): identity key
                    regrouped.setdefault(k, []).append((sp, r))
            groups = list(regrouped.values())
        elif isinstance(st, Coalesce):
            merged: list = []
            for g in groups:
                merged = _union(merged, g)
            groups = [merged] if merged else []
        else:
            raise TypeError(f"unknown pipeline stage {st!r}")
        groups = [g for g in groups if g]
        if not groups:
            return []
    return groups


def trace_matches(q, trace: Trace) -> bool:
    """True iff the trace satisfies the query: some span passes a
    spanset filter; structural/combinator expressions select a
    non-empty spanset; pipelines additionally pass every stage."""
    ctx = _TraceCtx(trace)
    if isinstance(q, Pipeline):
        return bool(_eval_pipeline_groups(q, ctx))
    return bool(_matched_spans(q, ctx))
