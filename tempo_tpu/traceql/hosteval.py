"""Exact host-side evaluation of a TraceQL spanset filter over a
materialized wire-model trace.

The device filter is allowed to over-match (clamped int32/f32 encodings,
mixed OR trees -- ops/filter.py docstring); queries whose plan sets
needs_verify re-check every surviving candidate here before it reaches
the user, the same role the final proto-level Matches() check plays in
the reference (pkg/model/object_decoder.go Matches).

Semantics: `{ expr }` matches a trace iff some single span satisfies
every span-level predicate, with trace intrinsics (traceDuration,
rootName, rootServiceName) evaluated trace-wide.
"""

from __future__ import annotations

import re

from ..wire.model import Resource, Span, Trace
from .ast import (
    Comparison,
    Field,
    LogicalExpr,
    Pipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
)

_STATUS_NAMES = {0: "unset", 1: "ok", 2: "error"}
_KIND_NAMES = {0: "unspecified", 1: "internal", 2: "server", 3: "client", 4: "producer", 5: "consumer"}


def _cmp_values(op: str, actual, want) -> bool:
    if op == "exists":
        return actual is not None
    if actual is None:
        return False
    if isinstance(want, bool) or isinstance(actual, bool):
        if not isinstance(actual, bool) or not isinstance(want, bool):
            return op == "!="
        return (actual == want) if op == "=" else (actual != want) if op == "!=" else False
    if isinstance(want, str):
        if not isinstance(actual, str):
            return op == "!="
        if op == "=~":
            return re.search(want, actual) is not None
        if op == "!~":
            return re.search(want, actual) is None
        if op == "=":
            return actual == want
        if op == "!=":
            return actual != want
        return False
    # numeric
    if isinstance(actual, str):
        return op == "!="
    try:
        a, w = float(actual), float(want)
    except (TypeError, ValueError):
        return op == "!="
    return {
        "=": a == w, "!=": a != w, "<": a < w, "<=": a <= w, ">": a > w, ">=": a >= w,
    }.get(op, False)


def _trace_values(trace: Trace):
    lo, hi = trace.time_range_nanos()
    # root = first span (document order) with an empty parent id, falling
    # back to the first span -- same rule as block/builder.py:267-274
    root = None
    first = None
    for rs in trace.resource_spans:
        for ss in rs.scope_spans:
            for sp in ss.spans:
                if first is None:
                    first = (sp, rs.resource)
                if root is None and not sp.parent_span_id.strip(b"\x00"):
                    root = (sp, rs.resource)
    pick = root or first
    return {
        "traceDuration": (hi or 0) - (lo or 0),
        "rootName": pick[0].name if pick else "",
        "rootServiceName": pick[1].service_name if pick else "",
    }


def _eval_cmp(cmp: Comparison, span: Span, res: Resource, tvals: dict) -> bool:
    f, op, lit = cmp.field, cmp.op, cmp.value
    want = lit.value if lit is not None else None
    if f.scope == Scope.INTRINSIC:
        if f.name == "name":
            return _cmp_values(op, span.name, want)
        if f.name == "duration":
            return _cmp_values(op, span.duration_nanos, want)
        if f.name == "status":
            return _cmp_values(op, int(span.status_code), int(want))
        if f.name == "kind":
            return _cmp_values(op, int(span.kind), int(want))
        if f.name == "traceDuration":
            return _cmp_values(op, tvals["traceDuration"], want)
        if f.name == "rootName":
            return _cmp_values(op, tvals["rootName"], want)
        if f.name == "rootServiceName":
            return _cmp_values(op, tvals["rootServiceName"], want)
        return False
    if f.scope == Scope.SPAN:
        return _cmp_values(op, span.attrs.get(f.name), want)
    if f.scope == Scope.RESOURCE:
        return _cmp_values(op, res.attrs.get(f.name), want)
    # EITHER: span wins, falls back to resource (reference precedence,
    # vparquet/block_traceql.go attribute scopes)
    if f.name in span.attrs:
        return _cmp_values(op, span.attrs.get(f.name), want)
    return _cmp_values(op, res.attrs.get(f.name), want)


def _eval_expr(expr, span: Span, res: Resource, tvals: dict) -> bool:
    if isinstance(expr, LogicalExpr):
        if expr.op == "&&":
            return _eval_expr(expr.lhs, span, res, tvals) and _eval_expr(expr.rhs, span, res, tvals)
        return _eval_expr(expr.lhs, span, res, tvals) or _eval_expr(expr.rhs, span, res, tvals)
    if isinstance(expr, Comparison):
        return _eval_cmp(expr, span, res, tvals)
    raise TypeError(f"cannot evaluate {expr!r}")


def _agg_field_value(f: Field, span: Span, res: Resource):
    """Numeric value of the aggregate's field for one span (None = the
    span contributes nothing to the fold)."""
    if f.scope == Scope.INTRINSIC:
        if f.name == "duration":
            return span.duration_nanos
        return None
    if f.scope == Scope.SPAN:
        v = span.attrs.get(f.name)
    elif f.scope == Scope.RESOURCE:
        v = res.attrs.get(f.name)
    else:  # EITHER
        v = span.attrs.get(f.name, res.attrs.get(f.name))
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) else None


def _matched_spans(expr, trace: Trace, tvals: dict) -> list[tuple[Span, Resource]]:
    """The spanset an expression selects from one trace: filter matches,
    or the structural/combinator result of two spansets
    (expr.y spansetExpression semantics)."""
    if isinstance(expr, SpansetFilter):
        out = []
        for rs in trace.resource_spans:
            for ss in rs.scope_spans:
                for sp in ss.spans:
                    if expr.expr is None or _eval_expr(expr.expr, sp, rs.resource, tvals):
                        out.append((sp, rs.resource))
        return out
    lhs = _matched_spans(expr.lhs, trace, tvals)
    rhs = _matched_spans(expr.rhs, trace, tvals)
    if expr.op == "&&":
        # both present: result is the union of both sides' spans
        return _union(lhs, rhs) if lhs and rhs else []
    if expr.op == "||":
        return _union(lhs, rhs)

    def _parent(sp: Span) -> bytes:
        # zero-filled parent ids mean "no parent", same rule as root
        # detection elsewhere in this module
        p = sp.parent_span_id
        return p if p and p.strip(b"\x00") else b""

    lhs_ids = {sp.span_id for sp, _ in lhs if sp.span_id}
    if expr.op == ">":
        return [(sp, r) for sp, r in rhs if _parent(sp) in lhs_ids]
    if expr.op == ">>":
        parent_of: dict[bytes, bytes] = {}
        for rs in trace.resource_spans:
            for ss in rs.scope_spans:
                for sp in ss.spans:
                    if sp.span_id:
                        parent_of[sp.span_id] = _parent(sp)
        out = []
        for sp, r in rhs:
            anc = _parent(sp)
            seen = set()
            while anc and anc not in seen:
                if anc in lhs_ids:
                    out.append((sp, r))
                    break
                seen.add(anc)
                anc = parent_of.get(anc, b"")
        return out
    if expr.op == "~":
        # siblings: some lhs span with the SAME parent and a DIFFERENT
        # id (pairwise, so `{x} ~ {x}` matches twin x spans)
        by_parent: dict[bytes, set] = {}
        for sp, _ in lhs:
            p = _parent(sp)
            if p:
                by_parent.setdefault(p, set()).add(sp.span_id)
        out = []
        for sp, r in rhs:
            sibs = by_parent.get(_parent(sp))
            if sibs and (sibs - {sp.span_id}):
                out.append((sp, r))
        return out
    raise TypeError(f"unknown spanset op {expr.op!r}")


def _union(a, b):
    seen = set()
    out = []
    for sp, r in a + b:
        if id(sp) not in seen:
            seen.add(id(sp))
            out.append((sp, r))
    return out


def _eval_pipeline(q: Pipeline, trace: Trace, tvals: dict) -> bool:
    """Exact evaluation: matched spans of the spanset expression, folded
    through every scalar aggregate stage (expr.y scalarFilter)."""
    matched = _matched_spans(q.filter, trace, tvals)
    if not matched:
        # an empty spanset never reaches the pipeline (reference drops
        # empty spansets first), so `| count() = 0` matches nothing --
        # identically to the device prefilter path
        return False
    for st in q.stages:
        if st.fn == "count":
            actual: float | int | None = len(matched)
        else:
            vals = [v for sp, res in matched
                    if (v := _agg_field_value(st.field, sp, res)) is not None]
            if not vals:
                return False  # nothing to fold: the scalar is undefined
            actual = {
                "avg": sum(vals) / len(vals),
                "min": min(vals),
                "max": max(vals),
                "sum": sum(vals),
            }[st.fn]
        want = st.value.value
        if not _cmp_values(st.op, actual, want):
            return False
    return True


def trace_matches(q, trace: Trace) -> bool:
    """True iff the trace satisfies the query: some span passes a
    spanset filter; structural/combinator expressions select a
    non-empty spanset; pipelines additionally pass every stage."""
    if isinstance(q, Pipeline):
        return _eval_pipeline(q, trace, _trace_values(trace))
    if isinstance(q, SpansetOp):
        return bool(_matched_spans(q, trace, _trace_values(trace)))
    if q.expr is None:
        return True
    tvals = _trace_values(trace)
    for rs in trace.resource_spans:
        for ss in rs.scope_spans:
            for sp in ss.spans:
                if _eval_expr(q.expr, sp, rs.resource, tvals):
                    return True
    return False
