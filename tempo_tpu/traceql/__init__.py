from .ast import (
    Comparison,
    Field,
    LogicalExpr,
    ParseError,
    SpansetFilter,
    Static,
)
from .parser import parse
from .plan import PlannedQuery, plan_query, plan_search_request
