"""TraceQL planner: AST -> device condition tree for one block.

The condition->column routing of the reference's
vparquet/block_traceql.go:330-451, re-targeted at vtpu columns:
intrinsics map to dedicated span/trace columns, well-known attrs to
dedicated columns, everything else to the generic attr tables; an
either-scope `.attr` ORs the span- and resource-side plans. String
operands resolve through the block dictionary (a miss folds to a
constant, which can prune the whole block); regexes evaluate host-side
over the dictionary into a code table (one device gather per row).

Durations compare exactly: nanos split into (us, ns-remainder) column
pairs => two-lane integer compares, no f64 needed.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field, replace

import numpy as np

from ..block.dictionary import Dictionary
from ..ops.filter import Cond, normalize_tree
from .ast import (
    Comparison,
    Field,
    LogicalExpr,
    MetricsQuery,
    ParseError,
    Pipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
)

_IMPOSSIBLE_CODE = -3  # operand code that matches no row (codes are >= -1)

_WELL_KNOWN_SPAN = {"http.method": "span.http_method_id", "http.url": "span.http_url_id"}
_WELL_KNOWN_SPAN_INT = {"http.status_code": "span.http_status"}
_WELL_KNOWN_RES = {
    "service.name": "res.service_id",
    "k8s.cluster.name": "res.cluster_id",
    "k8s.namespace.name": "res.namespace_id",
    "k8s.pod.name": "res.pod_id",
    "k8s.container.name": "res.container_id",
}

_OP_MAP = {"=": "eq", "!=": "ne_present", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}

TRUE = ("true",)
FALSE = ("false",)


@dataclass
class Plan:
    """Accumulates conditions while folding constants."""

    conds: list[Cond] = field(default_factory=list)
    rows: list[tuple] = field(default_factory=list)
    tables: dict[int, np.ndarray] = field(default_factory=dict)
    # set when some construct couldn't be compiled to device conds (field
    # arithmetic, parent scope, childCount, ...): the plan over-matches
    # (TRUE leaf) and every candidate is exactly re-checked on host
    force_verify: bool = False

    def cond(self, c: Cond, key: int = 0, v0: int = 0, v1: int = 0, f0: float = 0.0,
             f1: float = 0.0, table: np.ndarray | None = None):
        self.conds.append(c)
        self.rows.append((key, v0, v1, f0, f1))
        i = len(self.conds) - 1
        if table is not None:
            self.tables[i] = table
        return ("cond", i)


def _fold(op: str, children: list):
    """and/or with true/false constant folding."""
    out = []
    for ch in children:
        if ch == TRUE:
            if op == "or":
                return TRUE
            continue
        if ch == FALSE:
            if op == "and":
                return FALSE
            continue
        out.append(ch)
    if not out:
        return TRUE if op == "and" else FALSE
    if len(out) == 1:
        return out[0]
    return (op,) + tuple(out)


def _regex_table(d: Dictionary, pattern: str) -> np.ndarray:
    rx = re.compile(pattern)
    return np.fromiter((1 if rx.search(s) else 0 for s in d.strings), dtype=np.uint8, count=len(d.strings))


def _dur_pair_tree(p: Plan, target: str, us_col: str, lo_col: str, op: str, dur_ns: int):
    """Exact duration compare via the (us, ns%1000) column pair."""
    q, r = divmod(max(0, int(dur_ns)), 1000)
    INT_MAX = 2**31 - 1
    if q >= INT_MAX:
        # the us column is clamped at INT_MAX (builder); operands at/past
        # the clamp can't compare exactly on device -- match conservatively
        # and let the host re-verify (needs_verify consumer, db/search.py)
        if op in (">", ">=", "="):
            # only clamped spans can possibly satisfy this
            return p.cond(Cond(target=target, col=us_col, op="eq", needs_verify=True),
                          v0=INT_MAX)
        # <, <=, != : any span might satisfy it
        return p.cond(Cond(target=target, col=us_col, op="range", needs_verify=True),
                      v0=0, v1=INT_MAX)

    def c(col, cop, v):
        return p.cond(Cond(target=target, col=col, op=cop), v0=v)

    if op == "=":
        return _fold("and", [c(us_col, "eq", q), c(lo_col, "eq", r)])
    if op == "!=":
        return _fold("or", [c(us_col, "ne", q), c(lo_col, "ne", r)])
    if op in (">", ">="):
        lo_op = "gt" if op == ">" else "ge"
        return _fold("or", [c(us_col, "gt", q), _fold("and", [c(us_col, "eq", q), c(lo_col, lo_op, r)])])
    if op in ("<", "<="):
        lo_op = "lt" if op == "<" else "le"
        return _fold("or", [c(us_col, "lt", q), _fold("and", [c(us_col, "eq", q), c(lo_col, lo_op, r)])])
    raise ParseError(f"cannot {op} a duration")


def _str_col_cond(p: Plan, d: Dictionary, target: str, col: str, op: str, value) -> tuple:
    """String compare against a dedicated code column."""
    if op in ("=~", "!~"):
        table = _regex_table(d, str(value))
        kind = "intable" if op == "=~" else "notintable"
        return p.cond(Cond(target=target, col=col, op=kind), table=table)
    code = d.lookup(str(value))
    if op == "=":
        if code < 0:
            return FALSE
        return p.cond(Cond(target=target, col=col, op="eq"), v0=code)
    if op == "!=":
        return p.cond(
            Cond(target=target, col=col, op="ne_present"),
            v0=code if code >= 0 else _IMPOSSIBLE_CODE,
        )
    # ordered string compares use the sorted-dictionary property:
    # code order == lexicographic order
    lo, hi = 0, len(d) - 1
    import bisect

    pos = bisect.bisect_left(d.strings, str(value))
    exact = pos < len(d) and d.strings[pos] == str(value)
    if op == "<":
        return FALSE if pos == 0 else p.cond(Cond(target=target, col=col, op="range"), v0=0, v1=pos - 1)
    if op == "<=":
        end = pos if exact else pos - 1
        return FALSE if end < 0 else p.cond(Cond(target=target, col=col, op="range"), v0=0, v1=end)
    if op == ">":
        start = pos + 1 if exact else pos
        return FALSE if start > hi else p.cond(Cond(target=target, col=col, op="range"), v0=start, v1=hi)
    if op == ">=":
        return FALSE if pos > hi else p.cond(Cond(target=target, col=col, op="range"), v0=pos, v1=hi)
    raise ParseError(f"unsupported string op {op}")


def _attr_cond(p: Plan, d: Dictionary, table_target: str, key: str, op: str, lit: Static) -> tuple:
    """Generic attr-table condition (sattr or rattr)."""
    kcode = d.lookup(key)
    if kcode < 0:
        # key never appears in this block: != and exists-negative fold false
        return FALSE
    if op == "exists":
        return p.cond(Cond(target=table_target, col="any", op="exists"), key=kcode)
    if lit.kind == "str":
        if op in ("=~", "!~"):
            table = _regex_table(d, str(lit.value))
            kind = "intable" if op == "=~" else "notintable"
            return p.cond(Cond(target=table_target, col="str", op=kind), key=kcode, table=table)
        code = d.lookup(str(lit.value))
        if op == "=":
            if code < 0:
                return FALSE
            return p.cond(Cond(target=table_target, col="str", op="eq"), key=kcode, v0=code)
        if op == "!=":
            return p.cond(
                Cond(target=table_target, col="str", op="ne_present"),
                key=kcode,
                v0=code if code >= 0 else _IMPOSSIBLE_CODE,
            )
        raise ParseError(f"unsupported string op {op} on attribute")
    if lit.kind == "bool":
        if op not in ("=", "!="):
            raise ParseError("booleans support = and != only")
        mapped = "eq" if op == "=" else "ne"
        return p.cond(Cond(target=table_target, col="bool", op=mapped), key=kcode, v0=1 if lit.value else 0)
    if lit.kind in ("int", "duration"):
        v = int(lit.value)
        clamped = not (-(2**31) < v < 2**31)
        mop = _OP_MAP[op] if op != "!=" else "ne"
        int_c = p.cond(
            Cond(target=table_target, col="int", op=mop, needs_verify=clamped),
            key=kcode,
            v0=int(np.clip(v, -(2**31) + 1, 2**31 - 1)),
        )
        # numbers also match float-typed attrs (TraceQL numeric compare)
        flt_c = p.cond(
            Cond(target=table_target, col="float", op=mop, is_float=True, needs_verify=True),
            key=kcode,
            f0=float(v),
        )
        return _fold("or", [int_c, flt_c])
    if lit.kind == "float":
        mop = _OP_MAP[op] if op != "!=" else "ne"
        flt_c = p.cond(
            Cond(target=table_target, col="float", op=mop, is_float=True, needs_verify=True),
            key=kcode,
            f0=float(lit.value),
        )
        int_c = p.cond(
            Cond(target=table_target, col="int", op=mop, needs_verify=True),
            key=kcode,
            v0=int(np.clip(lit.value, -(2**31) + 1, 2**31 - 1)),
        )
        return _fold("or", [flt_c, int_c])
    raise ParseError(f"unsupported literal kind {lit.kind}")


def _plan_comparison(p: Plan, d: Dictionary, cmp: Comparison) -> tuple:
    f, op, lit = cmp.field, cmp.op, cmp.value

    if f.scope == Scope.INTRINSIC:
        if f.name == "name":
            if op == "exists":
                return TRUE
            return _str_col_cond(p, d, "span", "span.name_id", op, lit.value)
        if f.name == "duration":
            if lit.kind not in ("duration", "int", "float"):
                raise ParseError("duration compares against a duration literal")
            ns = int(lit.value)
            return _dur_pair_tree(p, "span", "span.dur_us", "span.dur_lo", op, ns)
        if f.name == "traceDuration":
            ns = int(lit.value)
            return _dur_pair_tree(p, "trace", "trace.dur_us", "trace.dur_lo", op, ns)
        if f.name == "status":
            if lit.kind not in ("status", "int"):
                raise ParseError("status compares against ok/error/unset")
            mapped = _OP_MAP.get(op)
            if mapped is None:
                raise ParseError(f"unsupported status op {op}")
            if mapped == "ne_present":
                mapped = "ne"
            return p.cond(Cond(target="span", col="span.status", op=mapped), v0=int(lit.value))
        if f.name == "kind":
            if lit.kind not in ("kind", "int"):
                raise ParseError("kind compares against server/client/...")
            mapped = _OP_MAP.get(op)
            if mapped is None:
                raise ParseError(f"unsupported kind op {op}")
            if mapped == "ne_present":
                mapped = "ne"
            return p.cond(Cond(target="span", col="span.kind", op=mapped), v0=int(lit.value))
        if f.name == "rootName":
            return _str_col_cond(p, d, "trace", "trace.root_name_id", op, lit.value)
        if f.name == "rootServiceName":
            return _str_col_cond(p, d, "trace", "trace.root_service_id", op, lit.value)
        raise ParseError(f"intrinsic {f.name} not supported")

    alts = []
    if f.scope in (Scope.SPAN, Scope.EITHER):
        ded = _WELL_KNOWN_SPAN.get(f.name)
        ded_int = _WELL_KNOWN_SPAN_INT.get(f.name)
        if ded is not None and lit.kind == "str" and op != "exists":
            alts.append(_str_col_cond(p, d, "span", ded, op, lit.value))
        elif ded_int is not None and lit.kind in ("int", "float") and op != "exists":
            mapped = _OP_MAP[op] if op != "!=" else "ne_present"
            alts.append(
                p.cond(Cond(target="span", col=ded_int, op=mapped), v0=int(lit.value))
            )
        else:
            alts.append(_attr_cond(p, d, "sattr", f.name, op, lit))
    if f.scope in (Scope.RESOURCE, Scope.EITHER):
        ded = _WELL_KNOWN_RES.get(f.name)
        if ded is not None and lit.kind == "str" and op != "exists":
            alts.append(_str_col_cond(p, d, "res", ded, op, lit.value))
        elif ded is not None and op == "exists":
            # well-known res attrs live ONLY in dedicated columns
            # (builder.py res_dedicated); -1 marks absent
            alts.append(p.cond(Cond(target="res", col=ded, op="ge"), v0=0))
        else:
            alts.append(_attr_cond(p, d, "rattr", f.name, op, lit))
    return _fold("or", alts)


def _tree_has_sibling(t) -> bool:
    if not isinstance(t, tuple) or t in (TRUE, FALSE) or t[0] == "cond":
        return False
    if t[0] == "struct":
        return t[1] == "~" or any(_tree_has_sibling(ch) for ch in t[2:])
    return any(_tree_has_sibling(ch) for ch in t[1:])


def _tree_has_trace_cond(t, conds) -> bool:
    if t in (TRUE, FALSE):
        return False
    if t[0] == "cond":
        return conds[t[1]].target == "trace"
    if t[0] == "struct":
        return any(_tree_has_trace_cond(ch, conds) for ch in t[2:])
    return any(_tree_has_trace_cond(ch, conds) for ch in t[1:])


def _span_tree(p: Plan, d: Dictionary, q):
    """Span-level tree for a spanset expression, or None when it can't
    be expressed purely at span level (trace-target conds, pipelines,
    unplannable constructs, && / || combinators whose result spanset is
    trace-dependent)."""
    if isinstance(q, SpansetFilter):
        if q.expr is None:
            return TRUE
        fv0 = p.force_verify
        t = _plan_expr(p, d, q.expr)
        if (p.force_verify and not fv0) or _tree_has_trace_cond(t, p.conds):
            return None
        return t
    if isinstance(q, SpansetOp) and q.op in (">", ">>", "~"):
        lt = _span_tree(p, d, q.lhs)
        rt = _span_tree(p, d, q.rhs)
        if lt is None or rt is None:
            return None
        return ("struct", q.op, lt, rt)
    return None


def _plan_spanset_expr(p: Plan, d: Dictionary, q, allow_struct: bool = True) -> tuple[tuple, bool]:
    """Spanset expression -> (trace-level tree, needs host verification).
    Each leaf spanset tracifies independently; && combinators AND them
    (a qualifying trace must contain every leaf's spans), || ORs.

    Structural relations (> >> ~) over pure span-level sides compile to
    EXACT ('struct', op, lhs, rhs) span trees: the engines resolve the
    relation with parent-row gathers / segment sums over
    span.parent_idx, so no host verification is needed. Anything the
    struct compiler can't express falls back to the conservative
    trace-level AND of both sides + exact host verification."""
    if isinstance(q, SpansetFilter):
        if q.expr is None:
            return TRUE, False
        t = _plan_expr(p, d, q.expr)
        if t in (TRUE, FALSE):
            return t, False
        # lift instead of blind-wrapping: a trace-target cond inside
        # ('tracify', ...) would reach the engines' SPAN evaluators and
        # crash (fuzz-found on `{...} ~ { traceDuration > 1ms }`).
        # normalize_tree keeps this leaf's span conds in ONE tracify
        # group (same-span semantics) with trace conds alongside. The
        # mixed-or verify flag is computed on the RAW tree here and
        # propagated by the combinator fold: _finish's _mixed_or can't
        # see through the pre-inserted tracify nodes.
        return normalize_tree(t, tuple(p.conds)), _mixed_or(t, tuple(p.conds))
    if isinstance(q, Pipeline):
        # wrapped-pipeline operand ((...|count()>1|{false}) && ...):
        # prefilter by its first spanset; the stages are exact-host-only
        t, _ = _plan_spanset_expr(p, d, q.filter, allow_struct)
        return t, True
    if allow_struct and q.op in (">", ">>", "~"):
        # snapshot the accumulator: a failed struct compile must not
        # leave half-planned conds behind (the fallback re-plans both
        # sides, and duplicates cost a device mask evaluation each)
        n0, fv0 = len(p.conds), p.force_verify
        st = _span_tree(p, d, q)
        if st is not None:
            # `~` over-matches orphan siblings (shared parent id whose
            # span is absent from the trace); exact host re-check needed
            return ("tracify", st), _tree_has_sibling(st)
        del p.conds[n0:]
        del p.rows[n0:]
        for k in [k for k in p.tables if k >= n0]:
            del p.tables[k]
        p.force_verify = fv0  # the fallback re-plans and re-flags
    lt, lv = _plan_spanset_expr(p, d, q.lhs, allow_struct)
    rt, rv = _plan_spanset_expr(p, d, q.rhs, allow_struct)
    structural = q.op in (">", ">>", "~")
    fold_op = "or" if q.op == "||" else "and"
    return _fold(fold_op, [lt, rt]), lv or rv or structural


def _plan_expr(p: Plan, d: Dictionary, expr) -> tuple:
    from .ast import BinaryOp, Field, Static, UnaryOp

    if isinstance(expr, LogicalExpr):
        op = "and" if expr.op == "&&" else "or"
        return _fold(op, [_plan_expr(p, d, expr.lhs), _plan_expr(p, d, expr.rhs)])
    if isinstance(expr, Comparison):
        f, lit = expr.field, expr.value
        if f.parent or (f.scope == Scope.INTRINSIC
                        and f.name in ("childCount", "parent")):
            p.force_verify = True  # host re-checks exactly (hosteval)
            return TRUE
        if lit.kind == "nil":
            if f.scope == Scope.INTRINSIC:
                # non-parent intrinsics (duration, name, status, ...)
                # always carry a value: nil compares resolve statically
                # (the parent intrinsic is caught by the branch above)
                return TRUE if expr.op == "!=" else FALSE
            if expr.op == "!=":
                # existence: != nil <=> the attribute is present
                return _plan_comparison(p, d, Comparison(f, "exists", lit))
            p.force_verify = True  # `= nil` (absence) has no device cond
            return TRUE
        return _plan_comparison(p, d, expr)
    if isinstance(expr, Field):
        # bare field in boolean position: value must be boolean true
        if expr.parent or expr.scope == Scope.INTRINSIC:
            p.force_verify = True
            return TRUE
        return _plan_comparison(p, d, Comparison(expr, "=", Static("bool", True)))
    if isinstance(expr, Static):
        # constant in boolean position ({ true }, { false })
        return TRUE if expr.value is True else FALSE
    if isinstance(expr, (BinaryOp, UnaryOp)):
        # general field algebra: no device compilation (yet); scan
        # conservatively and verify candidates exactly on host
        p.force_verify = True
        return TRUE
    raise ParseError(f"cannot plan {expr!r}")


@dataclass
class PlannedQuery:
    tree: tuple | None  # trace-level tree (see ops.filter); None => match-all
    conds: tuple
    rows: list
    tables: dict[int, np.ndarray]
    prune: bool = False  # statically false for this block
    needs_verify: bool = False
    # extra engine columns the TREE (not the conds) requires -- e.g.
    # span.parent_idx for compiled ('struct', ...) nodes
    extra_cols: tuple = ()

    @property
    def has_struct(self) -> bool:
        return "span.parent_idx" in self.extra_cols


def _mixed_or(tree, conds) -> bool:
    """True when the engines' shallow trace-level lift (ops/filter
    normalize_tree) is INEXACT for this tree, so candidates need exact
    host re-verification. Two shapes qualify:

    - an OR mixing span- and trace-level children: the lift evaluates
      the span side per-trace, over-matching same-span semantics;
    - an AND with a MIXED child (e.g. nested `(traceDur > 1s && kind =
      client) && name != "x"`): the lift groups only DIRECT span
      siblings into one tracify, so span conds separated by the nesting
      land in different same-span groups and over-match -- found by the
      three-way equivalence fuzzer.

    Flat mixes (every and/or child pure span or pure trace) lift
    exactly and stay verification-free."""

    def purity(t):
        if t[0] in ("tracify", "true", "false"):
            return "trace"
        if t[0] == "struct":
            return "span"
        if t[0] == "cond":
            return "trace" if conds[t[1]].target == "trace" else "span"
        ks = {purity(ch) for ch in t[1:]}
        return ks.pop() if len(ks) == 1 else "mixed"

    def walk(t):
        if t[0] in ("cond", "tracify", "true", "false", "struct"):
            return False
        if t[0] == "or" and purity(t) == "mixed":
            return True
        if t[0] == "and" and any(purity(ch) == "mixed" for ch in t[1:]):
            return True
        return any(walk(ch) for ch in t[1:])

    return walk(tree)


def _has_struct_node(t) -> bool:
    if not isinstance(t, tuple) or t in (TRUE, FALSE) or t[0] == "cond":
        return False
    if t[0] == "struct":
        return True
    return any(_has_struct_node(ch) for ch in t[1:])


def _finish(p: Plan, children: list) -> PlannedQuery:
    tree = _fold("and", children)
    if tree == FALSE:
        return PlannedQuery(None, (), [], {}, prune=True)
    if tree == TRUE:
        tree = None
    nv = p.force_verify or any(c.needs_verify for c in p.conds)
    if tree is not None and _mixed_or(tree, tuple(p.conds)):
        nv = True
    extra = ("span.parent_idx",) if tree is not None and _has_struct_node(tree) else ()
    return PlannedQuery(tree, tuple(p.conds), p.rows, p.tables,
                        needs_verify=nv, extra_cols=extra)


def plan_query(q: SpansetFilter, d: Dictionary) -> PlannedQuery:
    """One TraceQL spanset filter: the whole expression must hold on a
    single span (modulo trace intrinsics), so it normalizes into one
    tracify group."""
    p = Plan()
    if q.expr is None:
        return PlannedQuery(None, (), [], {})
    return _finish(p, [_plan_expr(p, d, q.expr)])


def plan_metrics_filter(q: MetricsQuery, d: Dictionary) -> PlannedQuery:
    """Span-LEVEL plan for a metrics query's spanset filter: unlike the
    search planner, the tree is NOT lifted to trace level (no tracify) --
    the timeseries kernels consume per-span masks directly, with
    trace-target conds gathered to spans through span.trace_sid.

    Only a single-spanset filter compiles; pipelines with intermediate
    stages and combinator/structural spansets force the exact engine
    (force-verify plan), mirroring the conservative-filter/exact-verify
    split of the search path."""
    p = Plan()
    filt = q.filter
    force = bool(q.stages)
    if isinstance(filt, Pipeline):
        force = True
        filt = filt.filter
    if isinstance(filt, SpansetOp):
        # conservative SPAN-level prefilter: the OR of every leaf
        # spanset's tree over-matches any combinator/structural result
        # (candidate traces = traces holding any leaf span); the exact
        # engine settles the relation over materialized traces
        def leaves(e):
            if isinstance(e, SpansetOp):
                return leaves(e.lhs) + leaves(e.rhs)
            if isinstance(e, Pipeline):
                return leaves(e.filter)
            return [e]

        trees = [TRUE if lf.expr is None else _plan_expr(p, d, lf.expr)
                 for lf in leaves(filt)]
        tree = _fold("or", trees)
        force = True
    elif filt.expr is None:
        tree = TRUE
    else:
        tree = _plan_expr(p, d, filt.expr)
    if tree == FALSE:
        return PlannedQuery(None, (), [], {}, prune=True)
    if tree == TRUE:
        tree = None
    nv = force or p.force_verify or any(c.needs_verify for c in p.conds)
    return PlannedQuery(tree, tuple(p.conds), p.rows, p.tables, needs_verify=nv)


def plan_search_request(
    d: Dictionary,
    tags: dict[str, str],
    query: str = "",
    min_duration_ms: int = 0,
    max_duration_ms: int = 0,
    start_rel_ms: tuple[int, int] | None = None,
    allow_struct: bool = True,
) -> PlannedQuery:
    """Tag-search / TraceQL request -> trace-level plan.

    Tag semantics follow the reference's search (each tag matches
    anywhere in the trace: per-tag tracify groups ANDed at trace level),
    while a TraceQL `query` keeps single-span semantics."""
    from .parser import parse

    p = Plan()
    children: list = []
    force_verify = False
    if query:
        q = parse(query)
        if isinstance(q, MetricsQuery):
            # metrics pipelines only make sense on the metrics endpoints
            # (/api/metrics/query_range -> db/metrics_exec); a search
            # request carrying one is a caller error, not a plan
            raise ParseError(
                "metrics queries (rate(), *_over_time()) are only valid "
                "on /api/metrics/query_range")
        if isinstance(q, Pipeline):
            # pipeline: the device filter prunes by the spanset; the
            # aggregate stages (count/avg/min/max/sum scalar filters)
            # evaluate EXACTLY on host over surviving candidates
            # (hosteval._eval_pipeline), so verification is mandatory
            force_verify = True
            q = q.filter
        if isinstance(q, SpansetOp):
            # structural/combinator spansets: > >> ~ over pure span
            # sides compile to exact struct nodes (no verification);
            # everything else prunes to traces whose spanset LEAVES are
            # all (or, for ||, any) present and re-checks on host
            tree, sv = _plan_spanset_expr(p, d, q, allow_struct)
            force_verify = force_verify or sv
            children.append(tree)
        elif q.expr is not None:
            children.append(_plan_expr(p, d, q.expr))
    for key, value in tags.items():
        lit = Static("str", value)
        if key == "name":
            f = Field(Scope.INTRINSIC, "name")
        else:
            f = Field(Scope.EITHER, key)
        t = _plan_comparison(p, d, Comparison(f, "=", lit))
        # bare-value convenience: numeric/bool tag values also match typed attrs
        if key != "name":
            extra = []
            try:
                iv = int(value)
                extra.append(_plan_comparison(p, d, Comparison(f, "=", Static("int", iv))))
            except ValueError:
                pass
            if value in ("true", "false"):
                extra.append(
                    _plan_comparison(p, d, Comparison(f, "=", Static("bool", value == "true")))
                )
            if extra:
                t = _fold("or", [t] + extra)
        if t == FALSE:
            return PlannedQuery(None, (), [], {}, prune=True)
        if t != TRUE:
            children.append(("tracify", t))
    # duration bounds compare EXACTLY via the (us, ns%1000) column pair,
    # so they don't force verification (which tag searches never run --
    # the old conservative +-1us range silently over-matched there, and
    # needlessly host-verified every TraceQL duration query)
    if min_duration_ms:
        children.append(_dur_pair_tree(
            p, "trace", "trace.dur_us", "trace.dur_lo", ">=",
            min_duration_ms * 1_000_000))
    if max_duration_ms:
        children.append(_dur_pair_tree(
            p, "trace", "trace.dur_us", "trace.dur_lo", "<=",
            max_duration_ms * 1_000_000))
    if start_rel_ms is not None:
        lo, hi = start_rel_ms
        children.append(
            p.cond(Cond(target="trace", col="trace.start_ms", op="range", needs_verify=True), v0=lo, v1=hi)
        )
    planned = _finish(p, children)
    if force_verify and not planned.prune:
        planned = replace(planned, needs_verify=True)
    return planned
