"""TraceQL AST (typed), matching the language surface of the reference
snapshot (pkg/traceql/ast.go + enum_*.go): spanset filters over span /
resource attributes and the intrinsics name, duration, status, kind,
with &&/||, comparison and regex operators, duration/status/kind
literals. The snapshot's engine executes single-spanset filters
(SURVEY.md 2.6); ours executes the same class, on device.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Union


class ParseError(ValueError):
    pass


class Scope(enum.Enum):
    SPAN = "span"
    RESOURCE = "resource"
    EITHER = "either"  # bare `.attr`
    INTRINSIC = "intrinsic"


INTRINSICS = ("name", "duration", "status", "kind", "childCount", "parent",
              "rootName", "rootServiceName", "traceDuration")

STATUS_NAMES = {"unset": 0, "ok": 1, "error": 2}
KIND_NAMES = {
    "unspecified": 0,
    "internal": 1,
    "server": 2,
    "client": 3,
    "producer": 4,
    "consumer": 5,
}


@dataclass(frozen=True)
class Field:
    scope: Scope
    name: str
    # parent-scoped attribute lookup: `parent.x`, `parent.span.x`,
    # `parent.resource.x`, `parent.duration` read the value off the
    # span's PARENT (expr.y:256-261 NewScopedAttribute parent flag)
    parent: bool = False


@dataclass(frozen=True)
class Static:
    """A literal: str, int, float, bool, duration-nanos, status, kind,
    or nil (expr.y statics incl. NIL)."""

    kind: str  # 'str','int','float','bool','duration','status','kind','nil'
    value: object


@dataclass(frozen=True)
class Comparison:
    field: Field
    op: str  # '=', '!=', '<', '<=', '>', '>=', '=~', '!~'
    value: Static


@dataclass(frozen=True)
class BinaryOp:
    """General field-expression algebra (expr.y fieldExpression:
    arithmetic + - * / % ^, comparisons between arbitrary expressions,
    regex between expressions). The parser emits Comparison for the
    planner-friendly `field op literal` shape and BinaryOp otherwise."""

    op: str  # '+','-','*','/','%','^','=','!=','<','<=','>','>=','=~','!~'
    lhs: "Expr"
    rhs: "Expr"


@dataclass(frozen=True)
class UnaryOp:
    """`-expr` (numeric negate) / `!expr` (boolean not)."""

    op: str  # '-' or '!'
    operand: "Expr"


@dataclass(frozen=True)
class LogicalExpr:
    op: str  # '&&' or '||'
    lhs: "Expr"
    rhs: "Expr"


Expr = Union[Comparison, LogicalExpr, BinaryOp, UnaryOp, Field, Static]


@dataclass(frozen=True)
class SpansetFilter:
    expr: Expr | None  # None = `{}` (match all spans)


AGGREGATE_FNS = ("count", "avg", "min", "max", "sum")

SPANSET_OPS = (">", ">>", "~", "&&", "||")


@dataclass(frozen=True)
class SpansetOp:
    """Two spansets combined at trace level (expr.y spansetExpression):
    `>` direct parent/child, `>>` ancestor/descendant, `~` siblings,
    `&&` both present, `||` either present. Left-associative chains
    nest on the lhs."""

    op: str  # one of SPANSET_OPS
    lhs: "SpansetExpr"
    rhs: "SpansetExpr"


@dataclass(frozen=True)
class Aggregate:
    """A scalar aggregate EXPRESSION: `count()`, `avg(fieldExpr)`, ...
    (expr.y aggregate). Appears inside ScalarFilter operands: the
    `| fn(field) op literal` stage is ScalarFilter(op,
    Aggregate(fn, expr), Static)."""

    fn: str  # one of AGGREGATE_FNS
    field: "Expr | None"  # fieldExpression argument (None for count)


@dataclass(frozen=True)
class ScalarOp:
    """Arithmetic between scalar expressions (expr.y scalarExpression:
    + - * / % ^ over aggregates and statics)."""

    op: str
    lhs: "Scalar"
    rhs: "Scalar"


@dataclass(frozen=True)
class ScalarFilter:
    """`scalar op scalar` -- a pipeline stage keeping spansets whose
    folded scalars satisfy the comparison (expr.y scalarFilter)."""

    op: str  # '=', '!=', '<', '<=', '>', '>='
    lhs: "Scalar"
    rhs: "Scalar"


@dataclass(frozen=True)
class GroupBy:
    """`by(fieldExpr)`: split each spanset into groups keyed by the
    expression's per-span value (expr.y groupOperation)."""

    expr: "Expr"


@dataclass(frozen=True)
class Coalesce:
    """`coalesce()`: merge grouped spansets back into one."""


@dataclass(frozen=True)
class ScalarPipeline:
    """`({ ... } | scalarExpr)` -- a wrapped pipeline whose value is a
    scalar (expr.y scalarPipeline); operand of pipeline-expression
    arithmetic like `({a}|count()) + ({b}|count()) = 1`."""

    filter: "PipelineExpr"
    scalar: "Scalar"


# metrics pipeline stages (the reference's TraceQL-metrics surface,
# traceql/ast.go metricsAggregate): terminal stages turning a spanset
# pipeline into step-aligned time series
METRICS_FNS = ("rate", "count_over_time", "min_over_time", "max_over_time",
               "avg_over_time", "sum_over_time")
# which metrics fns take a fieldExpression argument
METRICS_FIELD_FNS = ("min_over_time", "max_over_time", "avg_over_time",
                     "sum_over_time")


@dataclass(frozen=True)
class MetricsAggregate:
    """A terminal metrics stage: `rate()`, `count_over_time()`,
    `min/max/avg/sum_over_time(fieldExpr)`, each with an optional
    `by(fieldExpr, ...)` grouping clause."""

    fn: str  # one of METRICS_FNS
    field: "Expr | None"  # argument (None for rate/count_over_time)
    by: tuple = ()  # grouping field expressions


@dataclass(frozen=True)
class MetricsQuery:
    """`{ ... } | ... | rate() by(...)`: a spanset pipeline terminated
    by a metrics aggregate. Only valid on the metrics endpoints
    (/api/metrics/query_range); the search planner rejects it."""

    filter: "PipelineExpr"  # the spanset pipeline ahead of the stage
    stages: tuple  # intermediate pipeline stages (usually empty)
    agg: MetricsAggregate


Scalar = Union[Aggregate, Static, ScalarOp, ScalarPipeline]


@dataclass(frozen=True)
class Pipeline:
    """`{ ... } | stage | ...`: a spanset expression piped through
    filter / scalar-filter / by / coalesce stages; a trace matches when
    some spanset (group) survives every stage."""

    filter: "PipelineExpr"
    stages: tuple


SpansetExpr = Union[SpansetFilter, SpansetOp]
PipelineExpr = Union[SpansetFilter, SpansetOp, Pipeline]
Query = Union[SpansetFilter, SpansetOp, Pipeline]
