"""TraceQL AST (typed), matching the language surface of the reference
snapshot (pkg/traceql/ast.go + enum_*.go): spanset filters over span /
resource attributes and the intrinsics name, duration, status, kind,
with &&/||, comparison and regex operators, duration/status/kind
literals. The snapshot's engine executes single-spanset filters
(SURVEY.md 2.6); ours executes the same class, on device.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Union


class ParseError(ValueError):
    pass


class Scope(enum.Enum):
    SPAN = "span"
    RESOURCE = "resource"
    EITHER = "either"  # bare `.attr`
    INTRINSIC = "intrinsic"


INTRINSICS = ("name", "duration", "status", "kind", "rootName", "rootServiceName", "traceDuration")

STATUS_NAMES = {"unset": 0, "ok": 1, "error": 2}
KIND_NAMES = {
    "unspecified": 0,
    "internal": 1,
    "server": 2,
    "client": 3,
    "producer": 4,
    "consumer": 5,
}


@dataclass(frozen=True)
class Field:
    scope: Scope
    name: str


@dataclass(frozen=True)
class Static:
    """A literal: str, int, float, bool, duration-nanos, status, kind."""

    kind: str  # 'str','int','float','bool','duration','status','kind'
    value: object


@dataclass(frozen=True)
class Comparison:
    field: Field
    op: str  # '=', '!=', '<', '<=', '>', '>=', '=~', '!~'
    value: Static


@dataclass(frozen=True)
class LogicalExpr:
    op: str  # '&&' or '||'
    lhs: "Expr"
    rhs: "Expr"


Expr = Union[Comparison, LogicalExpr]


@dataclass(frozen=True)
class SpansetFilter:
    expr: Expr | None  # None = `{}` (match all spans)


AGGREGATE_FNS = ("count", "avg", "min", "max", "sum")

SPANSET_OPS = (">", ">>", "~", "&&", "||")


@dataclass(frozen=True)
class SpansetOp:
    """Two spansets combined at trace level (expr.y spansetExpression):
    `>` direct parent/child, `>>` ancestor/descendant, `~` siblings,
    `&&` both present, `||` either present. Left-associative chains
    nest on the lhs."""

    op: str  # one of SPANSET_OPS
    lhs: "SpansetExpr"
    rhs: "SpansetExpr"


@dataclass(frozen=True)
class Aggregate:
    """One pipeline stage: `| fn(field?) op literal` -- a scalar filter
    over the spanset's matched spans (expr.y's scalarFilter over
    aggregate expressions). count() takes no field; the others fold a
    numeric field (duration or a numeric attribute) of matched spans."""

    fn: str  # one of AGGREGATE_FNS
    field: Field | None
    op: str  # '=', '!=', '<', '<=', '>', '>='
    value: Static


@dataclass(frozen=True)
class Pipeline:
    """`{ ... } | agg ...` -- a spanset expression piped through scalar
    aggregate filters; a trace matches when its matched spans pass
    every stage."""

    filter: "SpansetExpr"
    stages: tuple[Aggregate, ...]


SpansetExpr = Union[SpansetFilter, SpansetOp]
Query = Union[SpansetFilter, SpansetOp, Pipeline]
