"""TraceQL static type checking -- the analog of the reference AST's
validate() pass (pkg/traceql/ast_validate.go semantics, exercised by
test_examples.yaml's validate_fails section).

Types form a tiny lattice: statics carry their literal type, attribute
lookups are UNKNOWN (dynamically typed at execution), intrinsics are
fully typed. Rules:

* a spanset filter expression must be boolean-typed (UNKNOWN allowed);
* arithmetic needs numeric operands (int/float/duration mix freely,
  per the reference's "we just accept it all" note);
* ordering comparisons need numeric operands; = and != additionally
  accept equal types, nil, and `parent` vs nil;
* regex needs strings; && || need booleans; unary - numeric, ! boolean;
* aggregate arguments must be numeric AND reference span data;
* by() expressions must reference span data;
* scalar filter operand types must be comparable.
"""

from __future__ import annotations

from .ast import (
    Aggregate,
    BinaryOp,
    Coalesce,
    Comparison,
    Field,
    GroupBy,
    LogicalExpr,
    METRICS_FIELD_FNS,
    MetricsAggregate,
    MetricsQuery,
    ParseError,
    Pipeline,
    ScalarFilter,
    ScalarOp,
    ScalarPipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
    UnaryOp,
)


class ValidationError(ParseError):
    """Parsed fine, but the types don't line up (reference: the error
    .validate() returns)."""


# type tags
T_INT, T_FLOAT, T_DUR, T_BOOL, T_STR, T_STATUS, T_KIND, T_NIL, T_SPAN_PTR, T_UNK = (
    "int", "float", "duration", "bool", "str", "status", "kind", "nil",
    "spanptr", "unknown",
)

_NUMERIC = {T_INT, T_FLOAT, T_DUR, T_UNK}

_STATIC_T = {"int": T_INT, "float": T_FLOAT, "duration": T_DUR, "bool": T_BOOL,
             "str": T_STR, "status": T_STATUS, "kind": T_KIND, "nil": T_NIL}

_INTRINSIC_T = {
    "duration": T_DUR, "name": T_STR, "status": T_STATUS, "kind": T_KIND,
    "childCount": T_INT, "parent": T_SPAN_PTR,
    "rootName": T_STR, "rootServiceName": T_STR, "traceDuration": T_DUR,
}


def _field_type(f: Field) -> str:
    if f.scope == Scope.INTRINSIC:
        return _INTRINSIC_T.get(f.name, T_UNK)
    return T_UNK


def _expr_type(e) -> str:
    """Type of a field expression; raises ValidationError on mismatch."""
    if isinstance(e, Static):
        return _STATIC_T.get(e.kind, T_UNK)
    if isinstance(e, Field):
        return _field_type(e)
    if isinstance(e, Comparison):
        _check_cmp(e.op, _field_type(e.field), _expr_type(e.value))
        return T_BOOL
    if isinstance(e, LogicalExpr):
        for side in (e.lhs, e.rhs):
            t = _expr_type(side)
            if t not in (T_BOOL, T_UNK):
                raise ValidationError(f"{e.op} needs boolean operands, got {t}")
        return T_BOOL
    if isinstance(e, UnaryOp):
        t = _expr_type(e.operand)
        if e.op == "-":
            if t not in _NUMERIC:
                raise ValidationError(f"unary - needs a numeric operand, got {t}")
            return t
        if t not in (T_BOOL, T_UNK):
            raise ValidationError(f"! needs a boolean operand, got {t}")
        return T_BOOL
    if isinstance(e, BinaryOp):
        lt, rt = _expr_type(e.lhs), _expr_type(e.rhs)
        if e.op in ("+", "-", "*", "/", "%", "^"):
            for t in (lt, rt):
                if t not in _NUMERIC:
                    raise ValidationError(f"{e.op} needs numeric operands, got {t}")
            if T_UNK in (lt, rt):
                return T_UNK
            return T_FLOAT if T_FLOAT in (lt, rt) else (
                T_DUR if T_DUR in (lt, rt) else T_INT)
        if e.op in ("&&", "||"):
            for t in (lt, rt):
                if t not in (T_BOOL, T_UNK):
                    raise ValidationError(f"{e.op} needs boolean operands, got {t}")
            return T_BOOL
        _check_cmp(e.op, lt, rt)
        return T_BOOL
    raise ValidationError(f"cannot type {e!r}")


def _check_cmp(op: str, lt: str, rt: str) -> None:
    if op in ("=~", "!~"):
        for t in (lt, rt):
            if t not in (T_STR, T_UNK):
                raise ValidationError(f"{op} needs string operands, got {t}")
        return
    if T_UNK in (lt, rt):
        return
    if op in ("=", "!="):
        if lt == rt:
            if lt == T_SPAN_PTR:
                raise ValidationError("parent compares only against nil")
            return
        if T_NIL in (lt, rt):
            return  # x = nil / parent = nil / .foo != nil
        if lt in _NUMERIC and rt in _NUMERIC:
            return
        raise ValidationError(f"cannot {op}-compare {lt} with {rt}")
    # ordering
    if lt in _NUMERIC and rt in _NUMERIC:
        return
    raise ValidationError(f"{op} needs numeric operands, got {lt} and {rt}")


def _references_span(e) -> bool:
    """True when the expression reads per-span data (reference rule:
    aggregates and by() must 'reference the span')."""
    if isinstance(e, Field):
        return True
    if isinstance(e, Static):
        return False
    if isinstance(e, (BinaryOp, LogicalExpr)):
        return _references_span(e.lhs) or _references_span(e.rhs)
    if isinstance(e, UnaryOp):
        return _references_span(e.operand)
    if isinstance(e, Comparison):
        return True
    return False


def _validate_scalar(s, *, in_filter: bool) -> str:
    """Type of a scalar expression; enforces aggregate argument rules."""
    if isinstance(s, Static):
        return _STATIC_T.get(s.kind, T_UNK)
    if isinstance(s, Aggregate):
        if s.fn == "count":
            return T_INT
        t = _expr_type(s.field)
        if t not in _NUMERIC:
            raise ValidationError(f"{s.fn}() needs a numeric argument, got {t}")
        if not _references_span(s.field):
            raise ValidationError(f"{s.fn}() must reference span data")
        return t
    if isinstance(s, ScalarOp):
        lt = _validate_scalar(s.lhs, in_filter=in_filter)
        rt = _validate_scalar(s.rhs, in_filter=in_filter)
        for t in (lt, rt):
            if t not in _NUMERIC:
                raise ValidationError(f"{s.op} needs numeric scalars, got {t}")
        if T_UNK in (lt, rt):
            return T_UNK
        return T_FLOAT if T_FLOAT in (lt, rt) else (
            T_DUR if T_DUR in (lt, rt) else T_INT)
    if isinstance(s, ScalarPipeline):
        validate(s.filter)
        return _validate_scalar(s.scalar, in_filter=in_filter)
    raise ValidationError(f"cannot type scalar {s!r}")


def _contains_aggregate(s) -> bool:
    if isinstance(s, Aggregate):
        return True
    if isinstance(s, ScalarOp):
        return _contains_aggregate(s.lhs) or _contains_aggregate(s.rhs)
    if isinstance(s, ScalarPipeline):
        return True
    return False


def _validate_scalar_filter(sf: ScalarFilter) -> None:
    lt = _validate_scalar(sf.lhs, in_filter=True)
    rt = _validate_scalar(sf.rhs, in_filter=True)
    _check_cmp(sf.op, lt, rt)


def _validate_metrics(agg: MetricsAggregate) -> None:
    """Metrics-stage typing: *_over_time(field) arguments follow the
    scalar-aggregate rules (numeric, span-referencing); by() expressions
    must reference span data (same rule as pipeline by())."""
    if agg.field is not None:
        t = _expr_type(agg.field)
        if t not in _NUMERIC:
            raise ValidationError(f"{agg.fn}() needs a numeric argument, got {t}")
        if not _references_span(agg.field):
            raise ValidationError(f"{agg.fn}() must reference span data")
    elif agg.fn in METRICS_FIELD_FNS:
        raise ValidationError(f"{agg.fn}() needs a field expression argument")
    for e in agg.by:
        _expr_type(e)
        if not _references_span(e):
            raise ValidationError("by() must reference span data")


def validate(q) -> None:
    """Raises ValidationError when the parsed query is ill-typed."""
    if isinstance(q, MetricsQuery):
        validate(q.filter)
        for st in q.stages:
            if isinstance(st, (SpansetFilter, SpansetOp)):
                validate(st)
            elif isinstance(st, ScalarFilter):
                _validate_scalar_filter(st)
            elif isinstance(st, GroupBy):
                _expr_type(st.expr)
            elif not isinstance(st, Coalesce):
                raise ValidationError(f"unknown pipeline stage {st!r}")
        _validate_metrics(q.agg)
        return
    if isinstance(q, SpansetFilter):
        if q.expr is not None:
            t = _expr_type(q.expr)
            if t not in (T_BOOL, T_UNK):
                raise ValidationError(
                    f"spanset expression must be boolean, got {t}")
        return
    if isinstance(q, SpansetOp):
        validate(q.lhs)
        validate(q.rhs)
        return
    if isinstance(q, Pipeline):
        validate(q.filter)
        for st in q.stages:
            if isinstance(st, (SpansetFilter, SpansetOp)):
                validate(st)
            elif isinstance(st, ScalarFilter):
                _validate_scalar_filter(st)
            elif isinstance(st, GroupBy):
                _expr_type(st.expr)
                if not _references_span(st.expr):
                    raise ValidationError("by() must reference span data")
            elif isinstance(st, Coalesce):
                pass
            else:
                raise ValidationError(f"unknown pipeline stage {st!r}")
        return
    raise ValidationError(f"cannot validate {q!r}")
