"""TraceQL lexer + recursive-descent parser.

Grammar subset (the executable class of the reference snapshot, whose
goyacc grammar lives at pkg/traceql/expr.y; ours is hand-rolled, no
parser generator needed at this size):

    query      := '{' expr? '}'
    expr       := or_expr
    or_expr    := and_expr ( '||' and_expr )*
    and_expr   := unary ( '&&' unary )*
    unary      := '(' expr ')' | comparison
    comparison := field op literal | literal op field | field
    field      := 'span.' ident | 'resource.' ident | '.' ident
                | 'name' | 'duration' | 'status' | 'kind' | ...
    literal    := string | number | duration | bool | status | kind

A bare field is an existence test. Duration literals: 10ns 5us 3ms 2s
1m 1h (combinable like 1h30m).
"""

from __future__ import annotations

import re

_ESCAPE_CHARS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "b": "\b", "0": "\0",
                 "\\": "\\", '"': '"', "'": "'", "/": "/"}


def _unescape(s: str) -> str:
    """Go-style string escapes: \\n -> newline etc.; unknown escapes keep
    the escaped character."""
    return re.sub(r"\\(.)", lambda m: _ESCAPE_CHARS.get(m.group(1), m.group(1)), s)


from .ast import (
    AGGREGATE_FNS,
    INTRINSICS,
    KIND_NAMES,
    STATUS_NAMES,
    Aggregate,
    Comparison,
    Field,
    LogicalExpr,
    ParseError,
    Pipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
)

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>"(?:[^"\\]|\\.)*"|`[^`]*`)
  | (?P<duration>\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h)(?:\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h))*)
  | (?P<number>-?\d+(?:\.\d+)?)
  | (?P<op>=~|!~|!=|<=|>=|>>|&&|\|\||[{}()=<>.|~])
  | (?P<ident>[a-zA-Z_][a-zA-Z0-9_./-]*)
""",
    re.VERBOSE,
)

_DUR_UNIT_NS = {"ns": 1, "us": 10**3, "µs": 10**3, "ms": 10**6, "s": 10**9, "m": 60 * 10**9, "h": 3600 * 10**9}
_DUR_PART = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")


def _parse_duration_ns(text: str) -> int:
    total = 0.0
    for m in _DUR_PART.finditer(text):
        total += float(m.group(1)) * _DUR_UNIT_NS[m.group(2)]
    return int(total)


def tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise ParseError(f"unexpected character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str):
        kind, val = self.next()
        if val != text:
            raise ParseError(f"expected {text!r}, got {val!r}")

    # ---- grammar
    def parse_query(self):
        expr = self.parse_spanset_expr()
        stages = []
        while self.peek()[1] == "|":
            self.next()
            stages.append(self.parse_aggregate())
        self._expect_eof()
        return Pipeline(expr, tuple(stages)) if stages else expr

    def parse_spanset_expr(self):
        # expr.y precedence: structural (> >> ~) binds tighter than the
        # spanset combinators (&& ||); both left-associative
        expr = self.parse_structural()
        while self.peek()[1] in ("&&", "||"):
            _, op = self.next()
            expr = SpansetOp(op, expr, self.parse_structural())
        return expr

    def parse_structural(self):
        expr = self.parse_spanset_primary()
        while self.peek()[1] in (">", ">>", "~"):
            _, op = self.next()
            expr = SpansetOp(op, expr, self.parse_spanset_primary())
        return expr

    def parse_spanset_primary(self):
        if self.peek()[1] == "(":  # ( spansetExpression ) per expr.y
            self.next()
            e = self.parse_spanset_expr()
            self.expect(")")
            return e
        return self.parse_spanset()

    def parse_spanset(self) -> SpansetFilter:
        self.expect("{")
        if self.peek()[1] == "}":
            self.next()
            return SpansetFilter(expr=None)
        expr = self.parse_or()
        self.expect("}")
        return SpansetFilter(expr=expr)

    def parse_aggregate(self) -> Aggregate:
        kind, fn = self.next()
        if fn not in AGGREGATE_FNS:
            raise ParseError(
                f"unsupported pipeline stage {fn!r} (supported: {AGGREGATE_FNS})"
            )
        self.expect("(")
        field = None
        if self.peek()[1] != ")":
            if fn == "count":
                raise ParseError("count() takes no argument")
            field = self.try_field()
            if field is None:
                raise ParseError(f"{fn}() needs a field argument")
            if field.scope == Scope.INTRINSIC and field.name != "duration":
                # the other intrinsics are strings/enums: folding them
                # can never match, so fail at parse time
                raise ParseError(
                    f"{fn}() needs a numeric field; intrinsic {field.name!r} is not"
                )
        elif fn != "count":
            raise ParseError(f"{fn}() needs a field argument")
        self.expect(")")
        kind, op = self.next()
        if op not in ("=", "!=", "<", "<=", ">", ">="):
            raise ParseError(f"bad aggregate comparison operator {op!r}")
        value = self.parse_literal(field)
        allowed = ("int",) if fn == "count" else ("int", "float", "duration")
        if value.kind not in allowed:
            raise ParseError(
                f"{fn}() comparisons need a {' / '.join(allowed)} literal, got {value.kind}"
            )
        return Aggregate(fn=fn, field=field, op=op, value=value)

    def _expect_eof(self):
        kind, val = self.peek()
        if kind != "eof":
            raise ParseError(f"unsupported trailing content {val!r}")

    def parse_or(self):
        lhs = self.parse_and()
        while self.peek()[1] == "||":
            self.next()
            lhs = LogicalExpr("||", lhs, self.parse_and())
        return lhs

    def parse_and(self):
        lhs = self.parse_unary()
        while self.peek()[1] == "&&":
            self.next()
            lhs = LogicalExpr("&&", lhs, self.parse_unary())
        return lhs

    def parse_unary(self):
        if self.peek()[1] == "(":
            self.next()
            e = self.parse_or()
            self.expect(")")
            return e
        return self.parse_comparison()

    def parse_comparison(self) -> Comparison:
        field = self.try_field()
        if field is not None:
            kind, val = self.peek()
            if val in ("=", "!=", "<", "<=", ">", ">=", "=~", "!~"):
                self.next()
                lit = self.parse_literal(field)
                return Comparison(field, val, lit)
            return Comparison(field, "exists", Static("bool", True))
        # literal op field (reversed operands)
        lit = self.parse_literal(None)
        kind, val = self.next()
        if val not in ("=", "!=", "<", "<=", ">", ">=", "=~", "!~"):
            raise ParseError(f"expected comparison operator, got {val!r}")
        field = self.try_field()
        if field is None:
            raise ParseError("expected attribute field after literal comparison")
        flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
        return Comparison(field, flip.get(val, val), lit)

    def try_field(self) -> Field | None:
        """The lexer folds dots into idents, so `span.http.method` is one
        token; `.attr` is the '.' operator followed by an ident."""
        kind, val = self.peek()
        if val == ".":
            self.next()
            k2, v2 = self.next()
            if k2 != "ident":
                raise ParseError(f"expected attribute name after '.', got {v2!r}")
            return Field(Scope.EITHER, v2)
        if kind == "ident":
            if val.startswith("span.") and len(val) > 5:
                self.next()
                return Field(Scope.SPAN, val[5:])
            if val.startswith("resource.") and len(val) > 9:
                self.next()
                return Field(Scope.RESOURCE, val[9:])
            if val in INTRINSICS:
                self.next()
                return Field(Scope.INTRINSIC, val)
            return None
        return None

    def parse_literal(self, field: Field | None) -> Static:
        kind, val = self.next()
        if kind == "string":
            if val.startswith('"'):
                s = _unescape(val[1:-1])
            else:
                s = val[1:-1]
            return Static("str", s)
        if kind == "duration":
            return Static("duration", _parse_duration_ns(val))
        if kind == "number":
            if "." in val:
                return Static("float", float(val))
            return Static("int", int(val))
        if kind == "ident":
            if val in ("true", "false"):
                return Static("bool", val == "true")
            if val in STATUS_NAMES and (field is None or field.name == "status"):
                return Static("status", STATUS_NAMES[val])
            if val in KIND_NAMES and (field is None or field.name == "kind"):
                return Static("kind", KIND_NAMES[val])
            raise ParseError(f"unexpected literal {val!r}")
        raise ParseError(f"expected literal, got {val!r}")


def parse(src: str):
    """-> SpansetFilter, or Pipeline when `| agg() op N` stages follow."""
    return _Parser(tokenize(src)).parse_query()
