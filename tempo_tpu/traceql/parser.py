"""TraceQL lexer + recursive-descent parser.

Covers the full grammar of the reference snapshot (goyacc grammar at
pkg/traceql/expr.y; ours is hand-rolled with one-token lookahead plus
cheap backtracking at the two genuinely ambiguous '(' positions):

  root        := spansetPipeline | spansetPipelineExpression
               | scalarPipelineExpressionFilter
  pipeline    := stage ('|' stage)*          stage kinds per expr.y:
                 spansetExpression, scalarFilter, by(fieldExpr),
                 coalesce() (not first)
  spanset ops := && || > >> ~ over spansets and wrapped pipelines
  fieldExpr   := full algebra: + - * / % ^, comparisons (incl. regex),
                 && || ! unary -, parent-scoped attributes
                 (parent.x / parent.span.x / parent.resource.x),
                 intrinsics incl. childCount and parent, nil statics
  scalarExpr  := arithmetic over aggregates (count/avg/min/max/sum) and
                 statics; pipeline-expression scalars range only over
                 wrapped pipelines (expr.y scalarPipelineExpression),
                 with a bare static allowed as the comparison RHS

Type checking lives in validate.py (the analog of the reference AST's
validate()); parse() runs it so callers get reference behavior --
parse errors and validation errors both surface as ParseError
subclasses. Duration literals: 10ns 5us 3ms 2s 1m 1h, combinable
(1h30m).
"""

from __future__ import annotations

import re

_ESCAPE_CHARS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "b": "\b", "0": "\0",
                 "\\": "\\", '"': '"', "'": "'", "/": "/"}


def _unescape(s: str) -> str:
    """Go-style string escapes: \\n -> newline etc.; unknown escapes keep
    the escaped character."""
    return re.sub(r"\\(.)", lambda m: _ESCAPE_CHARS.get(m.group(1), m.group(1)), s)


from .ast import (
    AGGREGATE_FNS,
    Aggregate,
    BinaryOp,
    Coalesce,
    Comparison,
    Field,
    GroupBy,
    INTRINSICS,
    KIND_NAMES,
    LogicalExpr,
    METRICS_FIELD_FNS,
    METRICS_FNS,
    MetricsAggregate,
    MetricsQuery,
    ParseError,
    Pipeline,
    Scalar,
    ScalarFilter,
    ScalarOp,
    ScalarPipeline,
    Scope,
    SpansetFilter,
    SpansetOp,
    Static,
    UnaryOp,
)

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<string>"(?:[^"\\]|\\.)*"|`[^`]*`)
  | (?P<duration>\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h)(?:\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h))*)
  | (?P<number>\d+(?:\.\d+)?)
  | (?P<op>=~|!~|!=|<=|>=|>>|&&|\|\||[{}()=<>.|~+\-*/%^!,])
  | (?P<ident>[a-zA-Z_][a-zA-Z0-9_./-]*)
""",
    re.VERBOSE,
)

_DUR_NS = {"ns": 1, "us": 1_000, "µs": 1_000, "ms": 1_000_000,
           "s": 1_000_000_000, "m": 60_000_000_000, "h": 3_600_000_000_000}

_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=", "=~", "!~")
_SCALAR_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")
_ARITH_OPS = ("+", "-", "*", "/", "%", "^")
_COMBINATORS = ("&&", "||", ">", ">>", "~")

# internal match-all spanset (the node `{}` would have produced; the
# SYNTAX `{ }` is a parse error per the reference, but pipelines whose
# first stage is a scalar filter or by() still need an initial spanset)
MATCH_ALL = SpansetFilter(expr=None)


def _parse_duration_ns(text: str) -> int:
    total = 0.0
    for m in re.finditer(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)", text):
        total += float(m.group(1)) * _DUR_NS[m.group(2)]
    return int(total)


def tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if m is None:
            raise ParseError(f"bad character {src[pos]!r} at {pos}")
        pos = m.end()
        kind = m.lastgroup
        if kind != "ws":
            out.append((kind, m.group()))
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self, ahead: int = 0):
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, text: str):
        kind, val = self.next()
        if val != text:
            raise ParseError(f"expected {text!r}, got {val!r}")

    def _expect_eof(self):
        kind, val = self.peek()
        if kind != "eof":
            raise ParseError(f"unsupported trailing content {val!r}")

    # ------------------------------------------------------------ root
    def parse_query(self):
        kind, val = self.peek()
        if val == "(":
            # ambiguous: wrapped spanset pipeline vs scalar pipeline
            # expression filter (`({a}|count()) + ... = 1`)
            mark = self.i
            try:
                q = self.parse_scalar_pipeline_filter()
                self._expect_eof()
                return q
            except ParseError:
                self.i = mark
            q = self.parse_pipeline_chain()
        elif val == "{" or val == "by":
            q = self.parse_pipeline_chain()
        else:
            # scalar filter root: `3 = 2`, `avg(.f) > 1`,
            # `count() = 1 | { true }`
            q = self.parse_pipeline(first_scalar=True)
        self._expect_eof()
        return q

    # spansetPipelineExpression: combinators over pipelines / wrapped
    # pipeline expressions. Structural ops (> >> ~) bind tighter than
    # && / || at this level too (expr.y precedence, mirroring
    # parse_spanset_expr / parse_structural for plain spansets)
    def parse_pipeline_chain(self):
        lhs = self.parse_pipeline_structural()
        while self.peek()[1] in ("&&", "||"):
            if isinstance(lhs, MetricsQuery):
                raise ParseError("metrics pipelines cannot be combined")
            _, op = self.next()
            rhs = self.parse_pipeline_structural()
            if isinstance(rhs, MetricsQuery):
                raise ParseError("metrics pipelines cannot be combined")
            lhs = SpansetOp(op, lhs, rhs)
        return lhs

    def parse_pipeline_structural(self):
        lhs = self.parse_pipeline_term()
        while self.peek()[1] in (">", ">>", "~"):
            if isinstance(lhs, MetricsQuery):
                raise ParseError("metrics pipelines cannot be combined")
            _, op = self.next()
            rhs = self.parse_pipeline_term()
            if isinstance(rhs, MetricsQuery):
                raise ParseError("metrics pipelines cannot be combined")
            lhs = SpansetOp(op, lhs, rhs)
        return lhs

    def parse_pipeline_term(self):
        if self.peek()[1] == "(":
            self.next()
            inner = self.parse_pipeline_chain()
            self.expect(")")
            return inner
        return self.parse_pipeline()

    def parse_pipeline(self, first_scalar: bool = False, allow_scalar_tail: bool = False):
        """One spansetPipeline: stages joined by '|'. Returns the bare
        spanset expression when there is just one spanset stage, else a
        Pipeline. With allow_scalar_tail (wrapped scalar pipelines), a
        trailing naked scalar expression is legal and returned via
        ScalarPipeline."""
        stages: list = []
        first = self.parse_stage(first=True, scalar_ok=first_scalar,
                                 allow_scalar_tail=False)
        stages.append(first)
        scalar_tail: Scalar | None = None
        metrics_agg: MetricsAggregate | None = None
        while self.peek()[1] == "|":
            self.next()
            last_ok = allow_scalar_tail
            st = self.parse_stage(first=False, scalar_ok=True,
                                  allow_scalar_tail=last_ok)
            if isinstance(st, tuple) and st[0] == "scalar_tail":
                scalar_tail = st[1]
                break
            if isinstance(st, MetricsAggregate):
                # terminal by construction: nothing may follow the stage
                if self.peek()[1] == "|":
                    raise ParseError(
                        f"{st.fn}() must be the final pipeline stage")
                metrics_agg = st
                break
            stages.append(st)
        if metrics_agg is not None:
            q = self._stages_to_query(stages)
            if isinstance(q, Pipeline):
                return MetricsQuery(q.filter, q.stages, metrics_agg)
            return MetricsQuery(q, (), metrics_agg)
        if scalar_tail is not None:
            filt = self._stages_to_query(stages)
            return ScalarPipeline(filt, scalar_tail)
        return self._stages_to_query(stages)

    def _stages_to_query(self, stages: list):
        if len(stages) == 1 and isinstance(stages[0], (SpansetFilter, SpansetOp)):
            return stages[0]
        if isinstance(stages[0], (SpansetFilter, SpansetOp)):
            return Pipeline(stages[0], tuple(stages[1:]))
        return Pipeline(MATCH_ALL, tuple(stages))

    def parse_stage(self, first: bool, scalar_ok: bool, allow_scalar_tail: bool):
        kind, val = self.peek()
        if val == "{" or val == "(":
            return self.parse_spanset_expr()
        if kind == "ident" and val == "by" and self.peek(1)[1] == "(":
            self.next()
            self.expect("(")
            if self.peek()[1] == ")":
                raise ParseError("by() needs a field expression")
            e = self.parse_or()
            self.expect(")")
            return GroupBy(e)
        if kind == "ident" and val in METRICS_FNS and self.peek(1)[1] == "(":
            if first:
                raise ParseError(
                    f"{val}() needs a spanset pipeline ahead of it")
            return self.parse_metrics_stage(val)
        if kind == "ident" and val == "coalesce" and self.peek(1)[1] == "(":
            if first:
                raise ParseError("pipelines can't start with coalesce()")
            self.next()
            self.expect("(")
            self.expect(")")
            return Coalesce()
        if not scalar_ok and not first:
            raise ParseError(f"unexpected pipeline stage at {val!r}")
        # scalar filter (or a naked scalar tail inside wrapped pipelines)
        lhs = self.parse_scalar_expr()
        nkind, nval = self.peek()
        if nval in _SCALAR_CMP_OPS:
            self.next()
            rhs = self.parse_scalar_expr()
            return ScalarFilter(nval, lhs, rhs)
        if allow_scalar_tail and nval == ")":
            return ("scalar_tail", lhs)
        raise ParseError(
            "naked scalar pipelines not allowed (scalar stages must compare)"
        )

    def parse_metrics_stage(self, fn: str) -> MetricsAggregate:
        """`rate() | count_over_time() | <fn>_over_time(fieldExpr)`, each
        with an optional trailing `by(fieldExpr, ...)` clause."""
        self.next()  # fn ident
        self.expect("(")
        arg = None
        if self.peek()[1] != ")":
            if fn not in METRICS_FIELD_FNS:
                raise ParseError(f"{fn}() takes no argument")
            arg = self.parse_or()
        elif fn in METRICS_FIELD_FNS:
            raise ParseError(f"{fn}() needs a field expression argument")
        self.expect(")")
        by: list = []
        if self.peek()[1] == "by" and self.peek(1)[1] == "(":
            self.next()
            self.expect("(")
            if self.peek()[1] == ")":
                raise ParseError("by() needs at least one field expression")
            by.append(self.parse_or())
            while self.peek()[1] == ",":
                self.next()
                by.append(self.parse_or())
            self.expect(")")
        return MetricsAggregate(fn=fn, field=arg, by=tuple(by))

    # spansetExpression: combinators over braced spansets; parens here
    # wrap spanset expressions only (stage-level grammar)
    def parse_spanset_expr(self):
        expr = self.parse_structural()
        while self.peek()[1] in ("&&", "||"):
            _, op = self.next()
            expr = SpansetOp(op, expr, self.parse_structural())
        return expr

    def parse_structural(self):
        expr = self.parse_spanset_primary()
        while self.peek()[1] in (">", ">>", "~"):
            _, op = self.next()
            expr = SpansetOp(op, expr, self.parse_spanset_primary())
        return expr

    def parse_spanset_primary(self):
        if self.peek()[1] == "(":
            self.next()
            e = self.parse_spanset_expr()
            self.expect(")")
            return e
        return self.parse_spanset()

    def parse_spanset(self) -> SpansetFilter:
        self.expect("{")
        if self.peek()[1] == "}":
            # `{ }` is a parse error in the reference grammar
            # (test_examples.yaml parse_fails); use `{ true }`
            raise ParseError("empty spanset filter { } (use { true })")
        expr = self.parse_or()
        self.expect("}")
        return SpansetFilter(expr=expr)

    # -------------------------------------------------- field algebra
    # precedence (expr.y): || < && < comparisons < + - < unary ! - <
    # * / % < ^ (right-assoc) < primary
    def parse_or(self):
        lhs = self.parse_and()
        while self.peek()[1] == "||":
            self.next()
            lhs = LogicalExpr("||", lhs, self.parse_and())
        return lhs

    def parse_and(self):
        lhs = self.parse_cmp()
        while self.peek()[1] == "&&":
            self.next()
            lhs = LogicalExpr("&&", lhs, self.parse_cmp())
        return lhs

    def parse_cmp(self):
        lhs = self.parse_addsub()
        while self.peek()[1] in _CMP_OPS:
            _, op = self.next()
            rhs = self.parse_addsub()
            lhs = self._make_cmp(lhs, op, rhs)
        return lhs

    @staticmethod
    def _make_cmp(lhs, op: str, rhs):
        """Planner-friendly normalization: `field op literal` (either
        order) becomes the legacy Comparison node; everything else is a
        general BinaryOp. Regex literals compile here so a bad pattern
        is a parse-time error (400 at the API), not a per-block plan or
        mid-verification failure."""
        if op in ("=~", "!~"):
            for side in (lhs, rhs):
                if isinstance(side, Static) and side.kind == "str":
                    try:
                        re.compile(side.value)
                    except re.error as e:
                        raise ParseError(f"bad regex {side.value!r}: {e}") from None
        if isinstance(lhs, Field) and isinstance(rhs, Static) and not lhs.parent:
            return Comparison(lhs, op, rhs)
        if isinstance(lhs, Static) and isinstance(rhs, Field) and not rhs.parent:
            flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
            if op in flip or op in ("=", "!="):
                return Comparison(rhs, flip.get(op, op), lhs)
        return BinaryOp(op, lhs, rhs)

    def parse_addsub(self):
        lhs = self.parse_unary_level()
        while self.peek()[1] in ("+", "-"):
            _, op = self.next()
            lhs = BinaryOp(op, lhs, self.parse_unary_level())
        return lhs

    def parse_unary_level(self):
        kind, val = self.peek()
        if val in ("-", "!"):
            self.next()
            inner = self.parse_unary_level()
            if (val == "-" and isinstance(inner, Static)
                    and inner.kind in ("int", "float", "duration")):
                # fold negative literals so `{ .a = -3 }` stays the
                # planner-compilable Comparison shape
                return Static(inner.kind, -inner.value)
            return UnaryOp(val, inner)
        return self.parse_muldiv()

    def parse_muldiv(self):
        lhs = self.parse_pow()
        while self.peek()[1] in ("*", "/", "%"):
            _, op = self.next()
            lhs = BinaryOp(op, lhs, self.parse_pow())
        return lhs

    def parse_pow(self):
        lhs = self.parse_field_primary()
        if self.peek()[1] == "^":
            self.next()
            return BinaryOp("^", lhs, self.parse_pow())  # right-assoc
        return lhs

    def parse_field_primary(self):
        kind, val = self.peek()
        if val == "(":
            self.next()
            e = self.parse_or()
            self.expect(")")
            return e
        f = self.try_field()
        if f is not None:
            return f
        return self.parse_literal(None)

    def try_field(self) -> Field | None:
        """The lexer folds dots into idents, so `span.http.method` is one
        token; `.attr` is the '.' operator followed by an ident."""
        kind, val = self.peek()
        if val == ".":
            self.next()
            k2, v2 = self.next()
            if k2 != "ident":
                raise ParseError(f"expected attribute name after '.', got {v2!r}")
            return Field(Scope.EITHER, v2)
        if kind == "ident":
            if val.startswith("parent.") and len(val) > 7:
                self.next()
                rest = val[7:]
                if rest.startswith("span.") and len(rest) > 5:
                    return Field(Scope.SPAN, rest[5:], parent=True)
                if rest.startswith("resource.") and len(rest) > 9:
                    return Field(Scope.RESOURCE, rest[9:], parent=True)
                if rest in INTRINSICS:
                    return Field(Scope.INTRINSIC, rest, parent=True)
                return Field(Scope.EITHER, rest, parent=True)
            if val.startswith("span.") and len(val) > 5:
                self.next()
                return Field(Scope.SPAN, val[5:])
            if val.startswith("resource.") and len(val) > 9:
                self.next()
                return Field(Scope.RESOURCE, val[9:])
            if val in INTRINSICS:
                self.next()
                return Field(Scope.INTRINSIC, val)
            if val.endswith("."):
                # the lexer folds `span.` into one ident; a scope prefix
                # with no attribute after it is malformed
                raise ParseError(f"malformed scoped attribute {val!r}")
            return None
        return None

    def parse_literal(self, field: Field | None) -> Static:
        kind, val = self.next()
        if kind == "string":
            if val.startswith('"'):
                s = _unescape(val[1:-1])
            else:
                s = val[1:-1]
            return Static("str", s)
        if kind == "duration":
            return Static("duration", _parse_duration_ns(val))
        if kind == "number":
            if "." in val:
                return Static("float", float(val))
            return Static("int", int(val))
        if kind == "ident":
            if val in ("true", "false"):
                return Static("bool", val == "true")
            if val == "nil":
                return Static("nil", None)
            from .ast import STATUS_NAMES

            if val in STATUS_NAMES and (field is None or field.name == "status"):
                return Static("status", STATUS_NAMES[val])
            if val in KIND_NAMES and (field is None or field.name == "kind"):
                return Static("kind", KIND_NAMES[val])
            raise ParseError(f"unexpected literal {val!r}")
        raise ParseError(f"expected literal, got {val!r}")

    # ------------------------------------------------- scalar algebra
    def parse_scalar_expr(self) -> Scalar:
        lhs = self.parse_scalar_muldiv()
        while self.peek()[1] in ("+", "-"):
            _, op = self.next()
            lhs = ScalarOp(op, lhs, self.parse_scalar_muldiv())
        return lhs

    def parse_scalar_muldiv(self) -> Scalar:
        lhs = self.parse_scalar_pow()
        while self.peek()[1] in ("*", "/", "%"):
            _, op = self.next()
            lhs = ScalarOp(op, lhs, self.parse_scalar_pow())
        return lhs

    def parse_scalar_pow(self) -> Scalar:
        lhs = self.parse_scalar_primary()
        if self.peek()[1] == "^":
            self.next()
            return ScalarOp("^", lhs, self.parse_scalar_pow())
        return lhs

    def parse_scalar_primary(self) -> Scalar:
        kind, val = self.peek()
        if val == "-":
            self.next()
            inner = self.parse_scalar_primary()
            if isinstance(inner, Static) and inner.kind in ("int", "float", "duration"):
                return Static(inner.kind, -inner.value)
            return ScalarOp("-", Static("int", 0), inner)
        if val == "(":
            self.next()
            e = self.parse_scalar_expr()
            self.expect(")")
            return e
        if kind == "ident" and val in AGGREGATE_FNS and self.peek(1)[1] == "(":
            self.next()
            self.expect("(")
            arg = None
            if self.peek()[1] != ")":
                if val == "count":
                    raise ParseError("count() takes no argument")
                arg = self.parse_or()
            elif val != "count":
                raise ParseError(f"{val}() needs a field expression argument")
            self.expect(")")
            return Aggregate(fn=val, field=arg)
        if kind == "ident" and self.peek(1)[1] == "(" and val not in ("by", "coalesce"):
            raise ParseError(f"{val!r} is not an aggregate "
                             f"(supported: {AGGREGATE_FNS})")
        return self.parse_literal(None)

    # scalarPipelineExpression filter: arithmetic over WRAPPED pipelines
    # only; a bare static is allowed as the whole comparison RHS
    # (expr.y:160-186 -- statics are not scalarPipelineExpressions,
    # which is why `(p) * 2 > 2` and `2 < (p)` are parse errors there)
    def parse_scalar_pipeline_filter(self):
        lhs = self.parse_scalar_pipe_expr()
        nkind, nval = self.peek()
        if nval not in _SCALAR_CMP_OPS:
            raise ParseError(f"expected scalar comparison, got {nval!r}")
        self.next()
        mark = self.i
        try:
            rhs: Scalar = self.parse_scalar_pipe_expr()
        except ParseError:
            self.i = mark
            rhs = self.parse_literal(None)
        return Pipeline(MATCH_ALL, (ScalarFilter(nval, lhs, rhs),))

    def parse_scalar_pipe_expr(self) -> Scalar:
        lhs = self.parse_scalar_pipe_term()
        while self.peek()[1] in _ARITH_OPS:
            _, op = self.next()
            lhs = ScalarOp(op, lhs, self.parse_scalar_pipe_term())
        return lhs

    def parse_scalar_pipe_term(self) -> Scalar:
        if self.peek()[1] != "(":
            raise ParseError("pipeline-expression scalars must wrap pipelines")
        if self.peek(1)[1] == "(":
            self.next()
            e = self.parse_scalar_pipe_expr()
            self.expect(")")
            return e
        self.next()
        inner = self.parse_pipeline(first_scalar=False, allow_scalar_tail=True)
        self.expect(")")
        if not isinstance(inner, ScalarPipeline):
            raise ParseError("wrapped pipeline used as a scalar must end "
                             "in a scalar expression (e.g. `| count()`)")
        return inner


def parse(src: str):
    """-> SpansetFilter | SpansetOp | Pipeline | MetricsQuery. Parses
    the full expr.y surface plus the TraceQL-metrics stages (rate(),
    *_over_time() with by(...)) and runs the reference's validate()
    analog; both failure modes raise ParseError subclasses."""
    q = _Parser(tokenize(src)).parse_query()
    from .validate import validate

    validate(q)
    return q
