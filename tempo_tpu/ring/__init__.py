"""Consistent-hash ring + lifecycler: the L1 distribution substrate.

The reference rides grafana/dskit's gossip ring (SURVEY.md 2.9,
cmd/tempo/app/modules.go:288-316); here the same abstractions are
re-built around a pluggable KV store: an in-memory KV for the
single-binary / test topology (the reference's inmemory ring,
cmd/tempo/main.go:186-194) and a file-backed KV for multi-process
nodes sharing a host. Write sharding, shuffle sharding, and
job-ownership hashing all hang off ring tokens exactly as in the
reference (pkg/util/hash.go TokenFor, modules/compactor Owns).
"""

from .ring import InstanceState, InstanceDesc, Ring, InMemoryKV, Lifecycler, ReplicationSet

__all__ = [
    "InstanceState",
    "InstanceDesc",
    "Ring",
    "InMemoryKV",
    "Lifecycler",
    "ReplicationSet",
]
