"""Token ring, replication sets, shuffle sharding, lifecycler.

Reference anatomy (all via dskit in the reference):
- ring tokens: each instance owns N random uint32 tokens; a key routes
  to the first token clockwise and walks on for replicas
  (ring.DoBatch semantics, modules/distributor/distributor.go:373).
- shuffle sharding: per-tenant deterministic sub-ring
  (modules/distributor/distributor.go:414, pkg/scheduler/queue).
- lifecycler: instance join/heartbeat/leave; unhealthy instances are
  skipped and eventually forgotten (modules/generator/generator.go:25-27).
"""

from __future__ import annotations

import bisect
import random
import threading
import time
from dataclasses import dataclass, field
from enum import Enum

from ..util.hashing import fnv1a_32

NUM_TOKENS = 128
HEARTBEAT_TIMEOUT_S = 60.0


def deterministic_tokens(ring_key: str, instance_id: str,
                         num_tokens: int = NUM_TOKENS) -> list[int]:
    """The token set an instance owns in a ring, as a pure function of
    (ring_key, instance_id): lifecyclers and transient read-plane rings
    (the frontend's block->querier affinity ring) must agree on
    placement without any coordination beyond knowing the member's
    name, so tokens cannot depend on join order or wall time."""
    rng = random.Random(fnv1a_32(f"{ring_key}/{instance_id}".encode()))
    return sorted(rng.randrange(0, 2**32) for _ in range(num_tokens))


class InstanceState(str, Enum):
    JOINING = "JOINING"
    ACTIVE = "ACTIVE"
    LEAVING = "LEAVING"
    LEFT = "LEFT"


@dataclass
class InstanceDesc:
    instance_id: str
    addr: str = ""  # opaque transport address (in-process: registry key)
    state: InstanceState = InstanceState.JOINING
    tokens: list[int] = field(default_factory=list)
    heartbeat_ts: float = 0.0

    def healthy(self, now: float | None = None, timeout: float = HEARTBEAT_TIMEOUT_S) -> bool:
        now = now if now is not None else time.time()
        return self.state == InstanceState.ACTIVE and (now - self.heartbeat_ts) < timeout


class InMemoryKV:
    """The single-binary ring store (reference: dskit inmemory KV,
    cmd/tempo/main.go:186-194). Thread-safe; watchers are synchronous."""

    def __init__(self):
        self._lock = threading.RLock()
        self._data: dict[str, dict[str, InstanceDesc]] = {}

    def update(self, ring_key: str, desc: InstanceDesc) -> None:
        with self._lock:
            self._data.setdefault(ring_key, {})[desc.instance_id] = desc

    def remove(self, ring_key: str, instance_id: str) -> None:
        with self._lock:
            self._data.get(ring_key, {}).pop(instance_id, None)

    def get_all(self, ring_key: str) -> dict[str, InstanceDesc]:
        with self._lock:
            return dict(self._data.get(ring_key, {}))


@dataclass
class ReplicationSet:
    instances: list[InstanceDesc]
    max_errors: int  # quorum slack: majority ((len-1)//2), EXCEPT rf=2
    # where it's len-1 (eventually-consistent minSuccess=1, Ring.get)


class Ring:
    """Read-side view over one ring key of a KV."""

    def __init__(self, kv: InMemoryKV, ring_key: str, replication_factor: int = 1,
                 heartbeat_timeout: float = HEARTBEAT_TIMEOUT_S):
        self.kv = kv
        self.ring_key = ring_key
        self.rf = replication_factor
        self.heartbeat_timeout = heartbeat_timeout
        # token-map cache keyed on the instance id set (the hot ingest
        # path calls get() once per trace; frontend affinity claims call
        # it per tenant-shard subset, so one slot would thrash). A dict
        # with immutable values is safe under concurrent readers --
        # per-key get/set are atomic, never a torn key/map pair
        self._cache: dict[tuple, tuple[list[int], list[InstanceDesc]]] = {}

    # ------------------------------------------------------------ views
    def instances(self) -> list[InstanceDesc]:
        return sorted(self.kv.get_all(self.ring_key).values(), key=lambda d: d.instance_id)

    def healthy_instances(self, now: float | None = None) -> list[InstanceDesc]:
        return [d for d in self.instances() if d.healthy(now, self.heartbeat_timeout)]

    def _token_map(self, descs: list[InstanceDesc]) -> tuple[list[int], list[InstanceDesc]]:
        pairs: list[tuple[int, InstanceDesc]] = []
        for d in descs:
            for t in d.tokens:
                pairs.append((t, d))
        pairs.sort(key=lambda p: p[0])
        return [p[0] for p in pairs], [p[1] for p in pairs]

    # ------------------------------------------------------------ routing
    def get(self, token: int, now: float | None = None,
            instances: list[InstanceDesc] | None = None) -> ReplicationSet:
        """Replication set for a key token: walk clockwise collecting RF
        distinct healthy instances."""
        descs = instances if instances is not None else self.healthy_instances(now)
        if not descs:
            return ReplicationSet([], 0)
        key = tuple(d.instance_id for d in descs)
        hit = self._cache.get(key)
        if hit is None:
            hit = self._token_map(descs)
            if len(self._cache) >= 64:  # membership/shard churn bound
                self._cache.clear()
            self._cache[key] = hit
        tokens, owners = hit
        out: list[InstanceDesc] = []
        seen: set[str] = set()
        i = bisect.bisect_right(tokens, token) % len(tokens)
        for _ in range(len(tokens)):
            d = owners[i]
            if d.instance_id not in seen:
                out.append(d)
                seen.add(d.instance_id)
                if len(out) >= self.rf:
                    break
            i = (i + 1) % len(tokens)
        if self.rf == 2:
            # the reference's whole reason for wrapping dskit's ring: at
            # RF=2 a majority quorum is ALL replicas, so one dead
            # ingester would fail every write until the heartbeat
            # timeout marks it out. EventuallyConsistentStrategy
            # (pkg/ring/ring.go:52-86) instead needs minSuccess=1 on
            # read and write -- NOT strongly consistent, eventually so.
            # READ-SIDE STALENESS: a minSuccess=1 write may have landed
            # on only one replica; readers (querier.find_trace_by_id)
            # best-effort fan out to EVERY ingester and merge partials,
            # but if the one replica holding the write errors while the
            # one that missed it answers, the trace is transiently
            # not-found until the flush or the retry hits the holder --
            # the same window the reference's eventually-consistent
            # strategy accepts.
            return ReplicationSet(out, max_errors=max(0, len(out) - 1))
        return ReplicationSet(out, max_errors=max(0, (len(out) - 1) // 2))

    def shuffle_shard(self, tenant: str, size: int) -> list[InstanceDesc]:
        """Deterministic per-tenant sub-ring (reference: dskit shuffle
        sharding used for generators + queriers). size<=0 => all."""
        descs = self.healthy_instances()
        if size <= 0 or size >= len(descs):
            return descs
        rng = random.Random(fnv1a_32(tenant.encode()))
        return rng.sample(descs, size)

    def owner_of(self, job_hash: str,
                 instances: list[InstanceDesc] | None = None) -> str | None:
        """First owner clockwise of a key's token -- the consistent-hash
        placement question both job ownership (compactor) and read-plane
        affinity (which querier owns this block's staged cache) ask.
        `instances` overrides the healthy-instance view for callers that
        maintain their own membership (frontend worker registry)."""
        rs = self.get(fnv1a_32(job_hash.encode()), instances=instances)
        return rs.instances[0].instance_id if rs.instances else None

    def owns(self, instance_id: str, job_hash: str) -> bool:
        """Ring-sharded job ownership: the instance owning the token of
        fnv32(job_hash) owns the job (modules/compactor/compactor.go:187)."""
        return self.owner_of(job_hash) == instance_id


class Lifecycler:
    """Joins an instance into a ring and heartbeats it.

    With `prune_timeout` set, every heartbeat also evicts ring entries
    whose own heartbeat is older than the timeout. A SIGKILLed peer
    never writes a LEAVE, so without pruning it stays in the ring until
    every reader's heartbeat_timeout filter -- but FileKV/GossipKV
    readers outside this process (the distributor picking replicas)
    keep seeing it as a token owner and send it doomed replica writes.
    Pruning removes the entry from the shared KV itself, so the dead
    instance leaves the write ring within ~one heartbeat period of the
    timeout expiring. A pruned-but-alive peer (partition, GC pause)
    re-resurrects itself: its next heartbeat writes a newer entry, and
    GossipKV's newest-wins merge propagates it back everywhere.
    """

    def __init__(self, kv: InMemoryKV, ring_key: str, instance_id: str, addr: str = "",
                 num_tokens: int = NUM_TOKENS, heartbeat_period: float = 5.0,
                 prune_timeout: float | None = None):
        self.kv = kv
        self.ring_key = ring_key
        self.desc = InstanceDesc(
            instance_id=instance_id,
            addr=addr or instance_id,
            tokens=deterministic_tokens(ring_key, instance_id, num_tokens),
        )
        self.heartbeat_period = heartbeat_period
        self.prune_timeout = prune_timeout
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def join(self, state: InstanceState = InstanceState.ACTIVE) -> None:
        self.desc.state = state
        self.desc.heartbeat_ts = time.time()
        self.kv.update(self.ring_key, self.desc)

    def heartbeat(self) -> None:
        self.desc.heartbeat_ts = time.time()
        self.kv.update(self.ring_key, self.desc)

    def prune(self, now: float | None = None) -> list[str]:
        """Evict peers whose heartbeat exceeded prune_timeout; returns
        the pruned instance ids."""
        if self.prune_timeout is None:
            return []
        now = time.time() if now is None else now
        pruned = []
        for iid, desc in self.kv.get_all(self.ring_key).items():
            if iid == self.desc.instance_id:
                continue
            if now - desc.heartbeat_ts > self.prune_timeout:
                self.kv.remove(self.ring_key, iid)
                pruned.append(iid)
        return pruned

    def start(self) -> None:
        self.join()

        def loop():
            while not self._stop.wait(self.heartbeat_period):
                self.heartbeat()
                self.prune()

        self._thread = threading.Thread(target=loop, daemon=True, name=f"lifecycler-{self.ring_key}")
        self._thread.start()

    def leave(self) -> None:
        self._stop.set()
        self.desc.state = InstanceState.LEFT
        self.kv.remove(self.ring_key, self.desc.instance_id)
