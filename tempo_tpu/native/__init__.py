"""ctypes bindings for the native runtime layer (native/vtpu_native.cc).

Loads native/libvtpu_native.so (built by `make -C native`; auto-built
once if the toolchain is present), exposing batch hashing, bloom
insertion, WAL frame scanning and threaded zstd codecs. Every entry
point has a pure-Python fallback so the framework runs without the
shared library -- `available()` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_TRIED = False

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO = os.path.join(_NATIVE_DIR, "libvtpu_native.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.exists(_SO):
        try:  # one silent build attempt; fallbacks cover failure
            subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True, timeout=120)
        except Exception:
            pass
    for attempt in (0, 1):
        if not os.path.exists(_SO):
            break
        try:
            _LIB = _bind(ctypes.CDLL(_SO))
            break
        except (OSError, AttributeError):
            # AttributeError = a stale prebuilt .so missing a newer
            # symbol: rebuild ONCE, else run on the pure-Python fallbacks
            _LIB = None
            if attempt:
                break
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR, "-B"],
                               capture_output=True, timeout=120)
            except Exception:
                break
    return _LIB


def _bind(lib):
    lib.vtpu_ring_tokens.argtypes = [
        ctypes.c_char_p, ctypes.c_int,
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
    ]
    lib.vtpu_bloom_add_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
    ]
    lib.vtpu_varint_frames.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.vtpu_varint_frames.restype = ctypes.c_int
    lib.vtpu_zstd_bound.argtypes = [ctypes.c_int64]
    lib.vtpu_zstd_bound.restype = ctypes.c_int64
    lib.vtpu_zstd_compress_batch.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 2 + [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.vtpu_zstd_compress_batch.restype = ctypes.c_int
    lib.vtpu_zstd_decompress_batch.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 2 + [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.vtpu_zstd_decompress_batch.restype = ctypes.c_int
    # snappy/lz4 block codecs: batch signatures mirror the zstd ones
    # (minus the level param -- neither format has levels)
    lib.vtpu_snappy_bound.argtypes = [ctypes.c_int64]
    lib.vtpu_snappy_bound.restype = ctypes.c_int64
    lib.vtpu_lz4_bound.argtypes = [ctypes.c_int64]
    lib.vtpu_lz4_bound.restype = ctypes.c_int64
    batch_args = [ctypes.c_void_p] * 6 + [ctypes.c_int, ctypes.c_int]
    for fn in (lib.vtpu_snappy_compress_batch, lib.vtpu_snappy_decompress_batch,
               lib.vtpu_lz4_compress_batch, lib.vtpu_lz4_decompress_batch):
        fn.argtypes = batch_args
        fn.restype = ctypes.c_int
    lib.vtpu_dict_union.argtypes = [
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.vtpu_dict_union.restype = ctypes.c_int64
    lib.vtpu_gather_runs.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.vtpu_gather_runs_addr.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64,
    ]
    lib.vtpu_gather_runs_remap.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
    ]
    lib.vtpu_gather_runs_remap.restype = ctypes.c_int64
    lib.vtpu_mask_cmp_i32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.vtpu_mask_cmp_i64.argtypes = lib.vtpu_mask_cmp_i32.argtypes
    lib.vtpu_mask_lut_i32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.vtpu_seg_count_mask.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.vtpu_seg_weighted_count.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.vtpu_lex_bisect16.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.vtpu_otlp_scan.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p,
    ]
    lib.vtpu_otlp_scan.restype = ctypes.c_int
    lib.vtpu_otlp_splice.argtypes = [
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p,
    ]
    lib.vtpu_otlp_splice.restype = ctypes.c_int
    lib.vtpu_span_metrics.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ctypes.c_int, ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
    ]
    return lib


def available() -> bool:
    return _load() is not None


# -------------------------------------------------------------- ring tokens
def ring_tokens(tenant: str, trace_ids: list[bytes]) -> np.ndarray:
    """Batch TokenFor: (n,) uint32. Identical to util.hashing.ring_token
    per id; the native fast path requires uniform 16-byte ids (the wire
    canonical form) so both paths hash exactly the same bytes."""
    lib = _load()
    n = len(trace_ids)
    if lib is None or n == 0 or any(len(t) != 16 for t in trace_ids):
        from ..util.hashing import ring_token

        return np.asarray([ring_token(tenant, t) for t in trace_ids], dtype=np.uint32)
    ids = np.frombuffer(b"".join(trace_ids), dtype=np.uint8)
    out = np.zeros(n, dtype=np.uint32)
    tb = tenant.encode()
    lib.vtpu_ring_tokens(tb, len(tb), ids.ctypes.data, 16, n, out.ctypes.data)
    return out


# -------------------------------------------------------------------- bloom
def bloom_add_batch(bloom, trace_ids: list[bytes], k: int) -> bool:
    """Insert ids into a block.bloom.ShardedBloom natively (k = the
    bloom's hash count, passed by the caller so both sides stay in
    sync). Returns False if the caller must fall back to add_many."""
    lib = _load()
    if lib is None or not trace_ids:
        return False
    ids = np.frombuffer(b"".join(trace_ids), dtype=np.uint8)
    lib.vtpu_bloom_add_batch(
        bloom.words.ctypes.data, bloom.n_shards, bloom.words.shape[1],
        bloom.shard_bits, k, ids.ctypes.data, 16, len(trace_ids),
    )
    return True


def bloom_add_ids_array(bloom, ids: np.ndarray, k: int) -> bool:
    """Insert a C-contiguous (n, 16) uint8 id array directly."""
    lib = _load()
    if lib is None or ids.shape[1:] != (16,) or not ids.flags.c_contiguous:
        return False
    lib.vtpu_bloom_add_batch(
        bloom.words.ctypes.data, bloom.n_shards, bloom.words.shape[1],
        bloom.shard_bits, k, ids.ctypes.data, 16, ids.shape[0],
    )
    return True


# --------------------------------------------------------------- wal frames
def varint_frames(data: bytes) -> tuple[np.ndarray, np.ndarray, bool, int] | None:
    """Scan uvarint frames: (body_offsets, body_lengths, clean, torn_at)
    -- torn_at is the file offset of the torn frame's header when not
    clean (len(data) otherwise). None when the native path is missing."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    cap = max(16, len(data) // 2 + 1)
    offs = np.zeros(cap, dtype=np.int64)
    lens = np.zeros(cap, dtype=np.int64)
    r = lib.vtpu_varint_frames(buf.ctypes.data if len(buf) else None, len(data),
                               offs.ctypes.data, lens.ctypes.data, cap)
    clean = r >= 0
    count = r if clean else (-r - 1)
    torn_at = len(data) if clean else int(offs[count])
    return offs[:count], lens[:count], clean, torn_at


# --------------------------------------------------------------------- zstd
# worker 0 runs on the calling thread, so 1 here means "no threads
# spawned at all" -- right on 1-core hosts where extra decode threads
# only add spawn/join and scheduler churn; multi-core hosts keep at
# least 2 workers so batch codecs overlap
_CPUS = os.cpu_count() or 4
_N_THREADS = 1 if _CPUS <= 1 else max(2, _CPUS // 2)


# --------------------------------------------------- snappy / lz4 blocks
# the non-zstd half of the codec matrix: hand-rolled native block codecs
# with the same batch ABI as zstd. Per-codec (bound name, compress name,
# decompress name) -- the worst-case bound comes from the library itself
# so it can never drift from the compressor's actual emission; callers
# fall back to the pure-Python codecs in block/blockcodecs.py when the
# library is absent.
_BLOCK_CODECS = {
    "snappy": ("vtpu_snappy_bound",
               "vtpu_snappy_compress_batch", "vtpu_snappy_decompress_batch"),
    "lz4": ("vtpu_lz4_bound",
            "vtpu_lz4_compress_batch", "vtpu_lz4_decompress_batch"),
}
_DECOMPRESS_RANGES = {
    "zstd": "vtpu_zstd_decompress_batch",
    "snappy": "vtpu_snappy_decompress_batch",
    "lz4": "vtpu_lz4_decompress_batch",
}


def block_compress_chunks(codec: str, chunks: list[bytes]) -> list[bytes] | None:
    """Batch-compress chunks with a non-zstd block codec on native
    threads. None -> caller falls back to the pure-Python codec."""
    lib = _load()
    spec = _BLOCK_CODECS.get(codec)
    n = len(chunks)
    if lib is None or spec is None or n == 0:
        return None
    bound_name, comp_name, _ = spec
    comp = getattr(lib, comp_name, None)
    bound = getattr(lib, bound_name, None)
    if comp is None or bound is None:
        return None
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    bounds = np.asarray([bound(int(l)) for l in in_lens], dtype=np.int64)
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(bounds[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.empty(int(bounds.sum()), dtype=np.uint8)
    out_lens = np.zeros(n, dtype=np.int64)
    rc = comp(src.ctypes.data if len(src) else None,
              in_offs.ctypes.data, in_lens.ctypes.data,
              dst.ctypes.data, out_offs.ctypes.data, out_lens.ctypes.data,
              n, _N_THREADS)
    if rc != 0:
        return None
    return [dst[out_offs[i] : out_offs[i] + out_lens[i]].tobytes() for i in range(n)]


def block_decompress_ranges(codec: str, src: np.ndarray, in_offs: np.ndarray,
                            in_lens: np.ndarray, dst: np.ndarray,
                            out_offs: np.ndarray, out_lens: np.ndarray) -> bool:
    """Decompress frames of one contiguous source straight into dst
    positions -- the zstd_decompress_ranges shape generalized over the
    whole codec matrix (the cold pipeline's decode stage dispatches per
    chunk-codec group through this)."""
    lib = _load()
    name = _DECOMPRESS_RANGES.get(codec)
    n = len(in_offs)
    if (lib is None or name is None or n == 0 or src.dtype != np.uint8
            or not src.flags.c_contiguous):
        return False
    fn = getattr(lib, name, None)
    if fn is None:
        return False
    in_offs = np.ascontiguousarray(in_offs, dtype=np.int64)
    in_lens = np.ascontiguousarray(in_lens, dtype=np.int64)
    out_offs = np.ascontiguousarray(out_offs, dtype=np.int64)
    out_lens = np.ascontiguousarray(out_lens, dtype=np.int64)
    rc = fn(src.ctypes.data if len(src) else None,
            in_offs.ctypes.data, in_lens.ctypes.data,
            dst.ctypes.data, out_offs.ctypes.data, out_lens.ctypes.data,
            n, _N_THREADS)
    return rc == 0


def block_decompress_chunks(codec: str, chunks: list[bytes],
                            out_sizes: list[int]) -> list[bytes] | None:
    """Batch-decompress per-chunk bytes with any matrix codec. None ->
    caller falls back to the per-chunk Python decoder."""
    if not chunks:
        return None
    n = len(chunks)
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    out_lens = np.asarray(out_sizes, dtype=np.int64)
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(out_lens[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.empty(int(out_lens.sum()), dtype=np.uint8)
    if not block_decompress_ranges(codec, src, in_offs, in_lens, dst, out_offs, out_lens):
        return None
    return [dst[out_offs[i] : out_offs[i] + out_lens[i]].tobytes() for i in range(n)]


def zstd_compress_chunks(chunks: list[bytes], level: int = 3) -> list[bytes] | None:
    if not chunks:
        return None
    n = len(chunks)
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    return zstd_compress_from(src, in_offs, in_lens, level)


# --------------------------------------------------------- run gather
def gather_runs(src: np.ndarray, dst: np.ndarray, src_offs: np.ndarray,
                dst_offs: np.ndarray, lens: np.ndarray) -> bool:
    """Row-range copies src->dst (both C-contiguous, same dtype/row
    shape): run i moves lens[i] rows from src_offs[i] to dst_offs[i].
    Returns False if the caller must fall back to numpy indexing."""
    lib = _load()
    if lib is None:
        return False
    if not (src.flags.c_contiguous and dst.flags.c_contiguous):
        return False
    itemsize = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    src_offs = np.ascontiguousarray(src_offs, dtype=np.int64)
    dst_offs = np.ascontiguousarray(dst_offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    lib.vtpu_gather_runs(
        src.ctypes.data, dst.ctypes.data,
        src_offs.ctypes.data, dst_offs.ctypes.data, lens.ctypes.data,
        len(src_offs), itemsize,
    )
    return True


def gather_runs_addr(src_addrs: np.ndarray, dst: np.ndarray,
                     dst_offs: np.ndarray, lens: np.ndarray) -> bool:
    """Run copies with per-run absolute source addresses (int64), dst
    offsets/lens in rows: the dst-sequential multi-source merge copy.
    Sources MUST be C-contiguous arrays kept alive by the caller."""
    lib = _load()
    if lib is None or not dst.flags.c_contiguous:
        return False
    itemsize = dst.dtype.itemsize * int(np.prod(dst.shape[1:], dtype=np.int64))
    src_addrs = np.ascontiguousarray(src_addrs, dtype=np.int64)
    dst_offs = np.ascontiguousarray(dst_offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    lib.vtpu_gather_runs_addr(
        src_addrs.ctypes.data, dst.ctypes.data,
        dst_offs.ctypes.data, lens.ctypes.data,
        len(src_addrs), itemsize,
    )
    return True


def gather_runs_remap(src_addrs: np.ndarray, dst: np.ndarray,
                      dst_offs: np.ndarray, lens: np.ndarray,
                      remap_addrs: np.ndarray, remap_lens: np.ndarray) -> bool:
    """gather_runs_addr fused with an int32 code remap (per-run remap
    table address + length; negative codes pass through). Returns False
    when the caller must redo via its checked fallback -- including
    out-of-range codes (corrupt input), which the kernel refuses to
    read past."""
    lib = _load()
    if lib is None or dst.dtype != np.int32 or not dst.flags.c_contiguous:
        return False
    src_addrs = np.ascontiguousarray(src_addrs, dtype=np.int64)
    dst_offs = np.ascontiguousarray(dst_offs, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int64)
    remap_addrs = np.ascontiguousarray(remap_addrs, dtype=np.int64)
    remap_lens = np.ascontiguousarray(remap_lens, dtype=np.int64)
    oob = lib.vtpu_gather_runs_remap(
        src_addrs.ctypes.data, dst.ctypes.data,
        dst_offs.ctypes.data, lens.ctypes.data,
        remap_addrs.ctypes.data, remap_lens.ctypes.data,
        len(src_addrs),
    )
    return oob == 0


# --------------------------------------------------- zstd into-buffer
def zstd_decompress_into(chunks: list[bytes], dst: np.ndarray,
                         out_offs: np.ndarray, out_lens: np.ndarray) -> bool:
    """Batch-decompress chunks straight into caller-provided positions of
    one destination buffer (uint8) -- no per-chunk bytes objects, no
    joins. Returns False -> caller falls back."""
    n = len(chunks)
    if _load() is None or n == 0:
        return False
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    return zstd_decompress_ranges(src, in_offs, in_lens, dst, out_offs, out_lens)


def zstd_decompress_ranges(src: np.ndarray, in_offs: np.ndarray,
                           in_lens: np.ndarray, dst: np.ndarray,
                           out_offs: np.ndarray, out_lens: np.ndarray) -> bool:
    """Decompress frames at in_offs/in_lens of one contiguous source
    buffer into out_offs/out_lens of dst. The zero-copy shape: callers
    that fetch a column's adjacent chunks with ONE ranged read pass the
    buffer straight through (no per-chunk bytes, no join)."""
    lib = _load()
    n = len(in_offs)
    if lib is None or n == 0 or src.dtype != np.uint8 or not src.flags.c_contiguous:
        return False
    # bind conversions to locals: .ctypes.data of an expression temporary
    # can be freed before the foreign call runs (dangling pointer)
    in_offs = np.ascontiguousarray(in_offs, dtype=np.int64)
    in_lens = np.ascontiguousarray(in_lens, dtype=np.int64)
    out_offs = np.ascontiguousarray(out_offs, dtype=np.int64)
    out_lens = np.ascontiguousarray(out_lens, dtype=np.int64)
    rc = lib.vtpu_zstd_decompress_batch(
        src.ctypes.data if len(src) else None,
        in_offs.ctypes.data, in_lens.ctypes.data,
        dst.ctypes.data,
        out_offs.ctypes.data, out_lens.ctypes.data,
        n, _N_THREADS,
    )
    return rc == 0


def zstd_compress_from(buf: np.ndarray, in_offs: np.ndarray, in_lens: np.ndarray,
                       level: int = 3) -> list[bytes] | None:
    """Batch-compress ranges of an existing contiguous buffer (uint8
    view) without materializing per-chunk source bytes."""
    lib = _load()
    n = len(in_offs)
    if lib is None or n == 0:
        return None
    in_offs = np.ascontiguousarray(in_offs, dtype=np.int64)
    in_lens = np.ascontiguousarray(in_lens, dtype=np.int64)
    # ZSTD_compressBound(n) = n + n/256 + small margin; computing it
    # vectorized (with extra slack) avoids one ctypes call per chunk
    bounds = in_lens + (in_lens >> 8) + 1024
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(bounds[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.empty(int(bounds.sum()), dtype=np.uint8)
    out_lens = np.zeros(n, dtype=np.int64)
    rc = lib.vtpu_zstd_compress_batch(
        buf.ctypes.data, in_offs.ctypes.data, in_lens.ctypes.data,
        dst.ctypes.data, out_offs.ctypes.data, out_lens.ctypes.data,
        n, level, _N_THREADS,
    )
    if rc != 0:
        return None
    return [dst[out_offs[i] : out_offs[i] + out_lens[i]].tobytes() for i in range(n)]


# ---------------------------------------------------------- dict union
def dict_union(raws: list[tuple[bytes, np.ndarray]]):
    """K-way merge of K sorted dictionaries given as (blob, u32 offsets)
    pairs (block.dictionary.Dictionary.raw()). Returns (merged_blob,
    merged_offsets, [per-source int32 remap]). Pure-numpy fallback when
    the native library is absent."""
    n_src = len(raws)
    counts = np.asarray([len(offs) - 1 for _, offs in raws], dtype=np.int64)
    total = int(counts.sum())
    if total == 0:
        return b"", np.zeros(1, dtype=np.uint32), [np.zeros(0, np.int32) for _ in raws]
    lib = _load()
    if lib is None:
        return _dict_union_py(raws, counts)
    all_offsets = np.concatenate([
        np.ascontiguousarray(offs, dtype=np.uint32) for _, offs in raws
    ])
    off_starts = np.zeros(n_src, dtype=np.int64)
    np.cumsum(counts[:-1] + 1, out=off_starts[1:]) if n_src > 1 else None
    blobs = b"".join(b for b, _ in raws)
    blob_lens = np.asarray([len(b) for b, _ in raws], dtype=np.int64)
    blob_starts = np.zeros(n_src, dtype=np.int64)
    np.cumsum(blob_lens[:-1], out=blob_starts[1:]) if n_src > 1 else None
    all_blobs = np.frombuffer(blobs, dtype=np.uint8)
    out_offsets = np.zeros(total + 1, dtype=np.uint32)
    out_blob = np.zeros(max(1, len(blobs)), dtype=np.uint8)
    remap_flat = np.zeros(total, dtype=np.int32)
    remap_starts = np.zeros(n_src, dtype=np.int64)
    np.cumsum(counts[:-1], out=remap_starts[1:]) if n_src > 1 else None
    out_blob_len = np.zeros(1, dtype=np.int64)
    n_out = lib.vtpu_dict_union(
        n_src, counts.ctypes.data, all_offsets.ctypes.data, off_starts.ctypes.data,
        all_blobs.ctypes.data if len(all_blobs) else None, blob_starts.ctypes.data,
        out_offsets.ctypes.data, out_blob.ctypes.data,
        remap_flat.ctypes.data, remap_starts.ctypes.data, out_blob_len.ctypes.data,
    )
    if n_out < 0:
        return _dict_union_py(raws, counts)
    merged_blob = out_blob[: int(out_blob_len[0])].tobytes()
    merged_offsets = out_offsets[: n_out + 1].copy()
    remaps = [
        remap_flat[remap_starts[i] : remap_starts[i] + counts[i]].copy()
        for i in range(n_src)
    ]
    return merged_blob, merged_offsets, remaps


def _dict_union_py(raws, counts):
    """Fallback: bytes-level set union + searchsorted remap."""
    per_src: list[list[bytes]] = []
    for blob, offs in raws:
        o = offs.tolist()
        per_src.append([blob[o[i] : o[i + 1]] for i in range(len(o) - 1)])
    merged = sorted(set().union(*[set(s) for s in per_src])) if per_src else []
    code_of = {s: i for i, s in enumerate(merged)}
    remaps = [
        np.asarray([code_of[s] for s in src], dtype=np.int32) for src in per_src
    ]
    blob = b"".join(merged)
    offs = np.zeros(len(merged) + 1, dtype=np.uint32)
    if merged:
        np.cumsum([len(s) for s in merged], out=offs[1:])
    return blob, offs, remaps


# --------------------------------------------------------- search eval
# op codes mirror native's CMP_* enum; hostfilter maps its op strings here
CMP_CODES = {"eq": 0, "ne": 1, "lt": 2, "le": 3, "gt": 4, "ge": 5,
             "range": 6, "ne_present": 7}


def mask_cmp(x: np.ndarray, op: str, a: int, b: int = 0) -> np.ndarray | None:
    """Single-pass comparison mask (uint8 0/1) over an int32/int64
    column. None -> caller falls back to numpy."""
    lib = _load()
    code = CMP_CODES.get(op)
    if lib is None or code is None or not x.flags.c_contiguous or x.ndim != 1:
        return None
    out = np.empty(x.shape[0], dtype=np.uint8)
    if x.dtype == np.int32:
        lib.vtpu_mask_cmp_i32(x.ctypes.data, x.shape[0], code, int(a), int(b),
                              out.ctypes.data)
    elif x.dtype == np.int64:
        lib.vtpu_mask_cmp_i64(x.ctypes.data, x.shape[0], code, int(a), int(b),
                              out.ctypes.data)
    else:
        return None
    return out


def mask_lut(idx: np.ndarray, lut: np.ndarray) -> np.ndarray | None:
    """out[j] = lut[idx[j]] with negative/out-of-range idx -> 0: the
    res-table -> span mask gather in one pass."""
    lib = _load()
    if (lib is None or idx.dtype != np.int32 or not idx.flags.c_contiguous
            or lut.dtype != np.uint8 or not lut.flags.c_contiguous):
        return None
    out = np.empty(idx.shape[0], dtype=np.uint8)
    lib.vtpu_mask_lut_i32(idx.ctypes.data, idx.shape[0], lut.ctypes.data,
                          lut.shape[0], out.ctypes.data)
    return out


def seg_count_mask(mask: np.ndarray, span_off: np.ndarray,
                   n_spans: int) -> np.ndarray | None:
    """Per-trace count of set mask bytes: out[t] = sum(mask[off[t]:off[t+1]])
    with offsets clipped to n_spans. mask may be bool or uint8."""
    lib = _load()
    if lib is None or span_off.dtype != np.int32 or not span_off.flags.c_contiguous:
        return None
    if mask.dtype == np.bool_:
        mask = mask.view(np.uint8)
    if mask.dtype != np.uint8 or not mask.flags.c_contiguous:
        return None
    n_traces = span_off.shape[0] - 1
    out = np.empty(n_traces, dtype=np.int32)
    lib.vtpu_seg_count_mask(mask.ctypes.data, span_off.ctypes.data,
                            n_traces, n_spans, out.ctypes.data)
    return out


def seg_weighted_count(mask: np.ndarray, weights: np.ndarray,
                       span_off: np.ndarray, n_spans: int) -> np.ndarray | None:
    """Weighted per-segment fold: out[t] = sum(weights[j] for j in
    off[t]:off[t+1] where mask[j]), offsets clipped to n_spans. The tres
    membership axis' matched-span counter (weights = entry span counts);
    replaces the pad+reduceat numpy path at ~5x the speed."""
    lib = _load()
    if lib is None or getattr(lib, "vtpu_seg_weighted_count", None) is None:
        return None
    if span_off.dtype != np.int32 or not span_off.flags.c_contiguous:
        return None
    if mask.dtype == np.bool_:
        mask = mask.view(np.uint8)
    if (mask.dtype != np.uint8 or not mask.flags.c_contiguous
            or weights.dtype != np.int32 or not weights.flags.c_contiguous):
        return None
    n_traces = span_off.shape[0] - 1
    out = np.empty(n_traces, dtype=np.int64)
    lib.vtpu_seg_weighted_count(mask.ctypes.data, weights.ctypes.data,
                                span_off.ctypes.data, n_traces, n_spans,
                                out.ctypes.data)
    return out


def lex_bisect16(ids: np.ndarray, queries: np.ndarray) -> np.ndarray | None:
    """Exact-match rows of 16-byte queries in a sorted (n, 16) id
    table (-1 miss). ids/queries: uint8, C-contiguous."""
    lib = _load()
    if lib is None or getattr(lib, "vtpu_lex_bisect16", None) is None:
        return None
    if (ids.dtype != np.uint8 or queries.dtype != np.uint8
            or ids.ndim != 2 or ids.shape[1] != 16
            or queries.ndim != 2 or queries.shape[1] != 16
            or not ids.flags.c_contiguous or not queries.flags.c_contiguous):
        return None
    q = queries.shape[0]
    out = np.empty(q, dtype=np.int32)
    lib.vtpu_lex_bisect16(ids.ctypes.data, ids.shape[0],
                          queries.ctypes.data, q, out.ctypes.data)
    return out


def otlp_scan(payload: bytes):
    """Structural scan of OTLP trace bytes (vtpu_otlp_scan): returns
    (span_off, span_len, span_rs, span_ss, trace_ids (n,16) u8,
    start_ns, end_ns, env_buf bytes, senv_buf bytes, rs_env (off,len),
    ss_env (off,len,rs)) or None (native unavailable / malformed
    payload -- caller decodes via the Python model path)."""
    lib = _load()
    if lib is None or getattr(lib, "vtpu_otlp_scan", None) is None:
        return None
    n = len(payload)
    if n == 0:
        return None
    buf = np.frombuffer(payload, dtype=np.uint8)
    # a span submessage can't be smaller than ~20 bytes (16B trace id +
    # framing); start generous, regrow on rc=2
    cap_spans = max(16, n // 24 + 8)
    cap_rs = cap_ss = max(8, n // 64 + 8)
    for _ in range(4):
        span_off = np.empty(cap_spans, np.int64)
        span_len = np.empty(cap_spans, np.int64)
        span_rs = np.empty(cap_spans, np.int32)
        span_ss = np.empty(cap_spans, np.int32)
        tids = np.empty((cap_spans, 16), np.uint8)
        start_ns = np.empty(cap_spans, np.uint64)
        end_ns = np.empty(cap_spans, np.uint64)
        env = np.empty(n + 16, np.uint8)
        senv = np.empty(n + 16, np.uint8)
        rs_off = np.empty(cap_rs, np.int64)
        rs_len = np.empty(cap_rs, np.int64)
        ss_off = np.empty(cap_ss, np.int64)
        ss_len = np.empty(cap_ss, np.int64)
        ss_rs = np.empty(cap_ss, np.int32)
        counts = np.zeros(5, np.int64)
        rc = lib.vtpu_otlp_scan(
            buf.ctypes.data, n,
            span_off.ctypes.data, span_len.ctypes.data, span_rs.ctypes.data,
            span_ss.ctypes.data, tids.ctypes.data, start_ns.ctypes.data,
            end_ns.ctypes.data, cap_spans,
            env.ctypes.data, env.shape[0],
            senv.ctypes.data, senv.shape[0],
            rs_off.ctypes.data, rs_len.ctypes.data, cap_rs,
            ss_off.ctypes.data, ss_len.ctypes.data, ss_rs.ctypes.data, cap_ss,
            counts.ctypes.data,
        )
        if rc == 2:
            cap_spans *= 4
            cap_rs *= 4
            cap_ss *= 4
            continue
        if rc != 0:
            return None
        k, nrs, nss = int(counts[0]), int(counts[1]), int(counts[2])
        return (span_off[:k], span_len[:k], span_rs[:k], span_ss[:k],
                tids[:k], start_ns[:k], end_ns[:k],
                env[: int(counts[3])].tobytes(),
                senv[: int(counts[4])].tobytes(),
                rs_off[:nrs], rs_len[:nrs],
                ss_off[:nss], ss_len[:nss], ss_rs[:nss])
    return None


def otlp_splice(payload: bytes):
    """Scan + group-by-trace + emit finished wire segments, ONE native
    call (vtpu_otlp_splice): returns (tids (K,16) u8, seg_off (K,),
    seg_len (K,), start_s (K,), end_s (K,), out u8 buffer, n_spans) or
    None (native unavailable / malformed -- caller uses the Python
    path). Each out[seg_off[u] : seg_off[u]+seg_len[u]] is a complete
    segment (9B header + per-trace TracesData)."""
    lib = _load()
    if lib is None or getattr(lib, "vtpu_otlp_splice", None) is None:
        return None
    n = len(payload)
    if n == 0:
        return None
    buf = np.frombuffer(payload, dtype=np.uint8)
    # envelopes repeat per trace, so output can exceed the payload;
    # 2n + slack covers typical shapes, rc=2 reports the exact need
    cap_out = 2 * n + 4096
    cap_tr = max(16, n // 64 + 8)
    for _ in range(3):
        out = np.empty(cap_out, np.uint8)
        tids = np.empty((cap_tr, 16), np.uint8)
        seg_off = np.empty(cap_tr, np.int64)
        seg_len = np.empty(cap_tr, np.int64)
        st = np.empty(cap_tr, np.int64)
        en = np.empty(cap_tr, np.int64)
        counts = np.zeros(3, np.int64)
        rc = lib.vtpu_otlp_splice(
            buf.ctypes.data, n, out.ctypes.data, cap_out,
            tids.ctypes.data, cap_tr,
            seg_off.ctypes.data, seg_len.ctypes.data,
            st.ctypes.data, en.ctypes.data, counts.ctypes.data,
        )
        if rc == 2:
            cap_tr = max(cap_tr * 2, int(counts[0]))
            cap_out = max(cap_out * 2, int(counts[1]))
            continue
        if rc != 0:
            return None
        K = int(counts[0])
        return (tids[:K], seg_off[:K], seg_len[:K], st[:K], en[:K], out,
                int(counts[2]))
    return None


def span_metrics_fold(sid: np.ndarray, dur: np.ndarray, edges: np.ndarray,
                      n_series: int):
    """Fused histogram + latency-sum fold: returns (hist (S, nb) i64,
    lat_sum (S,) f64) or None -> numpy fallback. Buckets match
    np.searchsorted(edges, dur) ('left')."""
    lib = _load()
    if (lib is None or sid.dtype != np.int32 or not sid.flags.c_contiguous
            or dur.dtype != np.float32 or not dur.flags.c_contiguous):
        return None
    edges = np.ascontiguousarray(edges, dtype=np.float32)
    nb = edges.shape[0] + 1
    hist = np.zeros((n_series, nb), dtype=np.int64)
    lat_sum = np.zeros(n_series, dtype=np.float64)
    lib.vtpu_span_metrics(
        sid.ctypes.data, dur.ctypes.data, sid.shape[0],
        edges.ctypes.data, edges.shape[0], n_series,
        hist.ctypes.data, lat_sum.ctypes.data,
    )
    return hist, lat_sum


def zstd_decompress_chunks(chunks: list[bytes], out_sizes: list[int]) -> list[bytes] | None:
    if not chunks:
        return None
    n = len(chunks)
    out_lens = np.asarray(out_sizes, dtype=np.int64)
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(out_lens[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.zeros(int(out_lens.sum()), dtype=np.uint8)
    if not zstd_decompress_into(chunks, dst, out_offs, out_lens):
        return None
    return [dst[out_offs[i]: out_offs[i] + out_lens[i]].tobytes() for i in range(n)]
