"""ctypes bindings for the native runtime layer (native/vtpu_native.cc).

Loads native/libvtpu_native.so (built by `make -C native`; auto-built
once if the toolchain is present), exposing batch hashing, bloom
insertion, WAL frame scanning and threaded zstd codecs. Every entry
point has a pure-Python fallback so the framework runs without the
shared library -- `available()` reports which path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_LIB = None
_TRIED = False

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_SO = os.path.join(_NATIVE_DIR, "libvtpu_native.so")


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.exists(_SO):
        try:  # one silent build attempt; fallbacks cover failure
            subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True, timeout=120)
        except Exception:
            pass
    if os.path.exists(_SO):
        try:
            lib = ctypes.CDLL(_SO)
            lib.vtpu_ring_tokens.argtypes = [
                ctypes.c_char_p, ctypes.c_int,
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ]
            lib.vtpu_bloom_add_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                ctypes.c_int, ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
            ]
            lib.vtpu_varint_frames.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int,
            ]
            lib.vtpu_varint_frames.restype = ctypes.c_int
            lib.vtpu_zstd_bound.argtypes = [ctypes.c_int64]
            lib.vtpu_zstd_bound.restype = ctypes.c_int64
            lib.vtpu_zstd_compress_batch.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 2 + [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ]
            lib.vtpu_zstd_compress_batch.restype = ctypes.c_int
            lib.vtpu_zstd_decompress_batch.argtypes = [ctypes.c_void_p] + [ctypes.c_void_p] * 2 + [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int, ctypes.c_int,
            ]
            lib.vtpu_zstd_decompress_batch.restype = ctypes.c_int
            _LIB = lib
        except OSError:
            _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


# -------------------------------------------------------------- ring tokens
def ring_tokens(tenant: str, trace_ids: list[bytes]) -> np.ndarray:
    """Batch TokenFor: (n,) uint32. Identical to util.hashing.ring_token
    per id; the native fast path requires uniform 16-byte ids (the wire
    canonical form) so both paths hash exactly the same bytes."""
    lib = _load()
    n = len(trace_ids)
    if lib is None or n == 0 or any(len(t) != 16 for t in trace_ids):
        from ..util.hashing import ring_token

        return np.asarray([ring_token(tenant, t) for t in trace_ids], dtype=np.uint32)
    ids = np.frombuffer(b"".join(trace_ids), dtype=np.uint8)
    out = np.zeros(n, dtype=np.uint32)
    tb = tenant.encode()
    lib.vtpu_ring_tokens(tb, len(tb), ids.ctypes.data, 16, n, out.ctypes.data)
    return out


# -------------------------------------------------------------------- bloom
def bloom_add_batch(bloom, trace_ids: list[bytes], k: int) -> bool:
    """Insert ids into a block.bloom.ShardedBloom natively (k = the
    bloom's hash count, passed by the caller so both sides stay in
    sync). Returns False if the caller must fall back to add_many."""
    lib = _load()
    if lib is None or not trace_ids:
        return False
    ids = np.frombuffer(b"".join(trace_ids), dtype=np.uint8)
    lib.vtpu_bloom_add_batch(
        bloom.words.ctypes.data, bloom.n_shards, bloom.words.shape[1],
        bloom.shard_bits, k, ids.ctypes.data, 16, len(trace_ids),
    )
    return True


# --------------------------------------------------------------- wal frames
def varint_frames(data: bytes) -> tuple[np.ndarray, np.ndarray, bool, int] | None:
    """Scan uvarint frames: (body_offsets, body_lengths, clean, torn_at)
    -- torn_at is the file offset of the torn frame's header when not
    clean (len(data) otherwise). None when the native path is missing."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    cap = max(16, len(data) // 2 + 1)
    offs = np.zeros(cap, dtype=np.int64)
    lens = np.zeros(cap, dtype=np.int64)
    r = lib.vtpu_varint_frames(buf.ctypes.data if len(buf) else None, len(data),
                               offs.ctypes.data, lens.ctypes.data, cap)
    clean = r >= 0
    count = r if clean else (-r - 1)
    torn_at = len(data) if clean else int(offs[count])
    return offs[:count], lens[:count], clean, torn_at


# --------------------------------------------------------------------- zstd
_N_THREADS = max(2, (os.cpu_count() or 4) // 2)


def zstd_compress_chunks(chunks: list[bytes], level: int = 3) -> list[bytes] | None:
    lib = _load()
    if lib is None or not chunks:
        return None
    n = len(chunks)
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    bounds = np.asarray([lib.vtpu_zstd_bound(int(l)) for l in in_lens], dtype=np.int64)
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(bounds[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.zeros(int(bounds.sum()), dtype=np.uint8)
    out_lens = np.zeros(n, dtype=np.int64)
    rc = lib.vtpu_zstd_compress_batch(
        src.ctypes.data if len(src) else None, in_offs.ctypes.data, in_lens.ctypes.data,
        dst.ctypes.data, out_offs.ctypes.data, out_lens.ctypes.data,
        n, level, _N_THREADS,
    )
    if rc != 0:
        return None
    return [dst[out_offs[i]: out_offs[i] + out_lens[i]].tobytes() for i in range(n)]


def zstd_decompress_chunks(chunks: list[bytes], out_sizes: list[int]) -> list[bytes] | None:
    lib = _load()
    if lib is None or not chunks:
        return None
    n = len(chunks)
    src = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    in_lens = np.asarray([len(c) for c in chunks], dtype=np.int64)
    in_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(in_lens[:-1], out=in_offs[1:]) if n > 1 else None
    out_lens = np.asarray(out_sizes, dtype=np.int64)
    out_offs = np.zeros(n, dtype=np.int64)
    np.cumsum(out_lens[:-1], out=out_offs[1:]) if n > 1 else None
    dst = np.zeros(int(out_lens.sum()), dtype=np.uint8)
    rc = lib.vtpu_zstd_decompress_batch(
        src.ctypes.data if len(src) else None, in_offs.ctypes.data, in_lens.ctypes.data,
        dst.ctypes.data, out_offs.ctypes.data, out_lens.ctypes.data,
        n, _N_THREADS,
    )
    if rc != 0:
        return None
    return [dst[out_offs[i]: out_offs[i] + out_lens[i]].tobytes() for i in range(n)]
