"""Quorum/merged reads: make one dead ingester invisible to readers.

With RF>=2 every trace's segments live on several replicas of the
owning ring token, and (because replicas diverge under failure: a
replica that missed a partial write holds a subset) the replicas are
near-duplicates of each other.  A naive fan-out-and-combine would pay
the duplicate decode cost RF times over; a naive first-answer-wins
read would silently drop the spans only a surviving replica holds.

The merge here does neither: each replica returns its raw segment
snapshot tagged with a content digest, the merge layer unions the
snapshots **by (trace id, segment digest)** so every distinct segment
is decoded exactly once, and the read succeeds as long as R replicas
of the owning token answered -- R from the same ReplicationSet rule
the write path uses (majority; RF=2's minSuccess=1), so the read
quorum always intersects the write quorum.
"""

from __future__ import annotations

import hashlib


class ReadQuorumError(OSError):
    """Too few replicas of the owning token answered a live read.

    Deliberately an OSError: the frontend's retry policy treats OSError
    as retryable, and a quorum miss (a restarting replica mid-deploy)
    is exactly the transient a requeued job survives.
    """


def segment_digest(seg: bytes) -> str:
    """Stable content digest for replica-side dedupe of one segment."""
    return hashlib.blake2b(seg, digest_size=8).hexdigest()


def merge_snapshots(snapshots: list[list[tuple[str, bytes]]]) -> list[bytes]:
    """Union replica snapshots of ONE trace, deduped by segment digest.

    Each snapshot is the [(digest, segment-bytes), ...] a replica holds
    for the trace; first sighting of a digest wins. Returns the unique
    segments in first-seen order (the combiner sorts spans anyway).
    """
    seen: set[str] = set()
    out: list[bytes] = []
    for snap in snapshots:
        for digest, seg in snap:
            if digest not in seen:
                seen.add(digest)
                out.append(seg)
    return out


def read_quorum_need(replica_count: int, max_errors: int) -> int:
    """R for a replication set: same arithmetic as the write quorum, so
    reads succeed exactly when they must intersect an acked write."""
    return max(1, replica_count - max_errors)


__all__ = ["ReadQuorumError", "segment_digest", "merge_snapshots",
           "read_quorum_need"]
