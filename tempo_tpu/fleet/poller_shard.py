"""Ring-sharded blocklist polling: each querier pays 1/M of the poll.

Unsharded, every querier lists every tenant's every block on every poll
cycle -- M queriers x N blocks of backend LIST traffic for one logical
blocklist.  Sharding reuses the compactor's owns-job pattern on the
querier ring: the querier owning the token of ``blocklist-poll/<tenant>``
is that tenant's poller; it lists the backend and publishes the result
as the per-tenant index (the same ``index.json.gz`` the Poller already
writes), and every non-owner serves its blocklist from the owner's
index instead of listing.  Ownership moves with ring membership, so a
dead querier's tenants are re-polled by the survivors within one
heartbeat-prune interval.
"""

from __future__ import annotations

from ..ring.ring import Ring


def shard_hash(tenant: str) -> str:
    return f"blocklist-poll/{tenant}"


class PollerShard:
    """Binds one querier's Poller to its slice of the tenant space."""

    def __init__(self, ring: Ring, instance_id: str):
        self.ring = ring
        self.instance_id = instance_id

    def owns(self, tenant: str) -> bool:
        """Solo fallback: an empty ring (shard plane not yet gossiped)
        must not stop a querier from polling -- own everything."""
        owner = self.ring.owner_of(shard_hash(tenant))
        return owner is None or owner == self.instance_id

    def shard_map(self, tenants: list[str]) -> dict[str, str]:
        """tenant -> owning querier instance id, for /status/fleet."""
        out = {}
        for t in tenants:
            owner = self.ring.owner_of(shard_hash(t))
            out[t] = owner if owner is not None else self.instance_id
        return out

    def install(self, db) -> None:
        """Wire this shard into a TempoDB's poller: owners build and
        write the tenant index, non-owners read the owner's index."""
        db.poller.owns_tenant = self.owns

    def status(self, tenants: list[str]) -> dict:
        members = [d.instance_id for d in self.ring.healthy_instances()]
        return {
            "instance_id": self.instance_id,
            "members": members,
            "owned": [t for t in tenants if self.owns(t)],
            "shard_map": self.shard_map(tenants),
        }


__all__ = ["PollerShard", "shard_hash"]
