"""RF>=2 replicated ingest: the distributor's quorum-write policy.

The distributor already walks the ring and fans each push window to the
`replication_factor` successive replicas of the owning token; what this
module adds is the cluster discipline around that fan-out:

- per-replica circuit breakers on the existing transport seam, so a
  flapping ingester sheds its own leg instead of stalling every push;
- the write-outcome classification the fleet alerts key on.  Per trace:

      quorum   ok_count >= desired replicas  (all RF copies landed)
      partial  quorum <= ok_count < desired  (acked, but under-replicated)
      failed   ok_count < quorum             (push rejected, 5xx to client)

  `desired` is the ring's replication factor, NOT the size of the
  replication set actually obtained -- a ring with fewer healthy
  instances than RF writes every trace as "partial", which is exactly
  the under-replication signal TempoReplicationPartialWrites fires on.

Quorum itself stays the ring's call (`ReplicationSet.max_errors`):
majority, except RF=2's eventually-consistent minSuccess=1 -- see the
design note in ring/ring.py.
"""

from __future__ import annotations

from ..util.breaker import CircuitBreaker, CircuitOpen, get_breaker
from ..util.metrics import Counter

REPLICATION_WRITES = Counter(
    "tempo_replication_writes_total",
    help="Replicated write outcomes per trace: quorum (all RF copies), "
    "partial (acked under quorum semantics but under-replicated), "
    "failed (below quorum, push rejected).")

# Breaker tuning for the replica-push leg: pushes are frequent and the
# quorum layer already tolerates one dead replica, so the breaker can
# trip fast and probe often.
_PUSH_BREAKER_PARAMS = dict(window_s=30.0, min_volume=5,
                            error_rate=0.5, open_s=5.0, probes=2)


def push_breaker(addr: str) -> CircuitBreaker:
    """The per-replica breaker guarding distributor -> ingester pushes."""
    return get_breaker(f"ingester-push:{addr}", **_PUSH_BREAKER_PARAMS)


def guarded_push(client, addr: str, tenant: str, batch) -> None:
    """Push one replica batch through its breaker.

    Raises CircuitOpen without touching the wire when the replica's
    breaker is open (the quorum layer counts that as a replica failure),
    and records success/failure so the breaker tracks replica health.
    """
    br = push_breaker(addr)
    if not br.allow():
        raise CircuitOpen(f"replica {addr} push breaker open")
    try:
        client.push_segments(tenant, batch)
    except Exception:
        br.record(False)
        raise
    br.record(True)


def record_write_outcomes(quorum_need: dict[bytes, int],
                          ok_count: dict[bytes, int],
                          desired: int) -> dict[str, int]:
    """Classify every trace of one push window and bump the counter.

    Returns the {outcome: n} tally (handy for tests and /status/fleet).
    """
    tally = {"quorum": 0, "partial": 0, "failed": 0}
    for tid, need in quorum_need.items():
        ok = ok_count.get(tid, 0)
        if ok < need:
            outcome = "failed"
        elif ok >= desired:
            outcome = "quorum"
        else:
            outcome = "partial"
        tally[outcome] += 1
    for outcome, n in tally.items():
        if n:
            REPLICATION_WRITES.inc(n, labels=f'outcome="{outcome}"')
    return tally


def replication_snapshot() -> dict[str, int]:
    """Current counter state keyed by outcome, for /status/fleet."""
    out = {"quorum": 0, "partial": 0, "failed": 0}
    for labels, v in REPLICATION_WRITES.snapshot().items():
        for outcome in out:
            if f'outcome="{outcome}"' in labels:
                out[outcome] += int(v)
    return out


def metrics_lines() -> list[str]:
    return REPLICATION_WRITES.text()


def help_entries() -> dict[str, tuple[str, str]]:
    return {"tempo_replication_writes_total":
            ("counter", REPLICATION_WRITES.help)}


__all__ = [
    "REPLICATION_WRITES", "push_breaker", "guarded_push",
    "record_write_outcomes", "replication_snapshot",
    "metrics_lines", "help_entries", "CircuitOpen",
]
