"""Fleet-scale serving: the multi-process cluster plane.

Turns the single-process topology into a certified N-frontend x
M-querier x K-ingester cluster (the reference's memberlist +
replicated-write story, SURVEY L1/L5/L6):

- replication.py  -- RF>=2 quorum writes on the distributor: each push
  window lands on `replication_factor` successive ring replicas behind
  per-replica circuit breakers, acked at quorum W (ring.ReplicationSet
  semantics: majority, except RF=2's eventually-consistent minSuccess=1),
  with every trace's outcome counted as quorum/partial/failed.
- quorum.py       -- quorum/merged reads on the querier: live-read legs
  fan to every replica of the owning token, partial snapshots dedupe by
  (trace id, segment digest) before combining, and the read succeeds on
  R = majority so one dead ingester is invisible to readers.
- poller_shard.py -- ring-sharded blocklist polling: tenants partition
  across queriers by ring ownership (the compactor's owns-job pattern);
  owners list the backend and write the tenant index, everyone else
  reads the owner's index, so each querier pays ~1/M of the poll.
- harness.py      -- the certification driver: launches the full
  multi-process topology over GossipKV, drives soak + vulture through
  it under chaos (rolling ingester restarts at RF=2), measures QPS
  scaling 1->4 queriers, and emits the FLEET_SCALE.json artifact.
"""

from .poller_shard import PollerShard  # noqa: F401
from .quorum import ReadQuorumError, segment_digest  # noqa: F401
from .replication import record_write_outcomes  # noqa: F401
