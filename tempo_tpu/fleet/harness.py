"""Fleet harness: launch, torture and certify the multi-process cluster.

`python -m tempo_tpu.fleet.harness --out FLEET_SCALE.json` builds the
N-frontend x M-querier x K-ingester topology as real OS processes over
gossip membership (the way dryrun_multichip emits MULTICHIP.json) and
runs two certifications:

1. **QPS scaling 1 -> 4 queriers.**  Every querier worker runs at
   concurrency 1 and every search job carries chaos-injected replica
   latency (`rpc.client` latency rule), so a job costs wall-clock, not
   CPU -- on a single-core box that is exactly the regime where adding
   queriers adds throughput (the fleet's dispatch concurrency is the
   bottleneck being certified, not the host's arithmetic).  The ratio
   of measured QPS at M=4 vs M=1 must clear 3x.

2. **Rolling ingester restart at RF=2 under vulture.**  K ingesters
   are SIGKILLed and respawned in turn -- never two at once -- while
   vulture's find_by_id/search probes run continuously against the
   frontend and pushes flow through the distributor (chaos latency on
   its replica legs the whole time).  Zero miss/corrupt outcomes are
   allowed (sheds OK), and the frontend's read-availability SLO verdict
   must end green.

The artifact records both runs plus the topology, so a regression in
replication, pruning, quorum reads or the sharded poller shows up as a
diffable JSON change.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# must clear the gossip full-sync cadence (1s) with margin: a live
# replica whose latest heartbeat is still in flight between peers must
# never look dead to the distributor's healthy-set snapshot
HEARTBEAT_TIMEOUT_S = 3.0
# per-job replica latency injected on querier rpc.client legs for the
# scaling run: makes jobs latency-bound so QPS measures fleet dispatch
# concurrency, not single-core arithmetic
JOB_LATENCY_S = 0.08
QUERIER_CHAOS = json.dumps({
    "seed": 7,
    "rules": [{"site": "rpc.client", "action": "latency",
               "latency_s": JOB_LATENCY_S, "p": 1.0}],
})
# the distributor's replica-write legs run with injected latency during
# the rolling restart (chaos active on the WRITE path throughout)
DISTRIBUTOR_CHAOS = json.dumps({
    "seed": 11,
    "rules": [{"site": "rpc.client", "action": "latency",
               "latency_s": 0.005, "p": 0.5}],
})


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _wait_ready(port: int, timeout: float = 90.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ready", timeout=1) as r:
                if r.status == 200:
                    return
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        time.sleep(0.3)
    raise TimeoutError(f"port {port} never became ready")


def _get_json(port: int, path: str, timeout: float = 10.0) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


class FleetTopology:
    """K ingesters + 1 distributor + 1 query-frontend + M queriers as
    real processes over gossip membership and a shared storage path."""

    def __init__(self, base_dir: str, ingesters: int = 2, queriers: int = 1,
                 rf: int = 2, worker_concurrency: int = 1,
                 querier_chaos: str = "", distributor_chaos: str = "",
                 heartbeat_timeout: float = HEARTBEAT_TIMEOUT_S):
        self.base_dir = base_dir
        self.storage = os.path.join(base_dir, "storage")
        os.makedirs(self.storage, exist_ok=True)
        self.rf = rf
        self.hb = heartbeat_timeout
        self.worker_concurrency = worker_concurrency
        self.querier_chaos = querier_chaos
        self.distributor_chaos = distributor_chaos
        self.ports: dict[str, int] = {}
        self.gports: dict[str, int] = {}
        self.procs: dict[str, subprocess.Popen] = {}
        self.logs: dict[str, object] = {}
        self._ingesters = [f"ing-{i + 1}" for i in range(ingesters)]
        self._queriers = [f"q-{i + 1}" for i in range(queriers)]

    # -------------------------------------------------------- process mgmt
    def _spawn(self, name: str, target: str, extra: tuple = ()) -> None:
        port = self.ports.setdefault(name, _free_port())
        gport = self.gports.setdefault(name, _free_port())
        seed = f"127.0.0.1:{self.gports[self._ingesters[0]]}"
        args = [sys.executable, "-m", "tempo_tpu.services.app",
                f"--target={target}", "--http.port", str(port),
                "--storage.path", self.storage,
                "--memberlist.bind", f"127.0.0.1:{gport}",
                "--instance.id", name,
                "--ring.heartbeat-timeout", str(self.hb),
                "--replication.factor", str(self.rf), *extra]
        if name != self._ingesters[0]:
            args += ["--memberlist.join", seed]
        log = open(os.path.join(self.base_dir, f"{name}.log"), "ab")
        self.logs[name] = log
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO_ROOT)
        env.pop("TEMPO_CHAOS", None)  # only explicit per-role rules
        self.procs[name] = subprocess.Popen(
            args, env=env, stdout=log, stderr=subprocess.STDOUT)

    def start(self) -> None:
        for name in self._ingesters:
            self._spawn(name, "ingester")
        for name in self._ingesters:
            _wait_ready(self.ports[name])
        dist_extra = (("--chaos.rules", self.distributor_chaos)
                      if self.distributor_chaos else ())
        self._spawn("dist", "distributor", dist_extra)
        self._spawn("fe", "query-frontend")
        _wait_ready(self.ports["dist"])
        _wait_ready(self.ports["fe"])
        fe_addr = f"http://127.0.0.1:{self.ports['fe']}"
        q_extra = ("--querier.frontend-address", fe_addr,
                   "--querier.worker-concurrency",
                   str(self.worker_concurrency))
        if self.querier_chaos:
            q_extra += ("--chaos.rules", self.querier_chaos)
        for name in self._queriers:
            self._spawn(name, "querier", q_extra)
        for name in self._queriers:
            _wait_ready(self.ports[name])

    def kill_ingester(self, name: str) -> None:
        """SIGKILL: no LEAVE is written; only the heartbeat prune can
        evict the corpse from the write ring."""
        p = self.procs[name]
        p.send_signal(signal.SIGKILL)
        p.wait(timeout=15)

    def respawn_ingester(self, name: str) -> None:
        self._spawn(name, "ingester")
        _wait_ready(self.ports[name])

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in self.logs.values():
            try:
                log.close()
            except Exception:
                pass

    # ------------------------------------------------------------- helpers
    @property
    def dist_url(self) -> str:
        return f"http://127.0.0.1:{self.ports['dist']}"

    @property
    def fe_url(self) -> str:
        return f"http://127.0.0.1:{self.ports['fe']}"

    def push_traces(self, n: int, seed: int = 5) -> list:
        from ..util.testdata import make_traces
        from ..wire import otlp_json

        traces = make_traces(n, seed=seed, n_spans=4)
        deadline = time.time() + 30
        for i, (_tid, tr) in enumerate(traces):
            body = otlp_json.dumps(tr).encode()
            while True:  # first pushes race the gossip round
                try:
                    req = urllib.request.Request(
                        self.dist_url + "/v1/traces", data=body,
                        headers={"Content-Type": "application/json"})
                    urllib.request.urlopen(req, timeout=15)
                    break
                except urllib.error.HTTPError:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.5)
        return traces

    def chaos_injected(self, name: str) -> int:
        try:
            st = _get_json(self.ports[name], "/status/chaos")
        except Exception:
            return 0
        return int(st.get("injected_total", 0))


# ----------------------------------------------------------- QPS scaling
def measure_qps(fe_url: str, duration_s: float = 12.0, clients: int = 8,
                warmup_s: float = 3.0) -> dict:
    """Closed-loop search load against the frontend: `clients` threads
    each re-issue /api/search as fast as the fleet completes it."""
    stop = threading.Event()
    counts = [0] * clients
    errors = [0] * clients
    started = time.monotonic()
    measure_from = started + warmup_s

    def worker(i: int) -> None:
        while not stop.is_set():
            try:
                with urllib.request.urlopen(
                        fe_url + "/api/search?limit=20", timeout=30) as r:
                    r.read()
                if time.monotonic() >= measure_from:
                    counts[i] += 1
            except Exception:
                if time.monotonic() >= measure_from:
                    errors[i] += 1

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    time.sleep(warmup_s + duration_s)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    done = sum(counts)
    return {"qps": round(done / duration_s, 2), "requests": done,
            "errors": sum(errors), "clients": clients,
            "duration_s": duration_s}


def run_qps_scaling(base_dir: str, querier_counts=(1, 4),
                    duration_s: float = 12.0) -> dict:
    """One topology per point: same ingesters/frontend shape, only M
    changes. Jobs are latency-bound (chaos) so QPS ∝ fleet concurrency."""
    points = []
    for m in querier_counts:
        topo = FleetTopology(
            os.path.join(base_dir, f"qps-m{m}"), ingesters=2, queriers=m,
            rf=2, worker_concurrency=1, querier_chaos=QUERIER_CHAOS)
        try:
            topo.start()
            topo.push_traces(6, seed=5)
            # one successful search proves the pipeline before timing
            deadline = time.time() + 30
            while True:
                try:
                    urllib.request.urlopen(
                        topo.fe_url + "/api/search?limit=5", timeout=20)
                    break
                except Exception:
                    if time.time() > deadline:
                        raise
                    time.sleep(0.5)
            res = measure_qps(topo.fe_url, duration_s=duration_s)
            res["queriers"] = m
            res["chaos_injected"] = sum(
                topo.chaos_injected(q) for q in topo._queriers)
            points.append(res)
        finally:
            topo.stop()
    base = points[0]["qps"] or 1e-9
    ratio = round(points[-1]["qps"] / base, 2)
    return {
        "job_latency_chaos_s": JOB_LATENCY_S,
        "worker_concurrency": 1,
        "points": points,
        "ratio": ratio,
        "target_ratio": 3.0,
        "pass": ratio >= 3.0 and all(p["errors"] == 0 for p in points),
    }


# ------------------------------------------------------- rolling restart
def run_rolling_restart(base_dir: str, ingesters: int = 3, queriers: int = 2,
                        settle_s: float = 4.0) -> dict:
    """SIGKILL + respawn each ingester in turn at RF=2 while vulture
    find_by_id/search probes run continuously. Zero miss/corrupt allowed."""
    from ..vulture import Vulture, VultureConfig

    topo = FleetTopology(
        os.path.join(base_dir, "rolling"), ingesters=ingesters,
        queriers=queriers, rf=2, worker_concurrency=2,
        distributor_chaos=DISTRIBUTOR_CHAOS)
    outcomes: dict[str, int] = {}
    details: list[str] = []
    stop = threading.Event()

    def vloop(v: Vulture) -> None:
        while not stop.is_set():
            try:
                results = v.cycle()
            except Exception as e:  # a sick probe loop is itself a failure
                outcomes["probe_crash"] = outcomes.get("probe_crash", 0) + 1
                details.append(f"probe loop: {e!r}")
                time.sleep(0.5)
                continue
            for r in results:
                outcomes[r.outcome] = outcomes.get(r.outcome, 0) + 1
                if r.outcome not in ("ok", "shed") and len(details) < 20:
                    details.append(f"{r.family}: {r.outcome} {r.detail}")

    try:
        topo.start()
        topo.push_traces(4, seed=13)  # warm the write path + gossip
        vcfg = VultureConfig(
            push_url=topo.dist_url, query_url=topo.fe_url,
            families=("find_by_id", "search"), flush_every=0,
            generator_probes=False, visibility_timeout_s=25.0,
            spans_per_trace=3, batch_ids=2, seed=3)
        v = Vulture(vcfg)
        vt = threading.Thread(target=vloop, args=(v,), daemon=True)
        vt.start()
        time.sleep(3.0)  # probes flowing before the first kill
        restarts = []
        for name in topo._ingesters:
            t0 = time.time()
            topo.kill_ingester(name)
            # the prune satellite's guarantee: the corpse leaves the
            # write ring within ~one heartbeat interval of the timeout
            time.sleep(topo.hb + 1.0)
            topo.respawn_ingester(name)
            time.sleep(settle_s)  # WAL replay + rejoin settle
            restarts.append({"ingester": name,
                             "outage_s": round(time.time() - t0, 2)})
        time.sleep(3.0)  # post-roll probes against the healed fleet
        stop.set()
        vt.join(timeout=60)
        try:
            slo = _get_json(topo.ports["fe"], "/status/slo")
            ra = slo.get("objectives", {}).get("read-availability", {})
            verdict = ra.get("verdict", slo.get("verdict", "unknown"))
        except Exception:
            verdict = "unknown"
        fleet_view = {}
        try:
            fleet_view = _get_json(topo.ports["dist"], "/status/fleet")
        except Exception:
            pass
        misses = outcomes.get("miss", 0) + outcomes.get("timeout", 0)
        corrupt = outcomes.get("corrupt", 0)
        bad = (misses + corrupt + outcomes.get("error", 0)
               + outcomes.get("probe_crash", 0))
        return {
            "rf": 2,
            "ingesters": ingesters,
            "queriers": queriers,
            "restarts": restarts,
            "probe_families": ["find_by_id", "search"],
            "cycles": v.cycles,
            "outcomes": outcomes,
            "misses": misses,
            "corrupt": corrupt,
            "failure_details": details,
            "chaos": {
                "distributor_injected": topo.chaos_injected("dist"),
            },
            "replication_writes": (fleet_view.get("replication", {})
                                   .get("writes", {})),
            "read_availability_verdict": verdict,
            "pass": bad == 0 and verdict == "ok" and v.cycles > 0,
        }
    finally:
        stop.set()
        topo.stop()


# ------------------------------------------------------------------ main
def certify(out_path: str, base_dir: str, quick: bool = False) -> dict:
    t0 = time.time()
    qps = run_qps_scaling(
        base_dir, querier_counts=(1, 4), duration_s=6.0 if quick else 12.0)
    rolling = run_rolling_restart(
        base_dir, ingesters=2 if quick else 3, queriers=2,
        settle_s=3.0 if quick else 4.0)
    artifact = {
        "schema": "fleet_scale/v1",
        "generated_unix": int(t0),
        "wall_s": round(time.time() - t0, 1),
        "topology": {
            "frontends": 1,
            "distributors": 1,
            "membership": "gossip",
            "ring_heartbeat_timeout_s": HEARTBEAT_TIMEOUT_S,
        },
        "qps_scaling": qps,
        "rolling_restart": rolling,
        "ok": bool(qps["pass"] and rolling["pass"]),
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2, sort_keys=True)
        f.write("\n")
    return artifact


def main(argv=None) -> int:
    ap = argparse.ArgumentParser("tempo-tpu-fleet-harness")
    ap.add_argument("--out", default="FLEET_SCALE.json")
    ap.add_argument("--work-dir", default="",
                    help="scratch dir for storage/logs (default: temp)")
    ap.add_argument("--quick", action="store_true",
                    help="shorter measurement windows / smaller fleet")
    args = ap.parse_args(argv)
    import tempfile

    base = args.work_dir or tempfile.mkdtemp(prefix="tempo-fleet-")
    artifact = certify(args.out, base, quick=args.quick)
    print(json.dumps(artifact, indent=2, sort_keys=True))
    print(f"\nFLEET_SCALE -> {args.out}  ok={artifact['ok']}")
    return 0 if artifact["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
