"""Resilience-propagation contract over services/, transport/ and
fleet/: every remote leg carries a deadline, sits behind a breaker or a
retry-budget/quorum error path, and is reachable through a declared
chaos seam so the fault plane can exercise it.

  * rpc-no-deadline: urlopen / HTTPIngesterClient / client_registry
    without a timeout kwarg. An unbounded remote call turns one stuck
    replica into a stuck fleet (the PR-14 deadline-propagation lesson).
  * rpc-unguarded: a call of a known RPC method on a client-ish
    receiver with no exception handler around it and no breaker /
    retry-budget / quorum machinery in the enclosing function. The
    receiver heuristic is deliberate: names containing "client", or
    locals bound from a *client* call (client_for(addr), clients[i]).
  * chaos-seam-gap: chaos/plane.py declares SEAM_MODULES (module ->
    seams it taps). Every declared SITE must be claimed by a module,
    every claimed module must actually name the seam, and every
    urlopen in scope must live in a module that claims a seam --
    a remote side effect the chaos plane cannot reach is a code path
    the fault-injection certification never exercises.
"""

from __future__ import annotations

import ast

from .core import Report, SourceModule, dotted_name, emit, register_rule

R_NO_DEADLINE = register_rule(
    "rpc-no-deadline",
    "remote call site without a timeout/deadline: one stuck peer "
    "wedges every caller above it",
    hint="pass timeout= (thread cfg.rpc_deadline_s / deadline_in_s "
         "through)")
R_UNGUARDED = register_rule(
    "rpc-unguarded",
    "remote RPC leg with no breaker, retry-budget or error path "
    "around it: a flapping replica cascades",
    hint="wrap in try/except feeding the quorum math, or route through "
         "a CircuitBreaker (fleet.replication.guarded_push style)")
R_SEAM_GAP = register_rule(
    "chaos-seam-gap",
    "side-effect site not reachable through a declared chaos seam: "
    "fault certification never exercises it",
    hint="declare the seam in chaos/plane.py SITES + SEAM_MODULES and "
         "tap the call site")

SCOPE = ("services/", "transport/", "fleet/")
RPC_METHODS = {"push_segments", "push_generator_blobs", "find_trace_by_id",
               "search", "metrics_query_range", "trace_snapshot"}
GUARD_TOKENS = ("breaker", "budget", "guarded", "quorum")
PLANE_REL = "chaos/plane.py"


def _callee_name(call: ast.Call) -> str:
    f = call.func
    return f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(k.arg == name or k.arg is None  # **kwargs may carry it
               for k in call.keywords)


# ------------------------------------------------------------ deadlines
def _check_deadlines(mod: SourceModule, report: Report) -> None:
    for n in ast.walk(mod.tree):
        if not isinstance(n, ast.Call):
            continue
        name = _callee_name(n)
        if name == "urlopen":
            if not _has_kw(n, "timeout") and len(n.args) < 3:
                emit(mod, report, n.lineno, R_NO_DEADLINE,
                     "urlopen without timeout=",
                     "pass an explicit timeout")
        elif name.endswith("IngesterClient") or name == "client_registry":
            if not _has_kw(n, "timeout"):
                emit(mod, report, n.lineno, R_NO_DEADLINE,
                     f"{name}(...) without timeout=: remote RPCs default "
                     "instead of inheriting the configured deadline",
                     "thread cfg.rpc_deadline_s through")


# ------------------------------------------------------------- guarding
def _client_locals(fn: ast.AST) -> set[str]:
    """Names bound from client-producing expressions inside fn."""
    out: set[str] = set()

    def producer(v: ast.AST) -> bool:
        if isinstance(v, ast.Call):
            return "client" in _callee_name(v).lower()
        if isinstance(v, ast.Subscript):
            d = dotted_name(v.value)
            return d is not None and "client" in d.lower()
        return False

    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and producer(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(n, ast.For) and producer(n.iter) \
                and isinstance(n.target, ast.Name):
            out.add(n.target.id)
    return out


def _fn_tokens(fn: ast.AST) -> str:
    """Lower-cased identifier soup of a function body: name references,
    attribute names, call targets -- the guard-token haystack."""
    parts = [getattr(fn, "name", "")]
    for n in ast.walk(fn):
        if isinstance(n, ast.Name):
            parts.append(n.id)
        elif isinstance(n, ast.Attribute):
            parts.append(n.attr)
    return " ".join(parts).lower()


def _check_guarding(mod: SourceModule, report: Report) -> None:
    fns = [n for n in ast.walk(mod.tree)
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fns:
        clientish = _client_locals(fn)
        guarded_fn = any(t in _fn_tokens(fn) for t in GUARD_TOKENS)

        def scan(node: ast.AST, in_handler: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                return  # nested defs get their own pass
            if isinstance(node, ast.Try) and node.handlers:
                for child in node.body + node.orelse:
                    scan(child, True)
                for h in node.handlers:
                    for child in h.body:
                        scan(child, in_handler)
                for child in node.finalbody:
                    scan(child, in_handler)
                return
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in RPC_METHODS:
                recv = node.func.value
                root = recv
                while isinstance(root, (ast.Attribute, ast.Subscript,
                                        ast.Call)):
                    root = root.func if isinstance(root, ast.Call) \
                        else root.value
                root_id = root.id if isinstance(root, ast.Name) else ""
                is_client = ("client" in root_id.lower()
                             or root_id in clientish
                             or (isinstance(recv, ast.Call)
                                 and "client" in _callee_name(recv).lower()))
                if is_client and not in_handler and not guarded_fn:
                    emit(mod, report, node.lineno, R_UNGUARDED,
                         f".{node.func.attr}() on a remote client outside "
                         "any error path",
                         "wrap in try/except or a breaker-guarded helper")
            for child in ast.iter_child_nodes(node):
                scan(child, in_handler)

        for stmt in fn.body:
            scan(stmt, False)


# ----------------------------------------------------------- chaos seams
def _parse_plane(mod: SourceModule) -> tuple[dict[str, int], dict, int]:
    """(SITES key->line, SEAM_MODULES literal, SEAM_MODULES line)."""
    sites: dict[str, int] = {}
    seams: dict = {}
    seams_line = 0
    for n in mod.tree.body:
        if not (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)
                and isinstance(n.value, ast.Dict)):
            continue
        name = n.targets[0].id
        if name == "SITES":
            for k in n.value.keys:
                if isinstance(k, ast.Constant):
                    sites[k.value] = k.lineno
        elif name == "SEAM_MODULES":
            try:
                seams = ast.literal_eval(n.value)
            except ValueError:
                seams = {}
            seams_line = n.lineno
    return sites, seams, seams_line


def run_seam_rules(modules: dict[str, SourceModule],
                   report: Report) -> None:
    plane = modules.get(PLANE_REL)
    if plane is None:
        return
    sites, seams, seams_line = _parse_plane(plane)
    if not seams:
        return  # registry predates SEAM_MODULES: nothing to check against

    claimed: set[str] = set()
    for rel, rel_sites in seams.items():
        claimed.update(rel_sites)
        m = modules.get(rel)
        if m is None:
            emit(plane, report, seams_line, R_SEAM_GAP,
                 f"SEAM_MODULES names '{rel}' which is not in the tree",
                 "fix the module path")
            continue
        for site in rel_sites:
            if f'"{site}"' not in m.text and f"'{site}'" not in m.text:
                emit(plane, report, seams_line, R_SEAM_GAP,
                     f"'{rel}' claims seam '{site}' but never names it",
                     "tap the site (plane.tap/call) or drop the claim")

    for site, line in sites.items():
        if site not in claimed:
            emit(plane, report, line, R_SEAM_GAP,
                 f"seam '{site}' is declared but no module claims it in "
                 "SEAM_MODULES",
                 "map the implementing module to the seam")

    for rel, mod in modules.items():
        if not rel.startswith(SCOPE) or rel in seams:
            continue
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.Call) and _callee_name(n) == "urlopen":
                emit(mod, report, n.lineno, R_SEAM_GAP,
                     "remote side effect outside every declared chaos "
                     "seam: fault injection cannot reach it",
                     "claim a seam for this module in chaos/plane.py "
                     "and tap the call")


def run_resilience_rules(modules: dict[str, SourceModule],
                         report: Report) -> None:
    for rel, mod in modules.items():
        if rel.startswith(SCOPE):
            _check_deadlines(mod, report)
            _check_guarding(mod, report)
    run_seam_rules(modules, report)
