"""Device/host twin cross-check.

Every device kernel the read path dispatches must have a numpy twin the
exact-verify machinery can fall back to -- the device encodings are
conservative (ops/filter docstring), so a kernel without a host twin is
a kernel whose over-matches can never be settled. The contract lives in
`ops/twins.py` as plain data; this pass keeps it honest from both ends:

  * twin-missing: a jit-reachable function in ops/ or parallel/ is
    imported by one of the db executor modules (search, metrics_exec,
    metrics_mesh, batchexec) but has no DEVICE_HOST_TWINS entry and no
    declared DEVICE_ONLY exemption.
  * twin-unresolvable: a registry entry names a device function or host
    twin that does not exist -- or a "host" twin that itself reaches
    jit, which would make exact-verify recurse onto the device.

Jit-reachability is a call-graph fixpoint over ops/ and parallel/: a
function is device-touching if its body uses jax.jit or calls (by
local or imported name) another device-touching function. The graph
machinery (import resolution, per-module facts, the fixpoint) lives in
analysis/callgraph.py -- this pass owns only the jit property and the
registry cross-check. Everything is AST-only; nothing is imported.
"""

from __future__ import annotations

import ast
from pathlib import Path

from .callgraph import ModuleFacts, reachable_fixpoint, resolve_import
from .core import Report, SourceModule, emit, register_rule

R_MISSING = register_rule(
    "twin-missing",
    "device kernel used by a db executor has no numpy twin registered "
    "in ops/twins.py (exact-verify cannot settle its over-matches)")
R_UNRESOLVABLE = register_rule(
    "twin-unresolvable",
    "ops/twins.py entry does not resolve to a real function (stale "
    "registry), or the registered host twin itself reaches jit")

# the executors whose device dispatches the registry must cover
DB_EXECUTORS = ("db/search.py", "db/metrics_exec.py", "db/metrics_mesh.py",
                "db/batchexec.py", "db/live_engine.py")
KERNEL_PKGS = ("ops", "parallel")


def _direct_jit(fn: ast.FunctionDef) -> bool:
    """One definition of 'jitted' shared with the jit rules: the two
    passes must never disagree about it. ast.walk yields fn itself
    first, so its own decorators are covered too."""
    from .jitrules import _is_jax_jit, _jit_decorator_info

    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and _is_jax_jit(n.func):
            return True
        if isinstance(n, ast.FunctionDef) and _jit_decorator_info(n)[0]:
            return True
    return False


def _jit_reachable(kernel_mods: list[ModuleFacts]) -> set[str]:
    direct: set[str] = set()
    edges: dict[str, set[str]] = {}
    for m in kernel_mods:
        for name, fn in m.defs.items():
            fq = f"{m.fq}.{name}"
            if _direct_jit(fn):
                direct.add(fq)
            edges[fq] = m.calls_of(fn)
    return reachable_fixpoint(direct, edges)


def _parse_registry(mod: SourceModule) -> tuple[dict, dict, dict[str, int]]:
    """(DEVICE_HOST_TWINS, DEVICE_ONLY, key -> line) via literal eval."""
    twins: dict = {}
    device_only: dict = {}
    lines: dict[str, int] = {}
    for n in mod.tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            target = n.targets[0]
        elif isinstance(n, ast.AnnAssign):
            target = n.target
        else:
            continue
        if not (isinstance(target, ast.Name)
                and isinstance(n.value, ast.Dict)):
            continue
        name = target.id
        if name not in ("DEVICE_HOST_TWINS", "DEVICE_ONLY"):
            continue
        try:
            value = ast.literal_eval(n.value)
        except ValueError:
            continue
        (twins if name == "DEVICE_HOST_TWINS" else device_only).update(value)
        for k in n.value.keys:
            if isinstance(k, ast.Constant):
                lines[k.value] = k.lineno
    return twins, device_only, lines


def run_twin_rules(modules: dict[str, SourceModule], report: Report) -> None:
    """`modules` is rel-path -> SourceModule for one scanned root."""
    reg_mod = modules.get("ops/twins.py")
    kernel_mods = [ModuleFacts(m, KERNEL_PKGS) for rel, m in modules.items()
                   if rel.split("/")[0] in KERNEL_PKGS
                   and rel != "ops/twins.py"]
    if not kernel_mods:
        return
    by_fq = {m.fq: m for m in kernel_mods}
    reachable = _jit_reachable(kernel_mods)

    twins: dict = {}
    device_only: dict = {}
    reg_lines: dict[str, int] = {}
    if reg_mod is not None:
        twins, device_only, reg_lines = _parse_registry(reg_mod)

    def resolves(fq_func: str) -> bool:
        mod_fq, _, func = fq_func.rpartition(".")
        m = by_fq.get(mod_fq)
        return m is not None and func in m.defs

    # registry -> tree direction
    if reg_mod is not None:
        for dev, host in twins.items():
            line = reg_lines.get(dev, 1)
            if not resolves(dev):
                emit(reg_mod, report, line, R_UNRESOLVABLE,
                     f"device entry '{dev}' does not resolve to a function "
                     "in ops/ or parallel/",
                     "delete the stale entry or fix the dotted path")
            if not resolves(host):
                emit(reg_mod, report, line, R_UNRESOLVABLE,
                     f"host twin '{host}' does not resolve to a function",
                     "point the entry at the numpy twin the exact-verify "
                     "path calls")
            elif host in reachable:
                emit(reg_mod, report, line, R_UNRESOLVABLE,
                     f"host twin '{host}' itself reaches jax.jit: "
                     "exact-verify would recurse onto the device",
                     "register the pure-numpy implementation instead")
        for dev in device_only:
            if not resolves(dev):
                emit(reg_mod, report, reg_lines.get(dev, 1), R_UNRESOLVABLE,
                     f"DEVICE_ONLY entry '{dev}' does not resolve to a "
                     "function in ops/ or parallel/",
                     "delete the stale exemption")

    # tree -> registry direction: every device kernel a db executor
    # imports must be covered
    for rel in DB_EXECUTORS:
        m = modules.get(rel)
        if m is None:
            continue
        cur_pkg = "/".join(Path(rel).parts[:-1])
        for n in ast.walk(m.tree):
            if not isinstance(n, ast.ImportFrom):
                continue
            target = resolve_import(cur_pkg, n, KERNEL_PKGS)
            if target is None or target.split(".")[0] not in KERNEL_PKGS:
                continue
            for al in n.names:
                fq = f"{target}.{al.name}"
                if fq not in reachable:
                    continue  # host helper, class, or constant
                if fq in twins or fq in device_only:
                    continue
                emit(m, report, n.lineno, R_MISSING,
                     f"'{fq}' is a device kernel (reaches jax.jit) with no "
                     "registered numpy twin",
                     "add a DEVICE_HOST_TWINS entry in ops/twins.py (or a "
                     "DEVICE_ONLY exemption with a reason)")
