"""Kernel-contract passes over ops/ and parallel/.

The device layer's whole performance story rests on conventions no
runtime test can see breaking until a production trace does:

  * launch keys must be SHAPE-only -- operand values ride in as traced
    arrays (ops/filter's docstring is the contract) -- or every distinct
    query value recompiles its own XLA program (the compile storm the
    TempoKernelCompileStorm alert pages on, after the fact);
  * jitted bodies must not synchronize with the host: one `.item()` in
    a kernel turns an async dispatch into a blocking round trip per
    call, which on a high-latency link erases the batching win;
  * jitted bodies trace with jnp; stray `np.` calls either break the
    trace or silently constant-fold a value that should be dynamic.

Scope is LEXICAL jit regions: a def decorated with @jax.jit (bare or
via functools.partial), plus local defs wrapped by a `jax.jit(...)`
call in the same function (chased through trivial assignments and
wrapper calls like shard_map(fn, ...)), plus everything nested inside
those. Module-level helpers invoked from traced code (ops/filter's
_cond_mask) are host functions that happen to run at trace time -- they
are out of region, the price of zero false positives on orchestration
code that legitimately calls np.asarray on fetched results.
"""

from __future__ import annotations

import ast
import builtins

from .core import Report, SourceModule, dotted_name, emit, register_rule

R_HOST_SYNC = register_rule(
    "jit-host-sync",
    "host synchronization inside a jitted body (.item/.tolist/"
    "block_until_ready/np.asarray/float(traced)) blocks the dispatch "
    "pipeline for a full link round trip")
R_NUMPY = register_rule(
    "jit-numpy",
    "np.* call inside a jitted body; traced math must use jnp or the "
    "value constant-folds at trace time")
R_CAPTURE = register_rule(
    "jit-nonstatic-capture",
    "jitted closure captures a name that varies across the enclosing "
    "scope (loop variable / rebound local): the first trace bakes one "
    "value, or every change silently retraces")
R_UNCACHED = register_rule(
    "jit-uncached-factory",
    "function builds a jax.jit wrapper on every call without lru_cache: "
    "every invocation retraces and recompiles")
R_VALUE_KEY = register_rule(
    "jit-value-key",
    "data-derived value (.item()/.max()/...) passed in a static "
    "launch-key position: every distinct data value compiles a fresh "
    "XLA program (compile storm)")

HOST_SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
NP_MATERIALIZE = {"asarray", "array", "frombuffer", "ascontiguousarray"}
# dtype constructors and trace-time metadata -- legitimate inside jit
NP_OK = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_", "dtype",
    "iinfo", "finfo", "promote_types", "result_type",
}
# reductions whose result in a static position keys compiles on DATA
VALUE_EXTRACTORS = {"item", "max", "min", "sum", "mean", "argmax",
                    "argmin", "tolist"}
_BUILTINS = set(dir(builtins))
_CACHE_DECORATORS = ("lru_cache", "functools.lru_cache", "cache",
                     "functools.cache")


def _is_jax_jit(node: ast.AST) -> bool:
    return dotted_name(node) in ("jax.jit", "jit")


def _jit_decorator_info(fn: ast.FunctionDef) -> tuple[bool, set[str]]:
    """(is_jitted, static param names) from the decorator list."""
    for dec in fn.decorator_list:
        if _is_jax_jit(dec):
            return True, set()
        if isinstance(dec, ast.Call):
            # @jax.jit(...) or @partial(jax.jit, static_argnames=...)
            if dotted_name(dec.func) in ("partial", "functools.partial"):
                if not (dec.args and _is_jax_jit(dec.args[0])):
                    continue
            elif not _is_jax_jit(dec.func):
                continue
            return True, _static_names(dec, fn)
    return False, set()


def _static_names(call: ast.Call, fn: ast.FunctionDef) -> set[str]:
    params = [a.arg for a in fn.args.args]
    out: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for el in ast.walk(kw.value):
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
        elif kw.arg == "static_argnums":
            for el in ast.walk(kw.value):
                if (isinstance(el, ast.Constant) and isinstance(el.value, int)
                        and 0 <= el.value < len(params)):
                    out.add(params[el.value])
    return out


def _has_cache_decorator(fn: ast.FunctionDef) -> bool:
    return any(
        dotted_name(d if not isinstance(d, ast.Call) else d.func)
        in _CACHE_DECORATORS
        for d in fn.decorator_list)


def _chase_jit_wrapped(owner: ast.AST) -> set[int]:
    """ids of local defs inside `owner` that end up under a jax.jit(...)
    call: the argument itself, a name assigned from a def, or a def
    passed through a wrapper call (fn = smap(local, ...); jax.jit(fn))."""
    defs = {n.name: n for n in ast.iter_child_nodes(owner)
            if isinstance(n, ast.FunctionDef)}
    assigned: dict[str, ast.expr] = {}
    for n in ast.walk(owner):
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            assigned[n.targets[0].id] = n.value

    def defs_in(expr: ast.expr, depth: int) -> list[ast.FunctionDef]:
        if depth > 4:
            return []
        if isinstance(expr, ast.Name):
            if expr.id in defs:
                return [defs[expr.id]]
            if expr.id in assigned:
                return defs_in(assigned[expr.id], depth + 1)
            return []
        if isinstance(expr, ast.Call):
            out = []
            for a in list(expr.args) + [kw.value for kw in expr.keywords]:
                out.extend(defs_in(a, depth + 1))
            return out
        return []

    out: set[int] = set()
    for n in ast.walk(owner):
        if isinstance(n, ast.Call) and _is_jax_jit(n.func) and n.args:
            out.update(id(d) for d in defs_in(n.args[0], 0))
    return out


def _params_of(fn) -> set[str]:
    a = fn.args
    out = {arg.arg for arg in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        out.add(a.vararg.arg)
    if a.kwarg:
        out.add(a.kwarg.arg)
    return out


def _bound_names(fn: ast.FunctionDef) -> set[str]:
    """Every name bound anywhere within fn, including nested scopes --
    used to decide what the jit region could NOT have captured."""
    bound = _params_of(fn)
    for n in ast.walk(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            bound.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            bound |= _params_of(n)
            if not isinstance(n, ast.Lambda):
                bound.add(n.name)
        elif isinstance(n, ast.ClassDef):
            bound.add(n.name)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for al in n.names:
                bound.add((al.asname or al.name).split(".")[0])
    return bound


def _module_bindings(tree: ast.Module) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, (ast.Import, ast.ImportFrom)):
            for al in n.names:
                out.add((al.asname or al.name).split(".")[0])
    for n in tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(n.name)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                for el in ast.walk(t):
                    if isinstance(el, ast.Name):
                        out.add(el.id)
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign)) and isinstance(
                n.target, ast.Name):
            out.add(n.target.id)
    return out


class _EnclosingScope:
    """Classify one enclosing def's bindings for the capture rule:
    `params` and `once` (bound exactly once, outside any loop) are
    static per factory call; `varying` (loop targets, rebound names)
    change under the closure's feet."""

    def __init__(self, fn: ast.FunctionDef):
        self.params = _params_of(fn)
        counts: dict[str, int] = {}
        loop_bound: set[str] = set()

        def note_stores(node: ast.AST, in_loop: bool, cnt: dict) -> None:
            for el in ast.walk(node):
                if isinstance(el, ast.Name) and isinstance(
                        el.ctx, (ast.Store, ast.Del)):
                    cnt[el.id] = cnt.get(el.id, 0) + 1
                    if in_loop:
                        loop_bound.add(el.id)

        def scan(body: list, in_loop: bool, cnt: dict) -> None:
            for n in body:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                    cnt[n.name] = cnt.get(n.name, 0) + 1
                    if in_loop:
                        loop_bound.add(n.name)
                elif isinstance(n, (ast.For, ast.AsyncFor)):
                    note_stores(n.target, True, cnt)
                    scan(n.body + n.orelse, True, cnt)
                elif isinstance(n, ast.While):
                    scan(n.body + n.orelse, True, cnt)
                elif isinstance(n, ast.If):
                    # disjoint branches: a name bound once in each arm is
                    # still bound once per call -- merge with max, not sum
                    note_stores(n.test, in_loop, cnt)
                    c_then: dict = {}
                    c_else: dict = {}
                    scan(n.body, in_loop, c_then)
                    scan(n.orelse, in_loop, c_else)
                    for k in set(c_then) | set(c_else):
                        cnt[k] = cnt.get(k, 0) + max(c_then.get(k, 0),
                                                     c_else.get(k, 0))
                elif isinstance(n, (ast.With, ast.AsyncWith)):
                    for item in n.items:
                        if item.optional_vars is not None:
                            note_stores(item.optional_vars, in_loop, cnt)
                    scan(n.body, in_loop, cnt)
                elif isinstance(n, ast.Try):
                    scan(n.body + n.orelse + n.finalbody, in_loop, cnt)
                    for h in n.handlers:
                        if h.name:
                            cnt[h.name] = cnt.get(h.name, 0) + 1
                        scan(h.body, in_loop, cnt)
                else:
                    note_stores(n, in_loop, cnt)

        scan(fn.body, False, counts)
        self.varying = loop_bound | {n for n, c in counts.items() if c > 1}
        self.once = {n for n in counts if n not in self.varying}


def _scan_jit_body(mod: SourceModule, report: Report, fn: ast.FunctionDef,
                   static_params: set[str], enclosing: list[ast.FunctionDef],
                   module_bound: set[str]) -> None:
    """jit-host-sync, jit-numpy and jit-nonstatic-capture over one
    lexical jit region (the wrapped def plus everything nested in it)."""
    traced_params = (_params_of(fn) - static_params)
    bound = _bound_names(fn)
    scopes = [_EnclosingScope(e) for e in enclosing]
    flagged_caps: set[str] = set()

    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            if isinstance(n.func, ast.Attribute):
                if n.func.attr in HOST_SYNC_ATTRS:
                    emit(mod, report, n.lineno, R_HOST_SYNC,
                         f".{n.func.attr}() inside jitted body",
                         "compute on device; fetch after the kernel returns")
                    continue
                root = n.func.value
                if isinstance(root, ast.Name) and root.id in ("np", "numpy"):
                    if n.func.attr in NP_MATERIALIZE:
                        emit(mod, report, n.lineno, R_HOST_SYNC,
                             f"np.{n.func.attr}() inside jitted body forces "
                             "a device->host transfer",
                             "keep the value a traced jnp array")
                    elif n.func.attr not in NP_OK:
                        emit(mod, report, n.lineno, R_NUMPY,
                             f"np.{n.func.attr}() inside jitted body",
                             f"use jnp.{n.func.attr} so the op traces")
                    continue
            if dotted_name(n.func) == "jax.device_get":
                emit(mod, report, n.lineno, R_HOST_SYNC,
                     "jax.device_get() inside jitted body",
                     "return the array and fetch outside the kernel")
                continue
            if (isinstance(n.func, ast.Name)
                    and n.func.id in ("float", "int", "bool")
                    and len(n.args) == 1 and isinstance(n.args[0], ast.Name)
                    and n.args[0].id in traced_params):
                emit(mod, report, n.lineno, R_HOST_SYNC,
                     f"{n.func.id}({n.args[0].id}) concretizes a traced "
                     "argument (host sync; fails under jit)",
                     "cast with .astype(...) on device, or mark the "
                     "argument static")
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            name = n.id
            if (name in bound or name in module_bound or name in _BUILTINS
                    or name in flagged_caps):
                continue
            for sc in scopes:
                if name in sc.params or name in sc.once:
                    break
                if name in sc.varying:
                    flagged_caps.add(name)
                    emit(mod, report, n.lineno, R_CAPTURE,
                         f"jitted closure captures '{name}', which varies "
                         "in the enclosing scope",
                         "pass it as a static factory parameter so it "
                         "joins the compile key explicitly")
                    break


# value: (static positional indices, static keyword names); (None, None)
# means EVERY argument is static (an lru_cache'd compile factory)
StaticSpec = tuple


def _collect_static_key_callables(tree: ast.Module) -> dict[str, StaticSpec]:
    """Module-level callables whose arguments key XLA compiles."""
    out: dict[str, StaticSpec] = {}
    for n in tree.body:
        if not isinstance(n, ast.FunctionDef):
            continue
        contains_jit = any(
            (isinstance(w, ast.Call) and _is_jax_jit(w.func))
            or (isinstance(w, ast.FunctionDef) and w is not n
                and _jit_decorator_info(w)[0])
            for w in ast.walk(n))
        if _has_cache_decorator(n) and contains_jit:
            out[n.name] = (None, None)
            continue
        jitted, statics = _jit_decorator_info(n)
        if jitted and statics:
            params = [a.arg for a in n.args.args]
            out[n.name] = ({i for i, p in enumerate(params) if p in statics},
                           statics)
    return out


def _arg_extracts_value(expr: ast.expr) -> str | None:
    for n in ast.walk(expr):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in VALUE_EXTRACTORS):
            return n.func.attr
    return None


def run_jit_rules(mod: SourceModule, report: Report) -> None:
    tree = mod.tree
    module_bound = _module_bindings(tree)

    def visit(owner: ast.AST, enclosing: list[ast.FunctionDef]) -> None:
        """Locate lexical jit regions; flag uncached top-level factories."""
        # chase jax.jit(name) wrapping at module level too: the
        # `kernel = jax.jit(_impl)` definition style is a jit region
        # exactly like the decorator form
        wrapped_here: set[int] = set()
        if isinstance(owner, (ast.FunctionDef, ast.Module)):
            wrapped_here = _chase_jit_wrapped(owner)
        if isinstance(owner, ast.FunctionDef):
            # jit creation inside a nested @lru_cache'd def is that
            # def's responsibility (and it memoizes it): exclude those
            # subtrees so a plain wrapper around a cached factory does
            # not false-positive
            cached_subtrees: set[int] = set()
            for w in ast.walk(owner):
                if (isinstance(w, ast.FunctionDef) and w is not owner
                        and _has_cache_decorator(w)):
                    cached_subtrees.update(id(x) for x in ast.walk(w))
            creates_jit = bool(wrapped_here) or any(
                isinstance(w, ast.Call) and _is_jax_jit(w.func)
                and id(w) not in cached_subtrees
                for w in ast.walk(owner)) or any(
                isinstance(c, ast.FunctionDef) and _jit_decorator_info(c)[0]
                for c in ast.iter_child_nodes(owner))
            if (creates_jit and not enclosing
                    and not _has_cache_decorator(owner)):
                emit(mod, report, owner.lineno, R_UNCACHED,
                     f"'{owner.name}' builds a jax.jit wrapper on every "
                     "call without lru_cache",
                     "decorate the factory with @lru_cache so identical "
                     "shapes reuse the compiled program")
        next_enclosing = ([owner] + enclosing
                          if isinstance(owner, ast.FunctionDef) else enclosing)
        for child in ast.iter_child_nodes(owner):
            if isinstance(child, ast.FunctionDef):
                jitted, statics = _jit_decorator_info(child)
                if jitted or id(child) in wrapped_here:
                    _scan_jit_body(mod, report, child, statics,
                                   next_enclosing, module_bound)
                else:
                    visit(child, next_enclosing)
            elif isinstance(child, (ast.ClassDef, ast.If, ast.Try, ast.With,
                                    ast.For, ast.While)):
                visit(child, next_enclosing)

    visit(tree, [])

    _check_value_key_calls(mod, report, _collect_static_key_callables(tree))


def _check_value_key_calls(mod: SourceModule, report: Report,
                           static_callables: dict[str, StaticSpec]) -> None:
    if not static_callables:
        return

    def check(arg: ast.expr, label: str, fname: str, line: int) -> None:
        attr = _arg_extracts_value(arg)
        if attr:
            emit(mod, report, line, R_VALUE_KEY,
                 f"argument {label} of '{fname}' derives from data "
                 f"(.{attr}()) but keys the compiled program",
                 "key compiles on the padded shape bucket "
                 "(ops/device.bucket); ship values as traced operands")

    for n in ast.walk(mod.tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)):
            continue
        if n.func.id not in static_callables:
            continue
        idxs, names = static_callables[n.func.id]
        for i, arg in enumerate(n.args):
            if idxs is not None and i not in idxs:
                continue
            check(arg, str(i), n.func.id, n.lineno)
        for kw in n.keywords:
            # static_argnames params are most naturally passed by
            # keyword: those key compiles exactly like positional ones
            if names is not None and kw.arg not in names:
                continue
            check(kw.value, f"'{kw.arg or '**'}'", n.func.id, n.lineno)


def run_value_key_cross(modules: dict[str, SourceModule],
                        report: Report) -> None:
    """Cross-module jit-value-key: the likeliest real compile storm is
    a db executor (or service) passing a data-derived value to an ops/
    compile factory it IMPORTED -- the per-module pass cannot see that.
    Phase 1 collects every kernel module's static-key callables under
    their fully-qualified names; phase 2 re-checks every module's calls
    to names imported from kernel modules."""
    from .callgraph import fq_module, resolve_import
    from .twinrules import KERNEL_PKGS
    from pathlib import Path

    fq_callables: dict[str, StaticSpec] = {}
    for rel, mod in modules.items():
        if rel.split("/")[0] not in KERNEL_PKGS:
            continue
        fq = fq_module(rel)
        for name, spec in _collect_static_key_callables(mod.tree).items():
            fq_callables[f"{fq}.{name}"] = spec

    if not fq_callables:
        return
    for rel, mod in modules.items():
        cur_pkg = "/".join(Path(rel).parts[:-1])
        cur_fq = fq_module(rel)
        local: dict[str, StaticSpec] = {}
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.ImportFrom):
                continue
            target = resolve_import(cur_pkg, n, KERNEL_PKGS)
            if target is None or target == cur_fq:
                continue  # same-module calls: per-module pass owns them
            for al in n.names:
                key = f"{target}.{al.name}"
                if key in fq_callables:
                    local[al.asname or al.name] = fq_callables[key]
        _check_value_key_calls(mod, report, local)
