"""tempo_tpu.analysis: kernel-contract & concurrency static checker.

Build-time enforcement of the invariants the device read path depends
on but no runtime test can check structurally: shape-only launch keys,
no host syncs inside jitted bodies, a numpy twin behind every device
kernel the executors dispatch, and lock-guarded module registries.

Run it:  python -m tempo_tpu.analysis --strict
Tier-1:  tests/test_analysis.py runs the same passes over the live
         tree (must stay clean) and over a seeded-violation corpus
         (every rule must still fire).

Scopes (directories relative to the scanned root, normally the
tempo_tpu package):

  * kernel-contract rules (jit-*):   ops/, parallel/
  * concurrency rules (global-/lock-*): services/, util/, ops/, db/,
    chaos/
  * twin registry rules (twin-*):    ops/ + parallel/ vs db/ executors
  * parse-error:                     every scanned file

All passes are pure-AST and stdlib-only: the checker never imports jax
or the code under analysis, so it runs in milliseconds anywhere.
"""

from __future__ import annotations

import time
from pathlib import Path

from .concurrency import run_concurrency_rules
from .core import (  # noqa: F401  (re-exported API)
    RULE_HINTS,
    RULES,
    SCHEMA_VERSION,
    Finding,
    Report,
    SourceModule,
    apply_baseline,
    load_baseline,
    run_pragma_rules,
    walk_py,
)
from .envrules import find_doc_texts, run_env_rules
from .jitrules import run_jit_rules, run_value_key_cross
from .lockgraph import run_lock_graph
from .resiliencerules import run_resilience_rules
from .telemetryrules import run_telemetry_rules
from .twinrules import run_twin_rules

KERNEL_SCOPE = ("ops/", "parallel/")
# chaos/ is in scope on purpose: the fault plane is exactly the kind of
# process-wide registry the concurrency rules exist to guard
CONCURRENCY_SCOPE = ("services/", "util/", "ops/", "db/", "chaos/",
                     "ingest/", "fleet/", "transport/")


def default_root() -> Path:
    """The tempo_tpu package directory this checker ships inside."""
    return Path(__file__).resolve().parents[1]


def _resolve_package_roots(root: Path) -> list[Path]:
    """Re-root a scan aimed above the package (e.g. the repo checkout
    dir): a root whose scope directories hold no Python at all would
    silently run zero scoped rules and report deceptively clean. A
    scope dir counts only if it actually contains .py files, so the
    repo-level ops/ bundle (dashboards, yaml) does not qualify.
    Several sibling packages under one root all get scanned -- falling
    back to the unscoped parent would be the deceptive-clean outcome
    this function exists to prevent."""
    def has_scoped_py(d: Path) -> bool:
        return any(
            next((d / s).glob("*.py"), None) is not None
            for s in ("ops", "parallel", "services", "util", "db"))

    if has_scoped_py(root):
        return [root]
    candidates = [c for c in sorted(root.iterdir())
                  if c.is_dir() and not c.name.startswith(".")
                  and has_scoped_py(c)]
    return candidates or [root]


def run_analysis(root: Path | None = None,
                 files: list[Path] | None = None,
                 scope_files: bool = False) -> Report:
    """Scan a package root (directory walk + scoped passes + twin
    cross-check) or an explicit file list (per-file passes, no twin
    check -- there is no tree to cross-reference). scope_files applies
    the directory scoping to a file list rooted under `root` (--diff
    mode: a changed file outside every scope must not surface findings
    the full scoped run would never report)."""
    report = Report()
    root = Path(root) if root is not None else default_root()

    if files is not None:
        # key by the path as given, not the basename: same-named files
        # in different directories must not collide (and baseline
        # matching on (file, rule) must distinguish them). Under
        # scope_files the key is root-relative so scopes can match.
        todo = []
        for f in files:
            rel = str(f)
            if scope_files:
                try:
                    rel = Path(f).resolve().relative_to(
                        root.resolve()).as_posix()
                except ValueError:
                    pass  # outside the root: unscoped, full passes
            todo.append((Path(f), rel))
        scoped = False
    else:
        roots = _resolve_package_roots(root)
        if len(roots) > 1:
            # sibling packages: full scoped run per package, findings
            # prefixed with the package dir so they stay distinguishable
            from dataclasses import replace

            for r in roots:
                sub = run_analysis(r)
                report.findings.extend(
                    replace(f, file=f"{r.name}/{f.file}")
                    for f in sub.findings)
                report.parse_errors.extend(
                    replace(f, file=f"{r.name}/{f.file}")
                    for f in sub.parse_errors)
                report.files_scanned += sub.files_scanned
                report.suppressed += sub.suppressed
                for k, v in sub.family_ms.items():
                    report.family_ms[k] = report.family_ms.get(k, 0.0) + v
            report.findings.sort(key=lambda f: (f.file, f.line, f.rule))
            return report
        root = roots[0]
        todo = walk_py(root)
        scoped = True

    modules: dict[str, SourceModule] = {}
    for path, rel in todo:
        report.files_scanned += 1
        try:
            modules[rel] = SourceModule.load(path, rel)
        except SyntaxError as e:
            report.parse_errors.append(Finding(
                rel, e.lineno or 1, "parse-error",
                f"does not parse: {e.msg}",
                "fix the syntax error (or run with --skip-unparsable to "
                "scan past it)"))
        except (UnicodeDecodeError, ValueError, OSError) as e:
            report.parse_errors.append(Finding(
                rel, 1, "parse-error", f"unreadable: {e}",
                "fix the encoding (or run with --skip-unparsable)"))

    def timed(family: str, fn, *a) -> None:
        t0 = time.perf_counter()
        fn(*a)
        report.family_ms[family] = (report.family_ms.get(family, 0.0)
                                    + (time.perf_counter() - t0) * 1e3)

    use_scopes = scoped or scope_files
    for rel, mod in modules.items():
        # files at the root of a flat scan (no package layout) get every
        # per-file pass; inside a package layout the directory scopes
        # keep orchestration-only layers out of the kernel rules
        flat = "/" not in rel
        if not use_scopes or flat or rel.startswith(KERNEL_SCOPE):
            timed("kernel", run_jit_rules, mod, report)
        if not use_scopes or flat or rel.startswith(CONCURRENCY_SCOPE):
            timed("concurrency", run_concurrency_rules, mod, report)

    if scoped:
        timed("kernel", run_twin_rules, modules, report)
        timed("kernel", run_value_key_cross, modules, report)
        timed("config", run_env_rules, modules, report,
              find_doc_texts(root))
        timed("telemetry", run_telemetry_rules, modules, report, root)
        timed("resilience", run_resilience_rules, modules, report)
        timed("lockgraph", run_lock_graph, modules, report)

    # LAST: the pragma audit needs every other pass's suppression marks
    timed("pragma", run_pragma_rules, modules, report, scoped)

    report.findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return report
