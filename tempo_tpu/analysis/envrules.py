"""Config-registry contract: every TEMPO_* knob the code reads must be
declared in tempo_tpu/config_registry.py, every declared knob must be
read somewhere, and every declared knob must be documented.

Detection is string-literal based on purpose: every read site in this
codebase spells the env name as a full literal (os.environ.get, the
ENV_DEFAULTS tables, SLOW_THRESHOLDS, f-string-free), so any Constant
exactly matching ``TEMPO_[A-Z0-9_]+`` in package code counts as a
reference. A knob name composed at runtime would evade this -- and
would equally evade an operator grepping for it, which is exactly the
property these rules exist to protect.

The registry itself is read with ast.literal_eval off the parsed tree
(never imported), and docs are plain-text membership checks against
README.md and ops/README.md looked up beside the scan root.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import Report, SourceModule, emit, register_rule

R_UNREGISTERED = register_rule(
    "env-unregistered",
    "code reads a TEMPO_* env var that is not declared in "
    "config_registry.py: the knob is invisible to operators",
    hint="add the name to KNOBS in tempo_tpu/config_registry.py with "
         "type/default/doc")
R_DEAD = register_rule(
    "env-dead",
    "config_registry.py declares a TEMPO_* knob no code reads: the "
    "registry is drifting from reality",
    hint="delete the entry (or wire the knob into the code that was "
         "supposed to read it)")
R_DOC_DRIFT = register_rule(
    "env-doc-drift",
    "registered TEMPO_* knob appears in no shipped doc (README.md / "
    "ops/README.md): operators cannot discover it",
    hint="document the knob in the README config table")

ENV_RE = re.compile(r"^TEMPO_[A-Z0-9_]+$")
REGISTRY_REL = "config_registry.py"


def parse_registry(mod: SourceModule) -> tuple[dict[str, tuple], dict[str, int]]:
    """(KNOBS literal, name -> declaration line) from the parsed tree."""
    knobs: dict[str, tuple] = {}
    lines: dict[str, int] = {}
    for n in mod.tree.body:
        target = None
        if isinstance(n, ast.Assign) and len(n.targets) == 1:
            target = n.targets[0]
        elif isinstance(n, ast.AnnAssign):
            target = n.target
        if not (isinstance(target, ast.Name) and target.id == "KNOBS"
                and isinstance(getattr(n, "value", None), ast.Dict)):
            continue
        try:
            knobs.update(ast.literal_eval(n.value))
        except ValueError:
            continue
        for k in n.value.keys:
            if isinstance(k, ast.Constant):
                lines[k.value] = k.lineno
    return knobs, lines


def _env_reads(mod: SourceModule) -> list[tuple[str, int]]:
    out = []
    for n in ast.walk(mod.tree):
        if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                and ENV_RE.match(n.value)):
            out.append((n.value, n.lineno))
    return out


def run_env_rules(modules: dict[str, SourceModule], report: Report,
                  doc_texts: list[str]) -> None:
    reg_mod = modules.get(REGISTRY_REL)
    if reg_mod is None:
        return  # no registry in this tree: nothing to hold it against
    knobs, knob_lines = parse_registry(reg_mod)

    read_names: set[str] = set()
    for rel, mod in modules.items():
        reads = _env_reads(mod)
        if rel == REGISTRY_REL:
            continue  # declarations are not reads
        read_names.update(name for name, _ in reads)
        for name, line in reads:
            if name not in knobs:
                emit(mod, report, line, R_UNREGISTERED,
                     f"'{name}' read here is not in config_registry.KNOBS",
                     "register it (name, type, default, doc) in "
                     "tempo_tpu/config_registry.py")

    docs = "\n".join(doc_texts)
    for name in knobs:
        line = knob_lines.get(name, 1)
        if name not in read_names:
            emit(reg_mod, report, line, R_DEAD,
                 f"'{name}' is registered but never read",
                 "delete the entry or wire the knob in")
        if doc_texts and name not in docs:
            emit(reg_mod, report, line, R_DOC_DRIFT,
                 f"'{name}' is undocumented (README.md / ops/README.md)",
                 "add it to the README config-knob table")


def find_doc_texts(root: Path) -> list[str]:
    """README.md + ops/README.md at the scan root, else one level up
    (the live layout: tempo_tpu/ is scanned, docs sit beside it)."""
    for base in (root, root.parent):
        found = []
        for rel in ("README.md", "ops/README.md"):
            p = base / rel
            if p.is_file():
                try:
                    found.append(p.read_text(encoding="utf-8"))
                except OSError:
                    pass
        if found:
            return found
    return []
