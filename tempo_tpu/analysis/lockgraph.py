"""Whole-program lock-order analysis.

PR-4's per-module `lock-order` rule catches a lexically inverted pair
inside one file; the deadlocks that actually ship cross a module
boundary -- function f in module A takes lock La then calls into module
B whose helper takes Lb, while a B-side path takes Lb before calling
back into A. Nothing lexical ever sees both orders.

This pass lifts lock acquisition onto the analysis/callgraph engine:

  1. per function: which locks its body acquires lexically (`with`
     contexts passing concurrency's lockish test, TimedLock/TimedRLock
     wrappers included), and which calls it makes *while holding* each;
  2. transitively: Acq*(g) = locks g or anything it reaches acquires;
  3. edges: La -> Lb whenever a path holds La while acquiring Lb
     (lexical nesting, or a held call whose callee reaches an acquire);
  4. cycles: an SCC in the lock digraph is a deadlock shape, reported
     once as `lock-order-global` with a witness call path.

Lock identity is namespaced heuristically -- `self.X` becomes
`<module>.<Class>.X`, module globals become `<module>.X` (resolved
through imports so one shared lock keeps one name), and
`Condition(self.lock)` aliases back to the underlying lock. Cycles
whose every edge is lexical inside a single module are skipped here:
the per-module rule already owns those, with better line anchoring.
"""

from __future__ import annotations

import ast

from .callgraph import CallGraph, ModuleFacts
from .concurrency import _is_lockish
from .core import Report, SourceModule, dotted_name, emit, register_rule

R_GLOBAL_ORDER = register_rule(
    "lock-order-global",
    "whole-program lock acquisition cycle across the callgraph: two "
    "threads entering from different ends deadlock",
    hint="pick one global order for the locks in the cycle (or collapse "
         "them into one lock)")


def _cond_aliases(facts: ModuleFacts) -> dict[str, str]:
    """'Cls.attr' -> 'Cls.other' for `self.attr = Condition(self.other)`
    style aliasing: waiting on the condition holds the underlying lock,
    so both spellings must map to one node in the graph."""
    out: dict[str, str] = {}
    for n in facts.mod.tree.body:  # top-level classes only, one pass
        if not isinstance(n, ast.ClassDef):
            continue
        for m in ast.walk(n):
            if not (isinstance(m, ast.Assign) and len(m.targets) == 1
                    and isinstance(m.value, ast.Call)):
                continue
            callee = m.value.func
            cname = callee.id if isinstance(callee, ast.Name) else (
                callee.attr if isinstance(callee, ast.Attribute) else "")
            if cname != "Condition" or not m.value.args:
                continue
            t, arg = m.targets[0], m.value.args[0]
            td, ad = dotted_name(t), dotted_name(arg)
            if td and ad and td.startswith("self.") \
                    and ad.startswith("self."):
                out[td[5:]] = ad[5:]
    return out


class _FnLocks(ast.NodeVisitor):
    """Lexical lock facts for one function body."""

    def __init__(self, facts: ModuleFacts, class_name: str,
                 aliases: dict[str, str]):
        self.facts = facts
        self.class_name = class_name
        self.aliases = aliases
        self.held: list[str] = []
        self.acquires: dict[str, int] = {}  # label -> first line
        self.pairs: list[tuple[str, str, int]] = []  # lexical L -> M
        self.held_calls: list[tuple[str, str, int]] = []  # (L, callee, line)

    def _label(self, expr: ast.AST) -> str:
        d = dotted_name(expr) or (
            dotted_name(expr.func) if isinstance(expr, ast.Call) else None)
        if d is None:
            return f"{self.facts.fq}.<lock>"
        if d.startswith("self.") and self.class_name:
            attr = self.aliases.get(d[5:], d[5:])
            return f"{self.facts.fq}.{self.class_name}.{attr}"
        root, _, rest = d.partition(".")
        if root in self.facts.module_imports and rest:
            return f"{self.facts.module_imports[root]}.{rest}"
        if root in self.facts.imports and not rest:
            return self.facts.imports[root]
        return f"{self.facts.fq}.{d}"

    def visit_FunctionDef(self, node) -> None:
        return  # nested defs run later, without the held locks

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_With(self, node: ast.With) -> None:
        labels = [self._label(it.context_expr) for it in node.items
                  if _is_lockish(it.context_expr)]
        for lb in labels:
            self.acquires.setdefault(lb, node.lineno)
            for outer in self.held:
                if outer != lb:
                    self.pairs.append((outer, lb, node.lineno))
            self.held.append(lb)
        self.generic_visit(node)
        del self.held[len(self.held) - len(labels):]

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            callee = self.facts.resolve_call(node.func, self.class_name)
            if callee is not None:
                for outer in self.held:
                    self.held_calls.append((outer, callee, node.lineno))
        self.generic_visit(node)


def run_lock_graph(modules: dict[str, SourceModule], report: Report,
                   graph: CallGraph | None = None) -> None:
    graph = graph or CallGraph(modules)
    aliases = {rel: _cond_aliases(f) for rel, f in graph.facts.items()}

    fn_locks: dict[str, _FnLocks] = {}
    for fq in sorted(graph.functions):
        facts, qn, node = graph.functions[fq]
        cls = qn.split(".")[0] if "." in qn else ""
        fl = _FnLocks(facts, cls, aliases.get(facts.rel, {}))
        for stmt in node.body:
            fl.visit(stmt)
        fn_locks[fq] = fl

    # Acq*: locks each function (or anything it reaches) acquires --
    # a fixpoint over the call edges, not a per-function DFS (the DFS
    # form is quadratic over the live tree's ~3k functions)
    acq_star: dict[str, set[str]] = {
        fq: set(fl.acquires) for fq, fl in fn_locks.items()}
    changed = True
    while changed:
        changed = False
        for fq, callees in graph.edges.items():
            mine = acq_star[fq]
            before = len(mine)
            for c in callees:
                mine |= acq_star.get(c, set())
            if len(mine) != before:
                changed = True

    # lock digraph: edge -> (rel, line, lexical, witness-call-path)
    edges: dict[tuple[str, str], tuple[str, int, bool, list[str]]] = {}
    direct_holders: dict[str, set[str]] = {}
    for fq, fl in fn_locks.items():
        for lb in fl.acquires:
            direct_holders.setdefault(lb, set()).add(fq)
    for fq in sorted(fn_locks):
        fl = fn_locks[fq]
        rel = graph.functions[fq][0].rel
        for outer, inner, line in fl.pairs:
            edges.setdefault((outer, inner), (rel, line, True, [fq]))
        for outer, callee, line in fl.held_calls:
            for inner in sorted(acq_star.get(callee, ())):
                if inner == outer or (outer, inner) in edges:
                    continue
                path = graph.witness_path(
                    callee, direct_holders.get(inner, set()))
                edges[(outer, inner)] = (rel, line, False, [fq] + path)

    # cycle detection: DFS from each lock, smallest-label-first, over
    # the lock digraph; each cycle is canonicalized (rotated to its
    # minimal lock) so it reports exactly once
    adj: dict[str, list[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    for v in adj.values():
        v.sort()

    seen_cycles: set[tuple[str, ...]] = set()
    for start in sorted(adj):
        stack: list[tuple[str, list[str]]] = [(start, [start])]
        while stack:
            cur, path = stack.pop()
            for nxt in adj.get(cur, ()):
                if nxt == start:
                    if len(path) < 2:
                        continue  # self-edge can't exist (outer != lb)
                    i = path.index(min(path))
                    canon = tuple(path[i:] + path[:i])
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    _report_cycle(modules, report, edges, list(canon))
                elif nxt not in path and len(path) < 6:
                    stack.append((nxt, path + [nxt]))


def _report_cycle(modules: dict[str, SourceModule], report: Report,
                  edges: dict, cycle: list[str]) -> None:
    ring = cycle + [cycle[0]]
    edge_infos = [edges[(ring[i], ring[i + 1])] for i in range(len(cycle))]
    rels = {rel for rel, _, _, _ in edge_infos}
    all_lexical = all(lex for _, _, lex, _ in edge_infos)
    if all_lexical and len(rels) == 1:
        return  # per-module lock-order owns single-file lexical cycles
    # anchor on the minimal (file, line) edge for a deterministic site
    rel, line, _, _ = min(edge_infos, key=lambda e: (e[0], e[1]))
    witness = max((w for _, _, _, w in edge_infos), key=len)
    mod = modules.get(rel)
    if mod is None:
        return
    emit(mod, report, line, R_GLOBAL_ORDER,
         "lock cycle " + " -> ".join(ring)
         + "; witness call path: " + " -> ".join(witness),
         "pick one global acquisition order for these locks")
