"""Shared interprocedural engine for the whole-program passes.

PR-4's twin gate needed exactly one cross-file question answered --
"which functions reach jax.jit?" -- and buried the machinery for it
(import resolution, per-module facts, a reachability fixpoint) inside
twinrules. The contract families ask the same *shape* of question about
different properties (which call paths hold which locks, which RPC legs
sit behind a breaker), so the machinery lives here now and the rule
modules own only their property.

Everything is AST-only and stdlib-only, like the rest of the package:
the graph is built from one parsed tree per file, nothing is imported.

Resolution model (deliberately first-order):

  * functions are identified by ``<module fq>.<qualname>`` where the
    module fq is the package-root-relative dotted path
    (``db/wal.py`` -> ``db.wal``) and qualname includes one class level
    (``WAL.append``);
  * a call edge resolves through local defs, ``from X import name``,
    ``import X [as y]`` + attribute access, ``self.method(...)`` inside
    a class, and bare-name references (kernels get passed to executors
    as values, so a Load of a function name counts as an edge);
  * anything pointing outside the scanned root (stdlib, third-party)
    resolves to nothing and simply contributes no edge.

That is exact enough for the twin gate and the lock graph; dynamic
dispatch through registries is invisible here on purpose -- those
seams have their own runtime tests.
"""

from __future__ import annotations

import ast
from collections import deque
from pathlib import Path

from .core import SourceModule


def fq_module(rel: str) -> str:
    """'ops/filter.py' -> 'ops.filter' (package-root-relative)."""
    return rel[:-3].replace("/", ".")


def resolve_import(cur_pkg: str, node: ast.ImportFrom,
                   packages: tuple[str, ...]) -> str | None:
    """Package-root-relative module for an ImportFrom, or None when it
    points outside the scanned root (stdlib, third-party). `packages`
    is the set of top-level package dirs the scan actually holds, so an
    absolute `tempo_tpu.ops.x` (or `<any root>.ops.x`) re-anchors at
    the first recognized segment."""
    mod = node.module or ""
    if node.level == 0:
        parts = mod.split(".")
        for i, p in enumerate(parts):
            if p in packages:
                return ".".join(parts[i:])
        return None
    parts = cur_pkg.split("/") if cur_pkg else []
    base = parts[:len(parts) - (node.level - 1)] if node.level - 1 else parts
    if node.level - 1 > len(parts):
        return None
    prefix = ".".join(base)
    return f"{prefix}.{mod}" if prefix and mod else (mod or prefix or None)


class ModuleFacts:
    """Per-module resolution facts: imports, defs (incl. one level of
    class methods), and the names each definition references."""

    def __init__(self, mod: SourceModule, packages: tuple[str, ...]):
        self.rel = mod.rel
        self.fq = fq_module(mod.rel)
        self.mod = mod
        # local name -> fq FUNCTION name (from X import f)
        self.imports: dict[str, str] = {}
        # local name -> fq MODULE name (import X as y / from . import X)
        self.module_imports: dict[str, str] = {}
        self.defs: dict[str, ast.FunctionDef] = {}
        self.classes: set[str] = set()
        # qualname ('f' or 'Cls.m') -> def node
        self.functions: dict[str, ast.FunctionDef] = {}
        cur_pkg = "/".join(Path(mod.rel).parts[:-1])
        for n in ast.walk(mod.tree):
            if isinstance(n, ast.ImportFrom):
                target = resolve_import(cur_pkg, n, packages)
                if target is None:
                    continue
                for al in n.names:
                    local = al.asname or al.name
                    # `from ..db import wal` imports a MODULE; record it
                    # in both maps -- which one applies depends on how
                    # the name is used (wal.append vs wal())
                    self.imports[local] = f"{target}.{al.name}"
                    self.module_imports[local] = f"{target}.{al.name}"
            elif isinstance(n, ast.Import):
                for al in n.names:
                    parts = al.name.split(".")
                    for i, p in enumerate(parts):
                        if p in packages:
                            fqm = ".".join(parts[i:])
                            self.module_imports[al.asname or al.name] = fqm
                            break
        for n in mod.tree.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs[n.name] = n
                self.functions[n.name] = n
            elif isinstance(n, ast.ClassDef):
                self.classes.add(n.name)
                for item in n.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.functions[f"{n.name}.{item.name}"] = item

    # ---------------------------------------------------------- resolve
    def resolve_call(self, node: ast.AST,
                     class_name: str = "") -> str | None:
        """fq function name a Name/Attribute reference resolves to
        within this module, or None."""
        if isinstance(node, ast.Name):
            if node.id in self.defs:
                return f"{self.fq}.{node.id}"
            if node.id in self.imports:
                return self.imports[node.id]
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if (isinstance(base, ast.Name) and base.id == "self"
                    and class_name):
                qn = f"{class_name}.{node.attr}"
                if qn in self.functions:
                    return f"{self.fq}.{qn}"
                return None
            if isinstance(base, ast.Name):
                fqm = self.module_imports.get(base.id)
                if fqm is not None:
                    return f"{fqm}.{node.attr}"
            # Class.method on a locally-defined or imported class
            if isinstance(base, ast.Name) and base.id in self.classes:
                qn = f"{base.id}.{node.attr}"
                if qn in self.functions:
                    return f"{self.fq}.{qn}"
        return None

    def calls_of(self, fn: ast.FunctionDef, class_name: str = "",
                 bare_names: bool = True) -> set[str]:
        """fq names this definition references. With bare_names, a Load
        of a function name counts even outside a call (kernels get
        passed to executors/vmaps as values)."""
        out: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Call):
                r = self.resolve_call(n.func, class_name)
                if r:
                    out.add(r)
            elif (bare_names and isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)):
                r = self.resolve_call(n, class_name)
                if r:
                    out.add(r)
        return out


class CallGraph:
    """Whole-tree call graph: functions keyed by fq name, resolved call
    edges, reachability fixpoints, and BFS witness paths."""

    def __init__(self, modules: dict[str, SourceModule]):
        self.packages = tuple(sorted(
            {rel.split("/")[0] for rel in modules if "/" in rel}))
        self.facts: dict[str, ModuleFacts] = {}
        self.functions: dict[str, tuple[ModuleFacts, str,
                                        ast.FunctionDef]] = {}
        self.edges: dict[str, set[str]] = {}
        for rel, mod in modules.items():
            f = ModuleFacts(mod, self.packages)
            self.facts[rel] = f
            for qn, node in f.functions.items():
                self.functions[f"{f.fq}.{qn}"] = (f, qn, node)
        for fq, (f, qn, node) in self.functions.items():
            cls = qn.split(".")[0] if "." in qn else ""
            callees = f.calls_of(node, class_name=cls)
            # keep only edges that land on a known function
            self.edges[fq] = {c for c in callees if c in self.functions}

    def reachable_from(self, fq: str) -> set[str]:
        """Transitive callees of one function (not including itself
        unless recursive)."""
        seen: set[str] = set()
        stack = list(self.edges.get(fq, ()))
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(self.edges.get(cur, ()))
        return seen

    def witness_path(self, src: str, targets: set[str]) -> list[str]:
        """Shortest call path src -> any target ([src] when src itself
        is a target, [] when unreachable)."""
        if src in targets:
            return [src]
        prev: dict[str, str] = {}
        q = deque([src])
        seen = {src}
        while q:
            cur = q.popleft()
            for nxt in sorted(self.edges.get(cur, ())):
                if nxt in seen:
                    continue
                seen.add(nxt)
                prev[nxt] = cur
                if nxt in targets:
                    path = [nxt]
                    while path[-1] != src:
                        path.append(prev[path[-1]])
                    return list(reversed(path))
                q.append(nxt)
        return []


def reachable_fixpoint(seeds: set[str],
                       edges: dict[str, set[str]]) -> set[str]:
    """Callers-of-closure: everything that reaches a seed through the
    edge relation (the twin gate's 'touches jit' question)."""
    reach = set(seeds)
    changed = True
    while changed:
        changed = False
        for fq, callees in edges.items():
            if fq not in reach and callees & reach:
                reach.add(fq)
                changed = True
    return reach
