"""CLI for the static checker.

    python -m tempo_tpu.analysis [paths...] [--strict] [--json]
                                 [--baseline FILE] [--skip-unparsable]
                                 [--list-rules]

Paths may be package roots (directory: full scoped run including the
twin cross-check) or individual .py files (per-file passes only).
Default: the tempo_tpu package this module ships in.

Exit codes:
  0  clean (or findings only outside --strict / covered by --baseline)
  1  findings remain under --strict
  2  a scanned file does not parse (unless --skip-unparsable): an
     unparsable file is an unvouched-for file, not a clean one
  3  invocation error (e.g. the --baseline file is missing or corrupt)
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import RULES, Report, apply_baseline, default_root, load_baseline, run_analysis


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tempo_tpu.analysis",
        description="kernel-contract & concurrency static checker")
    ap.add_argument("paths", nargs="*",
                    help="package roots or .py files (default: tempo_tpu)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any finding not covered by --baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", metavar="FILE",
                    help="accepted-findings JSON (ANALYSIS_BASELINE.json "
                         "format); matching (file, rule) pairs don't fail "
                         "--strict")
    ap.add_argument("--skip-unparsable", action="store_true",
                    help="report parse failures as findings but do not "
                         "exit 2 for them")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id and description, then exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}: {desc}")
        return 0

    t0 = time.perf_counter()
    roots: list[Path] = []
    files: list[Path] = []
    for p in args.paths:
        (roots if Path(p).is_dir() else files).append(Path(p))
    if not roots and not files:
        roots = [default_root()]

    report = Report()
    for root in roots:
        sub = run_analysis(root)
        _merge(report, sub)
    if files:
        _merge(report, run_analysis(files[0].parent, files=files))

    if args.baseline:
        try:
            apply_baseline(report, load_baseline(Path(args.baseline)))
        except (OSError, ValueError, KeyError, TypeError) as e:
            # structured shim (util/log is stdlib-only, like this CLI)
            from ..util.log import get_logger

            get_logger("analysis").error(
                "cannot read baseline %s: %s", args.baseline, e)
            return 3

    wall_ms = (time.perf_counter() - t0) * 1e3
    if args.as_json:
        out = report.to_dict()
        out["wall_ms"] = round(wall_ms, 2)
        print(json.dumps(out, indent=2))
    else:
        for f in report.parse_errors:
            print(f.render())
        for f in report.findings:
            print(f.render())
        print(f"{report.files_scanned} files, {len(RULES)} rules, "
              f"{len(report.findings)} finding(s), "
              f"{len(report.parse_errors)} parse error(s), "
              f"{report.suppressed} suppressed, "
              f"{report.baselined} baselined, {wall_ms:.0f} ms")

    if report.parse_errors and not args.skip_unparsable:
        return 2
    if args.strict and report.findings:
        return 1
    return 0


def _merge(into: Report, sub: Report) -> None:
    into.findings.extend(sub.findings)
    into.parse_errors.extend(sub.parse_errors)
    into.files_scanned += sub.files_scanned
    into.suppressed += sub.suppressed
    into.baselined += sub.baselined


if __name__ == "__main__":
    sys.exit(main())
