"""CLI for the static checker.

    python -m tempo_tpu.analysis [paths...] [--strict] [--json]
                                 [--baseline FILE] [--skip-unparsable]
                                 [--list-rules] [--diff REV]

Paths may be package roots (directory: full scoped run including the
twin cross-check) or individual .py files (per-file passes only).
Default: the tempo_tpu package this module ships in.

--diff REV scans only the .py files `git diff --name-only REV` reports
under the scan root (per-file passes; the cross-file families need the
whole tree). An empty diff is a clean exit; a failing git invocation
falls back to the full run -- "couldn't compute the diff" must degrade
to MORE checking, never less.

Exit codes:
  0  clean (or findings only outside --strict / covered by --baseline)
  1  findings remain under --strict
  2  a scanned file does not parse (unless --skip-unparsable): an
     unparsable file is an unvouched-for file, not a clean one
  3  invocation error (e.g. the --baseline file is missing or corrupt)
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

from . import (
    RULE_HINTS,
    RULES,
    Report,
    apply_baseline,
    default_root,
    load_baseline,
    run_analysis,
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tempo_tpu.analysis",
        description="kernel-contract & concurrency static checker")
    ap.add_argument("paths", nargs="*",
                    help="package roots or .py files (default: tempo_tpu)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any finding not covered by --baseline")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--baseline", metavar="FILE",
                    help="accepted-findings JSON (ANALYSIS_BASELINE.json "
                         "format); matching (file, rule) pairs don't fail "
                         "--strict")
    ap.add_argument("--skip-unparsable", action="store_true",
                    help="report parse failures as findings but do not "
                         "exit 2 for them")
    ap.add_argument("--list-rules", action="store_true",
                    help="print every rule id, description and fix hint, "
                         "then exit")
    ap.add_argument("--diff", metavar="REV",
                    help="scan only files changed since REV (git diff "
                         "--name-only); falls back to a full run if the "
                         "diff cannot be computed")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}: {desc}")
            hint = RULE_HINTS.get(rid)
            if hint:
                print(f"    fix: {hint}")
        return 0

    t0 = time.perf_counter()
    roots: list[Path] = []
    files: list[Path] = []
    for p in args.paths:
        (roots if Path(p).is_dir() else files).append(Path(p))
    if not roots and not files:
        roots = [default_root()]

    diff_root: Path | None = None
    if args.diff and not files:
        diff = _diff_paths(args.diff, roots)
        if diff is None:
            print(f"analysis: cannot compute git diff vs {args.diff!r}; "
                  "falling back to the full run", file=sys.stderr)
        else:
            diff_root, files, roots = roots[0], diff, []

    report = Report()
    for root in roots:
        sub = run_analysis(root)
        _merge(report, sub)
    if files and diff_root is not None:
        _merge(report, run_analysis(diff_root, files=files,
                                    scope_files=True))
    elif files:
        _merge(report, run_analysis(files[0].parent, files=files))

    if args.baseline:
        try:
            apply_baseline(report, load_baseline(Path(args.baseline)))
        except (OSError, ValueError, KeyError, TypeError) as e:
            # structured shim (util/log is stdlib-only, like this CLI)
            from ..util.log import get_logger

            get_logger("analysis").error(
                "cannot read baseline %s: %s", args.baseline, e)
            return 3

    wall_ms = (time.perf_counter() - t0) * 1e3
    if args.as_json:
        out = report.to_dict()
        out["wall_ms"] = round(wall_ms, 2)
        print(json.dumps(out, indent=2))
    else:
        for f in report.parse_errors:
            print(f.render())
        for f in report.findings:
            print(f.render())
        print(f"{report.files_scanned} files, {len(RULES)} rules, "
              f"{len(report.findings)} finding(s), "
              f"{len(report.parse_errors)} parse error(s), "
              f"{report.suppressed} suppressed, "
              f"{report.baselined} baselined, {wall_ms:.0f} ms")

    if report.parse_errors and not args.skip_unparsable:
        return 2
    if args.strict and report.errors():
        return 1  # warn-severity findings print but never gate
    return 0


def _diff_paths(rev: str, roots: list[Path]) -> list[Path] | None:
    """Changed .py files under the scan roots per `git diff --name-only
    REV`, or None when git cannot answer (missing binary, not a repo,
    bad rev): the caller falls back to the FULL run -- a broken diff
    must degrade to more checking, never less. Deleted files have
    nothing to scan and are dropped."""
    import subprocess

    def git(*argv: str) -> str | None:
        try:
            r = subprocess.run(["git", *argv], cwd=str(roots[0]),
                               capture_output=True, text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return r.stdout if r.returncode == 0 else None

    top = git("rev-parse", "--show-toplevel")
    names = git("diff", "--name-only", rev, "--")
    if top is None or names is None:
        return None
    topdir = Path(top.strip())
    root_strs = [r.resolve().as_posix() + "/" for r in roots]
    out: list[Path] = []
    for name in names.splitlines():
        if not name.endswith(".py"):
            continue
        p = topdir / name
        if p.is_file() and any(p.resolve().as_posix().startswith(rs)
                               for rs in root_strs):
            out.append(p)
    return out


def _merge(into: Report, sub: Report) -> None:
    into.findings.extend(sub.findings)
    into.parse_errors.extend(sub.parse_errors)
    into.files_scanned += sub.files_scanned
    into.suppressed += sub.suppressed
    into.baselined += sub.baselined
    for k, v in sub.family_ms.items():
        into.family_ms[k] = into.family_ms.get(k, 0.0) + v


if __name__ == "__main__":
    sys.exit(main())
