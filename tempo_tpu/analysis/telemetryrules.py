"""Telemetry contract: the metric families the code emits vs the names
ops/alerts.yaml, ops/dashboard-overview.json and the ops/README runbook
reference. An alert on a family nothing emits pages nobody -- silently.

Family extraction is AST-based over every scanned module:

  * Counter/Gauge/Histogram constructor calls (aliased imports like
    ``_Gauge`` count: callee name is matched stripped of leading
    underscores, case-insensitive) take their first string arg;
  * module-level ``METRIC_FAMILIES`` tuples declare families built
    dynamically at runtime (util/slo's prefixed gauges);
  * f-strings whose leading constant is ``tempo_x ...``/``tempo_x{``
    (hand-rendered exposition lines) contribute the name part;
  * a ``tempo_*`` string constant passed as a call's first argument or
    assigned to a ``*_NAME``/``*_FAMILY`` constant counts too.

Histogram families render as ``_bucket``/``_sum``/``_count`` series, so
references are matched with those suffixes stripped as a fallback.

Label hygiene: a label rendered from request-derived data (tenant, key,
query, org) must pass through an escaping helper (util/metrics
``escape_label`` or a local ``_esc*``) -- a raw ``{tenant}`` in a label
f-string is an unbounded-cardinality + exposition-injection bug
(PR-7's lesson).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from .core import Finding, Report, SourceModule, emit, register_rule

R_ALERT_UNKNOWN = register_rule(
    "alert-unknown-metric",
    "ops/alerts.yaml references a metric family no code emits: the "
    "alert can never fire",
    hint="fix the family name in the alert expr (or emit the metric)")
R_DASH_UNKNOWN = register_rule(
    "dashboard-unknown-metric",
    "ops/dashboard-overview.json references a metric family no code "
    "emits: the panel renders empty",
    hint="fix the family name in the panel expr")
R_LABEL_CARD = register_rule(
    "metric-label-cardinality",
    "request-derived label value rendered into a metric label without "
    "the escaping helper: cardinality + exposition injection",
    hint="wrap the value in util.metrics.escape_label()")
R_ORPHAN = register_rule(
    "metric-orphan",
    "metric family emitted but absent from the ops/README runbook "
    "mapping: on-call cannot act on it",
    hint="add the family to ops/README's metric->runbook table",
    severity="warn")

FAMILY_RE = re.compile(r"^tempo_[a-z0-9_]+$")
# tokens in ops files; names followed by / or . are paths/modules
REF_RE = re.compile(r"tempo_[a-z0-9_]+")
CTOR_NAMES = {"counter", "gauge", "histogram"}
REQUEST_LABELS = ("tenant", "key", "query", "org")
HIST_SUFFIXES = ("_bucket", "_sum", "_count")
NAME_SUFFIXES = ("_NAME", "_FAMILY")


def _ctor_name(call: ast.Call) -> str:
    f = call.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name.lstrip("_").lower()


def extract_families(mod: SourceModule) -> dict[str, int]:
    """family -> first emission line in this module."""
    out: dict[str, int] = {}

    def note(name: str, line: int) -> None:
        if FAMILY_RE.match(name) and name not in out:
            out[name] = line

    for n in ast.walk(mod.tree):
        if isinstance(n, ast.Call):
            args = n.args
            if args and isinstance(args[0], ast.Constant) \
                    and isinstance(args[0].value, str):
                if _ctor_name(n) in CTOR_NAMES:
                    note(args[0].value, args[0].lineno)
                elif FAMILY_RE.match(args[0].value):
                    # TEL.xyz("tempo_...") style emission helpers
                    note(args[0].value, args[0].lineno)
        elif isinstance(n, ast.JoinedStr) and n.values:
            first = n.values[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                m = re.match(r"(tempo_[a-z0-9_]+)[ {]", first.value)
                if m:
                    note(m.group(1), n.lineno)
        elif isinstance(n, ast.Assign) and len(n.targets) == 1:
            t, v = n.targets[0], n.value
            if not isinstance(t, ast.Name):
                continue
            if t.id == "METRIC_FAMILIES" and isinstance(v, (ast.Tuple,
                                                            ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        note(el.value, el.lineno)
            elif t.id.endswith(NAME_SUFFIXES) and isinstance(v, ast.Constant) \
                    and isinstance(v.value, str):
                note(v.value, v.lineno)
    return out


# ---------------------------------------------------------------- labels
def _is_escape_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else "")
    return name.lstrip("_").startswith("esc")


def _escaped_names(fn: ast.AST) -> set[str]:
    """Local names bound from an escape call (t = escape_label(x))."""
    out: set[str] = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and _is_escape_call(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _check_labels(mod: SourceModule, report: Report) -> None:
    # innermost-function scoping: each f-string is judged against the
    # escaped-locals of its nearest enclosing def (module level = whole
    # tree minus function bodies)
    def walk_scope(scope: ast.AST) -> None:
        escaped = _escaped_names(scope)
        stack = list(ast.iter_child_nodes(scope))
        strings: list[ast.JoinedStr] = []
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk_scope(n)
                continue
            if isinstance(n, ast.JoinedStr):
                strings.append(n)
            stack.extend(ast.iter_child_nodes(n))
        for n in strings:
            for i, part in enumerate(n.values[:-1]):
                if not (isinstance(part, ast.Constant)
                        and isinstance(part.value, str)):
                    continue
                label = next((lb for lb in REQUEST_LABELS
                              if part.value.endswith(f'{lb}="')), None)
                if label is None:
                    continue
                nxt = n.values[i + 1]
                if not isinstance(nxt, ast.FormattedValue):
                    continue
                v = nxt.value
                if _is_escape_call(v):
                    continue
                if isinstance(v, ast.Name) and v.id in escaped:
                    continue
                emit(mod, report, n.lineno, R_LABEL_CARD,
                     f'label {label}="..." rendered from an unescaped '
                     "request value",
                     "pass it through util.metrics.escape_label first")

    walk_scope(mod.tree)


# ------------------------------------------------------------- ops files
def _ops_refs(text: str, skip_comments: bool) -> list[tuple[str, int]]:
    out = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        if skip_comments and line.lstrip().startswith("#"):
            continue
        for m in REF_RE.finditer(line):
            end = m.end()
            if end < len(line) and line[end] in "/.":
                continue  # a path or module name, not a metric
            if m.group(0) == "tempo_tpu":
                continue
            out.append((m.group(0), lineno))
    return out


def _known(ref: str, families: set[str]) -> bool:
    if ref in families:
        return True
    for suf in HIST_SUFFIXES:
        if ref.endswith(suf) and ref[:-len(suf)] in families:
            return True
    return False


def find_ops_file(root: Path, rel: str) -> Path | None:
    for base in (root, root.parent):
        p = base / rel
        if p.is_file():
            return p
    return None


def run_telemetry_rules(modules: dict[str, SourceModule], report: Report,
                        root: Path) -> None:
    families: dict[str, tuple[str, int]] = {}  # family -> (rel, line)
    for rel, mod in modules.items():
        for fam, line in extract_families(mod).items():
            families.setdefault(fam, (rel, line))
        _check_labels(mod, report)
    if not families:
        return  # a tree that emits nothing has no telemetry contract
    fam_set = set(families)

    alerts = find_ops_file(root, "ops/alerts.yaml")
    if alerts is not None:
        for ref, line in _ops_refs(alerts.read_text(encoding="utf-8"),
                                   skip_comments=True):
            if not _known(ref, fam_set):
                report.findings.append(Finding(
                    "ops/alerts.yaml", line, R_ALERT_UNKNOWN,
                    f"alert references '{ref}' which nothing emits",
                    "fix the family name (or emit the metric)"))

    dash = find_ops_file(root, "ops/dashboard-overview.json")
    if dash is not None:
        for ref, line in _ops_refs(dash.read_text(encoding="utf-8"),
                                   skip_comments=False):
            if not _known(ref, fam_set):
                report.findings.append(Finding(
                    "ops/dashboard-overview.json", line, R_DASH_UNKNOWN,
                    f"panel references '{ref}' which nothing emits",
                    "fix the family name in the panel expr"))

    ops_readme = find_ops_file(root, "ops/README.md")
    if ops_readme is not None:
        runbook = ops_readme.read_text(encoding="utf-8")
        for fam, (rel, line) in sorted(families.items()):
            if fam not in runbook:
                mod = modules[rel]
                emit(mod, report, line, R_ORPHAN,
                     f"'{fam}' has no ops/README runbook entry",
                     "add it to the metric->runbook mapping table")
