"""Shared plumbing for the static checker: findings, rule registry,
ignore pragmas, file walking, the baseline filter, and the report.

Everything in this package is stdlib-only on purpose -- importing jax
just to *lint* kernel code would cost seconds of startup and tie the
checker to an accelerator runtime it never needs. The passes see the
tree exactly as `ast` parses it; nothing is imported or executed.

Suppression: a finding is dropped when its line (or the line above it)
carries `# tempo: ignore[rule-id]` (comma-separate several ids; a bare
`# tempo: ignore` suppresses every rule on that line). Pragmas should
carry a reason after the bracket -- the fixture tests keep the live
tree honest, but the reason is for the human reading the code.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

# rule-id -> one-line description; passes register at import time so the
# CLI's --list-rules and the bench row see one authoritative set.
# RULE_HINTS carries the one-line fix hint --list-rules prints beside
# each id; RULE_SEVERITY marks the warn-only rules ("warn" findings
# print and count but never fail --strict).
RULES: dict[str, str] = {
    "parse-error": "file does not parse; the checker cannot vouch for it",
}
RULE_HINTS: dict[str, str] = {
    "parse-error": "fix the syntax error (or --skip-unparsable to scan past)",
}
RULE_SEVERITY: dict[str, str] = {}

# the documented --json shape; bump when a field changes meaning
SCHEMA_VERSION = 2

IGNORE_RE = re.compile(
    r"#\s*tempo:\s*ignore(?:\[([A-Za-z0-9_\-, ]+)\])?[ \t]*(.*)$")


def register_rule(rule_id: str, description: str, hint: str = "",
                  severity: str = "error") -> str:
    RULES[rule_id] = description
    if hint:
        RULE_HINTS[rule_id] = hint
    if severity != "error":
        RULE_SEVERITY[rule_id] = severity
    return rule_id


def rule_severity(rule_id: str) -> str:
    return RULE_SEVERITY.get(rule_id, "error")


@dataclass(frozen=True)
class Finding:
    file: str  # path relative to the scan root
    line: int
    rule: str
    message: str
    hint: str = ""
    severity: str = "error"  # "warn" findings never fail --strict

    def render(self) -> str:
        tag = self.rule if self.severity == "error" else f"{self.rule}:warn"
        s = f"{self.file}:{self.line}: [{tag}] {self.message}"
        if self.hint:
            s += f" (fix: {self.hint})"
        return s

    def to_dict(self) -> dict:
        return {"file": self.file, "line": self.line, "rule": self.rule,
                "message": self.message, "hint": self.hint,
                "severity": self.severity}


@dataclass
class SourceModule:
    """One parsed file plus its pragma index."""

    path: Path
    rel: str  # forward-slash path relative to the scan root
    text: str
    tree: ast.Module
    # line -> set of suppressed rule ids ("*" = all)
    pragmas: dict[int, set[str]] = field(default_factory=dict)
    # line -> the pragma carries trailing reason text
    pragma_reasons: dict[int, bool] = field(default_factory=dict)
    # pragma lines that actually suppressed a finding this run
    pragma_used: set[int] = field(default_factory=set)

    @classmethod
    def load(cls, path: Path, rel: str) -> "SourceModule":
        text = path.read_text(encoding="utf-8")
        tree = ast.parse(text, filename=str(path))  # SyntaxError -> caller
        pragmas: dict[int, set[str]] = {}
        reasons: dict[int, bool] = {}
        # only real COMMENT tokens count: a docstring *describing* the
        # pragma syntax must not register as a suppression (and must
        # not trip the pragma-unused audit)
        import io
        import tokenize

        try:
            tokens = list(tokenize.generate_tokens(io.StringIO(text).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = IGNORE_RE.search(tok.string)
            if m:
                i = tok.start[0]
                rules = m.group(1)
                pragmas[i] = ({r.strip() for r in rules.split(",")} if rules
                              else {"*"})
                # a chained `# ...` marker after the pragma is its own
                # annotation, not the suppression's justification
                reason = re.sub(r"#.*$", "", m.group(2) or "").strip()
                reasons[i] = bool(reason)
        return cls(path=path, rel=rel, text=text, tree=tree, pragmas=pragmas,
                   pragma_reasons=reasons)

    def suppressed(self, line: int, rule: str) -> bool:
        for ln in (line, line - 1):
            rules = self.pragmas.get(ln)
            if rules and ("*" in rules or rule in rules):
                self.pragma_used.add(ln)
                return True
        return False


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    parse_errors: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    suppressed: int = 0
    baselined: int = 0
    # rule family -> wall ms, filled by run_analysis (bench trajectory)
    family_ms: dict[str, float] = field(default_factory=dict)

    def errors(self) -> list[Finding]:
        """The findings --strict gates on (warn-severity ones don't)."""
        return [f for f in self.findings if f.severity == "error"]

    def to_dict(self) -> dict:
        return {
            "schema_version": SCHEMA_VERSION,
            "rules": dict(sorted(RULES.items())),
            "files_scanned": self.files_scanned,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "family_ms": {k: round(v, 2)
                          for k, v in sorted(self.family_ms.items())},
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.file, f.line, f.rule))],
            "parse_errors": [f.to_dict() for f in self.parse_errors],
        }


def load_baseline(path: Path) -> set[tuple[str, str]]:
    """Accepted-findings file: matches on (file, rule) so line drift in
    unrelated edits does not resurrect an accepted finding."""
    data = json.loads(path.read_text(encoding="utf-8"))
    return {(f["file"], f["rule"]) for f in data.get("findings", [])}


def apply_baseline(report: Report, baseline: set[tuple[str, str]]) -> None:
    kept = []
    for f in report.findings:
        if (f.file, f.rule) in baseline:
            report.baselined += 1
        else:
            kept.append(f)
    report.findings = kept


def walk_py(root: Path) -> list[tuple[Path, str]]:
    out = []
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        out.append((p, p.relative_to(root).as_posix()))
    return out


def emit(module: SourceModule, report: Report, line: int, rule: str,
         message: str, hint: str = "") -> None:
    """Route one raw finding through the pragma filter into the report."""
    if module.suppressed(line, rule):
        report.suppressed += 1
        return
    report.findings.append(Finding(module.rel, line, rule, message, hint,
                                   severity=rule_severity(rule)))


R_PRAGMA_NO_REASON = register_rule(
    "pragma-no-reason",
    "a `# tempo: ignore[...]` pragma without a trailing reason: the "
    "suppression is policy, the reason is the review record",
    hint="append why the violation is intentional after the bracket")
R_PRAGMA_UNUSED = register_rule(
    "pragma-unused",
    "a `# tempo: ignore[...]` pragma that suppressed nothing this run: "
    "the violation it excused is gone (or the rule id is misspelled)",
    hint="delete the stale pragma (or fix the rule id inside the bracket)")


def run_pragma_rules(modules: dict[str, "SourceModule"], report: Report,
                     check_unused: bool = True) -> None:
    """Audit the suppressions themselves. MUST run after every other
    pass: pragma_used is only complete once all emits have happened.
    check_unused is off in file mode (--diff): the cross-file passes
    don't run there, so their suppressions would read as stale."""
    for mod in modules.values():
        for line in sorted(mod.pragmas):
            if not mod.pragma_reasons.get(line):
                emit(mod, report, line, R_PRAGMA_NO_REASON,
                     "suppression carries no reason",
                     "add the why after the bracket: "
                     "# tempo: ignore[rule] <reason>")
            if check_unused and line not in mod.pragma_used:
                emit(mod, report, line, R_PRAGMA_UNUSED,
                     "suppression matched no finding in this run",
                     "delete it, or fix the rule id it names")


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
