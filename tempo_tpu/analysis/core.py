"""Shared plumbing for the static checker: findings, rule registry,
ignore pragmas, file walking, the baseline filter, and the report.

Everything in this package is stdlib-only on purpose -- importing jax
just to *lint* kernel code would cost seconds of startup and tie the
checker to an accelerator runtime it never needs. The passes see the
tree exactly as `ast` parses it; nothing is imported or executed.

Suppression: a finding is dropped when its line (or the line above it)
carries `# tempo: ignore[rule-id]` (comma-separate several ids; a bare
`# tempo: ignore` suppresses every rule on that line). Pragmas should
carry a reason after the bracket -- the fixture tests keep the live
tree honest, but the reason is for the human reading the code.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

# rule-id -> one-line description; passes register at import time so the
# CLI's --list-rules and the bench row see one authoritative set
RULES: dict[str, str] = {
    "parse-error": "file does not parse; the checker cannot vouch for it",
}

IGNORE_RE = re.compile(r"#\s*tempo:\s*ignore(?:\[([A-Za-z0-9_\-, ]+)\])?")


def register_rule(rule_id: str, description: str) -> str:
    RULES[rule_id] = description
    return rule_id


@dataclass(frozen=True)
class Finding:
    file: str  # path relative to the scan root
    line: int
    rule: str
    message: str
    hint: str = ""

    def render(self) -> str:
        s = f"{self.file}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            s += f" (fix: {self.hint})"
        return s

    def to_dict(self) -> dict:
        return {"file": self.file, "line": self.line, "rule": self.rule,
                "message": self.message, "hint": self.hint}


@dataclass
class SourceModule:
    """One parsed file plus its pragma index."""

    path: Path
    rel: str  # forward-slash path relative to the scan root
    text: str
    tree: ast.Module
    # line -> set of suppressed rule ids ("*" = all)
    pragmas: dict[int, set[str]] = field(default_factory=dict)

    @classmethod
    def load(cls, path: Path, rel: str) -> "SourceModule":
        text = path.read_text(encoding="utf-8")
        tree = ast.parse(text, filename=str(path))  # SyntaxError -> caller
        pragmas: dict[int, set[str]] = {}
        for i, line in enumerate(text.splitlines(), start=1):
            m = IGNORE_RE.search(line)
            if m:
                rules = m.group(1)
                pragmas[i] = ({r.strip() for r in rules.split(",")} if rules
                              else {"*"})
        return cls(path=path, rel=rel, text=text, tree=tree, pragmas=pragmas)

    def suppressed(self, line: int, rule: str) -> bool:
        for ln in (line, line - 1):
            rules = self.pragmas.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    parse_errors: list[Finding] = field(default_factory=list)
    files_scanned: int = 0
    suppressed: int = 0
    baselined: int = 0

    def to_dict(self) -> dict:
        return {
            "rules": dict(sorted(RULES.items())),
            "files_scanned": self.files_scanned,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.file, f.line, f.rule))],
            "parse_errors": [f.to_dict() for f in self.parse_errors],
        }


def load_baseline(path: Path) -> set[tuple[str, str]]:
    """Accepted-findings file: matches on (file, rule) so line drift in
    unrelated edits does not resurrect an accepted finding."""
    data = json.loads(path.read_text(encoding="utf-8"))
    return {(f["file"], f["rule"]) for f in data.get("findings", [])}


def apply_baseline(report: Report, baseline: set[tuple[str, str]]) -> None:
    kept = []
    for f in report.findings:
        if (f.file, f.rule) in baseline:
            report.baselined += 1
        else:
            kept.append(f)
    report.findings = kept


def walk_py(root: Path) -> list[tuple[Path, str]]:
    out = []
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        out.append((p, p.relative_to(root).as_posix()))
    return out


def emit(module: SourceModule, report: Report, line: int, rule: str,
         message: str, hint: str = "") -> None:
    """Route one raw finding through the pragma filter into the report."""
    if module.suppressed(line, rule):
        report.suppressed += 1
        return
    report.findings.append(Finding(module.rel, line, rule, message, hint))


def dotted_name(node: ast.AST) -> str | None:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
