"""Concurrency lint over services/, util/, ops/, db/, chaos/ and
ingest/.

The process-wide registries this codebase leans on (TEL, the staged
LRU, RequestQueue rotation) are exactly the state the mesh-dispatch
race and the staged-cache weakref leak corrupted at runtime in earlier
PRs. These passes make the locking discipline structural:

  * module-level mutable state (dicts/lists/sets/deques, and module
    globals rebound via `global`) must be mutated under a lock.
    Convention: functions named `*_locked` are exempt -- their contract
    is "caller holds the lock" (ops/stage._evict_over_budget_locked);
    module top-level statements run at import time, single-threaded.
  * nested lock acquisitions must order consistently module-wide; an
    inverted pair in two call paths is a deadlock waiting for load.
  * bare `lock.acquire()` without an immediate try/finally release
    leaks the lock on any exception between acquire and release.

Lock identification is heuristic on purpose: any `with` context whose
dotted name contains "lock" counts as holding one, and a statement-form
`lock.acquire()` immediately followed by a try whose finally releases
the same lock counts for the try body. We verify that *a* lock is held,
not that it is the right one -- the wrong-lock case is rare and the
pragma escape hatch documents the intentional ones. Value-form acquires
(`ok = lock.acquire(timeout=...)`, `if lock.acquire(blocking=False):`)
are deliberately out of scope: those are try-lock idioms that cannot
use `with`, and their release discipline is control-flow-dependent in
ways a lexical pass would only misjudge.
"""

from __future__ import annotations

import ast
import re

from .core import Report, SourceModule, dotted_name, emit, register_rule

R_GLOBAL = register_rule(
    "global-mutation-unlocked",
    "module-level mutable state mutated outside any lock: concurrent "
    "queriers interleave and corrupt the registry")
R_LOCK_ORDER = register_rule(
    "lock-order",
    "locks acquired in inconsistent nesting order across functions in "
    "this module: two threads taking opposite orders deadlock")
R_BARE_ACQUIRE = register_rule(
    "lock-bare-acquire",
    "lock.acquire() without an immediate try/finally release leaks the "
    "lock on any exception in between")

MUTATORS = {"append", "add", "update", "pop", "popitem", "setdefault",
            "remove", "discard", "clear", "extend", "insert",
            "appendleft", "popleft", "move_to_end", "__setitem__"}
MUTABLE_CTORS = {"dict", "list", "set", "defaultdict", "deque",
                 "OrderedDict", "Counter"}


_LOCK_TOKENS = {"lock", "rlock", "mutex", "cv", "cond", "condition",
                "timedlock", "timedrlock"}


def _is_lockish(expr: ast.AST) -> bool:
    """Token match, not substring: this codebase's primary domain noun
    is 'block', so `with staged_block:` must NOT read as a lock.
    Condition variables count (cv/cond tokens): `with self._cv:` holds
    the condition's underlying lock -- the stream/compaction pipelines'
    turnstile-and-gate shape. The profiler's TimedLock/TimedRLock
    wrappers (util/profiler) count too: a hot lock adopting contention
    timing must keep counting as a lock to every concurrency rule."""
    d = dotted_name(expr)
    if d is None and isinstance(expr, ast.Call):
        d = dotted_name(expr.func)
    if d is None:
        return False
    return bool(_LOCK_TOKENS & set(re.split(r"[._]+", d.lower())))


def _module_mutables(tree: ast.Module) -> dict[str, int]:
    """name -> definition line for module-level mutable containers."""
    out: dict[str, int] = {}
    for n in tree.body:
        targets: list[ast.expr] = []
        value = None
        if isinstance(n, ast.Assign):
            targets, value = n.targets, n.value
        elif isinstance(n, ast.AnnAssign) and n.value is not None:
            targets, value = [n.target], n.value
        if value is None:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set,
                                     ast.DictComp, ast.ListComp, ast.SetComp))
        if isinstance(value, ast.Call):
            f = value.func
            fname = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            mutable = fname in MUTABLE_CTORS
        if not mutable:
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = n.lineno
    return out


def _root_name(expr: ast.AST) -> str | None:
    """Leftmost Name of an attribute/subscript chain."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class _FnLint(ast.NodeVisitor):
    """One function body: mutations vs. held locks, lock sequences,
    bare acquires. Nested defs are visited as part of their parent
    (a closure mutating module state needs the same lock)."""

    def __init__(self, mod: SourceModule, report: Report,
                 mutables: dict[str, int], exempt: bool, class_name: str):
        self.mod = mod
        self.report = report
        self.mutables = mutables
        self.exempt = exempt
        self.class_name = class_name
        self.lock_depth = 0
        self.held_stack: list[str] = []  # dotted lock names, outer->inner
        self.pairs: list[tuple[str, str, int]] = []  # (outer, inner, line)
        self.global_names: set[str] = set()

    def visit_FunctionDef(self, node) -> None:
        # a def nested under `with lock:` runs LATER, without the lock:
        # its body must not inherit the lexically-held lock state
        saved_depth, saved_stack = self.lock_depth, self.held_stack
        self.lock_depth, self.held_stack = 0, []
        self.generic_visit(node)
        self.lock_depth, self.held_stack = saved_depth, saved_stack

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    # ------------------------------------------------------------ locks
    def _lock_label(self, expr: ast.AST) -> str:
        d = dotted_name(expr) or (
            dotted_name(expr.func) if isinstance(expr, ast.Call) else None)
        d = d or "<lock>"
        if d.startswith("self.") and self.class_name:
            d = f"{self.class_name}.{d[5:]}"
        return d

    def visit_With(self, node: ast.With) -> None:
        lock_items = [it for it in node.items
                      if _is_lockish(it.context_expr)]
        for it in lock_items:
            label = self._lock_label(it.context_expr)
            for outer in self.held_stack:
                if outer != label:
                    self.pairs.append((outer, label, it.context_expr.lineno))
            self.held_stack.append(label)
        self.lock_depth += len(lock_items)
        self.generic_visit(node)
        self.lock_depth -= len(lock_items)
        del self.held_stack[len(self.held_stack) - len(lock_items):]

    visit_AsyncWith = visit_With

    def visit_Expr(self, node: ast.Expr) -> None:
        v = node.value
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                and v.func.attr == "acquire" and _is_lockish(v.func.value)):
            self._check_bare_acquire(node, v)
        self.generic_visit(node)

    def visit_Try(self, node: ast.Try) -> None:
        # acquire(); try: ... finally: release() -- the sanctioned
        # non-with form (lock-bare-acquire's own fix hint): the try body
        # holds every lock the finalbody releases
        released = []
        for fin in node.finalbody:
            for el in ast.walk(fin):
                if (isinstance(el, ast.Call)
                        and isinstance(el.func, ast.Attribute)
                        and el.func.attr == "release"
                        and _is_lockish(el.func.value)):
                    released.append(self._lock_label(el.func.value))
        self.lock_depth += len(released)
        self.held_stack.extend(released)
        # handlers run before finally, so they hold the lock too
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        for h in node.handlers:
            for stmt in h.body:
                self.visit(stmt)
        self.lock_depth -= len(released)
        del self.held_stack[len(self.held_stack) - len(released):]
        for stmt in node.finalbody:
            self.visit(stmt)

    def _check_bare_acquire(self, stmt: ast.Expr, call: ast.Call) -> None:
        parent_body = getattr(stmt, "_parent_body", None)
        ok = False
        if parent_body is not None:
            idx = parent_body.index(stmt)
            lock_name = dotted_name(call.func.value)
            for follower in parent_body[idx + 1:idx + 2]:
                if isinstance(follower, ast.Try):
                    for fin in follower.finalbody:
                        for el in ast.walk(fin):
                            if (isinstance(el, ast.Call)
                                    and isinstance(el.func, ast.Attribute)
                                    and el.func.attr == "release"
                                    and dotted_name(el.func.value) == lock_name):
                                ok = True
        if not ok:
            emit(self.mod, self.report, call.lineno, R_BARE_ACQUIRE,
                 f"{dotted_name(call.func.value)}.acquire() without an "
                 "immediate try/finally release",
                 "use `with lock:` (or wrap the critical section in "
                 "try/finally releasing the lock)")

    # -------------------------------------------------------- mutations
    def visit_Global(self, node: ast.Global) -> None:
        self.global_names.update(node.names)

    def _flag(self, line: int, name: str, what: str) -> None:
        if self.exempt or self.lock_depth > 0:
            return
        emit(self.mod, self.report, line, R_GLOBAL,
             f"{what} of module-level '{name}' outside any lock",
             "guard with the module lock, or suffix the function _locked "
             "if the caller holds it")

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node.lineno, aug=True)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                root = _root_name(t)
                if root in self.mutables:
                    self._flag(node.lineno, root, "del on item")
        self.generic_visit(node)

    def _check_target(self, t: ast.expr, line: int, aug: bool = False) -> None:
        if isinstance(t, ast.Name):
            # plain rebind of a module global (requires `global` stmt)
            if t.id in self.global_names:
                self._flag(line, t.id, "rebind")
        elif isinstance(t, ast.Subscript):
            root = _root_name(t)
            if root in self.mutables:
                self._flag(line, root, "item assignment")

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            root = _root_name(f.value)
            if root in self.mutables and isinstance(f.value, ast.Name):
                self._flag(node.lineno, root, f".{f.attr}()")
        self.generic_visit(node)


def _link_parents(tree: ast.AST) -> None:
    """Stamp statements with their containing body list (for the
    acquire-then-try lookahead)."""
    for node in ast.walk(tree):
        for field in ("body", "orelse", "finalbody"):
            body = getattr(node, field, None)
            if isinstance(body, list):
                for child in body:
                    child._parent_body = body
        for h in getattr(node, "handlers", []) or []:
            for child in h.body:
                child._parent_body = h.body


def run_concurrency_rules(mod: SourceModule, report: Report) -> None:
    tree = mod.tree
    mutables = _module_mutables(tree)
    _link_parents(tree)

    pair_order: dict[frozenset, tuple[str, str]] = {}

    def lint_fn(fn: ast.FunctionDef, class_name: str) -> None:
        exempt = fn.name.endswith("_locked")
        # `global X` must lexically precede any binding of X, so
        # visit_Global has always populated global_names (scalars count
        # too: _HOST_RATE_BPS-style EMAs are registries of one value)
        # by the time a rebind of X is visited
        lint = _FnLint(mod, report, mutables, exempt, class_name)
        for stmt in fn.body:
            lint.visit(stmt)
        for outer, inner, line in lint.pairs:
            key = frozenset((outer, inner))
            seen = pair_order.get(key)
            if seen is None:
                pair_order[key] = (outer, inner)
            elif seen != (outer, inner):
                emit(mod, report, line, R_LOCK_ORDER,
                     f"acquires '{inner}' while holding '{outer}', but "
                     f"another path in this module acquires "
                     f"'{seen[1]}' while holding '{seen[0]}'",
                     "pick one module-wide acquisition order and stick to it")

    def walk_defs(owner: ast.AST, class_name: str) -> None:
        for child in ast.iter_child_nodes(owner):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                lint_fn(child, class_name)
            elif isinstance(child, ast.ClassDef):
                walk_defs(child, child.name)
            elif isinstance(child, (ast.If, ast.Try, ast.With)):
                walk_defs(child, class_name)

    walk_defs(tree, "")
