"""Bloom filter kernels: batch membership test and compaction union.

The union is the north-star "pmap'd sketch union" (BASELINE.json): when
compaction inputs share bloom geometry, the output block's filter is a
single elementwise OR over stacked (n_blocks, n_shards, words) bits --
one fused VPU pass instead of the reference's per-key re-insertion
(v2/streaming_block.go bloom adds during merge).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..block.bloom import ShardedBloom, shard_for_trace_id
from ..util.hashing import bloom_hashes


@jax.jit
def _union_kernel(stacked: jnp.ndarray) -> jnp.ndarray:
    """(K, n_shards, words) uint32 -> (n_shards, words) bitwise-OR union."""
    return jax.lax.reduce(
        stacked, jnp.uint32(0), jax.lax.bitwise_or, dimensions=(0,)
    )


def union_blooms(blooms: list[ShardedBloom]) -> ShardedBloom:
    """Device union of same-geometry blooms; falls back to ValueError on
    geometry mismatch (caller rebuilds instead)."""
    import time as _time

    from ..util.kerneltel import TEL

    first = blooms[0]
    for b in blooms[1:]:
        if b.n_shards != first.n_shards or b.shard_bits != first.shard_bits:
            raise ValueError("bloom geometry mismatch")
    stacked = jnp.asarray(np.stack([b.words for b in blooms]))
    TEL.record_launch("bloom_union", ("union", stacked.shape), stacked.shape[0])
    t0 = _time.perf_counter()
    out = ShardedBloom(first.n_shards, first.shard_bits)
    out.words = np.asarray(_union_kernel(stacked))
    TEL.observe_device("bloom_union", stacked.shape[0], t0)
    return out


@jax.jit
def _test_kernel(words: jnp.ndarray, word_idx: jnp.ndarray, bit_idx: jnp.ndarray) -> jnp.ndarray:
    """words: (S, W) u32; word_idx/bit_idx: (Q, K) per-query bloom positions
    (word_idx pre-offset by query shard * W is NOT needed -- words indexed
    per query via first column of word_idx... see batch_test)."""
    gathered = words[word_idx[..., 0], word_idx[..., 1]]  # (Q, K)
    bits = (gathered >> bit_idx.astype(jnp.uint32)) & jnp.uint32(1)
    return jnp.all(bits == 1, axis=-1)


def batch_test(bloom_words: np.ndarray, shard_bits: int, n_shards: int, trace_ids: list[bytes]) -> np.ndarray:
    """Test many trace ids against a block's full bloom (n_shards, W).
    Hash positions are host-computed (cheap, control plane); the bit
    gather+AND runs on device."""
    q = len(trace_ids)
    if q == 0:
        return np.zeros(0, dtype=bool)
    k = len(bloom_hashes(b"x", 7, shard_bits))
    word_idx = np.zeros((q, k, 2), dtype=np.int32)
    bit_idx = np.zeros((q, k), dtype=np.int32)
    for i, tid in enumerate(trace_ids):
        shard = shard_for_trace_id(tid, n_shards)
        for j, pos in enumerate(bloom_hashes(tid, 7, shard_bits)):
            word_idx[i, j] = (shard, pos // 32)
            bit_idx[i, j] = pos % 32
    import time as _time

    from ..util.kerneltel import TEL

    TEL.record_launch("bloom_test", ("test", bloom_words.shape, q, k),
                      bloom_words.shape[1])
    t0 = _time.perf_counter()
    out = np.asarray(
        _test_kernel(jnp.asarray(bloom_words), jnp.asarray(word_idx), jnp.asarray(bit_idx))
    )
    TEL.observe_device("bloom_test", bloom_words.shape[1], t0)
    return out
