"""Stage vtpu block columns onto the device for filtering.

Reads only the columns a condition set needs (ops.filter.required_columns),
optionally only a row-group range (the unit of search-job sharding,
mirroring the reference's StartPage/TotalPages jobs,
modules/frontend/searchsharding.go), pads every axis to its power-of-two
bucket, and uploads. Staged device arrays are cached on the (immutable)
block object keyed by (column set, group range), so repeated queries
against a hot block skip IO, decompression, AND the host->device
transfer -- the device-memory analog of the reference's page cache +
memcached layers, and the biggest win when the host<->device link has
high latency."""

from __future__ import annotations

import weakref
from collections import OrderedDict
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..block import schema as S
from ..block.reader import BackendBlock
from ..util.profiler import timed_lock
from .device import PAD_I32, bucket, pad_rows

_CACHE_MAX_ENTRIES = 32  # per block
_CACHE_MAX_ENTRY_BYTES = 256 << 20

# aggregate device-memory budget across EVERY block's staged cache: an
# LRU over (block, entry) pairs, so a wide working set evicts the
# coldest block's columns instead of growing until HBM OOMs
_GLOBAL_CACHE_BUDGET = 4 << 30
# a cataloged hot lock: TEMPO_LOCK_PROFILE arms contention timing
# (tempo_lock_wait_seconds{lock="stage_lru"}); off = a raw Lock
_lru_lock = timed_lock("stage_lru")
_lru: OrderedDict[tuple[int, tuple], tuple] = OrderedDict()  # -> (blk weakref, nbytes)
_lru_bytes = 0

# HBM-evicted entries awaiting demotion into the host chunk pool
# (ops/chunkpool): collected under _lru_lock, compressed OUTSIDE it --
# the D2H pull + codec work is milliseconds, the lock guards
# microsecond bookkeeping
_pending_demote: list[tuple[str, tuple, object]] = []


def staged_cache_stats(max_entries: int = 32) -> dict:
    """Point-in-time view of the device staged-column cache for
    /status/kernels: aggregate occupancy plus the hottest (most recently
    touched) entries' shape."""
    with _lru_lock:
        items = list(_lru.items())
        total = _lru_bytes
        budget = _GLOBAL_CACHE_BUDGET
    entries = []
    for (_bid, key), (wr, nbytes) in reversed(items[-max_entries:]):
        blk = wr()
        cols, groups = key
        entries.append({
            "block_id": getattr(getattr(blk, "meta", None), "block_id", "")[:8],
            "columns": len(cols),
            "groups": list(groups) if groups is not None else None,
            "nbytes": int(nbytes),
        })
    return {"entries": len(items), "bytes": int(total),
            "budget_bytes": int(budget), "hottest": entries}


def set_staged_cache_budget(n_bytes: int) -> None:
    global _GLOBAL_CACHE_BUDGET
    with _lru_lock:
        # budget write must be inside the lock: an eviction pass racing
        # an unlocked shrink could evict against the stale budget and
        # leave the cache over the new one
        _GLOBAL_CACHE_BUDGET = n_bytes
        _evict_over_budget_locked()
    _drain_demotions()


def _sweep_dead_locked() -> None:
    """Drop entries whose block weakref has died: their device arrays
    are gone, so leaving their nbytes in _lru_bytes would make the HBM
    budget evict live columns to pay for freed ones. Called under the
    lock on every insert and eviction pass."""
    global _lru_bytes
    dead = [k for k, (wr, _) in _lru.items() if wr() is None]
    for k in dead:
        _lru_bytes -= _lru.pop(k)[1]


def _lru_touch(blk, key: tuple, nbytes: int) -> None:
    global _lru_bytes
    k = (id(blk), key)
    with _lru_lock:
        existing = _lru.get(k)
        if existing is not None:
            if existing[0]() is blk:
                _lru.move_to_end(k)
                return
            # id() reuse after the old block was GC'd: replace the stale
            # entry and its accounting
            _lru_bytes -= existing[1]
            del _lru[k]
        _lru[k] = (weakref.ref(blk), nbytes)
        _lru_bytes += nbytes
        # the eviction pass sweeps dead weakrefs first, so every insert
        # restores the accounting invariant in one O(n) scan
        _evict_over_budget_locked()
    _drain_demotions()


def _lru_drop(blk, key: tuple) -> None:
    """Per-block cap evictions must release their global accounting."""
    global _lru_bytes
    k = (id(blk), key)
    with _lru_lock:
        entry = _lru.pop(k, None)
        if entry is not None:
            _lru_bytes -= entry[1]


def _evict_over_budget_locked() -> None:
    global _lru_bytes
    _sweep_dead_locked()  # freed arrays must not force live evictions
    while _lru_bytes > _GLOBAL_CACHE_BUDGET and len(_lru) > 1:
        (_bid, key), (wr, nbytes) = _lru.popitem(last=False)
        _lru_bytes -= nbytes
        blk = wr()
        if blk is not None:
            store = getattr(blk, "_staged_cache", None)
            if store is not None:
                staged = store.pop(key, None)
                if staged is not None:
                    # Tier B demotion candidate: the padded device
                    # arrays still exist here -- park them for the
                    # post-lock compress instead of discarding
                    block_id = getattr(
                        getattr(blk, "meta", None), "block_id", "") or ""
                    if block_id:
                        _pending_demote.append((block_id, key, staged))


def _drain_demotions() -> None:
    """Compress HBM-evicted entries into the host chunk pool. Called by
    every path that may have run an eviction pass, AFTER _lru_lock is
    released. With TEMPO_CHUNK_CACHE=0 the pool refuses every entry and
    eviction degrades to exactly the old discard."""
    if not _pending_demote:
        return
    with _lru_lock:
        victims = list(_pending_demote)
        _pending_demote.clear()
    if not victims:
        return
    from . import chunkpool

    for block_id, key, staged in victims:
        chunkpool.demote(block_id, key, staged)

# absolute-seconds origin (2020-01-01 UTC) for the derived trace@gkey_s
# column: a global trace start time in int32 seconds (valid until 2088)
# that orders traces ACROSS blocks -- per-block relative ms don't
GKEY_ORIGIN_S = 1_577_836_800


def gkey_from_start_ms(meta, start_ms):
    """The cross-block top-k ordering key (trace@gkey_s convention):
    absolute seconds since GKEY_ORIGIN_S, derived from a block's
    relative start_ms column. ONE definition -- the staged device
    column and the host raw-select path must order identically."""
    import numpy as np

    base_s = meta.start_time_unix_nano // 1_000_000_000 - GKEY_ORIGIN_S
    return np.asarray(start_ms).astype(np.int64) // 1000 + base_s

@jax.jit
def _res_to_span(res_vals, res_idx):
    """Broadcast a res-axis column to span rows; PAD where no resource."""
    out = res_vals[jnp.clip(res_idx, 0, res_vals.shape[0] - 1)]
    return jnp.where(res_idx >= 0, out, PAD_I32)


_AXIS_OF = {
    "span": S.AX_SPAN,
    "sattr": S.AX_SATTR,
    "rattr": None,  # res-axis tables are small: always loaded whole
    "res": None,
    "trace": None,
}


@dataclass
class StagedBlock:
    n_spans: int
    n_traces: int
    n_res: int
    n_spans_b: int
    n_traces_b: int
    n_res_b: int
    span_base: int  # global row of first staged span (group-range staging)
    cols: dict[str, jnp.ndarray] = field(default_factory=dict)


@dataclass
class StagePlan:
    """The name bookkeeping stage_block used to do inline, precomputed
    so the pipeline can run the read / assemble / upload phases on
    different schedules."""

    read_names: list[str]  # real pack columns to read
    materialize: list[str]  # res columns to broadcast to span level
    want_gkey: bool
    start_ms_for_gkey_only: bool


def plan_stage(needed: list[str]) -> StagePlan:
    materialize = [n.split("@", 1)[1] for n in needed if n.startswith("span@")]
    want_gkey = "trace@gkey_s" in needed
    read_names = [n for n in needed if not n.startswith(("span@", "trace@"))]
    start_ms_for_gkey_only = want_gkey and "trace.start_ms" not in read_names
    if start_ms_for_gkey_only:
        read_names = read_names + ["trace.start_ms"]
    return StagePlan(read_names, materialize, want_gkey, start_ms_for_gkey_only)


def stage_fetch_wants(blk: BackendBlock, plan: StagePlan,
                      groups: list[int] | None) -> list[tuple[str, list[int] | None]]:
    """The (column, groups) set the read phase will touch, in
    ColumnPack.plan_fetch form -- the pipeline's fetch/decompress stages
    warm exactly these so read_stage_columns is pure cache assembly."""
    span_ax = blk.pack.axes.get(S.AX_SPAN)
    sliced = span_ax is not None and span_ax.n_groups > 0 and groups is not None
    wants: list[tuple[str, list[int] | None]] = []
    for name in plan.read_names:
        ax = _AXIS_OF.get(name.split(".", 1)[0])
        wants.append((name, list(groups) if (ax is not None and sliced) else None))
    return wants


def read_stage_columns(blk: BackendBlock, plan: StagePlan,
                       groups: list[int]) -> tuple[dict, int]:
    """The host-read phase: raw columns (sliced to `groups` on their
    axis) + the res-axis row count."""
    pack = blk.pack
    span_ax = pack.axes[S.AX_SPAN]
    host: dict[str, np.ndarray] = {}
    n_res = 0
    for name in plan.read_names:
        pref = name.split(".", 1)[0]
        ax = _AXIS_OF.get(pref)
        if ax is None:
            arr = pack.read(name)
        else:
            arr = pack.read_groups(name, groups) if span_ax.n_groups else pack.read(name)
        host[name] = arr
    for name, arr in host.items():
        if name.startswith("res."):
            n_res = max(n_res, arr.shape[0])
    return host, n_res


def stage_block(
    blk: BackendBlock,
    needed: list[str],
    groups: list[int] | None = None,
    cache: bool = True,
) -> StagedBlock:
    """Load `needed` columns (padded, on device). If `groups` is given,
    span/sattr-axis columns cover only those contiguous row groups.
    Results cache on the block object (blocks are immutable)."""
    from ..util.kerneltel import TEL

    key = (tuple(needed), tuple(groups) if groups is not None else None)
    store: dict | None = getattr(blk, "_staged_cache", None) if cache else None
    if store is not None:
        hit = store.get(key)
        if hit is not None:
            TEL.staged_cache_hits.inc()
            # attribute the hit to the dequeue placement of the job
            # asking (own/steal/unowned): the affinity scheduler's
            # whole point is moving this ratio
            TEL.record_staged_lookup(True)
            _lru_touch(blk, key, sum(a.nbytes for a in hit.cols.values()))
            return hit
    if cache:
        TEL.staged_cache_misses.inc()
        TEL.record_staged_lookup(False)
        # Tier B probe: a previous HBM eviction may have demoted exactly
        # this (block, columns, groups) entry into the host chunk pool
        # -- restaging from there skips the backend ranged read, the
        # column decode AND the pad/assemble phase
        block_id = getattr(blk.meta, "block_id", "") or ""
        if block_id:
            from . import chunkpool

            chunkpool.note_stage(block_id, key)
            warm = chunkpool.restage(block_id, key)
            if warm is not None:
                _cache_insert(blk, key, warm)
                return warm
    plan = plan_stage(needed)
    span_ax = blk.pack.axes[S.AX_SPAN]
    if groups is None:
        groups = list(range(span_ax.n_groups))
    host, n_res = read_stage_columns(blk, plan, groups)
    staged, padded, real_rows = assemble_stage(blk, plan, groups, host, n_res)
    upload_stage(blk, plan, staged, padded, real_rows)
    if cache:
        _cache_insert(blk, key, staged)
    return staged


def _cache_insert(blk: BackendBlock, key: tuple, staged: StagedBlock) -> None:
    """Admit a freshly staged (or pool-restaged) entry into the
    per-block store + global LRU; a per-block cap victim demotes into
    the host chunk pool the same way budget evictions do."""
    nbytes = sum(a.nbytes for a in staged.cols.values())
    if nbytes > _CACHE_MAX_ENTRY_BYTES:
        return
    store = getattr(blk, "_staged_cache", None)
    if store is None:
        store = {}
        blk._staged_cache = store
    if len(store) >= _CACHE_MAX_ENTRIES:
        victim = next(iter(store))
        vstaged = store.pop(victim)
        _lru_drop(blk, victim)
        block_id = getattr(blk.meta, "block_id", "") or ""
        if block_id and vstaged is not None:
            from . import chunkpool

            chunkpool.demote(block_id, victim, vstaged)
    store[key] = staged
    _lru_touch(blk, key, nbytes)


def assemble_stage(blk: BackendBlock, plan: StagePlan, groups: list[int],
                   host: dict, n_res: int) -> tuple[StagedBlock, dict, dict]:
    """The pad/assemble phase: owner-offset transforms, derived columns,
    bucket padding. Pure host numpy -- no IO, no device."""
    host = dict(host)  # owner-offset transforms mutate; callers may retry
    pack = blk.pack
    span_ax = pack.axes[S.AX_SPAN]
    span_base = span_ax.offsets[groups[0]] if groups else 0
    span_hi = span_ax.offsets[groups[-1] + 1] if groups else 0
    n_spans = span_hi - span_base
    n_traces = blk.meta.total_traces

    n_spans_b = bucket(max(n_spans, 1))
    n_traces_b = bucket(max(n_traces, 1))
    n_res_b = bucket(max(n_res, 1))

    want_gkey = plan.want_gkey
    start_ms_for_gkey_only = plan.start_ms_for_gkey_only

    staged = StagedBlock(
        n_spans=n_spans,
        n_traces=n_traces,
        n_res=n_res,
        n_spans_b=n_spans_b,
        n_traces_b=n_traces_b,
        n_res_b=n_res_b,
        span_base=span_base,
    )
    # owner-offset columns: rows of every child table are grouped by
    # owner, so the kernel aggregates with cumsum + offset gathers
    # (ops/filter._offset_counts) -- the owner row columns themselves
    # never need to reach the device.
    real_rows: dict[str, int] = {}  # pre-padding lengths (telemetry)
    if "sattr.span" in host:
        owners = np.clip(host["sattr.span"] - span_base, 0, max(n_spans, 1) - 1)
        cnt = np.bincount(owners, minlength=max(n_spans, 1)) if owners.size else np.zeros(
            max(n_spans, 1), dtype=np.int64
        )
        off = np.concatenate([[0], np.cumsum(cnt)]).astype(np.int32)
        real_rows["sattr.off"] = int(off.shape[0])
        host["sattr.off"] = pad_rows(off, n_spans_b + 1, off[-1] if off.size else 0)
        del host["sattr.span"]
    if "rattr.res" in host:
        owners = np.clip(host["rattr.res"], 0, max(n_res, 1) - 1)
        cnt = np.bincount(owners, minlength=max(n_res, 1)) if owners.size else np.zeros(
            max(n_res, 1), dtype=np.int64
        )
        off = np.concatenate([[0], np.cumsum(cnt)]).astype(np.int32)
        real_rows["rattr.off"] = int(off.shape[0])
        host["rattr.off"] = pad_rows(off, n_res_b + 1, off[-1] if off.size else 0)
        del host["rattr.res"]  # superseded on device by the offsets

    if want_gkey:
        # derived column: the cross-block top-k ordering key
        host["trace@gkey_s"] = gkey_from_start_ms(
            blk.meta, host["trace.start_ms"]).astype(np.int32)
        if start_ms_for_gkey_only:
            host.pop("trace.start_ms", None)  # read only to derive the key

    padded: dict[str, np.ndarray] = {}
    for name, arr in host.items():
        pref = name.split(".", 1)[0].split("@", 1)[0]
        if name == "trace.span_off":
            # rebase global span rows to the staged slice; padded trace
            # rows collapse to empty segments (count 0)
            arr = (np.clip(arr, span_base, span_hi) - span_base).astype(np.int32)
            arr = pad_rows(arr, n_traces_b + 1, arr[-1] if arr.size else 0)
        elif name in ("sattr.off", "rattr.off"):
            pass  # already padded above
        elif name == "trace@gkey_s":
            arr = pad_rows(arr, n_traces_b, np.int32(-(2**31)))
        elif pref == "span":
            arr = pad_rows(arr, n_spans_b, PAD_I32)
        elif pref == "sattr":
            arr = pad_rows(arr, bucket(max(arr.shape[0], 1)), PAD_I32)
        elif pref == "rattr":
            arr = pad_rows(arr, bucket(max(arr.shape[0], 1)), PAD_I32)
        elif pref == "res":
            arr = pad_rows(arr, n_res_b, PAD_I32)
        elif pref == "trace":
            if arr.dtype in (np.int32, np.float32):
                arr = pad_rows(arr, n_traces_b, PAD_I32 if arr.dtype == np.int32 else np.float32(0))
            else:
                continue  # host-only trace columns are not staged
        padded[name] = arr
    # complete the per-column real (pre-padding) row counts for the
    # upload phase's padding-waste telemetry
    real_full = {n: real_rows.get(n, int(host[n].shape[0])) for n in padded}
    return staged, padded, real_full


def upload_stage(blk: BackendBlock, plan: StagePlan, staged: StagedBlock,
                 padded: dict, real_rows: dict) -> StagedBlock:
    """The host->device phase: one batched transfer + the query-
    independent res->span materialization."""
    import time as _time

    from ..util.kerneltel import TEL

    t0_wall = _time.time()
    # ONE batched transfer for the whole block: per-array device_puts
    # each pay a full link round trip on a high-latency tunnel
    staged.cols = dict(zip(padded, jax.device_put(list(padded.values()))))
    # telemetry: upload volume + padding waste (padded vs real rows
    # summed per column -- columns live on different axes)
    nbytes = sum(int(a.nbytes) for a in padded.values())
    TEL.record_transfer(
        nbytes,
        sum(real_rows.values()),
        sum(int(a.shape[0]) for a in padded.values()),
    )
    # timeline span for the active self-trace: this is THE host->device
    # upload, whether a warm staging miss or a stream-pipeline unit
    TEL.child_span("stream:upload", t0_wall, _time.time(),
                   {"bytes": nbytes, "block": blk.meta.block_id[:8]})

    # materialize requested res columns at SPAN level: the res->span
    # broadcast gather is query-independent, so paying it once here
    # (cached with the staged entry) removes a span-length random gather
    # -- one of the most expensive TPU ops -- from every query's kernel
    if plan.materialize and "span.res_idx" in staged.cols:
        for name in plan.materialize:
            if name in staged.cols:
                staged.cols[f"span@{name}"] = _res_to_span(
                    staged.cols[name], staged.cols["span.res_idx"]
                )
    return staged
