"""Stage vtpu block columns onto the device for filtering.

Reads only the columns a condition set needs (ops.filter.required_columns),
optionally only a row-group range (the unit of search-job sharding,
mirroring the reference's StartPage/TotalPages jobs,
modules/frontend/searchsharding.go), pads every axis to its power-of-two
bucket, and uploads. Staged device arrays are cached on the (immutable)
block object keyed by (column set, group range), so repeated queries
against a hot block skip IO, decompression, AND the host->device
transfer -- the device-memory analog of the reference's page cache +
memcached layers, and the biggest win when the host<->device link has
high latency."""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from ..block import schema as S
from ..block.reader import BackendBlock
from .device import PAD_I32, bucket, pad_rows

_CACHE_MAX_ENTRIES = 32  # per block
_CACHE_MAX_ENTRY_BYTES = 256 << 20

_AXIS_OF = {
    "span": S.AX_SPAN,
    "sattr": S.AX_SATTR,
    "rattr": None,  # res-axis tables are small: always loaded whole
    "res": None,
    "trace": None,
}


@dataclass
class StagedBlock:
    n_spans: int
    n_traces: int
    n_res: int
    n_spans_b: int
    n_traces_b: int
    n_res_b: int
    span_base: int  # global row of first staged span (group-range staging)
    cols: dict[str, jnp.ndarray] = field(default_factory=dict)


def stage_block(
    blk: BackendBlock,
    needed: list[str],
    groups: list[int] | None = None,
    cache: bool = True,
) -> StagedBlock:
    """Load `needed` columns (padded, on device). If `groups` is given,
    span/sattr-axis columns cover only those contiguous row groups.
    Results cache on the block object (blocks are immutable)."""
    key = (tuple(needed), tuple(groups) if groups is not None else None)
    store: dict | None = getattr(blk, "_staged_cache", None) if cache else None
    if store is not None:
        hit = store.get(key)
        if hit is not None:
            return hit
    pack = blk.pack
    span_ax = pack.axes[S.AX_SPAN]
    if groups is None:
        groups = list(range(span_ax.n_groups))
    span_base = span_ax.offsets[groups[0]] if groups else 0
    span_hi = span_ax.offsets[groups[-1] + 1] if groups else 0

    host: dict[str, np.ndarray] = {}
    n_res = 0
    for name in needed:
        pref = name.split(".", 1)[0]
        ax = _AXIS_OF.get(pref)
        if ax is None:
            arr = pack.read(name)
            if pref == "res" or name == "rattr.res":
                n_res = max(n_res, arr.shape[0] if name.startswith("res.") else 0)
        else:
            arr = pack.read_groups(name, groups) if span_ax.n_groups else pack.read(name)
        host[name] = arr

    n_spans = span_hi - span_base
    n_traces = blk.meta.total_traces
    for name, arr in host.items():
        if name.startswith("res."):
            n_res = max(n_res, arr.shape[0])

    n_spans_b = bucket(max(n_spans, 1))
    n_traces_b = bucket(max(n_traces, 1))
    n_res_b = bucket(max(n_res, 1))

    staged = StagedBlock(
        n_spans=n_spans,
        n_traces=n_traces,
        n_res=n_res,
        n_spans_b=n_spans_b,
        n_traces_b=n_traces_b,
        n_res_b=n_res_b,
        span_base=span_base,
    )
    for name, arr in host.items():
        pref = name.split(".", 1)[0]
        if pref == "span":
            arr = pad_rows(arr, n_spans_b, PAD_I32)
        elif pref == "sattr":
            if name == "sattr.span":
                # rebase owner to staged-local rows; pads clip safely since
                # their key_id sentinel never matches
                arr = arr - span_base
            arr = pad_rows(arr, bucket(max(arr.shape[0], 1)), PAD_I32)
        elif pref == "rattr":
            arr = pad_rows(arr, bucket(max(arr.shape[0], 1)), PAD_I32)
        elif pref == "res":
            arr = pad_rows(arr, n_res_b, PAD_I32)
        elif pref == "trace":
            if arr.dtype in (np.int32, np.float32):
                arr = pad_rows(arr, n_traces_b, PAD_I32 if arr.dtype == np.int32 else np.float32(0))
            else:
                continue  # host-only trace columns are not staged
        staged.cols[name] = jnp.asarray(arr)
    if cache:
        nbytes = sum(a.nbytes for a in staged.cols.values())
        if nbytes <= _CACHE_MAX_ENTRY_BYTES:
            if store is None:
                store = {}
                blk._staged_cache = store
            if len(store) >= _CACHE_MAX_ENTRIES:
                store.pop(next(iter(store)))
            store[key] = staged
    return staged
