"""Batched trace-ID lookup kernel.

Replaces the reference's per-block bloom -> index binary search -> page
scan (vparquet/block_findtracebyid.go:56-203) with one vectorized
device binary search: Q query ids against a block's sorted 128-bit
trace-id index, ids as 4 order-preserving int32 lanes
(schema.trace_id_to_codes). All Q queries step through the log2(T)
bisection together as one (Q,4) vs (T,4) lexicographic compare per
step -- the shape the VPU wants, and the unit the sharded multi-chip
Find distributes (parallel/find.py).
"""

from __future__ import annotations

import time as _time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..util.kerneltel import TEL
from .device import PAD_I32, bucket, pad_rows


def _lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Rowwise a < b for (..., 4) int32 lanes, lexicographic."""
    lt = a < b
    eq = a == b
    return lt[..., 0] | (
        eq[..., 0] & (lt[..., 1] | (eq[..., 1] & (lt[..., 2] | (eq[..., 2] & lt[..., 3]))))
    )


def _lex_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def bisect_ids(ids: jnp.ndarray, queries: jnp.ndarray, n_valid, n_steps: int) -> jnp.ndarray:
    """Core lockstep bisection (unjitted; shared with parallel/find.py).
    ids: (T,4) sorted i32 codes (padded with +max rows), queries: (Q,4),
    n_valid: () number of real id rows. -> (Q,) int32 sid or -1."""
    T = ids.shape[0]
    Q = queries.shape[0]
    lo = jnp.zeros((Q,), dtype=jnp.int32)
    hi = jnp.full((Q,), n_valid, dtype=jnp.int32)

    def step(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        mid_ids = ids[jnp.clip(mid, 0, T - 1)]
        less = _lex_less(mid_ids, queries)
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, n_steps, step, (lo, hi))
    found_ids = ids[jnp.clip(lo, 0, T - 1)]
    ok = (lo < n_valid) & _lex_eq(found_ids, queries)
    return jnp.where(ok, lo, -1)


@partial(jax.jit, static_argnames=("n_steps",))
def _lookup_kernel(ids: jnp.ndarray, queries: jnp.ndarray, n_valid: jnp.ndarray, n_steps: int):
    return bisect_ids(ids, queries, n_valid, n_steps)


@partial(jax.jit, static_argnames=("n_steps",))
def _lookup_blocks_kernel(ids: jnp.ndarray, queries: jnp.ndarray, n_valid: jnp.ndarray,
                          n_steps: int):
    """ids: (B, T, 4) stacked per-block indexes -> (B, Q) sids. One fused
    program bisects every candidate block at once: the single-chip unit
    of the multi-block Find (parallel/find.py shards the B axis)."""
    return jax.vmap(lambda a, nv: bisect_ids(a, queries, nv, n_steps))(ids, n_valid)


def _device_ids(blk) -> tuple[jnp.ndarray, int]:
    """Padded (T,4) device copy of a block's sorted id codes, cached on
    the (immutable) block object: repeated finds skip the host->device
    upload, which dominates per-lookup latency on a high-latency link."""
    cached = getattr(blk, "_dev_ids", None)
    a = blk.trace_index["trace.id_codes"]
    n = int(a.shape[0])
    if cached is not None and cached[1] == n:
        return cached
    tb = bucket(max(n, 1))
    ids = pad_rows(np.asarray(a, dtype=np.int32), tb, np.int32(2**31 - 1))
    cached = (jnp.asarray(ids), n)
    blk._dev_ids = cached
    return cached


def _ids_void(blk) -> np.ndarray:
    """The block's sorted trace ids as a void16 view (numpy compares V16
    lexicographically by bytes = the on-disk sort order), cached on the
    immutable block."""
    v = getattr(blk, "_ids_void_cache", None)
    if v is None:
        v = blk._ids_void_cache = np.ascontiguousarray(
            blk.trace_index["trace.id"]).view("V16").ravel()
    return v


def lookup_ids_blocks_host(blocks: list, query_codes: np.ndarray) -> np.ndarray:
    """Host engine: ONE vectorized searchsorted per block over the void16
    id index. O(Q log T) with zero device round trips -- on a single chip
    behind a high-latency link this beats the kernel by the full
    dispatch+fetch RTT; the device kernel's value is mesh sharding
    (parallel/find.py) and fused multi-block batches at scale."""
    B, q = len(blocks), query_codes.shape[0]
    out = np.full((B, q), -1, dtype=np.int32)
    if B == 0 or q == 0:
        return out
    from ..block.schema import codes_to_id_bytes

    qbytes = np.ascontiguousarray(codes_to_id_bytes(np.asarray(query_codes, np.int32)))
    qv = qbytes.view("V16").ravel()
    from ..native import lex_bisect16

    for i, blk in enumerate(blocks):
        iv = _ids_void(blk)
        n = iv.shape[0]
        if n == 0:
            continue
        # native memcmp bisect (~10x numpy's void16 searchsorted, whose
        # per-probe compares go through object machinery)
        rows = lex_bisect16(iv.view(np.uint8).reshape(n, 16), qbytes)
        if rows is not None:
            out[i] = rows
            continue
        pos = np.searchsorted(iv, qv)
        clip = np.minimum(pos, n - 1)
        ok = (pos < n) & (iv[clip] == qv)
        out[i, ok] = pos[ok].astype(np.int32)
    return out


def _lookup_blocks_device(blocks: list, query_codes: np.ndarray) -> np.ndarray:
    """The device engine body: per-block cached device id indexes, one
    lockstep bisection kernel per id-row bucket, one timing window over
    the whole batch. Shared by the routed entry below and the
    calibration race."""
    q = query_codes.shape[0]
    qb = bucket(q)
    # host arrays ride the dispatch upload; eager jnp conversions here
    # would each pay a blocking host->device round trip
    queries = pad_rows(np.asarray(query_codes, np.int32), qb, PAD_I32)
    outs = []
    t0 = _time.perf_counter()
    buckets = []
    for blk in blocks:
        dev_ids, n = _device_ids(blk)
        tb = int(dev_ids.shape[0])  # id-row bucket: the launch key's label
        n_steps = tb.bit_length()
        nv = np.int32(n)
        TEL.record_launch(
            "find", ("find1", tb, qb), tb,
            cost=lambda dev_ids=dev_ids, nv=nv, n_steps=n_steps: _costmodel(
            ).spec(_lookup_kernel, dev_ids, queries, nv, n_steps))
        buckets.append(tb)
        outs.append(_lookup_kernel(dev_ids, queries, nv, n_steps))
    stacked = jnp.stack(outs) if len(outs) > 1 else outs[0][None]
    res = np.asarray(stacked)[:, :q]
    # one timing window covers the whole batch (per-block syncs would
    # serialize the pipeline): the histogram gets one observation, each
    # launched bucket's kernel row an amortized share
    dt = _time.perf_counter() - t0
    TEL.device_time.observe(dt, 'op="find"')
    for tb in buckets:
        TEL.credit_device("find", tb, dt / len(buckets))
    return res


def _costmodel():
    from ..util import costmodel

    return costmodel


def _n_devices() -> int:
    """Visible chip count (own function so topology tests can pin it)."""
    return len(jax.devices())


def _find_policy(mode: str, rows: int) -> tuple[str, str]:
    """Resolve the find engine for a SINGLE-chip topology:
    (engine, routing reason). TEMPO_FIND_MODE overrides the caller's
    mode (env always wins); 'auto' consults the CostLedger's measured
    find race (tempo-tpu-cli calibrate / the find_auto_crossover_rows
    bench row): host cost is linear in scanned id rows while the device
    path is ~fixed, so THIS batch's row count is compared against the
    committed crossover_rows -- a race calibrated on a small block
    still routes a huge multi-block lookup to the device once it is
    past the crossover. Entries without crossover_rows fall back to
    the race's binary winner; no entry at all falls back to the
    host-on-one-chip assumption."""
    import os

    env = os.environ.get("TEMPO_FIND_MODE", "")
    if env in ("host", "device", "auto"):
        mode = env
    if mode == "host":
        return "host", "forced"
    if mode == "device":
        return "device", "forced"
    from ..util.costledger import KEY_FIND, ledger

    entry = ledger().get(KEY_FIND)
    if entry:
        cross = entry.get("crossover_rows")
        if cross and float(cross) > 0:
            return (("device" if rows >= float(cross) else "host"),
                    "ledger_crossover")
        if entry.get("winner") in ("host", "device"):
            return entry["winner"], "ledger_crossover"
    return "host", "single_chip_rtt"


def lookup_ids_blocks_cached(blocks: list, query_codes: np.ndarray,
                             mode: str = "auto") -> np.ndarray:
    """Batched multi-block lookup, engine picked per topology +
    measured crossover. A mesh of chips always runs the device kernel
    (ids stay device-resident and shard over the mesh); on a single
    chip 'auto' routes by the CostLedger's committed host-vs-device
    race (_find_policy) -- the host searchsorted engine remains the
    default only until someone actually measures. Both engines return
    bit-identical (B, Q) int32 row-in-block (-1 miss)."""
    B = len(blocks)
    q = query_codes.shape[0]
    if B == 0 or q == 0:
        return np.full((B, q), -1, dtype=np.int32)
    if mode != "host" and _n_devices() > 1:
        TEL.record_routing("find", "device",
                           "forced" if mode == "device" else "mesh")
        return _lookup_blocks_device(blocks, query_codes)
    # id-index rows of THIS batch, from footer metadata (no IO)
    rows = sum(int(b.meta.total_traces) for b in blocks)
    engine, reason = _find_policy(mode, rows)
    TEL.record_routing("find", engine, reason)
    if engine == "host":
        return lookup_ids_blocks_host(blocks, query_codes)
    return _lookup_blocks_device(blocks, query_codes)


def calibrate_find(blocks: list, query_codes: np.ndarray, repeats: int = 3,
                   record: bool = True) -> dict:
    """THE find race (ROADMAP item 5): run both engines over the same
    blocks/queries, take best-of-repeats (noise only ever adds time),
    and commit the measured crossover to the CostLedger so the `auto`
    policy stops guessing. Returns the ledger entry.

    crossover_rows models the host engine as linear in scanned id rows
    and the device engine as a ~fixed dispatch+fetch: the id-row count
    at which the device path starts winning for this query batch."""
    rows = int(sum(b.trace_index["trace.id_codes"].shape[0] for b in blocks))
    q = int(query_codes.shape[0])

    def best(fn) -> float:
        fn()  # warm: device compiles + id uploads; host void16 caches
        times = []
        for _ in range(max(1, repeats)):
            t0 = _time.perf_counter()
            fn()
            times.append(_time.perf_counter() - t0)
        return min(times)

    host_s = best(lambda: lookup_ids_blocks_host(blocks, query_codes))
    device_s = best(lambda: _lookup_blocks_device(blocks, query_codes))
    host_per_row = host_s / max(rows, 1)
    entry = {
        "host_s": round(host_s, 6),
        "device_s": round(device_s, 6),
        "host_s_per_row": host_per_row,
        "rows": rows,
        "queries": q,
        "repeats": int(repeats),
        "winner": "host" if host_s <= device_s else "device",
        "crossover_rows": round(device_s / max(host_per_row, 1e-12), 1),
    }
    if record:
        from ..util.costledger import KEY_FIND, ledger

        ledger().update(KEY_FIND, **entry)
        ledger().publish()
    return entry


def lookup_ids_blocks(id_code_arrays: list[np.ndarray], query_codes: np.ndarray) -> np.ndarray:
    """Batched multi-block lookup on one chip: Q query ids against B
    per-block sorted id-code arrays. Returns (B, Q) int32 row-in-block
    (-1 miss). Every block reporting its own hit row (rather than electing
    one winner) is what lets callers combine partial traces, matching the
    reference's Find fan-out + combiner (tempodb/tempodb.go:271-352)."""
    B = len(id_code_arrays)
    q = query_codes.shape[0]
    if B == 0 or q == 0:
        return np.full((B, q), -1, dtype=np.int32)
    T = bucket(max(max(a.shape[0] for a in id_code_arrays), 1))
    ids = np.full((B, T, 4), np.int32(2**31 - 1), dtype=np.int32)
    n_valid = np.zeros((B,), dtype=np.int32)
    for i, a in enumerate(id_code_arrays):
        ids[i, : a.shape[0]] = a
        n_valid[i] = a.shape[0]
    qb = bucket(q)
    queries = pad_rows(np.asarray(query_codes, dtype=np.int32), qb, PAD_I32)
    n_steps = int(T).bit_length()
    TEL.record_launch(
        "find", ("findB", B, T, qb), T,
        cost=lambda: _costmodel().spec(
            _lookup_blocks_kernel, ids, queries, n_valid, n_steps))
    t0 = _time.perf_counter()
    out = _lookup_blocks_kernel(ids, queries, n_valid, n_steps)
    res = np.asarray(out)[:, :q]
    TEL.observe_device("find", T, t0)
    return res


def lookup_ids(id_codes: np.ndarray, query_codes: np.ndarray) -> np.ndarray:
    """Host wrapper: pad to buckets, run the kernel, return (Q,) sids (-1 miss)."""
    n = id_codes.shape[0]
    q = query_codes.shape[0]
    if n == 0 or q == 0:
        return np.full((q,), -1, dtype=np.int32)
    tb = bucket(n)
    qb = bucket(q)
    # pad ids with +inf rows (max codes) so they sort after everything
    ids = pad_rows(np.asarray(id_codes, dtype=np.int32), tb, np.int32(2**31 - 1))
    queries = pad_rows(np.asarray(query_codes, dtype=np.int32), qb, PAD_I32)
    n_steps = int(tb).bit_length()  # ceil(log2(tb)) + 1 covers the range
    nv = np.int32(n)
    TEL.record_launch(
        "find", ("find1", tb, qb), tb,
        cost=lambda: _costmodel().spec(_lookup_kernel, ids, queries, nv, n_steps))
    t0 = _time.perf_counter()
    out = _lookup_kernel(ids, queries, nv, n_steps)
    res = np.asarray(out)[:q]
    TEL.observe_device("find", tb, t0)
    return res
