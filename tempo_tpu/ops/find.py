"""Batched trace-ID lookup kernel.

Replaces the reference's per-block bloom -> index binary search -> page
scan (vparquet/block_findtracebyid.go:56-203) with one vectorized
device binary search: Q query ids against a block's sorted 128-bit
trace-id index, ids as 4 order-preserving int32 lanes
(schema.trace_id_to_codes). All Q queries step through the log2(T)
bisection together as one (Q,4) vs (T,4) lexicographic compare per
step -- the shape the VPU wants, and the unit the sharded multi-chip
Find distributes (parallel/find.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .device import PAD_I32, bucket, pad_rows


def _lex_less(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Rowwise a < b for (..., 4) int32 lanes, lexicographic."""
    lt = a < b
    eq = a == b
    return lt[..., 0] | (
        eq[..., 0] & (lt[..., 1] | (eq[..., 1] & (lt[..., 2] | (eq[..., 2] & lt[..., 3]))))
    )


def _lex_eq(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.all(a == b, axis=-1)


def bisect_ids(ids: jnp.ndarray, queries: jnp.ndarray, n_valid, n_steps: int) -> jnp.ndarray:
    """Core lockstep bisection (unjitted; shared with parallel/find.py).
    ids: (T,4) sorted i32 codes (padded with +max rows), queries: (Q,4),
    n_valid: () number of real id rows. -> (Q,) int32 sid or -1."""
    T = ids.shape[0]
    Q = queries.shape[0]
    lo = jnp.zeros((Q,), dtype=jnp.int32)
    hi = jnp.full((Q,), n_valid, dtype=jnp.int32)

    def step(_, state):
        lo, hi = state
        mid = (lo + hi) // 2
        mid_ids = ids[jnp.clip(mid, 0, T - 1)]
        less = _lex_less(mid_ids, queries)
        lo = jnp.where(less, mid + 1, lo)
        hi = jnp.where(less, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, n_steps, step, (lo, hi))
    found_ids = ids[jnp.clip(lo, 0, T - 1)]
    ok = (lo < n_valid) & _lex_eq(found_ids, queries)
    return jnp.where(ok, lo, -1)


@partial(jax.jit, static_argnames=("n_steps",))
def _lookup_kernel(ids: jnp.ndarray, queries: jnp.ndarray, n_valid: jnp.ndarray, n_steps: int):
    return bisect_ids(ids, queries, n_valid, n_steps)


def lookup_ids(id_codes: np.ndarray, query_codes: np.ndarray) -> np.ndarray:
    """Host wrapper: pad to buckets, run the kernel, return (Q,) sids (-1 miss)."""
    n = id_codes.shape[0]
    q = query_codes.shape[0]
    if n == 0 or q == 0:
        return np.full((q,), -1, dtype=np.int32)
    tb = bucket(n)
    qb = bucket(q)
    # pad ids with +inf rows (max codes) so they sort after everything
    ids = pad_rows(np.asarray(id_codes, dtype=np.int32), tb, np.int32(2**31 - 1))
    queries = pad_rows(np.asarray(query_codes, dtype=np.int32), qb, PAD_I32)
    n_steps = int(tb).bit_length()  # ceil(log2(tb)) + 1 covers the range
    out = _lookup_kernel(jnp.asarray(ids), jnp.asarray(queries), jnp.int32(n), n_steps)
    return np.asarray(out)[:q]
