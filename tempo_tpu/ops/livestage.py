"""Live-head staging: incremental device columns for WAL/live traces.

The ingester's live/cut/flushing traces used to be searchable only
through a host-side per-trace index walk (services/ingester.py
_SearchEntry) while complete blocks run the fused device engine -- the
hottest data got the slowest engine. This module maintains per-tenant
APPEND-ONLY columnar tails for the live head so the same fused
filter->top-k shape (segment-membership masks + ops/select top-k)
covers live traces too:

  * one SLOT per live trace id (merged across the live/cut/flushing
    lifecycle states) carrying the filterable per-trace aggregates:
    push-metadata time bounds, the exact span-time selection key
    (seconds since ops/stage.GKEY_ORIGIN_S), a conservative duration,
    an alive flag, and the 4x int32 trace-id codes for find;
  * append-only ROW tails for tag membership: (owner slot, code) rows
    for every (key, lowered-str-value) attr pair and every span name,
    through an append-only dictionary whose codes never remap.

New segments are delta-encoded into the host tails off the push lock
(the ingester only marks trace ids dirty at push time; the decode
amortizes into the next refresh), and refreshes delta-upload: when the
row bucket is unchanged only the NEW rows cross the host->device link
(jax.lax.dynamic_update_slice builds the next generation's array from
the resident one -- a device-side copy, not a PCIe transfer), while the
tiny slot columns re-upload whole. Every refresh stamps a new
generation and returns an immutable LiveSnapshot, so an in-flight query
keeps a consistent view while later refreshes build new generations;
cut/flush retiring a trace only flips its slot's alive flag (no row
re-staging), and a compaction pass rebuilds the tails from the
per-trace fragments once dead slots / garbage rows pass a threshold.

Conservative-filter contract (same as ops/filter): the device mask may
over-match but never under-match the host oracle (_SearchEntry
semantics) -- tag/name membership and the time prefilter are exact,
min-duration filters on the per-segment-union duration (>= the
combined-trace duration combine_traces dedupe can shrink), and
max-duration / TraceQL are settled ONLY by the exact host verification
of the selected candidates (db/live_engine).
"""

from __future__ import annotations

import threading
import weakref
from dataclasses import dataclass, field
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

# LiveDict / kv_pair_key moved to the columnar ingest plane (ISSUE 16)
# so WAL feature checkpoints and staging share one dictionary; re-
# exported here for existing importers
from ..ingest.columnar import LiveDict, compute_features, kv_pair_key  # noqa: F401
from ..util.profiler import timed_rlock
from .device import PAD_I32, bucket, pad_rows
from .stage import GKEY_ORIGIN_S

_I32_MIN = -(2**31)
_I32_MAX = 2**31 - 1

# live stagers (one per ingester instance/tenant), weakly held so the
# HBM ledger (util/costmodel) can account their resident device tails
# without keeping drained instances alive
_registry_lock = threading.Lock()
_stagers: "weakref.WeakSet" = weakref.WeakSet()


def stager_device_bytes() -> tuple[int, int]:
    """(total device bytes of all live stagers' resident columns,
    stager count) -- the livestage component of the HBM ledger."""
    with _registry_lock:
        stagers = list(_stagers)
    return sum(s.device_bytes() for s in stagers), len(stagers)


def _clip_i32(v: int) -> int:
    return int(min(max(v, _I32_MIN + 1), _I32_MAX))


def _delta_bucket(n: int, floor: int = 64) -> int:
    """Small power-of-two bucket for delta-row uploads (no MIN_BUCKET
    floor: a 50-row delta must not pad to 1024 rows or the in-place
    append could not fit before the full bucket does)."""
    b = floor
    while b < n:
        b <<= 1
    return b


@dataclass
class _TraceTail:
    """Host-side per-trace fragment: which segments are staged and the
    rows/aggregates they contributed. Fragments survive until the trace
    retires so a compaction rebuild never re-decodes segments."""

    slot: int
    staged_segs: list = field(default_factory=list)  # segment refs
    kv_codes: list = field(default_factory=list)
    name_codes: list = field(default_factory=list)
    kv_seen: set = field(default_factory=set)  # staged kv CODES
    name_seen: set = field(default_factory=set)  # staged name CODES
    min_start_ns: int | None = None
    max_end_ns: int | None = None
    state: str = "live"


@dataclass(frozen=True)
class LiveSnapshot:
    """One consistent, immutable view of the staged live head. Slot
    arrays are copies (they mutate in place across refreshes); row
    arrays are views into append-only storage (rows below the recorded
    counts are never rewritten; growth reallocates, compaction swaps in
    fresh arrays -- either way this snapshot's references stay valid)."""

    generation: int
    n_slots: int
    n_kv: int
    n_name: int
    slot_b: int
    kv_b: int
    name_b: int
    # host columns (numpy)
    start_s: np.ndarray
    end_s: np.ndarray
    dur_ms: np.ndarray
    key_s: np.ndarray
    alive: np.ndarray
    id_codes: np.ndarray  # (n_slots, 4)
    kv_owner: np.ndarray
    kv_code: np.ndarray
    name_owner: np.ndarray
    name_code: np.ndarray
    # device columns (None until the device path first stages)
    dev: dict | None
    # slot -> trace id (the collect step maps winners back through the
    # caller's own groups snapshot for segments/verification)
    slot_tid: dict


# ------------------------------------------------------------ kernels


@lru_cache(maxsize=128)
def _compiled_live_filter(n_tags: int, n_names: int, f_start: bool, f_end: bool,
                          f_min: bool, slot_b: int, kv_b: int, name_b: int):
    """Structure (tag/name counts, which scalar prefilters exist,
    buckets) keys the compile; codes and thresholds are traced, so
    every live query with the same shape shares one program (the
    ops/filter launch-key contract)."""

    @jax.jit
    def run(start_s, end_s, dur_ms, alive, kv_owner, kv_code,
            name_owner, name_code, tag_codes, name_qcodes,
            t0, t1, dmin, n_slots):
        valid = jnp.arange(slot_b, dtype=jnp.int32) < n_slots
        mask = (alive > 0) & valid
        if f_start:
            mask = mask & (end_s >= t0)
        if f_end:
            mask = mask & (start_s <= t1)
        if f_min:
            # conservative: staged dur is the per-segment-union duration,
            # >= the exact combined duration, so >= dmin never
            # under-matches (exact check happens in host verification)
            mask = mask & (dur_ms >= dmin)
        kv_own = jnp.clip(kv_owner, 0, slot_b - 1)
        for i in range(n_tags):
            hit = (kv_code == tag_codes[i]).astype(jnp.int32)
            mask = mask & (jax.ops.segment_max(hit, kv_own, num_segments=slot_b) > 0)
        nm_own = jnp.clip(name_owner, 0, slot_b - 1)
        for i in range(n_names):
            hit = (name_code == name_qcodes[i]).astype(jnp.int32)
            mask = mask & (jax.ops.segment_max(hit, nm_own, num_segments=slot_b) > 0)
        return mask

    return run


def eval_live_device(snap: LiveSnapshot, tag_codes: list[int],
                     name_codes: list[int], t0: int, t1: int, dmin: int):
    """Fused live-head filter on device: slot mask over the staged
    columns. t0/t1/dmin <= 0 mean 'no filter' (matching SearchRequest's
    zero-is-unset convention). Returns the device mask (slot_b,)."""
    from ..util.kerneltel import TEL

    d = snap.dev
    key = (len(tag_codes), len(name_codes), t0 > 0, t1 > 0, dmin > 0,
           snap.slot_b, snap.kv_b, snap.name_b)
    fn = _compiled_live_filter(*key)
    args = (
        d["start_s"], d["end_s"], d["dur_ms"], d["alive"],
        d["kv_owner"], d["kv_code"], d["name_owner"], d["name_code"],
        np.asarray(tag_codes or [0], dtype=np.int32),
        np.asarray(name_codes or [0], dtype=np.int32),
        np.int32(_clip_i32(t0)), np.int32(_clip_i32(t1)),
        np.int32(_clip_i32(dmin)), np.int32(snap.n_slots),
    )
    from ..util import costmodel

    TEL.record_launch("live_filter", ("live_filter",) + key, snap.slot_b,
                      cost=lambda: costmodel.spec(fn, *args))
    import time as _time

    t_start = _time.perf_counter()
    out = fn(*args)
    return TEL.observe_device("live_filter", snap.slot_b, t_start, out)


def eval_live_host(snap: LiveSnapshot, tag_codes: list[int],
                   name_codes: list[int], t0: int, t1: int, dmin: int) -> np.ndarray:
    """Numpy twin of eval_live_device over the snapshot's host columns:
    identical mask semantics with zero device round trips -- the
    tiny-head engine below the measured row-count crossover."""
    n = snap.n_slots
    mask = snap.alive[:n] > 0
    if t0 > 0:
        mask &= snap.end_s[:n] >= _clip_i32(t0)
    if t1 > 0:
        mask &= snap.start_s[:n] <= _clip_i32(t1)
    if dmin > 0:
        mask &= snap.dur_ms[:n] >= _clip_i32(dmin)
    kv_owner = snap.kv_owner[: snap.n_kv]
    kv_code = snap.kv_code[: snap.n_kv]
    for c in tag_codes:
        hit = np.zeros(max(n, 1), dtype=bool)
        owners = kv_owner[kv_code == c]
        hit[owners[(owners >= 0) & (owners < n)]] = True
        mask &= hit[:n]
    nm_owner = snap.name_owner[: snap.n_name]
    nm_code = snap.name_code[: snap.n_name]
    for c in name_codes:
        hit = np.zeros(max(n, 1), dtype=bool)
        owners = nm_owner[nm_code == c]
        hit[owners[(owners >= 0) & (owners < n)]] = True
        mask &= hit[:n]
    return mask


@lru_cache(maxsize=32)
def _compiled_find(slot_b: int):
    @jax.jit
    def run(id_codes, alive, q, n_slots):
        valid = jnp.arange(slot_b, dtype=jnp.int32) < n_slots
        m = jnp.all(id_codes == q[None, :], axis=1) & (alive > 0) & valid
        return jnp.where(jnp.any(m), jnp.argmax(m), -1)

    return run


def find_slot_device(snap: LiveSnapshot, trace_id: bytes) -> int:
    """Locate a live trace's slot on device by its 4x int32 id codes;
    -1 = not staged/alive. One tiny fetch."""
    from ..block import schema as S
    from ..util.kerneltel import TEL

    d = snap.dev
    fn = _compiled_find(snap.slot_b)
    q = np.asarray(S.trace_id_to_codes(trace_id.rjust(16, b"\x00")), dtype=np.int32)
    ns = np.int32(snap.n_slots)
    from ..util import costmodel

    TEL.record_launch(
        "live_find", ("live_find", snap.slot_b), snap.slot_b,
        cost=lambda: costmodel.spec(fn, d["id_codes"], d["alive"], q, ns))
    import time as _time

    t0 = _time.perf_counter()
    out = fn(d["id_codes"], d["alive"], q, ns)
    out = TEL.observe_device("live_find", snap.slot_b, t0, out)
    return int(np.asarray(out))


def find_slot_host(snap: LiveSnapshot, trace_id: bytes) -> int:
    """Numpy twin of find_slot_device."""
    from ..block import schema as S

    n = snap.n_slots
    if n == 0:
        return -1
    q = np.asarray(S.trace_id_to_codes(trace_id.rjust(16, b"\x00")), dtype=np.int32)
    m = np.all(snap.id_codes[:n] == q[None, :], axis=1) & (snap.alive[:n] > 0)
    idx = int(np.argmax(m))
    return idx if m[idx] else -1


@jax.jit
def _append_rows_device(dst, src, start):
    """Delta append: next generation's column = resident array with the
    new rows written at `start`. The copy is device-side; only `src`
    (the padded delta) crosses the host->device link."""
    return jax.lax.dynamic_update_slice(dst, src, (start,))


@jax.jit
def _patch_slots_device(dst, idx, vals):
    """Dirty-slot patch: scatter the changed slot values into the
    resident column. idx is padded by REPEATING real indices (the
    overwrite is idempotent), so pad lanes never touch foreign rows."""
    return dst.at[idx].set(vals)


# ------------------------------------------------------------- stager


class LiveStager:
    """Per-tenant live-head staging state. All mutation happens under
    self.lock (refresh/retire/compact); queries run lock-free against
    the immutable LiveSnapshot a refresh returns."""

    # rebuild the tails once dead slots or dead rows dominate
    COMPACT_DEAD_FRACTION = 0.5

    def __init__(self, dictionary: LiveDict | None = None, features_fn=None):
        # cataloged hot lock: pushes, refreshes and retirements all
        # serialize on the tail here (TEMPO_LOCK_PROFILE arms timing;
        # the wrapper's RLock keeps refresh->retire recursion legal)
        self.lock = timed_rlock("livestage_tail")
        self.dict = dictionary or LiveDict()
        # seg -> SegFeatures source: the instance's ColumnarIngest cache
        # when wired (decode once per segment across consumers), else a
        # direct compute against this stager's own dictionary
        self._features = features_fn or (lambda seg: compute_features(seg, self.dict))
        self.tails: dict[bytes, _TraceTail] = {}
        self.generation = 0
        # slot columns (numpy, capacity-grown; n_slots is the high-water)
        self.n_slots = 0
        self.dead_slots = 0
        self._slot_cap = 0
        self.start_s = np.empty(0, np.int32)
        self.end_s = np.empty(0, np.int32)
        self.dur_ms = np.empty(0, np.int32)
        self.key_s = np.empty(0, np.int32)
        self.alive = np.empty(0, np.int32)
        self.id_codes = np.empty((0, 4), np.int32)
        # append-only row tails
        self.n_kv = 0
        self.dead_kv = 0
        self.kv_owner = np.empty(0, np.int32)
        self.kv_code = np.empty(0, np.int32)
        self.n_name = 0
        self.dead_name = 0
        self.name_owner = np.empty(0, np.int32)
        self.name_code = np.empty(0, np.int32)
        # device generation (arrays + the row counts they cover)
        self._dev: dict | None = None
        self._dev_rows: tuple[int, int, int] | None = None  # slots, kv, name
        self._dirty_slots: set[int] = set()  # slots changed since last upload
        self._snap: LiveSnapshot | None = None
        with _registry_lock:
            _stagers.add(self)

    def device_bytes(self) -> int:
        """Resident device bytes of the staged tails (HBM ledger)."""
        with self.lock:
            dev = self._dev
            return sum(int(a.nbytes) for a in dev.values()) if dev else 0

    # ------------------------------------------------------ host tails
    def _grow_slots_locked(self, need: int) -> None:
        if need <= self._slot_cap:
            return
        cap = max(64, self._slot_cap * 2, need)
        for name in ("start_s", "end_s", "dur_ms", "key_s", "alive"):
            old = getattr(self, name)
            new = np.zeros(cap, np.int32)
            new[: old.shape[0]] = old
            setattr(self, name, new)
        old = self.id_codes
        new = np.zeros((cap, 4), np.int32)
        new[: old.shape[0]] = old
        self.id_codes = new
        self._slot_cap = cap

    @staticmethod
    def _append_rows(arr: np.ndarray, n: int, vals: list) -> np.ndarray:
        """Append vals at arr[n:]; grows by reallocation (old arrays --
        and any snapshot views into them -- stay intact)."""
        need = n + len(vals)
        if need > arr.shape[0]:
            cap = max(256, arr.shape[0] * 2, need)
            new = np.full(cap, PAD_I32, np.int32)
            new[: arr.shape[0]] = arr
            arr = new
        arr[n:need] = vals
        return arr

    def note_rows(self) -> tuple[int, int, int]:
        """(slots, kv rows, name rows) -- the engine's routing input."""
        with self.lock:
            return self.n_slots, self.n_kv, self.n_name

    def _alloc_slot_locked(self, tid: bytes) -> _TraceTail:
        from ..block import schema as S

        slot = self.n_slots
        self._grow_slots_locked(slot + 1)
        self.n_slots += 1
        self.alive[slot] = 1
        self.id_codes[slot] = np.asarray(
            S.trace_id_to_codes(tid.rjust(16, b"\x00")), dtype=np.int32)
        tail = _TraceTail(slot=slot)
        self.tails[tid] = tail
        self._dirty_slots.add(slot)
        return tail

    def _retire_locked(self, tid: bytes, tail: _TraceTail) -> None:
        self.alive[tail.slot] = 0
        self._dirty_slots.add(tail.slot)
        self.dead_slots += 1
        self.dead_kv += len(tail.kv_codes)
        self.dead_name += len(tail.name_codes)
        del self.tails[tid]

    def _stage_trace_locked(self, tid: bytes, segs: list,
                            start_s: int, end_s: int, state: str) -> bool:
        """Bring one trace's tail up to `segs`; returns True when slot
        or row state changed. Segment identity is the staleness check:
        the lifecycle keeps a trace's merged segment list prefix-stable
        (cut extends, flush snapshots, failed flushes restore in order),
        and any violation simply restages the trace on a fresh slot."""
        tail = self.tails.get(tid)
        if tail is not None:
            ns = len(tail.staged_segs)
            if any(a is not b for a, b in zip(tail.staged_segs, segs)):
                # reordered merge (or reborn id): the old rows are
                # garbage now -- kill the slot, restage whole
                self._retire_locked(tid, tail)
                tail = None
            elif len(segs) < ns:
                # a strict prefix of what is already staged: a stale
                # snapshot racing a newer refresh (the engine serializes
                # these, but stay safe) -- staged state is newer, no-op
                return False
        if tail is None:
            tail = self._alloc_slot_locked(tid)
        dirty = False
        for seg in segs[len(tail.staged_segs):]:
            feat = self._features(seg)
            lo, hi = feat.lo_ns, feat.hi_ns
            kv_add = [c for c in feat.kv_codes if c not in tail.kv_seen]
            tail.kv_seen.update(kv_add)
            nm_add = [c for c in feat.name_codes if c not in tail.name_seen]
            tail.name_seen.update(nm_add)
            if kv_add:
                self.kv_owner = self._append_rows(
                    self.kv_owner, self.n_kv, [tail.slot] * len(kv_add))
                self.kv_code = self._append_rows(self.kv_code, self.n_kv, kv_add)
                self.n_kv += len(kv_add)
                tail.kv_codes.extend(kv_add)
            if nm_add:
                self.name_owner = self._append_rows(
                    self.name_owner, self.n_name, [tail.slot] * len(nm_add))
                self.name_code = self._append_rows(self.name_code, self.n_name, nm_add)
                self.n_name += len(nm_add)
                tail.name_codes.extend(nm_add)
            if lo is not None and (tail.min_start_ns is None or lo < tail.min_start_ns):
                tail.min_start_ns = lo
            if hi is not None and (tail.max_end_ns is None or hi > tail.max_end_ns):
                tail.max_end_ns = hi
            tail.staged_segs.append(seg)
            dirty = True
        slot = tail.slot
        lo_ns = tail.min_start_ns or 0
        hi_ns = tail.max_end_ns or 0
        dur = _clip_i32(max(0, (hi_ns - lo_ns) // 1_000_000))
        key = _clip_i32(lo_ns // 1_000_000_000 - GKEY_ORIGIN_S) if lo_ns else _I32_MIN + 1
        vals = (int(np.int32(_clip_i32(start_s))), int(np.int32(_clip_i32(end_s))),
                dur, key)
        cur = (int(self.start_s[slot]), int(self.end_s[slot]),
               int(self.dur_ms[slot]), int(self.key_s[slot]))
        if dirty or cur != vals or tail.state != state:
            if cur != vals or dirty:
                self._dirty_slots.add(slot)
            self.start_s[slot], self.end_s[slot] = vals[0], vals[1]
            self.dur_ms[slot], self.key_s[slot] = vals[2], vals[3]
            tail.state = state
            dirty = True
        return dirty

    def _compact_locked(self) -> None:
        """Rebuild slots + row tails from the live per-trace fragments:
        dead slots and their rows vanish, fragments re-own fresh
        contiguous slots. Rebuilt arrays are NEW objects, so earlier
        snapshots keep their old views."""
        tails = sorted(self.tails.items(), key=lambda kv: kv[1].slot)
        n = len(tails)
        cap = max(64, n)
        start_s = np.zeros(cap, np.int32)
        end_s = np.zeros(cap, np.int32)
        dur_ms = np.zeros(cap, np.int32)
        key_s = np.zeros(cap, np.int32)
        alive = np.zeros(cap, np.int32)
        id_codes = np.zeros((cap, 4), np.int32)
        kv_owner: list[int] = []
        kv_code: list[int] = []
        nm_owner: list[int] = []
        nm_code: list[int] = []
        for new_slot, (tid, tail) in enumerate(tails):
            old = tail.slot
            start_s[new_slot] = self.start_s[old]
            end_s[new_slot] = self.end_s[old]
            dur_ms[new_slot] = self.dur_ms[old]
            key_s[new_slot] = self.key_s[old]
            alive[new_slot] = 1
            id_codes[new_slot] = self.id_codes[old]
            kv_owner.extend([new_slot] * len(tail.kv_codes))
            kv_code.extend(tail.kv_codes)
            nm_owner.extend([new_slot] * len(tail.name_codes))
            nm_code.extend(tail.name_codes)
            tail.slot = new_slot
        self.start_s, self.end_s = start_s, end_s
        self.dur_ms, self.key_s, self.alive = dur_ms, key_s, alive
        self.id_codes = id_codes
        self._slot_cap = cap
        self.n_slots, self.dead_slots = n, 0
        self.kv_owner = np.asarray(kv_owner or [], dtype=np.int32)
        self.kv_code = np.asarray(kv_code or [], dtype=np.int32)
        self.n_kv, self.dead_kv = len(kv_code), 0
        self.name_owner = np.asarray(nm_owner or [], dtype=np.int32)
        self.name_code = np.asarray(nm_code or [], dtype=np.int32)
        self.n_name, self.dead_name = len(nm_code), 0
        self._dev = None  # buckets/ownership changed: next upload is full
        self._dev_rows = None

    # ---------------------------------------------------------- refresh
    def refresh(self, items: dict, stage_device: bool = True) -> LiveSnapshot:
        """Reconcile the tails against `items` ({tid: (segments, state,
        start_s, end_s)} -- the caller's consistent instance-lock
        snapshot, segments merged flushing+cut+live per tid) and return
        the new generation's snapshot. stage_device=False keeps the
        refresh host-only (the tiny-head path pays no upload)."""
        import time as _time

        from ..util.kerneltel import TEL

        with self.lock:
            t_delta = _time.perf_counter()
            dirty = False
            for tid in [t for t in self.tails if t not in items]:
                self._retire_locked(tid, self.tails[tid])
                dirty = True
            for tid, (segs, state, start_s, end_s) in items.items():
                dirty |= self._stage_trace_locked(tid, segs, start_s, end_s, state)
            if dirty:
                # ingest-stage ledger: the host delta encode (includes any
                # segment decodes the columnar cache had not absorbed)
                TEL.record_ingest_stage("stage_delta",
                                        _time.perf_counter() - t_delta)
            total_rows = self.n_kv + self.n_name
            dead_rows = self.dead_kv + self.dead_name
            if self.n_slots and (
                self.dead_slots > self.COMPACT_DEAD_FRACTION * self.n_slots
                or (total_rows and dead_rows > self.COMPACT_DEAD_FRACTION * total_rows)
            ):
                self._compact_locked()
                dirty = True
            snap = self._snap
            if (not dirty and snap is not None
                    and (not stage_device or snap.dev is not None)):
                return snap  # same generation still describes the tails
            dev = self._upload_locked() if stage_device else None
            self.generation += 1
            n = self.n_slots
            states: dict[str, int] = {"dead": self.dead_slots}
            for tail in self.tails.values():
                states[tail.state] = states.get(tail.state, 0) + 1
            TEL.set_livestage_rows(states, self.n_kv + self.n_name,
                                   self.generation)
            snap = LiveSnapshot(
                generation=self.generation,
                n_slots=n, n_kv=self.n_kv, n_name=self.n_name,
                slot_b=bucket(max(n, 1)),
                kv_b=bucket(max(self.n_kv, 1)),
                name_b=bucket(max(self.n_name, 1)),
                start_s=self.start_s[:n].copy(),
                end_s=self.end_s[:n].copy(),
                dur_ms=self.dur_ms[:n].copy(),
                key_s=self.key_s[:n].copy(),
                alive=self.alive[:n].copy(),
                id_codes=self.id_codes[:n].copy(),
                kv_owner=self.kv_owner[: self.n_kv],
                kv_code=self.kv_code[: self.n_kv],
                name_owner=self.name_owner[: self.n_name],
                name_code=self.name_code[: self.n_name],
                dev=dev,
                slot_tid={tail.slot: tid for tid, tail in self.tails.items()},
            )
            self._snap = snap
            return snap

    def _upload_locked(self) -> dict:
        """Bring the device columns up to the host tails. Slot columns
        re-upload whole (tiny); row tails append in place via
        dynamic_update_slice when they fit under the resident bucket,
        else re-upload full. Returns the device column dict."""
        from ..util.kerneltel import TEL

        n = self.n_slots
        slot_b = bucket(max(n, 1))
        kv_b = bucket(max(self.n_kv, 1))
        name_b = bucket(max(self.n_name, 1))
        dev = dict(self._dev) if self._dev is not None else None
        prev = self._dev_rows
        full = (
            dev is None or prev is None
            or dev["start_s"].shape[0] != slot_b
            or dev["kv_owner"].shape[0] != kv_b
            or dev["name_owner"].shape[0] != name_b
        )
        sent = 0
        rows_sent = 0
        if full:
            host = {
                "start_s": pad_rows(self.start_s[:n], slot_b, np.int32(0)),
                "end_s": pad_rows(self.end_s[:n], slot_b, np.int32(0)),
                "dur_ms": pad_rows(self.dur_ms[:n], slot_b, np.int32(0)),
                "key_s": pad_rows(self.key_s[:n], slot_b, np.int32(_I32_MIN)),
                "alive": pad_rows(self.alive[:n], slot_b, np.int32(0)),
                "id_codes": pad_rows(self.id_codes[:n], slot_b, PAD_I32),
                "kv_owner": pad_rows(self.kv_owner[: self.n_kv], kv_b, np.int32(0)),
                "kv_code": pad_rows(self.kv_code[: self.n_kv], kv_b, PAD_I32),
                "name_owner": pad_rows(self.name_owner[: self.n_name], name_b,
                                       np.int32(0)),
                "name_code": pad_rows(self.name_code[: self.n_name], name_b,
                                      PAD_I32),
            }
            dev = dict(zip(host, jax.device_put(list(host.values()))))
            sent = sum(int(a.nbytes) for a in host.values())
            rows_sent = n + self.n_kv + self.n_name
        else:
            # slot columns: scatter-patch only the DIRTY slots (idx
            # lanes pad by repeating a real index -- idempotent), so a
            # 2-trace push moves tens of bytes, not the padded columns
            dirty = sorted(s for s in self._dirty_slots if s < slot_b)
            if dirty:
                db_ = _delta_bucket(len(dirty), 16)
                idx = np.asarray(dirty + [dirty[0]] * (db_ - len(dirty)),
                                 dtype=np.int32)
                for name_ in ("start_s", "end_s", "dur_ms", "key_s", "alive",
                              "id_codes"):
                    src = getattr(self, name_)[idx]
                    dev[name_] = _patch_slots_device(dev[name_], idx, src)
                    sent += int(idx.nbytes + src.nbytes)
                rows_sent += len(dirty)
            for owner_name, code_name, n_new, fill_owner in (
                ("kv_owner", "kv_code", self.n_kv, 0),
                ("name_owner", "name_code", self.n_name, 0),
            ):
                n_old = prev[1] if owner_name == "kv_owner" else prev[2]
                if n_new == n_old:
                    continue
                delta = n_new - n_old
                db = _delta_bucket(delta)
                bkt = dev[owner_name].shape[0]
                owner_src = getattr(self, owner_name)[n_old:n_new]
                code_src = getattr(self, code_name)[n_old:n_new]
                if n_old + db <= bkt:
                    owner_p = pad_rows(owner_src, db, np.int32(fill_owner))
                    code_p = pad_rows(code_src, db, PAD_I32)
                    dev[owner_name] = _append_rows_device(
                        dev[owner_name], owner_p, np.int32(n_old))
                    dev[code_name] = _append_rows_device(
                        dev[code_name], code_p, np.int32(n_old))
                    sent += int(owner_p.nbytes + code_p.nbytes)
                else:  # padded delta would clip: full column re-upload
                    owner_p = pad_rows(getattr(self, owner_name)[:n_new], bkt,
                                       np.int32(fill_owner))
                    code_p = pad_rows(getattr(self, code_name)[:n_new], bkt, PAD_I32)
                    dev[owner_name], dev[code_name] = jax.device_put(
                        [owner_p, code_p])
                    sent += int(owner_p.nbytes + code_p.nbytes)
                rows_sent += delta
        self._dev = dev
        self._dev_rows = (n, self.n_kv, self.n_name)
        self._dirty_slots.clear()
        if sent:
            TEL.record_livestage_upload(sent, rows_sent, full)
        return dev
