"""Data-driven predicate evaluation over vtpu columns: the TraceQL /
tag-search execution kernel.

This replaces the reference's iterator-tree engine (pkg/parquetquery
ColumnIterator/JoinIterator + vparquet/block_search.go pipelines) with
one vectorized pass: every condition becomes a boolean mask over its
axis (span rows, attr rows, resource rows), attr/resource hits scatter
to span rows with a segment-max, masks combine through a static boolean
expression tree on the VPU, and the span mask aggregates to a trace
mask with another segment-max. No Dremel rep/def levels anywhere:
hierarchy is explicit segment ids (SURVEY.md 7.3 "the crux" -- this
layout dissolves it).

Only the STRUCTURE (expression tree + condition targets/ops) keys a jit
compile; operand values -- dictionary codes, thresholds, regex-match
tables -- are traced arrays, so `{span.foo = "bar"}` and
`{span.foo = "baz"}` share one compiled program.

Regex and set predicates use *dictionary tables*: the host evaluates the
regex once over the block's sorted dictionary (the same trick as
parquet dictionary-page pruning, pkg/parquetquery/predicates.go:38-89)
and ships a boolean table; on device the predicate is a single gather.

Device filters are *conservative* (may over-match, never under-match):
clamped int32 / f32 encodings use widened comparisons; conditions whose
encodings can over-match are flagged needs_verify and re-checked
exactly on host over the surviving candidates (db/search.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

# condition targets
T_SPAN = "span"  # direct span-axis column
T_TRACE = "trace"  # trace-axis column
T_RES = "res"  # resource-axis dedicated column (gathered via span.res_idx)
T_SATTR = "sattr"  # generic span attr table
T_RATTR = "rattr"  # generic resource attr table

# ops: v0/v1 int operands, f0/f1 float operands, table = dict-code table
OPS = (
    "eq", "ne", "ne_present", "lt", "le", "gt", "ge", "range",
    "exists", "ne_clamped", "intable", "notintable",
)


@dataclass(frozen=True)
class Cond:
    """One predicate. Hashable => part of the jit key."""

    target: str
    col: str  # device column ('span.dur_us', 'res.service_id', ...) or
    # value kind for attr targets: 'str', 'int', 'float', 'bool', 'any'
    op: str
    is_float: bool = False
    needs_verify: bool = False


@dataclass
class Operands:
    """Per-condition operand values (traced; NOT part of the jit key).
    ints[i] = (key_code, v0, v1); floats[i] = (f0, f1);
    tables[i] = bool array over dictionary codes (intable ops only)."""

    ints: np.ndarray  # (n_conds, 3) int32
    floats: np.ndarray  # (n_conds, 2) float32
    tables: dict[int, np.ndarray] | None = None

    @classmethod
    def build(cls, rows: list, tables: dict[int, np.ndarray] | None = None) -> "Operands":
        if not rows:
            ints = np.zeros((0, 3), np.int32)
            floats = np.zeros((0, 2), np.float32)
        else:
            ints = np.asarray([[r[0], r[1], r[2]] for r in rows], dtype=np.int64)
            ints = np.clip(ints, -(2**31), 2**31 - 1).astype(np.int32)
            floats = np.asarray([[r[3], r[4]] for r in rows], dtype=np.float32)
        return cls(ints, floats, tables)


_ATTR_VALUE_COL = {"str": "str_id", "int": "int32", "bool": "int32", "float": "f32"}
_VT_CODE = {"str": 0, "int": 1, "float": 2, "bool": 3, "any": -1}

# expression trees: ('cond', i) | ('and', *children) | ('or', *children)
CondTree = tuple


def all_conds_tree(n: int) -> CondTree:
    return ("and",) + tuple(("cond", i) for i in range(n))


def _flatten(conds) -> list:
    out = []
    for g in conds:
        if isinstance(g, Cond):
            out.append(g)
        else:
            out.extend(g)
    return out


def required_columns(conds) -> list[str]:
    # trace.span_off: spans are stored grouped by trace, so span->trace
    # aggregation is cumsum + gather-at-offsets (no scatter; see
    # _offset_counts). trace_sid still feeds the trace->span gather.
    # span@<res col> entries are NOT physical columns: they ask the
    # staging layer to materialize that res column at span level once
    # (query-independent), so the kernel avoids a per-query span-length
    # gather. Readers of raw columns must skip them.
    need = {"span.trace_sid", "trace.span_off"}
    for c in _flatten(conds):
        if c.target in (T_SPAN, T_TRACE):
            need.add(c.col)
        elif c.target == T_RES:
            need.add(c.col)
            need.add("span.res_idx")
            need.add(f"span@{c.col}")
        elif c.target == T_SATTR:
            need.update({"sattr.span", "sattr.key_id", "sattr.vtype"})
            if c.col in _ATTR_VALUE_COL:
                need.add(f"sattr.{_ATTR_VALUE_COL[c.col]}")
        elif c.target == T_RATTR:
            # res.service_id rides along to size the resource axis
            need.update({"rattr.res", "rattr.key_id", "rattr.vtype", "span.res_idx", "res.service_id"})
            if c.col in _ATTR_VALUE_COL:
                need.add(f"rattr.{_ATTR_VALUE_COL[c.col]}")
    return sorted(need)


def _cmp(op: str, x, v0, v1, f0, f1, is_float: bool, table):
    if is_float:
        a, b = f0, f1
    else:
        a, b = v0, v1
    if op == "eq":
        return x == a
    if op == "ne":
        return x != a
    if op == "ne_present":  # value present (code >= 0) and differs
        return (x != a) & (x >= 0)
    if op == "ne_clamped":  # conservative ne on a clamped int encoding
        return (x != a) | (x == 2**31 - 1) | (x == -(2**31) + 1)
    if op == "lt":
        return x < a
    if op == "le":
        return x <= a
    if op == "gt":
        return x > a
    if op == "ge":
        return x >= a
    if op == "range":  # inclusive [a, b]
        return (x >= a) & (x <= b)
    if op == "exists":
        return jnp.ones_like(x, dtype=bool)
    if op in ("intable", "notintable"):
        hit = table[jnp.clip(x, 0, table.shape[0] - 1)] > 0
        if op == "notintable":
            hit = ~hit
        return hit & (x >= 0)
    raise ValueError(f"unknown op {op}")


def _offset_counts(mask, off):
    """Per-segment True counts when rows are GROUPED by segment (the
    vtpu layout: spans sorted by trace, attrs sorted by owner):
    exclusive cumsum + two gathers at the segment offsets. On TPU this
    is a parallel scan instead of a scatter -- XLA lowers segment_sum/
    segment_max over 1M+ rows to a serialized scatter loop that costs
    tens of ms and monopolizes the chip; the scan form is ~10x faster
    and pipelines across concurrent queries. off: (n_seg+1,) rows."""
    ecs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(mask.astype(jnp.int32))]
    )
    return ecs[off[1:]] - ecs[off[:-1]]


def _cond_mask(c: Cond, i, cols, ops_i, ops_f, tables, n_spans_b, n_res_b, valid_span):
    """Span-level mask for one condition."""
    key, v0, v1 = ops_i[i, 0], ops_i[i, 1], ops_i[i, 2]
    f0, f1 = ops_f[i, 0], ops_f[i, 1]
    table = tables.get(i)
    if c.target == T_SPAN:
        return _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, table) & valid_span
    if c.target == T_RES:
        pre = cols.get(f"span@{c.col}")
        if pre is not None:
            # span-level materialization of the res column (one gather at
            # STAGE time, query-independent, cached) -- a direct compare
            # here instead of a span-length gather per query. The PAD
            # sentinel marks spans with no resource row (idx < 0).
            from .device import PAD_I32

            return (
                _cmp(c.op, pre, v0, v1, f0, f1, c.is_float, table)
                & (pre != PAD_I32)
                & valid_span
            )
        res_mask = _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, table)
        idx = jnp.clip(cols["span.res_idx"], 0, res_mask.shape[0] - 1)
        return res_mask[idx] & (cols["span.res_idx"] >= 0) & valid_span
    if c.target in (T_SATTR, T_RATTR):
        pre = c.target
        key_match = cols[f"{pre}.key_id"] == key
        if c.col == "any":
            row_hit = key_match
        else:
            vcol = cols[f"{pre}.{_ATTR_VALUE_COL[c.col]}"]
            vt_ok = cols[f"{pre}.vtype"] == _VT_CODE[c.col]
            row_hit = key_match & vt_ok & _cmp(c.op, vcol, v0, v1, f0, f1, c.is_float, table)
        if pre == T_SATTR:
            if "sattr.off" in cols:  # grouped-by-span rows: scan, no scatter
                return (_offset_counts(row_hit, cols["sattr.off"]) > 0) & valid_span
            owner = jnp.clip(cols["sattr.span"], 0, n_spans_b - 1)
            return (
                jax.ops.segment_max(row_hit.astype(jnp.int32), owner, num_segments=n_spans_b) > 0
            ) & valid_span
        if "rattr.off" in cols:
            res_mask = _offset_counts(row_hit, cols["rattr.off"]) > 0
        else:
            owner = jnp.clip(cols["rattr.res"], 0, n_res_b - 1)
            res_mask = (
                jax.ops.segment_max(row_hit.astype(jnp.int32), owner, num_segments=n_res_b) > 0
            )
        idx = jnp.clip(cols["span.res_idx"], 0, n_res_b - 1)
        return res_mask[idx] & (cols["span.res_idx"] >= 0) & valid_span
    raise ValueError(f"bad target {c.target}")


def normalize_tree(tree: CondTree, conds: tuple[Cond, ...]) -> CondTree:
    """Lift a mixed tree into trace-level form: pure-span subtrees wrap in
    ('tracify', t); trace-target conds stay direct. A mix below an 'or'
    of span and trace conds is allowed: the span side tracifies."""
    trace_idx = {i for i, c in enumerate(conds) if c.target == T_TRACE}

    def purity(t):  # 'trace' | 'span' | 'mixed'
        if t[0] == "tracify":
            return "trace"
        if t[0] == "struct":
            # ('struct', op, lhs, rhs): spanset-relation node, span-level
            # by construction (t[1] is the op STRING -- never recurse it)
            return "span"
        if t[0] == "cond":
            return "trace" if t[1] in trace_idx else "span"
        kinds = {purity(ch) for ch in t[1:]}
        return kinds.pop() if len(kinds) == 1 else "mixed"

    def lift(t):
        p = purity(t)
        if p == "span":
            return ("tracify", t)
        if p == "trace":
            return t
        if t[0] == "and":
            # span-pure children must hold on the SAME span (single-spanset
            # semantics): group them under ONE tracify, don't lift each
            span_ch = [ch for ch in t[1:] if purity(ch) == "span"]
            rest = [lift(ch) for ch in t[1:] if purity(ch) != "span"]
            if span_ch:
                sub = span_ch[0] if len(span_ch) == 1 else ("and",) + tuple(span_ch)
                rest = [("tracify", sub)] + rest
            return rest[0] if len(rest) == 1 else ("and",) + tuple(rest)
        return (t[0],) + tuple(lift(ch) for ch in t[1:])

    return lift(tree)


@lru_cache(maxsize=256)
def _compiled(tree: CondTree | None, conds: tuple[Cond, ...], table_idxs: tuple[int, ...],
              n_spans_b: int, n_res_b: int, n_traces_b: int, span_out: bool = True):
    """tree is a TRACE-level expression: leaves are ('cond', i) with a
    trace-target cond or ('tracify', span_tree) aggregating a span-level
    subtree; None matches everything.

    span_out=False drops the span-level mask output, which lets the
    program skip the trace->span survival gather entirely (counts are
    zeroed at TRACE level instead) -- a span-length random gather is one
    of the most expensive ops on the TPU, and the search path only ever
    consumes trace-level outputs."""

    @jax.jit
    def run(cols, ops_i, ops_f, table_list, n_spans, n_traces):
        tables = dict(zip(table_idxs, table_list))
        valid_span = jnp.arange(n_spans_b, dtype=jnp.int32) < n_spans
        valid_trace = jnp.arange(n_traces_b, dtype=jnp.int32) < n_traces
        span_masks: list = []  # union for reporting/counts

        def ev_span(t):
            if t == ("true",):
                return valid_span
            if t == ("false",):
                return jnp.zeros_like(valid_span)
            if t[0] == "cond":
                i = t[1]
                return _cond_mask(conds[i], i, cols, ops_i, ops_f, tables,
                                  n_spans_b, n_res_b, valid_span)
            if t[0] == "struct":
                return ev_struct(t[1], ev_span(t[2]), ev_span(t[3]))
            masks = [ev_span(ch) for ch in t[1:]]
            out = masks[0]
            for m in masks[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        def ev_struct(op, lm, rm):
            """Exact structural relation over the parent-row column:
            result = rhs spans standing in `op` relation to an lhs span
            (enum_operators.go OpSpansetChild/Descendant/Sibling).
            `>` is one parent gather; `>>` is pointer-doubling (log2
            passes of gather, all fused on device); `~` is one
            segment-sum + gather."""
            pidx = cols["span.parent_idx"]
            has_p = (pidx >= 0) & valid_span
            safe = jnp.clip(pidx, 0, n_spans_b - 1)
            if op == ">":
                return rm & has_p & lm[safe]
            if op == ">>":
                # acc[i] = any lhs match among ancestors reached so far;
                # ptr doubles the jump distance every iteration
                acc = has_p & lm[safe]
                ptr = jnp.where(has_p, safe, -1)
                for _ in range(max(1, (n_spans_b - 1).bit_length())):
                    psafe = jnp.clip(ptr, 0, n_spans_b - 1)
                    alive = ptr >= 0
                    acc = acc | (alive & acc[psafe])
                    ptr = jnp.where(alive, jnp.where(ptr[psafe] >= 0, ptr[psafe], -1), -1)
                return rm & acc
            # '~': some DIFFERENT lhs span with the same parent. Orphans
            # (parent_idx == -2: parent id set but its span absent) can
            # still be siblings by shared parent ID; the row kernel can't
            # resolve that, so orphan-orphan pairs OVER-match (any lhs
            # orphan in the batch) and host verification settles them
            # (the plan flags '~' trees needs_verify).
            lhs_child = (lm & has_p).astype(jnp.int32)
            owner = jnp.where(has_p & lm, safe, n_spans_b)
            cnt = jax.ops.segment_sum(
                lhs_child, owner, num_segments=n_spans_b + 1)[:n_spans_b]
            sibs = cnt[safe] - (lm & has_p).astype(jnp.int32)
            orphan = (pidx == -2) & valid_span
            any_lhs_orphan = jnp.any(lm & orphan)
            return (rm & has_p & (sibs > 0)) | (rm & orphan & any_lhs_orphan)

        def seg_counts(span_mask):
            """Matched-span count per trace."""
            if "trace.span_off" in cols:  # grouped layout: scan + gather
                return _offset_counts(span_mask & valid_span, cols["trace.span_off"])
            sid = jnp.where(valid_span & span_mask, cols["span.trace_sid"], n_traces_b)
            sid = jnp.clip(sid, 0, n_traces_b)
            return jax.ops.segment_sum(
                span_mask.astype(jnp.int32), sid, num_segments=n_traces_b + 1
            )[:n_traces_b]

        def tracify(span_mask):
            return seg_counts(span_mask) > 0

        def ev_trace(t):
            if t[0] == "tracify":
                sm = ev_span(t[1])
                span_masks.append(sm)
                return tracify(sm)
            if t[0] == "cond":
                i = t[1]
                c = conds[i]
                return _cmp(c.op, cols[c.col], ops_i[i, 1], ops_i[i, 2],
                            ops_f[i, 0], ops_f[i, 1], c.is_float, tables.get(i))
            ms = [ev_trace(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        if tree is None:
            trace_mask = valid_trace
            union = valid_span
        else:
            trace_mask = ev_trace(tree) & valid_trace
            if span_masks:
                union = span_masks[0]
                for m in span_masks[1:]:
                    union = union | m
            else:
                union = valid_span

        if not span_out:
            # spans only count toward surviving traces; zero at trace
            # level -- no span-length gather needed
            span_count = jnp.where(trace_mask, seg_counts(union), 0)
            return trace_mask, span_count

        # a span only counts if its trace survived trace-level conds
        tsid = jnp.clip(cols["span.trace_sid"], 0, n_traces_b - 1)
        span_mask = union & trace_mask[tsid] & valid_span
        span_count = seg_counts(span_mask)
        return span_mask, trace_mask, span_count

    return run


def _groups_to_tree(groups) -> tuple[CondTree, tuple[Cond, ...]]:
    """CNF condition groups (tuple of OR-tuples) -> expression tree."""
    conds: list[Cond] = []
    children = []
    for g in groups:
        if isinstance(g, Cond):
            g = (g,)
        ors = []
        for c in g:
            conds.append(c)
            ors.append(("cond", len(conds) - 1))
        children.append(ors[0] if len(ors) == 1 else ("or",) + tuple(ors))
    tree = children[0] if len(children) == 1 else ("and",) + tuple(children)
    return tree, tuple(conds)


def eval_block(
    query,
    combinator_or_cols,
    *args,
    span_out: bool = True,
):
    """Two call forms:

    eval_block((tree, conds), cols, operands, n_spans, n_traces,
               n_spans_b, n_res_b, n_traces_b)               -- tree form
    eval_block(groups, "and", cols, operands, ...)            -- CNF form

    Returns (span_mask (n_spans_b,), trace_mask (n_traces_b,),
    per-trace matched span count); with span_out=False just
    (trace_mask, counts) -- cheaper on device (no span-level gather)."""
    if isinstance(combinator_or_cols, str):
        groups = query
        if combinator_or_cols != "and":
            tree, conds = _groups_to_tree([tuple(_flatten(groups))])  # single OR group
        else:
            tree, conds = _groups_to_tree(groups)
        cols, operands, n_spans, n_traces, n_spans_b, n_res_b, n_traces_b = args
    else:
        tree, conds = query
        cols = combinator_or_cols
        operands, n_spans, n_traces, n_spans_b, n_res_b, n_traces_b = args
    if tree is not None:
        tree = normalize_tree(tree, conds)  # idempotent

    from .device import bucket, pad_rows

    tables = operands.tables or {}
    table_idxs = tuple(sorted(tables))
    # host arrays/scalars go straight into the jit call: the dispatch
    # uploads them as one batch. Eager jnp conversions here would each
    # issue a separate device_put -- a blocking round trip per array on
    # a high-latency host<->device link.
    table_list = [
        pad_rows(np.asarray(tables[i], dtype=np.uint8), bucket(max(1, len(tables[i]))), 0)
        for i in table_idxs
    ]
    fn = _compiled(tree, conds, table_idxs, n_spans_b, n_res_b, n_traces_b, span_out)
    from ..util import costmodel
    from ..util.kerneltel import TEL

    ns, nt = np.int32(n_spans), np.int32(n_traces)
    TEL.record_launch(
        "filter",
        ("filter", tree, conds, table_idxs, n_spans_b, n_res_b, n_traces_b, span_out),
        n_spans_b,
        cost=lambda: costmodel.spec(fn, cols, operands.ints, operands.floats,
                                    table_list, ns, nt),
    )
    import time as _time

    t0 = _time.perf_counter()
    out = fn(
        cols,
        operands.ints,
        operands.floats,
        table_list,
        ns,
        nt,
    )
    return TEL.observe_device("filter", n_spans_b, t0, out)
