"""Data-driven predicate evaluation over vtpu columns: the TraceQL /
tag-search execution kernel.

This replaces the reference's iterator-tree engine (pkg/parquetquery
ColumnIterator/JoinIterator + vparquet/block_search.go pipelines) with
one vectorized pass: every condition becomes a boolean mask over its
axis (span rows, attr rows, resource rows), attr/resource hits scatter
to span rows with a segment-max, masks combine with AND/OR on the VPU,
and the span mask aggregates to a trace mask with another segment-max.
No Dremel rep/def levels anywhere: hierarchy is explicit segment ids
(SURVEY.md 7.3 "the crux" -- this layout dissolves it).

Only the condition STRUCTURE (targets/ops/value kinds) keys a jit
compile; operand values -- dictionary codes, thresholds -- are traced
arrays, so `{span.foo = "bar"}` and `{span.foo = "baz"}` share one
compiled program.

Device filters are *conservative* (may over-match, never under-match):
clamped int32 / f32 encodings use widened comparisons; conditions whose
encodings can over-match are flagged needs_verify and re-checked
exactly on host over the surviving spans (db/search.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

# condition targets
T_SPAN = "span"  # direct span-axis column
T_TRACE = "trace"  # trace-axis column
T_RES = "res"  # resource-axis dedicated column (gathered via span.res_idx)
T_SATTR = "sattr"  # generic span attr table
T_RATTR = "rattr"  # generic resource attr table

# ops: v0/v1 are the int operands, f0/f1 the float operands
OPS = ("eq", "ne", "ne_present", "lt", "le", "gt", "ge", "range", "exists", "ne_clamped")


@dataclass(frozen=True)
class Cond:
    """One predicate. Hashable => part of the jit key."""

    target: str
    col: str  # device column ('span.dur_us', 'res.service_id', ...) or
    # value kind for attr targets: 'str', 'int', 'float', 'bool', 'any'
    op: str
    is_float: bool = False
    needs_verify: bool = False


@dataclass
class Operands:
    """Per-condition operand values (traced; NOT part of the jit key).
    ints[i] = (key_code, v0, v1); floats[i] = (f0, f1)."""

    ints: np.ndarray  # (n_conds, 3) int32
    floats: np.ndarray  # (n_conds, 2) float32

    @classmethod
    def build(cls, rows: list[tuple[int, int, int, float, float]]) -> "Operands":
        if not rows:
            return cls(np.zeros((0, 3), np.int32), np.zeros((0, 2), np.float32))
        ints = np.asarray([[r[0], r[1], r[2]] for r in rows], dtype=np.int64)
        ints = np.clip(ints, -(2**31), 2**31 - 1).astype(np.int32)
        floats = np.asarray([[r[3], r[4]] for r in rows], dtype=np.float32)
        return cls(ints, floats)


_ATTR_VALUE_COL = {"str": "str_id", "int": "int32", "bool": "int32", "float": "f32"}


def _flatten(groups) -> list[Cond]:
    out = []
    for g in groups:
        if isinstance(g, Cond):
            out.append(g)
        else:
            out.extend(g)
    return out


def required_columns(groups) -> list[str]:
    need = {"span.trace_sid"}
    for c in _flatten(groups):
        if c.target in (T_SPAN, T_TRACE):
            need.add(c.col)
        elif c.target == T_RES:
            need.add(c.col)
            need.add("span.res_idx")
        elif c.target == T_SATTR:
            need.update({"sattr.span", "sattr.key_id", "sattr.vtype"})
            if c.col in _ATTR_VALUE_COL:
                need.add(f"sattr.{_ATTR_VALUE_COL[c.col]}")
        elif c.target == T_RATTR:
            # res.service_id rides along to size the resource axis
            need.update({"rattr.res", "rattr.key_id", "rattr.vtype", "span.res_idx", "res.service_id"})
            if c.col in _ATTR_VALUE_COL:
                need.add(f"rattr.{_ATTR_VALUE_COL[c.col]}")
    return sorted(need)


def _cmp(op: str, col, v0, v1, f0, f1, is_float: bool):
    x = col
    if is_float:
        a, b = f0, f1
    else:
        a, b = v0, v1
    if op == "eq":
        return x == a
    if op == "ne":
        return x != a
    if op == "ne_present":  # value present (code >= 0) and differs
        return (x != a) & (x >= 0)
    if op == "ne_clamped":  # conservative ne on a clamped int encoding
        return (x != a) | (x == 2**31 - 1) | (x == -(2**31) + 1)
    if op == "lt":
        return x < a
    if op == "le":
        return x <= a
    if op == "gt":
        return x > a
    if op == "ge":
        return x >= a
    if op == "range":  # inclusive [a, b]
        return (x >= a) & (x <= b)
    if op == "exists":
        return jnp.ones_like(x, dtype=bool)
    raise ValueError(f"unknown op {op}")


_VT_CODE = {"str": 0, "int": 1, "float": 2, "bool": 3, "any": -1}


def _eval_conds(conds, cols, ops_i, ops_f, n_spans_b, n_res_b, valid_span):
    """-> list of (span-level mask) per condition."""
    masks = []
    for i, c in enumerate(conds):
        v0, v1, key = ops_i[i, 1], ops_i[i, 2], ops_i[i, 0]
        f0, f1 = ops_f[i, 0], ops_f[i, 1]
        if c.target in (T_SPAN,):
            m = _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float) & valid_span
        elif c.target == T_RES:
            res_mask = _cmp(c.op, cols[c.col], v0, v1, f0, f1, c.is_float)
            idx = jnp.clip(cols["span.res_idx"], 0, res_mask.shape[0] - 1)
            m = res_mask[idx] & (cols["span.res_idx"] >= 0) & valid_span
        elif c.target in (T_SATTR, T_RATTR):
            pre = c.target
            key_match = cols[f"{pre}.key_id"] == key
            if c.col == "any":
                row_hit = key_match
            else:
                vcol = cols[f"{pre}.{_ATTR_VALUE_COL[c.col]}"]
                vt_ok = cols[f"{pre}.vtype"] == _VT_CODE[c.col]
                if c.col == "bool":
                    vt_ok = cols[f"{pre}.vtype"] == 3
                row_hit = key_match & vt_ok & _cmp(c.op, vcol, v0, v1, f0, f1, c.is_float)
            if pre == T_SATTR:
                owner = jnp.clip(cols["sattr.span"], 0, n_spans_b - 1)
                m = (
                    jax.ops.segment_max(
                        row_hit.astype(jnp.int32), owner, num_segments=n_spans_b
                    )
                    > 0
                ) & valid_span
            else:
                owner = jnp.clip(cols["rattr.res"], 0, n_res_b - 1)
                res_mask = (
                    jax.ops.segment_max(
                        row_hit.astype(jnp.int32), owner, num_segments=n_res_b
                    )
                    > 0
                )
                idx = jnp.clip(cols["span.res_idx"], 0, n_res_b - 1)
                m = res_mask[idx] & (cols["span.res_idx"] >= 0) & valid_span
        else:
            raise ValueError(f"bad target {c.target}")
        masks.append(m)
    return masks


@lru_cache(maxsize=256)
def _compiled(groups: tuple, combinator: str, n_spans_b: int, n_res_b: int, n_traces_b: int):
    """groups: tuple of condition groups; members of a group OR together
    (a tag may live in span attrs OR resource attrs OR a dedicated
    column), groups combine with `combinator`. Trace-target conditions
    must be single-member groups (applied after span->trace aggregation).
    Operand rows index flattened (group, member) order."""
    flat: list[tuple[int, Cond]] = []
    span_groups: list[list[int]] = []  # per group: flat indices of non-trace members
    trace_conds: list[tuple[int, Cond]] = []
    pos = 0
    for g in groups:
        members = []
        for c in g:
            if c.target == T_TRACE:
                trace_conds.append((pos, c))
            else:
                flat.append((pos, c))
                members.append(len(flat) - 1)
            pos += 1
        if members:
            span_groups.append(members)

    @jax.jit
    def run(cols, ops_i, ops_f, n_spans, n_traces):
        valid_span = jnp.arange(n_spans_b, dtype=jnp.int32) < n_spans
        if flat:
            sub = tuple(c for _, c in flat)
            idx = jnp.asarray([i for i, _ in flat], dtype=jnp.int32)
            masks = _eval_conds(sub, cols, ops_i[idx], ops_f[idx], n_spans_b, n_res_b, valid_span)
            gmasks = []
            for members in span_groups:
                gm = masks[members[0]]
                for m in members[1:]:
                    gm = gm | masks[m]
                gmasks.append(gm)
            span_mask = gmasks[0]
            for gm in gmasks[1:]:
                span_mask = (span_mask & gm) if combinator == "and" else (span_mask | gm)
        else:
            span_mask = valid_span

        sid = jnp.where(valid_span & span_mask, cols["span.trace_sid"], n_traces_b)
        sid = jnp.clip(sid, 0, n_traces_b)
        trace_mask = (
            jax.ops.segment_max(
                span_mask.astype(jnp.int32), sid, num_segments=n_traces_b + 1
            )[:n_traces_b]
            > 0
        )
        span_count = jax.ops.segment_sum(
            span_mask.astype(jnp.int32), sid, num_segments=n_traces_b + 1
        )[:n_traces_b]

        valid_trace = jnp.arange(n_traces_b, dtype=jnp.int32) < n_traces
        trace_mask = trace_mask & valid_trace
        for i, c in trace_conds:
            tm = _cmp(c.op, cols[c.col], ops_i[i, 1], ops_i[i, 2], ops_f[i, 0], ops_f[i, 1], c.is_float)
            trace_mask = trace_mask & tm & valid_trace

        return span_mask, trace_mask, span_count

    return run


def eval_block(
    groups,
    combinator: str,
    cols: dict[str, jnp.ndarray],
    operands: Operands,
    n_spans: int,
    n_traces: int,
    n_spans_b: int,
    n_res_b: int,
    n_traces_b: int,
):
    """Run the filter over staged (padded) device columns.

    `groups` is a tuple of condition groups (inner tuples OR, outer
    `combinator`); a bare tuple of Cond is accepted and treated as
    single-member groups. Returns (span_mask (n_spans_b,), trace_mask
    (n_traces_b,), per-trace matched span count)."""
    if groups and isinstance(groups[0], Cond):
        groups = tuple((c,) for c in groups)
    fn = _compiled(tuple(groups), combinator, n_spans_b, n_res_b, n_traces_b)
    return fn(
        cols,
        jnp.asarray(operands.ints),
        jnp.asarray(operands.floats),
        jnp.int32(n_spans),
        jnp.int32(n_traces),
    )
