"""Host (numpy) mirror of the device filter semantics for one-shot scans.

Cost-based dispatch: staging a block's columns onto the accelerator pays
off when the block is queried repeatedly (the production querier keeps
immutable blocks hot -- ops/stage.py caches the padded device arrays).
For a COLD one-shot scan the device path's fixed costs (host->device
upload of every needed column + a dispatch/sync round trip) exceed the
scan itself, so the planner evaluates the same condition tree vectorized
on host instead -- identical semantics (conservative encodings, same
needs_verify contract), no padding, no upload. The reference has only
this mode (vparquet/block_search.go is all-CPU); we have both and pick
by block temperature (db/search.py).

Everything is O(rows) numpy: predicate masks, attr->span scatter via
bincount, span->trace aggregation via bincount over trace_sid.
"""

from __future__ import annotations

import numpy as np

from .filter import (
    Cond,
    Operands,
    T_RATTR,
    T_RES,
    T_SATTR,
    T_SPAN,
    T_TRACE,
    _ATTR_VALUE_COL,
    _VT_CODE,
    normalize_tree,
)


def _cmp_np(op: str, x: np.ndarray, v0, v1, f0, f1, is_float: bool, table):
    a, b = (f0, f1) if is_float else (v0, v1)
    if not is_float and x.ndim == 1 and x.dtype in (np.int32, np.int64):
        # single-pass native compare (native/vtpu_native.cc mask_cmp):
        # one C loop instead of numpy's compare + combine temporaries
        from ..native import mask_cmp

        m = mask_cmp(x, op, a, b)
        if m is not None:
            return m.view(np.bool_)
    if op == "eq":
        return x == a
    if op == "ne":
        return x != a
    if op == "ne_present":
        return (x != a) & (x >= 0)
    if op == "ne_clamped":
        return (x != a) | (x == 2**31 - 1) | (x == -(2**31) + 1)
    if op == "lt":
        return x < a
    if op == "le":
        return x <= a
    if op == "gt":
        return x > a
    if op == "ge":
        return x >= a
    if op == "range":
        return (x >= a) & (x <= b)
    if op == "exists":
        return np.ones(x.shape, dtype=bool)
    if op in ("intable", "notintable"):
        t = np.asarray(table)
        hit = t[np.clip(x, 0, t.shape[0] - 1)] > 0
        if op == "notintable":
            hit = ~hit
        return hit & (x >= 0)
    raise ValueError(f"unknown op {op}")


def _scatter_owner(row_hit: np.ndarray, owner: np.ndarray, n: int) -> np.ndarray:
    """OR rows onto their owner axis: True where any owned row hit."""
    if not row_hit.any():
        return np.zeros(n, dtype=bool)
    o = owner[row_hit]
    o = o[(o >= 0) & (o < n)]
    return np.bincount(o, minlength=n).astype(bool)


def _cond_mask_np(c: Cond, i: int, cols, ops_i, ops_f, tables, n_spans, n_res):
    key, v0, v1 = int(ops_i[i, 0]), int(ops_i[i, 1]), int(ops_i[i, 2])
    f0, f1 = float(ops_f[i, 0]), float(ops_f[i, 1])
    table = tables.get(i)
    if c.target == T_SPAN:
        return _cmp_np(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, table)
    if c.target == T_RES:
        rm = _cmp_np(c.op, cols[c.col], v0, v1, f0, f1, c.is_float, table)
        idx = cols["span.res_idx"]
        return _lut_gather(rm, idx)
    if c.target in (T_SATTR, T_RATTR):
        pre = c.target
        key_match = cols[f"{pre}.key_id"] == key
        if c.col == "any":
            row_hit = key_match
        else:
            vcol = cols[f"{pre}.{_ATTR_VALUE_COL[c.col]}"]
            vt_ok = cols[f"{pre}.vtype"] == _VT_CODE[c.col]
            row_hit = key_match & vt_ok & _cmp_np(c.op, vcol, v0, v1, f0, f1, c.is_float, table)
        if pre == T_SATTR:
            return _scatter_owner(row_hit, cols["sattr.span"], n_spans)
        res_hit = _scatter_owner(row_hit, cols["rattr.res"], n_res)
        idx = cols["span.res_idx"]
        return _lut_gather(res_hit, idx)
    raise ValueError(f"bad target {c.target}")


def _lut_gather(table_mask: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """res-table mask -> span mask through span.res_idx; negative /
    out-of-range indices (absent resource) never match."""
    from ..native import mask_lut

    if idx.dtype == np.int32 and idx.flags.c_contiguous:
        lut = np.ascontiguousarray(table_mask, dtype=np.uint8)
        m = mask_lut(idx, lut)
        if m is not None:
            return m.view(np.bool_)
    return table_mask[np.clip(idx, 0, table_mask.shape[0] - 1)] & (idx >= 0)


def _struct_mask_np(op: str, lm: np.ndarray, rm: np.ndarray,
                    pidx: np.ndarray, n_spans: int) -> np.ndarray:
    """Exact structural relation over the parent-row column (numpy twin
    of ops.filter's ev_struct): result spans = rhs matches standing in
    `op` relation to some lhs match."""
    has_p = pidx >= 0
    safe = np.clip(pidx, 0, max(n_spans - 1, 0))
    if op == ">":
        return rm & has_p & lm[safe]
    if op == ">>":
        acc = has_p & lm[safe]
        ptr = np.where(has_p, safe, -1)
        for _ in range(max(1, int(n_spans - 1).bit_length())):
            alive = ptr >= 0
            if not alive.any():
                break
            psafe = np.clip(ptr, 0, n_spans - 1)
            new_acc = acc | (alive & acc[psafe])
            ptr = np.where(alive, ptr[psafe], -1)
            if (new_acc == acc).all() and (ptr < 0).all():
                acc = new_acc
                break
            acc = new_acc
        return rm & acc
    # '~': some DIFFERENT lhs span with the same parent. Orphan rows
    # (parent_idx == -2) over-match when any lhs orphan exists; the plan
    # flags '~' trees needs_verify so the host settles the exact pairs
    lhs_child = (lm & has_p)
    cnt = np.bincount(safe[lhs_child], minlength=n_spans) if n_spans else np.zeros(0, int)
    sibs = cnt[safe] - lhs_child.astype(np.int64)
    orphan = pidx == -2
    return (rm & has_p & (sibs > 0)) | (rm & orphan & bool((lm & orphan).any()))


def eval_span_mask_host(
    query,
    cols: dict[str, np.ndarray],
    operands: Operands,
    n_spans: int,
    n_traces: int,
) -> np.ndarray:
    """SPAN-level mask of a raw (un-lifted) condition tree -- the host
    engine of the metrics path (db/metrics_exec): no tracify nodes, no
    trace-level output. Trace-target conds evaluate on the trace axis
    and gather to spans through span.trace_sid (a span inherits its
    trace's truth value). Returns a bool (n_spans,) mask with the same
    conservative-encoding semantics as the search engines."""
    tree, conds = query
    if tree is None:
        return np.ones(n_spans, dtype=bool)
    tables = operands.tables or {}
    ops_i, ops_f = operands.ints, operands.floats
    n_res = 0
    for n, a in cols.items():
        if n.startswith("res."):
            n_res = max(n_res, a.shape[0])
    tsid = cols.get("span.trace_sid")

    def ev(t):
        if t == ("true",):
            return np.ones(n_spans, dtype=bool)
        if t == ("false",):
            return np.zeros(n_spans, dtype=bool)
        if t[0] == "cond":
            i = t[1]
            c = conds[i]
            if c.target == T_TRACE:
                tm = _cmp_np(c.op, cols[c.col], int(ops_i[i, 1]), int(ops_i[i, 2]),
                             float(ops_f[i, 0]), float(ops_f[i, 1]), c.is_float,
                             tables.get(i))
                return _lut_gather(np.asarray(tm, dtype=bool), tsid)
            return _cond_mask_np(c, i, cols, ops_i, ops_f, tables, n_spans, n_res)
        ms = [ev(ch) for ch in t[1:]]
        out = ms[0]
        for m in ms[1:]:
            out = (out & m) if t[0] == "and" else (out | m)
        return out

    return ev(tree) & np.ones(n_spans, dtype=bool)


def eval_block_host(
    query,
    cols: dict[str, np.ndarray],
    operands: Operands,
    n_spans: int,
    n_traces: int,
):
    """Evaluate (tree, conds) over RAW unpadded host columns.

    `cols['sattr.span']` must be rebased to local span rows when the
    columns cover a row-group slice (same contract as ops/stage.py).
    `span.trace_sid` stays global. Returns (trace_mask (n_traces,) bool,
    span_count (n_traces,) int64) -- identical semantics to
    ops.filter.eval_block's trace outputs.
    """
    tree, conds = query
    if tree is not None:
        tree = normalize_tree(tree, conds)
    tables = operands.tables or {}
    ops_i, ops_f = operands.ints, operands.floats
    n_res = 0
    for n, a in cols.items():
        if n.startswith("res."):
            n_res = max(n_res, a.shape[0])
    # trace_sid only backs the bincount fallback; when the grouped
    # span_off offsets are present (the normal case) callers may skip
    # reading the whole span-length column
    tsid = cols.get("span.trace_sid")
    span_masks: list[np.ndarray] = []

    def ev_span(t):
        if t == ("true",):
            return np.ones(n_spans, dtype=bool)
        if t == ("false",):
            return np.zeros(n_spans, dtype=bool)
        if t[0] == "cond":
            i = t[1]
            return _cond_mask_np(conds[i], i, cols, ops_i, ops_f, tables, n_spans, n_res)
        if t[0] == "struct":
            return _struct_mask_np(t[1], ev_span(t[2]), ev_span(t[3]),
                                   cols["span.parent_idx"], n_spans)
        ms = [ev_span(ch) for ch in t[1:]]
        out = ms[0]
        for m in ms[1:]:
            out = (out & m) if t[0] == "and" else (out | m)
        return out

    span_off = cols.get("trace.span_off")
    # optional per-row fold weights ("@seg_weights"): when rows are tres
    # membership entries rather than spans, the weight is the entry's
    # span count, keeping matched-span counts exact (db/search._host_eval)
    weights = cols.get("@seg_weights")

    # (mask, counts) memo holding STRONG refs: tracify and the final
    # counts usually fold the same union mask; identity on live objects
    # can't alias, unlike id() keys of freed temporaries
    seg_memo: list[tuple[np.ndarray, np.ndarray]] = []

    def seg_counts(span_mask):
        """Matched spans per trace: one reduceat over the grouped span
        axis (5x a cumsum scan), else bincount by trace sid."""
        for m, c in seg_memo:
            if m is span_mask:
                return c
        if span_off is not None:
            out = None
            if n_spans == 0 or span_off.shape[0] <= 1:
                out = np.zeros(n_traces, dtype=np.int64)
            elif span_off.shape[0] - 1 == n_traces:
                # one-pass native fold (no astype/concatenate temps);
                # int64 keeps the documented counts dtype uniform across
                # the three branches
                if weights is None:
                    from ..native import seg_count_mask

                    out = seg_count_mask(np.ascontiguousarray(span_mask),
                                         np.ascontiguousarray(span_off, np.int32),
                                         n_spans)
                    if out is not None:
                        out = out.astype(np.int64)
                else:
                    from ..native import seg_weighted_count

                    out = seg_weighted_count(
                        np.ascontiguousarray(span_mask),
                        np.ascontiguousarray(weights, np.int32),
                        np.ascontiguousarray(span_off, np.int32), n_spans)
            if out is None:
                # sentinel-padded reduceat: starts may legally equal
                # n_spans (sliced row-group shards clip trailing
                # offsets), and reduceat yields vals[start] for empty
                # segments -- the zero sentinel makes both exact. With
                # fold weights, rows contribute their weight instead of 1
                vals = (span_mask.astype(np.int64) if weights is None
                        else np.where(span_mask, weights.astype(np.int64), 0))
                padded = np.concatenate([vals, np.zeros(1, np.int64)])
                starts = np.minimum(span_off[:-1], n_spans)
                out = np.add.reduceat(padded, starts)
                empty = span_off[1:] == span_off[:-1]
                if empty.any():
                    out[empty] = 0
        else:
            h = tsid[span_mask]
            h = h[(h >= 0) & (h < n_traces)]
            out = np.bincount(h, minlength=n_traces)
        seg_memo.append((span_mask, out))
        return out

    def tracify(span_mask):
        return seg_counts(span_mask) > 0

    def ev_trace(t):
        if t[0] == "tracify":
            sm = ev_span(t[1])
            span_masks.append(sm)
            return tracify(sm)
        if t[0] == "cond":
            i = t[1]
            c = conds[i]
            return _cmp_np(c.op, cols[c.col], int(ops_i[i, 1]), int(ops_i[i, 2]),
                           float(ops_f[i, 0]), float(ops_f[i, 1]), c.is_float,
                           tables.get(i))
        ms = [ev_trace(ch) for ch in t[1:]]
        out = ms[0]
        for m in ms[1:]:
            out = (out & m) if t[0] == "and" else (out | m)
        return out

    if tree is None:
        trace_mask = np.ones(n_traces, dtype=bool)
        union = np.ones(n_spans, dtype=bool)
    else:
        trace_mask = ev_trace(tree)
        if trace_mask.shape[0] != n_traces:  # pure trace-cond trees
            trace_mask = trace_mask[:n_traces]
        if span_masks:
            union = span_masks[0]
            for m in span_masks[1:]:
                union = union | m
        else:
            union = np.ones(n_spans, dtype=bool)

    # spans only count toward surviving traces; zero at trace level
    # (mirrors ops/filter's span_out=False program)
    counts = np.where(trace_mask, seg_counts(union), 0)
    return trace_mask, counts
