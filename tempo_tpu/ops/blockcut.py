"""Block-cut kernels: the flush path's device-side heavy lifting.

When the ingester cuts a head block, three per-row host loops dominate
the wall time (ISSUE 16): dictionary finalization remaps every code
column through the sorted-order permutation, the trace-id bloom sets
K=7 bits per trace, and row-group pruning stats take a min/max per
column slice. Each is a gather / scatter-OR / segmented-reduce -- VPU
shapes -- so they run here as jitted kernels with bit-identical numpy
twins (pure integer ops, so device == host EXACTLY, registered in
ops/twins.py). The builder routes through cut_engine() and falls back
to its original host code when jax or a device backend is absent.

Bucketed shapes keep compiled-program count logarithmic (ops/device):
pad codes with -1 (remap passes negatives through unchanged), pad bloom
scatter entries with (word 0, bits 0) no-ops, pad row-group ids into a
trash segment that is sliced away.
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from ..block.bloom import _K, WORD_BITS, shard_for_trace_id
from ..util.hashing import bloom_hashes
from .device import bucket, pad_rows

_I32_MIN = -(2**31)
_I32_MAX = 2**31 - 1


def cut_engine() -> str:
    """'device' | 'host' for this process's block cuts. TEMPO_CUT_ENGINE
    overrides; otherwise device kernels engage only on a real
    accelerator backend (on cpu-jax the jit round trip loses to numpy)."""
    from ..util.kerneltel import TEL

    eng = os.environ.get("TEMPO_CUT_ENGINE", "").strip().lower()
    if eng in ("device", "host"):
        reason = "env"
    else:
        eng = "device" if jax.default_backend() != "cpu" else "host"
        reason = "backend"
    TEL.record_routing("block_cut", eng, reason)
    return eng


# ---------------------------------------------------------------- remap
@lru_cache(maxsize=None)
def _compiled_remap(n_b: int, r_b: int):
    def kern(col, remap):
        return jnp.where(col >= 0, remap[jnp.maximum(col, 0)], col)

    return jax.jit(kern)


def remap_codes_device(col: np.ndarray, remap: np.ndarray) -> np.ndarray:
    """Dictionary-finalize remap of one code column: negatives (absent /
    sentinel codes) pass through, everything else gathers through the
    sort permutation. Twin: remap_codes_host."""
    import time as _time

    from ..util.kerneltel import TEL

    n, r = len(col), len(remap)
    n_b, r_b = bucket(n), bucket(r)
    col_p = pad_rows(np.asarray(col, dtype=np.int32), n_b, -1)
    rm_p = pad_rows(np.asarray(remap, dtype=np.int32), r_b, 0)
    fn = _compiled_remap(n_b, r_b)
    TEL.record_launch("cut_remap", ("remap", n_b, r_b), n_b)
    t0 = _time.perf_counter()
    out = np.asarray(fn(jnp.asarray(col_p), jnp.asarray(rm_p)))[:n]
    TEL.observe_device("cut_remap", n_b, t0)
    return out.astype(np.int32)


def remap_codes_host(col: np.ndarray, remap: np.ndarray) -> np.ndarray:
    """Pure-numpy twin of remap_codes_device (== dictionary.apply_remap)."""
    col = np.asarray(col, dtype=np.int32)
    remap = np.asarray(remap, dtype=np.int32)
    return np.where(col >= 0, remap[np.maximum(col, 0)], col).astype(np.int32)


# ---------------------------------------------------------------- bloom
def _bloom_scatter(trace_ids: list[bytes], n_shards: int, shard_bits: int):
    """Host control plane: hash every id to (global word index, bit
    word) scatter pairs, DEDUPED so a scatter-add of single-bit words
    equals the scatter-OR the filter semantics need."""
    n_words_per_shard = shard_bits // WORD_BITS
    keys = set()
    for tid in trace_ids:
        base = shard_for_trace_id(tid, n_shards) * shard_bits
        for pos in bloom_hashes(tid, _K, shard_bits):
            keys.add(base + pos)  # global bit index
    bit_idx = np.fromiter(keys, dtype=np.int64, count=len(keys))
    word_idx = (bit_idx // WORD_BITS).astype(np.int32)
    bits = (np.uint32(1) << (bit_idx % WORD_BITS).astype(np.uint32)).astype(np.uint32)
    return word_idx, bits, n_shards * n_words_per_shard


@lru_cache(maxsize=None)
def _compiled_bloom(n_b: int, n_words: int):
    def kern(flat, word_idx, bits):
        # entries are distinct bits, so the scatter-ADD of one-hot words
        # is exactly the scatter-OR; pads add 0 to word 0 (a no-op)
        return flat | jnp.zeros(n_words, jnp.uint32).at[word_idx].add(bits)

    return jax.jit(kern)


def bloom_bits_device(words: np.ndarray, trace_ids: list[bytes],
                      shard_bits: int) -> np.ndarray:
    """Set every trace id's K bloom bits in a (n_shards, W) word array,
    returning the updated array. Twin: bloom_bits_host."""
    import time as _time

    from ..util.kerneltel import TEL

    n_shards = words.shape[0]
    word_idx, bits, n_words = _bloom_scatter(trace_ids, n_shards, shard_bits)
    n_b = bucket(len(word_idx))
    word_idx = pad_rows(word_idx, n_b, 0)
    bits = pad_rows(bits, n_b, 0)
    fn = _compiled_bloom(n_b, n_words)
    TEL.record_launch("cut_bloom", ("bloom", n_b, n_words), n_b)
    t0 = _time.perf_counter()
    out = np.asarray(fn(jnp.asarray(words.reshape(-1)), jnp.asarray(word_idx),
                        jnp.asarray(bits)))
    TEL.observe_device("cut_bloom", n_b, t0)
    return out.reshape(words.shape)


def bloom_bits_host(words: np.ndarray, trace_ids: list[bytes],
                    shard_bits: int) -> np.ndarray:
    """Pure-numpy twin of bloom_bits_device (== ShardedBloom.add loop)."""
    out = words.copy()
    n_shards = out.shape[0]
    for tid in trace_ids:
        shard = shard_for_trace_id(tid, n_shards)
        for pos in bloom_hashes(tid, _K, shard_bits):
            out[shard, pos // WORD_BITS] |= np.uint32(1 << (pos % WORD_BITS))
    return out


# ----------------------------------------------------------- row groups
@lru_cache(maxsize=None)
def _compiled_rowgroup(n_b: int, n_seg: int):
    def kern(gid, start_ms, dur_us):
        lo = jax.ops.segment_min(start_ms, gid, num_segments=n_seg)
        hi = jax.ops.segment_max(start_ms, gid, num_segments=n_seg)
        du = jax.ops.segment_max(dur_us, gid, num_segments=n_seg)
        return lo, hi, du

    return jax.jit(kern)


def rowgroup_minmax_device(start_ms: np.ndarray, dur_us: np.ndarray,
                           bounds: list[int]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-row-group (start_ms min, start_ms max, dur_us max) pruning
    stats as one segmented reduce. bounds are the group boundaries
    (len n_groups+1, covering every row, all groups non-empty).
    Twin: rowgroup_minmax_host."""
    import time as _time

    from ..util.kerneltel import TEL

    n_groups = len(bounds) - 1
    n = int(bounds[-1])
    gid = np.repeat(np.arange(n_groups, dtype=np.int32), np.diff(bounds))
    n_b = bucket(n)
    gid = pad_rows(gid, n_b, n_groups)  # pads land in a trash segment
    sm = pad_rows(np.asarray(start_ms, dtype=np.int32), n_b, 0)
    du = pad_rows(np.asarray(dur_us, dtype=np.int32), n_b, 0)
    fn = _compiled_rowgroup(n_b, n_groups + 1)
    TEL.record_launch("cut_rowgroups", ("rowgroups", n_b, n_groups + 1), n_b)
    t0 = _time.perf_counter()
    lo, hi, dmax = fn(jnp.asarray(gid), jnp.asarray(sm), jnp.asarray(du))
    out = (np.asarray(lo)[:n_groups], np.asarray(hi)[:n_groups],
           np.asarray(dmax)[:n_groups])
    TEL.observe_device("cut_rowgroups", n_b, t0)
    return out


def rowgroup_minmax_host(start_ms: np.ndarray, dur_us: np.ndarray,
                         bounds: list[int]) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pure-numpy twin of rowgroup_minmax_device (per-slice reductions,
    == the builder's original per-group loop)."""
    n_groups = len(bounds) - 1
    lo = np.empty(n_groups, dtype=np.int32)
    hi = np.empty(n_groups, dtype=np.int32)
    du = np.empty(n_groups, dtype=np.int32)
    for g in range(n_groups):
        a, b = bounds[g], bounds[g + 1]
        lo[g] = start_ms[a:b].min()
        hi[g] = start_ms[a:b].max()
        du[g] = dur_us[a:b].max()
    return lo, hi, du
