"""Multi-query predicate programs: Q queries x R rows in ONE launch.

The single-query engine (ops/filter.py) compiles one XLA program per
condition-tree STRUCTURE; under concurrency the device therefore runs Q
small launches over the same staged block -- Q dispatch round trips and
Q trace-through-jit risks for work the VPU could do in one pass. This
module is the kernel half of the cross-query batching executor
(db/batchexec.py), the serving-stack analog of continuous batching in
inference servers (Orca, OSDI '22): concurrent queries merge into one
device step.

Lowering (`lower_plan`) turns a planned query's condition tree into a
fixed-shape *predicate program*:

  * span-level conditions become padded (column-id, op-code, operand)
    tables -- data, not structure, so they ride the traced-operand path;
  * the boolean tree flattens to CNF at two levels: span conds group
    into OR-clauses under AND per tracify group (same-span semantics
    preserved), and trace-level atoms (tracify-group results + trace
    conds) group into OR-clauses under AND;
  * every table pads to a power-of-two bucket (ProgramShape), so the
    launch key depends only on the shape buckets + column set -- never
    on which queries happen to share a window.

Evaluation (`eval_multiquery`) vmaps the program interpreter over the
query axis: one fused filter -> clause-fold -> segmented-fold kernel
produces per-query (trace_mask, matched-span counts), bit-identical to
running ops/filter.eval_block per query (CNF is a boolean identity and
every aggregation reuses the same cumsum+gather segment fold).
`select_multiquery` then runs ONE batched top-k over all Q mask rows --
two launches total for the whole window, vs 2Q sequentially.

Eligibility is conservative: conditions over dedicated int32 columns
(span/trace intrinsics, well-known res/span attrs via the span@
materialization) with scalar compare ops. Regex tables, generic attr
tables, struct relations and float compares return None from
`lower_plan`; the caller falls back to the single-query path unchanged.
Per-query `needs_verify` semantics are untouched -- exact host
re-verification happens after demux, per query.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from .device import PAD_I32
from .filter import Cond, T_RES, T_SPAN, T_TRACE, normalize_tree

# op codes (order matters: _cmp_code dispatches on these)
_OPC = {"eq": 0, "ne": 1, "ne_present": 2, "lt": 3, "le": 4,
        "gt": 5, "ge": 6, "range": 7}
_NOP = -1  # padded condition slot: mask is False everywhere

# per-query program-size ceilings; a query that lowers past any of them
# is ineligible (falls back to the single-query engine)
MAX_CONDS = 32
MAX_CLAUSES = 16
MAX_GROUPS = 8
MAX_TCONDS = 16
MAX_ATOMS = 16
MAX_TCLAUSES = 8


def _p2(n: int, lo: int = 2) -> int:
    """Small power-of-two bucket (program tables, not row axes)."""
    b = lo
    while b < n:
        b <<= 1
    return b


@dataclass(frozen=True)
class ProgramShape:
    """Bucketed program dims + column set: the plan-signature half of
    the coalesce key, and (with the axis buckets) the launch key."""

    n_conds_b: int
    n_clauses_b: int
    n_groups_b: int
    n_tconds_b: int
    n_atoms_b: int
    n_tclauses_b: int
    span_cols: tuple[str, ...]  # staged span-axis columns, indexed by cond_col
    trace_cols: tuple[str, ...]  # staged trace-axis columns, by tcond_col


@dataclass
class LoweredQuery:
    """One query's predicate program (host-side numpy tables, padded to
    the ProgramShape buckets)."""

    shape: ProgramShape
    # span-level conds, sorted by (group, clause); padded slots op=_NOP
    cond_col: np.ndarray  # (P,) index into shape.span_cols
    cond_op: np.ndarray  # (P,)
    cond_v0: np.ndarray  # (P,)
    cond_v1: np.ndarray  # (P,)
    cond_guard: np.ndarray  # (P,) bool: require x != PAD (span@res cols)
    clause_off: np.ndarray  # (NC+1,) cond-slot boundaries per clause
    group_off: np.ndarray  # (NG+1,) clause boundaries per tracify group
    n_groups: int
    # trace-level conds + atoms, atoms sorted by trace clause
    tcond_col: np.ndarray  # (PT,)
    tcond_op: np.ndarray  # (PT,)
    tcond_v0: np.ndarray  # (PT,)
    tcond_v1: np.ndarray  # (PT,)
    atom_kind: np.ndarray  # (NA,) 0=group result, 1=trace cond, -1=pad
    atom_idx: np.ndarray  # (NA,)
    tclause_off: np.ndarray  # (TC+1,) atom boundaries per trace clause
    n_tclauses: int


# --------------------------------------------------------------- lowering


def _cnf(tree, clause_cap: int = MAX_CLAUSES):
    """and/or tree with hashable leaves -> list of OR-clauses (lists of
    leaves) whose AND is equivalent. None when distribution would exceed
    clause_cap (OR-of-AND blowup)."""
    if not isinstance(tree, tuple) or tree[0] not in ("and", "or"):
        return [[tree]]
    parts = [_cnf(ch, clause_cap) for ch in tree[1:]]
    if any(p is None for p in parts):
        return None
    if tree[0] == "and":
        out = [c for p in parts for c in p]
        return out if len(out) <= clause_cap else None
    # or: cross-product of the children's clause sets
    out = [[]]
    for p in parts:
        nxt = []
        for acc in out:
            for clause in p:
                nxt.append(acc + clause)
                if len(nxt) > clause_cap:
                    return None
        out = nxt
    return out


def lower_plan(planned) -> LoweredQuery | None:
    """PlannedQuery (traceql/plan.py) -> predicate program, or None when
    the plan can't be expressed in the fixed-shape op set (caller falls
    back to the single-query engine). Must be given a non-pruned plan."""
    conds = tuple(planned.conds)
    if planned.tables:  # regex / set tables: per-query table shapes
        return None
    if getattr(planned, "has_struct", False):
        return None
    for c in conds:
        if c.target not in (T_SPAN, T_TRACE, T_RES):
            return None  # generic attr tables (sattr/rattr)
        if c.op not in _OPC or c.is_float:
            return None
    tree = planned.tree
    rows = planned.rows

    # trace-level tree -> atoms (tracify groups + trace conds)
    groups: list[list[list[int]]] = []  # per group: clauses of cond idxs
    atoms: list[tuple[int, int]] = []  # (kind, idx)
    tcond_idx: list[int] = []  # cond indices used at trace level

    def span_leaf(t):
        """span-CNF leaf check: ('cond', i) with span/res target."""
        return (isinstance(t, tuple) and len(t) == 2 and t[0] == "cond"
                and conds[t[1]].target in (T_SPAN, T_RES))

    def lower_tracify(span_tree) -> int | None:
        """span subtree -> group id (appended), or None if unlowerable."""
        if span_tree == ("true",):
            clauses: list[list[int]] | None = []  # AND of nothing: all spans
        elif span_tree == ("false",):
            return None  # planner folds these away; don't guess
        else:
            clauses = _cnf(span_tree)
            if clauses is None or len(clauses) > MAX_CLAUSES:
                return None
            for cl in clauses:
                for leaf in cl:
                    if not span_leaf(leaf):
                        return None
        groups.append([[leaf[1] for leaf in cl] for cl in (clauses or [])])
        return len(groups) - 1

    if tree is not None:
        tree = normalize_tree(tree, conds)
        tcnf = _cnf(tree, MAX_TCLAUSES)
        if tcnf is None or len(tcnf) > MAX_TCLAUSES:
            return None
        tclauses: list[list[int]] = []  # per trace clause: atom ids
        for cl in tcnf:
            atom_ids = []
            for leaf in cl:
                if isinstance(leaf, tuple) and leaf[0] == "tracify":
                    g = lower_tracify(leaf[1])
                    if g is None:
                        return None
                    atoms.append((0, g))
                elif isinstance(leaf, tuple) and leaf[0] == "cond" \
                        and conds[leaf[1]].target == T_TRACE:
                    tcond_idx.append(leaf[1])
                    atoms.append((1, len(tcond_idx) - 1))
                else:
                    return None  # struct / constants inside a clause
                atom_ids.append(len(atoms) - 1)
            tclauses.append(atom_ids)
    else:
        tclauses = []

    n_sconds = sum(len(cl) for g in groups for cl in g)
    n_clauses = sum(len(g) for g in groups)
    if (n_sconds > MAX_CONDS or n_clauses > MAX_CLAUSES
            or len(groups) > MAX_GROUPS or len(tcond_idx) > MAX_TCONDS
            or len(atoms) > MAX_ATOMS or len(tclauses) > MAX_TCLAUSES):
        return None

    # column maps (sorted for a canonical signature)
    span_cols = sorted({
        (f"span@{conds[i].col}" if conds[i].target == T_RES else conds[i].col)
        for g in groups for cl in g for i in cl
    })
    trace_cols = sorted({conds[i].col for i in tcond_idx})
    scol_of = {n: j for j, n in enumerate(span_cols)}
    tcol_of = {n: j for j, n in enumerate(trace_cols)}

    shape = ProgramShape(
        n_conds_b=_p2(max(n_sconds, 1)),
        n_clauses_b=_p2(max(n_clauses, 1)),
        n_groups_b=_p2(max(len(groups), 1), lo=1),
        n_tconds_b=_p2(max(len(tcond_idx), 1), lo=1),
        n_atoms_b=_p2(max(len(atoms), 1), lo=1),
        n_tclauses_b=_p2(max(len(tclauses), 1), lo=1),
        span_cols=tuple(span_cols),
        trace_cols=tuple(trace_cols),
    )

    def v01(i):
        v0 = int(np.clip(rows[i][1], -(2**31), 2**31 - 1))
        v1 = int(np.clip(rows[i][2], -(2**31), 2**31 - 1))
        return v0, v1

    P, NC, NG = shape.n_conds_b, shape.n_clauses_b, shape.n_groups_b
    PT, NA, TC = shape.n_tconds_b, shape.n_atoms_b, shape.n_tclauses_b
    cond_col = np.zeros(P, np.int32)
    cond_op = np.full(P, _NOP, np.int32)
    cond_v0 = np.zeros(P, np.int32)
    cond_v1 = np.zeros(P, np.int32)
    cond_guard = np.zeros(P, bool)
    clause_off = np.zeros(NC + 1, np.int32)
    group_off = np.zeros(NG + 1, np.int32)
    s = c_i = 0
    for gi, g in enumerate(groups):
        group_off[gi] = c_i
        for cl in g:
            clause_off[c_i] = s
            for i in cl:
                c = conds[i]
                name = f"span@{c.col}" if c.target == T_RES else c.col
                cond_col[s] = scol_of[name]
                cond_op[s] = _OPC[c.op]
                cond_v0[s], cond_v1[s] = v01(i)
                cond_guard[s] = c.target == T_RES
                s += 1
            c_i += 1
            clause_off[c_i] = s
    group_off[len(groups):] = c_i
    clause_off[c_i:] = s  # padded clauses: empty ranges past the real conds

    tcond_col = np.zeros(PT, np.int32)
    tcond_op = np.full(PT, _NOP, np.int32)
    tcond_v0 = np.zeros(PT, np.int32)
    tcond_v1 = np.zeros(PT, np.int32)
    for j, i in enumerate(tcond_idx):
        tcond_col[j] = tcol_of[conds[i].col]
        tcond_op[j] = _OPC[conds[i].op]
        tcond_v0[j], tcond_v1[j] = v01(i)

    atom_kind = np.full(NA, _NOP, np.int32)
    atom_idx = np.zeros(NA, np.int32)
    tclause_off = np.zeros(TC + 1, np.int32)
    a = 0
    for ti, atom_ids in enumerate(tclauses):
        tclause_off[ti] = a
        for aid in atom_ids:
            atom_kind[a], atom_idx[a] = atoms[aid]
            a += 1
        tclause_off[ti + 1] = a
    tclause_off[len(tclauses):] = a

    return LoweredQuery(
        shape=shape,
        cond_col=cond_col, cond_op=cond_op, cond_v0=cond_v0, cond_v1=cond_v1,
        cond_guard=cond_guard, clause_off=clause_off, group_off=group_off,
        n_groups=len(groups),
        tcond_col=tcond_col, tcond_op=tcond_op,
        tcond_v0=tcond_v0, tcond_v1=tcond_v1,
        atom_kind=atom_kind, atom_idx=atom_idx, tclause_off=tclause_off,
        n_tclauses=len(tclauses),
    )


def pack_queries(lowered: list[LoweredQuery], q_b: int) -> dict[str, np.ndarray]:
    """Stack Q programs (identical ProgramShape) into (q_b, ...) tables;
    padded query rows match nothing (one impossible trace clause)."""
    shape = lowered[0].shape
    out: dict[str, np.ndarray] = {}
    fields = ("cond_col", "cond_op", "cond_v0", "cond_v1", "cond_guard",
              "clause_off", "group_off", "tcond_col", "tcond_op",
              "tcond_v0", "tcond_v1", "atom_kind", "atom_idx", "tclause_off")
    for f in fields:
        out[f] = np.stack([getattr(lq, f) for lq in lowered]
                          + [np.zeros_like(getattr(lowered[0], f))]
                          * (q_b - len(lowered)))
    ng = np.asarray([lq.n_groups for lq in lowered]
                    + [0] * (q_b - len(lowered)), np.int32)
    # padded queries: one empty trace clause => OR over nothing => False
    ntc = np.asarray([lq.n_tclauses for lq in lowered]
                     + [1] * (q_b - len(lowered)), np.int32)
    out["n_groups"] = ng
    out["n_tclauses"] = ntc
    assert all(lq.shape == shape for lq in lowered)
    return out


# ----------------------------------------------------------------- kernel


def _cmp_code(opc, x, v0, v1):
    """Data-driven compare: op code is a traced array, so one compiled
    program serves every operand mix. Padded slots (opc == _NOP) and
    unknown codes yield False."""
    return (
        ((opc == 0) & (x == v0))
        | ((opc == 1) & (x != v0))
        | ((opc == 2) & ((x != v0) & (x >= 0)))
        | ((opc == 3) & (x < v0))
        | ((opc == 4) & (x <= v0))
        | ((opc == 5) & (x > v0))
        | ((opc == 6) & (x >= v0))
        | ((opc == 7) & ((x >= v0) & (x <= v1)))
    )


@lru_cache(maxsize=64)
def _compiled_multiquery(shape: ProgramShape, q_b: int, n_spans_b: int,
                         n_traces_b: int):
    n_sc = max(1, len(shape.span_cols))
    n_tc = max(1, len(shape.trace_cols))

    @jax.jit
    def run(span_cols, trace_cols, span_off, progs, n_spans, n_traces):
        valid_span = jnp.arange(n_spans_b, dtype=jnp.int32) < n_spans
        valid_trace = jnp.arange(n_traces_b, dtype=jnp.int32) < n_traces
        span_mat = (jnp.stack(span_cols) if span_cols
                    else jnp.zeros((1, n_spans_b), jnp.int32))
        trace_mat = (jnp.stack(trace_cols) if trace_cols
                     else jnp.zeros((1, n_traces_b), jnp.int32))

        def one(p):
            # span conds -> (P, S) masks
            x = span_mat[jnp.clip(p["cond_col"], 0, n_sc - 1)]
            m = _cmp_code(p["cond_op"][:, None], x,
                          p["cond_v0"][:, None], p["cond_v1"][:, None])
            m = m & (~p["cond_guard"][:, None] | (x != PAD_I32))
            m = m & valid_span[None, :]
            # OR within clauses: cumsum along the cond axis + boundary
            # gathers (the same scan-not-scatter fold as ops/filter)
            cs = jnp.concatenate(
                [jnp.zeros((1, n_spans_b), jnp.int32),
                 jnp.cumsum(m.astype(jnp.int32), axis=0)])
            co = p["clause_off"]
            clause_ok = (cs[co[1:]] - cs[co[:-1]]) > 0  # (NC, S)
            # AND across a group's clauses: count == clause count
            cs2 = jnp.concatenate(
                [jnp.zeros((1, n_spans_b), jnp.int32),
                 jnp.cumsum(clause_ok.astype(jnp.int32), axis=0)])
            go = p["group_off"]
            n_cl = (go[1:] - go[:-1])[:, None]
            grp_ok = ((cs2[go[1:]] - cs2[go[:-1]]) == n_cl) & valid_span[None, :]
            # per-group per-trace matched counts (grouped span layout)
            cs3 = jnp.concatenate(
                [jnp.zeros((grp_ok.shape[0], 1), jnp.int32),
                 jnp.cumsum(grp_ok.astype(jnp.int32), axis=1)], axis=1)
            gcounts = cs3[:, span_off[1:]] - cs3[:, span_off[:-1]]  # (NG, T)
            gmask = gcounts > 0
            # trace conds
            tx = trace_mat[jnp.clip(p["tcond_col"], 0, n_tc - 1)]
            tcm = _cmp_code(p["tcond_op"][:, None], tx,
                            p["tcond_v0"][:, None], p["tcond_v1"][:, None])
            # atoms -> trace clauses -> AND
            kind = p["atom_kind"]
            aval = jnp.where(
                (kind == 0)[:, None],
                gmask[jnp.clip(p["atom_idx"], 0, gmask.shape[0] - 1)],
                tcm[jnp.clip(p["atom_idx"], 0, tcm.shape[0] - 1)],
            ) & (kind >= 0)[:, None]
            cs4 = jnp.concatenate(
                [jnp.zeros((1, n_traces_b), jnp.int32),
                 jnp.cumsum(aval.astype(jnp.int32), axis=0)])
            to = p["tclause_off"]
            tcl_ok = ((cs4[to[1:]] - cs4[to[:-1]]) > 0) | (
                jnp.arange(to.shape[0] - 1) >= p["n_tclauses"])[:, None]
            tm = jnp.all(tcl_ok, axis=0) & valid_trace
            # union of group span masks = the reporting mask; no groups
            # (pure trace conds / match-all) counts every valid span
            live = (jnp.arange(grp_ok.shape[0]) < p["n_groups"])[:, None]
            union = jnp.where(p["n_groups"] > 0,
                              jnp.any(grp_ok & live, axis=0), valid_span)
            ucs = jnp.concatenate(
                [jnp.zeros(1, jnp.int32),
                 jnp.cumsum(union.astype(jnp.int32))])
            counts = jnp.where(tm, ucs[span_off[1:]] - ucs[span_off[:-1]], 0)
            return tm, counts

        return jax.vmap(one)(progs)

    return run


def mq_bytes_estimate(shape: ProgramShape, q_b: int, n_spans_b: int) -> int:
    """Dominant intermediate footprint of one fused launch (the (Q, P,
    S) cond masks + cumsums in int32); the executor budget-gates on it."""
    return q_b * max(1, shape.n_conds_b) * n_spans_b * 4 * 3


def eval_multiquery(lowered: list[LoweredQuery], staged, progs: dict):
    """Run Q packed programs against one staged block: ONE fused launch.
    Returns device (q_b, n_traces_b) trace_mask, counts."""
    import time as _time

    from ..util.kerneltel import TEL

    shape = lowered[0].shape
    q_b = progs["cond_op"].shape[0]
    fn = _compiled_multiquery(shape, q_b, staged.n_spans_b, staged.n_traces_b)
    TEL.record_launch(
        "multiquery",
        ("mq", shape, q_b, staged.n_spans_b, staged.n_traces_b),
        staged.n_spans_b,
    )
    span_cols = tuple(staged.cols[n] for n in shape.span_cols)
    trace_cols = tuple(staged.cols[n] for n in shape.trace_cols)
    t0 = _time.perf_counter()
    tm, counts = fn(span_cols, trace_cols, staged.cols["trace.span_off"],
                    progs, np.int32(staged.n_spans), np.int32(staged.n_traces))
    TEL.observe_device("multiquery", staged.n_spans_b, t0, (tm, counts))
    return tm, counts


_NEG = -(2**31)


@lru_cache(maxsize=64)
def _compiled_mq_select(k: int, q_b: int):
    @jax.jit
    def sel(tm, key, counts):
        keyed = jnp.where(tm, key.astype(jnp.int32)[None, :], jnp.int32(_NEG))
        _, topi = jax.lax.top_k(keyed, k)  # (Q, k), rowwise == 1-D top_k
        valid = jnp.take_along_axis(tm, topi, axis=1).astype(jnp.int32)
        cnt = jnp.take_along_axis(counts, topi, axis=1)
        nm = jnp.sum(tm.astype(jnp.int32), axis=1)
        return jnp.concatenate(
            [topi.astype(jnp.int32), cnt, valid, nm[:, None]], axis=1)

    return sel


def select_multiquery(tm, key, counts, k: int):
    """Batched twin of ops/select.select_topk_device: one launch + one
    fetch for all Q queries. Returns per query the RAW (sids, counts,
    valid, n_match) arrays of length k, still in top-k order -- callers
    slice to their own smaller k' THEN apply valid, which reproduces the
    single-query select at k' exactly (top_k's order is deterministic,
    so the first k' slots of a k-select equal a k'-select)."""
    import time as _time

    from ..util.kerneltel import TEL

    q_b, nt = int(tm.shape[0]), int(tm.shape[1])
    k = int(min(k, nt))
    TEL.record_launch("mq_select", ("mqsel", k, q_b, nt), k)
    t0 = _time.perf_counter()
    out = np.asarray(_compiled_mq_select(k, q_b)(tm, key, counts))
    TEL.observe_device("mq_select", k, t0)
    res = []
    for q in range(q_b):
        row = out[q]
        res.append((row[:k], row[k:2 * k], row[2 * k:3 * k] > 0,
                    int(row[3 * k])))
    return res
