"""Top-k result selection: pick the `limit` newest matching traces
WITHOUT shipping full masks to host.

The device filter produces (trace_mask, span_count) sized to the trace
axis. Materializing results used to mean one device->host transfer per
array plus a Python loop over every candidate -- on a high-latency
host<->device link each sync costs tens of ms, and the loop cost scaled
with match count, not with the result limit. Instead the selection
itself runs on device: key = trace start time under the mask,
`lax.top_k`, gather the per-trace counts at the winners, and return ONE
small fused int32 vector `[sids | counts | valid | n_match]` -- a single
fetch whose size is O(k), so query cost is O(limit) past the filter
kernel no matter how many traces matched.

Host re-verification may reject candidates (conservative device
encodings), so callers over-select and escalate k (db/search.py's
collect loop). The numpy variant serves the host evaluation path
(ops/hostfilter.py) with identical ordering semantics.
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

_NEG = -(2**31)


def k_bucket(k: int) -> int:
    """Power-of-two k so escalation reuses few compiled programs."""
    b = 16
    while b < k:
        b <<= 1
    return b


@lru_cache(maxsize=64)
def _compiled_select(k: int):
    @jax.jit
    def sel(mask, key, counts):
        keyed = jnp.where(mask, key.astype(jnp.int32), jnp.int32(_NEG))
        _, topi = jax.lax.top_k(keyed, k)
        valid = jnp.take(mask, topi).astype(jnp.int32)
        return jnp.concatenate([
            topi.astype(jnp.int32),
            jnp.take(counts, topi).astype(jnp.int32),
            valid,
            jnp.sum(mask.astype(jnp.int32))[None],
        ])

    return sel


def select_topk_device(mask, key, counts, k: int):
    """mask/key/counts: same-length device (or host) arrays; k <= len.
    Returns (sids desc-by-key, counts at sids, n_match) as numpy --
    one device sync total."""
    import time as _time

    from ..util.kerneltel import TEL

    k = int(min(k, mask.shape[0]))
    from ..util import costmodel

    sel = _compiled_select(k)
    TEL.record_launch("select", ("sel1", k, int(mask.shape[0])), k,
                      cost=lambda: costmodel.spec(sel, mask, key, counts))
    t0 = _time.perf_counter()
    out = np.asarray(sel(mask, key, counts))
    TEL.observe_device("select", k, t0)
    sids, cnts, valid = out[:k], out[k : 2 * k], out[2 * k : 3 * k] > 0
    return sids[valid], cnts[valid], int(out[3 * k])


@lru_cache(maxsize=64)
def _compiled_select_multi(k: int, n_parts: int):
    """Fused cross-block selection: concatenate per-block (mask, key,
    count) vectors ON DEVICE and top-k once. n_parts is only a cache
    discriminator; jax.jit itself re-specializes on the part shapes."""

    @jax.jit
    def sel(masks, keys, counts):
        m = jnp.concatenate(masks)
        key = jnp.concatenate(keys).astype(jnp.int32)
        c = jnp.concatenate(counts)
        keyed = jnp.where(m, key, jnp.int32(_NEG))
        _, topi = jax.lax.top_k(keyed, k)
        valid = jnp.take(m, topi).astype(jnp.int32)
        return jnp.concatenate([
            topi.astype(jnp.int32),
            jnp.take(c, topi).astype(jnp.int32),
            valid,
            jnp.sum(m.astype(jnp.int32))[None],
        ])

    return sel


def select_topk_device_multi(masks, keys, counts, k: int):
    """Top-k across MANY blocks' device mask/key/count vectors in one
    fused program -> ONE device sync for the whole multi-block query.
    Returns (global_idx desc-by-key, counts at winners, total n_match);
    global_idx indexes the concatenation of the (padded) parts -- the
    caller maps it back to (block, sid) with the part offsets."""
    import time as _time

    from ..util.kerneltel import TEL

    total = int(sum(m.shape[0] for m in masks))
    k = int(min(k, total))
    from ..util import costmodel

    sel = _compiled_select_multi(k, len(masks))
    TEL.record_launch(
        "select", ("selN", k, tuple(int(m.shape[0]) for m in masks)), k,
        cost=lambda: costmodel.spec(
            sel, tuple(masks), tuple(keys), tuple(counts)))
    t0 = _time.perf_counter()
    out = np.asarray(
        sel(tuple(masks), tuple(keys), tuple(counts))
    )
    TEL.observe_device("select", k, t0)
    gids, cnts, valid = out[:k], out[k : 2 * k], out[2 * k : 3 * k] > 0
    return gids[valid], cnts[valid], int(out[3 * k])


def select_topk_host_multi(masks, keys, counts, k: int):
    """Host twin of select_topk_device_multi: one global top-k over many
    blocks' (mask, key, count) vectors. Keys must already be globally
    comparable (the cross-block gkey convention); returned ids index the
    concatenation of the parts."""
    return select_topk_host(
        np.concatenate(masks), np.concatenate(keys), np.concatenate(counts), k)


def select_topk_host(mask: np.ndarray, key: np.ndarray, counts: np.ndarray, k: int):
    """Numpy twin: argpartition + sort, same descending-key order."""
    n = mask.shape[0]
    n_match = int(np.count_nonzero(mask))
    k = int(min(k, n))
    keyed = np.where(mask, key.astype(np.int64), np.int64(-(2**62)))
    if k < n:
        part = np.argpartition(-keyed, k - 1)[:k] if k > 0 else np.empty(0, np.int64)
    else:
        part = np.arange(n)
    part = part[np.argsort(-keyed[part], kind="stable")]
    sids = part[mask[part]]
    return sids.astype(np.int64), counts[sids], n_match
