"""Device/host twin registry: the exact-verify contract as data.

Device kernels are *conservative* (clamped encodings may over-match,
never under-match; ops/filter docstring), so every device kernel a db
executor dispatches needs a pure-numpy twin the verify path can replay
candidates through bit-exactly. This module records that pairing
explicitly; `tempo_tpu.analysis` cross-checks it both ways at build
time (twin-missing / twin-unresolvable), so adding a kernel without a
twin -- or deleting a twin a kernel still relies on -- fails tier-1.

Names are dotted paths relative to the tempo_tpu package. Several
device kernels share one host twin: the fused multi-query program and
the mesh variants demux to per-query/per-block calls whose semantics
are exactly the single-block host evaluator's.
"""

from __future__ import annotations

DEVICE_HOST_TWINS: dict[str, str] = {
    # single-block filter program and its streamed wrapper
    "ops.filter.eval_block": "ops.hostfilter.eval_block_host",
    "ops.stream.eval_block_streamed": "ops.hostfilter.eval_block_host",
    # top-k selection (single and cross-shard merge forms)
    "ops.select.select_topk_device": "ops.select.select_topk_host",
    "ops.select.select_topk_device_multi": "ops.select.select_topk_host_multi",
    # TraceQL metrics time-bucketed folds
    "ops.timeseries.eval_timeseries_device": "ops.timeseries.eval_timeseries_host",
    "parallel.timeseries.sharded_timeseries": "ops.timeseries.eval_timeseries_host",
    # fused multi-query batch programs: demuxed per query, each query's
    # exact-verify replays through the single-block host evaluator
    "ops.multiquery.eval_multiquery": "ops.hostfilter.eval_block_host",
    "ops.multiquery.select_multiquery": "ops.select.select_topk_host",
    # mesh-batched window launch (Q programs x sharded rows): demuxes
    # to the same per-query verify as the single-chip fused launch
    "parallel.multiquery.mesh_eval_multiquery": "ops.hostfilter.eval_block_host",
    # trace-id bisection (single-chip, batched, and mesh-sharded forms)
    "ops.find.lookup_ids": "ops.find.lookup_ids_blocks_host",
    "ops.find.lookup_ids_blocks": "ops.find.lookup_ids_blocks_host",
    "ops.find.lookup_ids_blocks_cached": "ops.find.lookup_ids_blocks_host",
    "parallel.find.sharded_find_rows": "ops.find.lookup_ids_blocks_host",
    # mesh search: per-block results match the host evaluator per block
    "parallel.search.sharded_search": "ops.hostfilter.eval_block_host",
    # span-metrics segmented reduce routes to its host fold internally
    "ops.reduce.span_metrics_reduce": "ops.reduce._reduce_host",
    # service-graph fused edge reduce (streaming generator): host twin
    # replays the legacy two-launch + bincount sequence bit-exactly
    "ops.reduce.edge_metrics_reduce": "ops.reduce._edge_reduce_host",
    # live-head engine: staged slot filter + id lookup, numpy twins run
    # the tiny-head path and the differential harness
    "ops.livestage.eval_live_device": "ops.livestage.eval_live_host",
    "ops.livestage.find_slot_device": "ops.livestage.find_slot_host",
    # block-cut kernels (write path): pure integer ops, so the numpy
    # twins are bit-identical and double as the jax-less fallback
    "ops.blockcut.remap_codes_device": "ops.blockcut.remap_codes_host",
    "ops.blockcut.bloom_bits_device": "ops.blockcut.bloom_bits_host",
    "ops.blockcut.rowgroup_minmax_device": "ops.blockcut.rowgroup_minmax_host",
}

# Device entry points with no host twin BY DESIGN; each carries the
# reason exact-verify does not need it. The checker accepts these but
# flags stale names.
DEVICE_ONLY: dict[str, str] = {
    # staging is transport, not evaluation: the host path reads columns
    # straight from the pack (db/search._host_cols), so there is no
    # semantic result to mirror
    "ops.stage.stage_block": "transport only; host path reads raw columns",
    # probabilistic admission gate: a false positive only costs an exact
    # downstream lookup, and misses are impossible by construction
    "ops.bloom_ops.batch_test": "conservative gate; hits are re-verified "
                                "by exact id bisection",
    "ops.bloom_ops.union_blooms": "ingest-side aggregation of filter "
                                  "words; nothing to verify",
    "parallel.bloom.sharded_bloom_union": "mesh variant of union_blooms",
    # live-head delta append is transport (dynamic_update_slice into the
    # resident column); the host tails ARE the source of truth it copies
    "ops.livestage._append_rows_device": "transport only; host tails are "
                                         "the authoritative copy",
}
