"""TPU kernels for the read-side hot path.

Everything in this package is jit-compiled JAX operating on the flat
int32/float32 columns of a vtpu block. Shapes are padded to power-of-two
buckets (device.py) so the jit cache stays small across blocks; kernels
are data-driven -- predicate operand VALUES are traced arrays, only the
predicate STRUCTURE (column set + op kinds) keys a compile.
"""
