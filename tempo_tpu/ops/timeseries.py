"""Time-bucketed segmented reductions: the TraceQL-metrics kernel.

One fused device pass per (block, query): evaluate the span-level
predicate tree (the same data-driven condition machinery as ops/filter,
so `{span.foo = "bar"} | rate()` and `{span.foo = "baz"} | rate()`
share a compiled program), bucketize each surviving span's start time
onto the request's step-aligned axis, and fold into
`[num_groups, num_buckets]` accumulators with one segment reduce over a
combined (group, bucket) index -- the same combined-index trick the
span-metrics generator reduce uses (ops/reduce.py histogram scatter).

Only the tree/condition STRUCTURE and the padded (groups, buckets)
shapes key the jit compile; operand values, group ids, value columns
and the time origin are traced, so the program is shared across blocks
and across steps/ranges of the same query shape.

Group ids arrive as a per-span int32 column computed host-side from the
by() field's dictionary codes (db/metrics_exec) -- group-key resolution
is per-block (each block has its own dictionary), the kernel only ever
sees dense ids in [0, num_groups). -1 drops the span (missing label).

Value folds (`min/avg/sum/max_over_time(field)`) take a per-span f32
value + presence mask derived host-side from the EXACT host columns
(sattr.int64/f64, span.start_ns/end_ns), so the only device-side loss
is the f32 cast -- integer counts are exact on both engines.

The host twin (eval_timeseries_host) mirrors the semantics in numpy
over raw columns (f64 accumulation) for cold blocks; exact-verify
queries bypass both engines entirely (db/metrics_exec exact path).
"""

from __future__ import annotations

from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from .device import bucket, pad_rows
from .filter import Cond, Operands, T_TRACE, _cmp, _cond_mask
from .hostfilter import eval_span_mask_host


@lru_cache(maxsize=256)
def _compiled_ts(tree, conds: tuple[Cond, ...], table_idxs: tuple[int, ...],
                 has_val: bool, n_spans_b: int, n_res_b: int, n_traces_b: int,
                 G_b: int, B_b: int):
    """tree: raw SPAN-level expression (no tracify); None matches all.
    Trace-target conds gather through span.trace_sid."""

    @jax.jit
    def run(cols, ops_i, ops_f, table_list, gid, val, vpres,
            t0_ms, step_ms, n_spans, n_buckets):
        tables = dict(zip(table_idxs, table_list))
        valid = jnp.arange(n_spans_b, dtype=jnp.int32) < n_spans

        def ev(t):
            if t == ("true",):
                return valid
            if t == ("false",):
                return jnp.zeros_like(valid)
            if t[0] == "cond":
                i = t[1]
                c = conds[i]
                if c.target == T_TRACE:
                    tm = _cmp(c.op, cols[c.col], ops_i[i, 1], ops_i[i, 2],
                              ops_f[i, 0], ops_f[i, 1], c.is_float,
                              tables.get(i))
                    sid = jnp.clip(cols["span.trace_sid"], 0, n_traces_b - 1)
                    return tm[sid] & valid
                return _cond_mask(c, i, cols, ops_i, ops_f, tables,
                                  n_spans_b, n_res_b, valid)
            ms = [ev(ch) for ch in t[1:]]
            out = ms[0]
            for m in ms[1:]:
                out = (out & m) if t[0] == "and" else (out | m)
            return out

        sm = valid if tree is None else (ev(tree) & valid)
        # int32 bucket math (x64 stays off): the caller clips t0 into
        # int32, and blocks span hours, not the ~24-day int32-ms range
        b = (cols["span.start_ms"] - t0_ms) // step_ms
        ok = sm & (b >= 0) & (b < n_buckets) & (gid >= 0)
        b32 = jnp.clip(b, 0, B_b - 1).astype(jnp.int32)
        seg = jnp.where(ok, gid * B_b + b32, G_b * B_b)
        nseg = G_b * B_b + 1
        counts = jax.ops.segment_sum(ok.astype(jnp.int32), seg,
                                     num_segments=nseg)[:-1].reshape(G_b, B_b)
        if not has_val:
            return (counts,)
        pres = ok & vpres
        segv = jnp.where(pres, seg, G_b * B_b)
        vcnt = jax.ops.segment_sum(pres.astype(jnp.int32), segv,
                                   num_segments=nseg)[:-1].reshape(G_b, B_b)
        v = jnp.where(pres, val, jnp.float32(0))
        vsum = jax.ops.segment_sum(v, segv, num_segments=nseg)[:-1].reshape(G_b, B_b)
        vmin = jax.ops.segment_min(
            jnp.where(pres, val, jnp.float32(jnp.inf)), segv,
            num_segments=nseg)[:-1].reshape(G_b, B_b)
        vmax = jax.ops.segment_max(
            jnp.where(pres, val, jnp.float32(-jnp.inf)), segv,
            num_segments=nseg)[:-1].reshape(G_b, B_b)
        return counts, vcnt, vsum, vmin, vmax

    return run


def _table_list(operands: Operands):
    tables = operands.tables or {}
    table_idxs = tuple(sorted(tables))
    return table_idxs, [
        pad_rows(np.asarray(tables[i], dtype=np.uint8),
                 bucket(max(1, len(tables[i]))), 0)
        for i in table_idxs
    ]


def eval_timeseries_device(query, staged, operands: Operands,
                           gid: np.ndarray, val: np.ndarray | None,
                           vpres: np.ndarray | None,
                           t0_rel_ms: int, step_ms: int,
                           n_buckets: int, n_groups: int):
    """One fused device dispatch over a StagedBlock (ops/stage).
    gid/val/vpres are raw span-length host arrays for the staged span
    slice; the padded uploads ride the jit call's batched transfer.
    Returns numpy accumulators clipped to (n_groups, n_buckets):
    (counts,) or (counts, vcnt, vsum, vmin, vmax)."""
    tree, conds = query
    G_b, B_b = bucket(max(n_groups, 1)), bucket(max(n_buckets, 1))
    table_idxs, tabs = _table_list(operands)
    has_val = val is not None
    fn = _compiled_ts(tree, conds, table_idxs, has_val,
                      staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
                      G_b, B_b)
    gid_p = pad_rows(np.asarray(gid, np.int32), staged.n_spans_b, np.int32(-1))
    if has_val:
        val_p = pad_rows(np.asarray(val, np.float32), staged.n_spans_b,
                         np.float32(0))
        pres_p = pad_rows(np.asarray(vpres, bool), staged.n_spans_b, False)
    else:
        val_p = pres_p = np.zeros(0, np.float32)
    t0 = int(np.clip(t0_rel_ms, -(2**31) + 1, 2**31 - 1))
    import time as _time

    from ..util import costmodel
    from ..util.kerneltel import TEL

    t0_i = np.int32(t0)
    step_i = np.int32(max(1, step_ms))
    ns_i, nb_i = np.int32(staged.n_spans), np.int32(n_buckets)
    TEL.record_launch(
        "timeseries",
        ("ts", tree, conds, table_idxs, has_val, staged.n_spans_b,
         staged.n_res_b, staged.n_traces_b, G_b, B_b),
        staged.n_spans_b,
        cost=lambda: costmodel.spec(fn, staged.cols, operands.ints,
                                    operands.floats, tabs, gid_p, val_p,
                                    pres_p, t0_i, step_i, ns_i, nb_i),
    )
    tw = _time.perf_counter()
    outs = fn(staged.cols, operands.ints, operands.floats, tabs,
              gid_p, val_p, pres_p,
              t0_i, step_i, ns_i, nb_i)
    res = tuple(np.asarray(o)[:n_groups, :n_buckets] for o in outs)
    TEL.observe_device("timeseries", staged.n_spans_b, tw)
    return res


def eval_timeseries_host(query, cols: dict[str, np.ndarray],
                         operands: Operands, n_spans: int, n_traces: int,
                         gid: np.ndarray, val: np.ndarray | None,
                         vpres: np.ndarray | None,
                         t0_rel_ms: int, step_ms: int,
                         n_buckets: int, n_groups: int):
    """Numpy twin of the device kernel over RAW host columns (the cold-
    block engine): same masks, same bucketing, f64 value accumulation.
    Returns the same accumulator tuple shapes as the device path."""
    sm = eval_span_mask_host(query, cols, operands, n_spans, n_traces)
    b = (cols["span.start_ms"].astype(np.int64) - int(t0_rel_ms)) // int(step_ms)
    ok = sm & (b >= 0) & (b < n_buckets) & (gid >= 0)
    nb = int(n_buckets)
    key = gid.astype(np.int64) * nb + np.clip(b, 0, nb - 1)
    nk = max(n_groups, 1) * nb
    counts = np.bincount(key[ok], minlength=nk)[:nk].reshape(-1, nb)
    counts = counts[:n_groups]
    if val is None:
        return (counts,)
    pres = ok & vpres
    kp = key[pres]
    vcnt = np.bincount(kp, minlength=nk)[:nk].reshape(-1, nb)[:n_groups]
    vv = val.astype(np.float64)[pres]
    vsum = np.bincount(kp, weights=vv, minlength=nk)[:nk].reshape(-1, nb)[:n_groups]
    vmin = np.full(nk, np.inf)
    vmax = np.full(nk, -np.inf)
    np.minimum.at(vmin, kp, vv)
    np.maximum.at(vmax, kp, vv)
    return (counts, vcnt, vsum,
            vmin.reshape(-1, nb)[:n_groups], vmax.reshape(-1, nb)[:n_groups])
