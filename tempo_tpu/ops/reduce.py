"""Segmented reduces for the metrics-generator: span-metrics as one
fused device pass (BASELINE config #5).

The reference updates per-series counters span by span
(modules/generator/processor/spanmetrics/spanmetrics.go:79-96 +
registry histogram.go); here a collection cycle's buffered spans fold
into (calls, latency_sum, latency_histogram) with three segment reduces
in one jitted program: series ids are the segments, the histogram
scatter uses a combined (series, bucket) index.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .device import bucket as pow2


@partial(jax.jit, static_argnames=("n_series_b", "n_buckets"))
def _reduce_kernel(sid, dur, n_valid, edges, n_series_b: int, n_buckets: int):
    """sid: (N,) int32 (pad: n_series_b), dur: (N,) f32, edges: (n_buckets-1,)
    -> calls (S,), lat_sum (S,), hist (S, n_buckets)."""
    valid = jnp.arange(sid.shape[0]) < n_valid
    seg = jnp.where(valid, sid, n_series_b)
    ones = valid.astype(jnp.int32)
    calls = jax.ops.segment_sum(ones, seg, num_segments=n_series_b + 1)[:n_series_b]
    lat_sum = jax.ops.segment_sum(jnp.where(valid, dur, 0.0), seg,
                                  num_segments=n_series_b + 1)[:n_series_b]
    bidx = jnp.searchsorted(edges, dur)  # 0..n_buckets-1
    combo = jnp.where(valid, seg * n_buckets + bidx, n_series_b * n_buckets)
    hist = jax.ops.segment_sum(ones, combo, num_segments=n_series_b * n_buckets + 1)[:-1]
    return calls, lat_sum, hist.reshape(n_series_b, n_buckets)


def _reduce_host(sid: np.ndarray, dur_s: np.ndarray, n_series: int,
                 bucket_edges: tuple):
    """Host twin of the device kernel: one fused native pass (the
    series x bucket table stays cache-resident), numpy fallback of one
    searchsorted + two bincounts. Exact same outputs."""
    edges = np.asarray(bucket_edges, np.float32)
    from ..native import span_metrics_fold

    out = span_metrics_fold(np.ascontiguousarray(sid, np.int32),
                            np.ascontiguousarray(dur_s, np.float32),
                            edges, n_series)
    if out is not None:
        hist, lsum = out
        return hist.sum(axis=1).astype(np.int64), lsum, hist
    nb = len(bucket_edges) + 1
    bidx = np.searchsorted(edges, dur_s.astype(np.float32))
    combo = sid.astype(np.int64) * nb + bidx
    hist = np.bincount(combo, minlength=n_series * nb)[: n_series * nb]
    hist = hist.reshape(n_series, nb)
    lsum = np.bincount(sid, weights=dur_s.astype(np.float64), minlength=n_series)[:n_series]
    return (hist.sum(axis=1).astype(np.int64), lsum.astype(np.float64),
            hist.astype(np.int64))


@partial(jax.jit, static_argnames=("n_edges_b", "n_buckets"))
def _edge_reduce_kernel(eid, cdur, sdur, failed, n_valid, edges,
                        n_edges_b: int, n_buckets: int):
    """One fused program for a window's completed service-graph edges:
    eid (N,) int32 (pad: n_edges_b), cdur/sdur (N,) f32, failed (N,)
    int32 -> counts (E,), failed_counts (E,), client_sum (E,),
    server_sum (E,), client_hist (E, nb), server_hist (E, nb). Six
    segment reduces sharing one upload instead of the legacy two
    span_metrics launches + host bincount."""
    valid = jnp.arange(eid.shape[0]) < n_valid
    seg = jnp.where(valid, eid, n_edges_b)
    ones = valid.astype(jnp.int32)
    ns = n_edges_b + 1
    counts = jax.ops.segment_sum(ones, seg, num_segments=ns)[:n_edges_b]
    fcounts = jax.ops.segment_sum(jnp.where(valid, failed, 0), seg,
                                  num_segments=ns)[:n_edges_b]
    csum = jax.ops.segment_sum(jnp.where(valid, cdur, 0.0), seg,
                               num_segments=ns)[:n_edges_b]
    ssum = jax.ops.segment_sum(jnp.where(valid, sdur, 0.0), seg,
                               num_segments=ns)[:n_edges_b]
    nhist = n_edges_b * n_buckets + 1
    ccombo = jnp.where(valid, seg * n_buckets + jnp.searchsorted(edges, cdur),
                       n_edges_b * n_buckets)
    scombo = jnp.where(valid, seg * n_buckets + jnp.searchsorted(edges, sdur),
                       n_edges_b * n_buckets)
    chist = jax.ops.segment_sum(ones, ccombo, num_segments=nhist)[:-1]
    shist = jax.ops.segment_sum(ones, scombo, num_segments=nhist)[:-1]
    return (counts, fcounts, csum, ssum,
            chist.reshape(n_edges_b, n_buckets),
            shist.reshape(n_edges_b, n_buckets))


def _edge_reduce_host(eid: np.ndarray, cdur: np.ndarray, sdur: np.ndarray,
                      failed: np.ndarray, n_edges: int, bucket_edges: tuple):
    """Host twin of the edge kernel: composes the span-metrics host fold
    per side plus a failed bincount -- numerically EXACTLY the legacy
    ServiceGraphsProcessor.collect sequence, which is what makes the
    streaming-vs-legacy differential bit-for-bit."""
    counts, csum, chist = _reduce_host(eid, cdur, n_edges, bucket_edges)
    _, ssum, shist = _reduce_host(eid, sdur, n_edges, bucket_edges)
    fcounts = np.bincount(eid[failed.astype(bool)],
                          minlength=n_edges)[:n_edges].astype(np.int64)
    return counts, fcounts, csum, ssum, chist, shist


def edge_metrics_reduce(eid: np.ndarray, cdur: np.ndarray, sdur: np.ndarray,
                        failed: np.ndarray, n_edges: int, bucket_edges: tuple):
    """-> (counts, failed_counts, client_sum, server_sum, client_hist,
    server_hist) per edge id, as numpy. Same engine policy as
    span_metrics_reduce: host fold through a high-latency link, one
    fused device program otherwise."""
    n = eid.shape[0]
    nb = len(bucket_edges) + 1
    if n == 0 or n_edges == 0:
        z = np.zeros(n_edges, np.int64)
        zf = np.zeros(n_edges, np.float64)
        zh = np.zeros((n_edges, nb), np.int64)
        return z, z.copy(), zf, zf.copy(), zh, zh.copy()
    from ..util.kerneltel import TEL
    from ..util.linkcost import link_rtt_ms

    if link_rtt_ms() > 2.0:
        TEL.record_routing("edge_reduce", "host", "link_rtt")
        return _edge_reduce_host(eid, cdur, sdur, failed, n_edges, bucket_edges)
    TEL.record_routing("edge_reduce", "device", "link_fast")
    Np = pow2(n)
    Eb = pow2(n_edges)
    eid_p = np.full(Np, Eb, dtype=np.int32)
    eid_p[:n] = eid
    cdur_p = np.zeros(Np, dtype=np.float32)
    cdur_p[:n] = cdur
    sdur_p = np.zeros(Np, dtype=np.float32)
    sdur_p[:n] = sdur
    failed_p = np.zeros(Np, dtype=np.int32)
    failed_p[:n] = failed.astype(np.int32)
    import time as _time

    TEL.record_launch("edge_reduce", ("edge_reduce", Np, Eb, nb), Np)
    t0 = _time.perf_counter()
    counts, fcounts, csum, ssum, chist, shist = _edge_reduce_kernel(
        jnp.asarray(eid_p), jnp.asarray(cdur_p), jnp.asarray(sdur_p),
        jnp.asarray(failed_p), jnp.int32(n),
        jnp.asarray(np.asarray(bucket_edges, np.float32)), Eb, nb
    )
    out = (np.asarray(counts[:n_edges]).astype(np.int64),
           np.asarray(fcounts[:n_edges]).astype(np.int64),
           np.asarray(csum[:n_edges]).astype(np.float64),
           np.asarray(ssum[:n_edges]).astype(np.float64),
           np.asarray(chist[:n_edges]).astype(np.int64),
           np.asarray(shist[:n_edges]).astype(np.int64))
    TEL.observe_device("edge_reduce", Np, t0)
    return out


def span_metrics_reduce(sid: np.ndarray, dur_s: np.ndarray, n_series: int,
                        bucket_edges: tuple) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """-> (calls (n_series,), latency_sum (n_series,),
    histogram (n_series, len(edges)+1)) as numpy.

    Engine choice mirrors search: the device fold is one fused program
    but costs an upload of 8 bytes/span plus sync round trips -- through
    a high-latency tunnel the host bincount fold wins outright, on a
    real interconnect the device does (util/linkcost.py)."""
    n = sid.shape[0]
    if n == 0 or n_series == 0:
        nb = len(bucket_edges) + 1
        return (np.zeros(n_series, np.int64), np.zeros(n_series, np.float64),
                np.zeros((n_series, nb), np.int64))
    from ..util.kerneltel import TEL
    from ..util.linkcost import link_rtt_ms

    if link_rtt_ms() > 2.0:
        TEL.record_routing("spanmetrics", "host", "link_rtt")
        return _reduce_host(sid, dur_s, n_series, bucket_edges)
    TEL.record_routing("spanmetrics", "device", "link_fast")
    nb = len(bucket_edges) + 1
    Np = pow2(n)
    Sb = pow2(n_series)
    sid_p = np.full(Np, Sb, dtype=np.int32)
    sid_p[:n] = sid
    dur_p = np.zeros(Np, dtype=np.float32)
    dur_p[:n] = dur_s
    import time as _time

    TEL.record_launch("reduce", ("reduce", Np, Sb, nb), Np)
    t0 = _time.perf_counter()
    calls, lsum, hist = _reduce_kernel(
        jnp.asarray(sid_p), jnp.asarray(dur_p), jnp.int32(n),
        jnp.asarray(np.asarray(bucket_edges, np.float32)), Sb, nb
    )
    out = (np.asarray(calls[:n_series]).astype(np.int64),
           np.asarray(lsum[:n_series]).astype(np.float64),
           np.asarray(hist[:n_series]).astype(np.int64))
    TEL.observe_device("reduce", Np, t0)
    return out
