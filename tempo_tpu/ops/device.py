"""Shape bucketing and host->device column staging.

XLA compiles one program per input-shape signature; trace blocks all have
different row counts. Padding every axis to a power-of-two bucket keeps
the number of distinct compiled programs logarithmic in block size
(SURVEY.md 7.3 "recompilation"). Pad rows carry sentinels that can never
match a predicate and never land in a real segment.
"""

from __future__ import annotations

import numpy as np

# persistent XLA compilation cache (TEMPO_COMPILE_CACHE_DIR): enabled at
# import of THE module every kernel imports, so it covers the first
# compile of any entry point (app, CLI, bench, tests) that honors the
# env var. A no-op when the var is unset or the app already enabled it.
from ..util.costmodel import maybe_enable_compile_cache_from_env

maybe_enable_compile_cache_from_env()

MIN_BUCKET = 1024
PAD_I32 = np.int32(-(2**31))  # sentinel for code/int columns (never a valid code)


def launch_tap(op: str) -> None:
    """Chaos launch shim: every device-kernel launch passes here (via
    TEL.record_launch, the one chokepoint all entry points share) so a
    chaos rule on site `device.launch` can simulate an XLA compile
    failure, a device OOM (RESOURCE_EXHAUSTED), or a slow launch --
    keyed by op name. Only called when a fault plane is active; with
    chaos off the kerneltel fast path never reaches this module."""
    from ..chaos import plane as chaos_plane

    chaos_plane.tap("device.launch", key=str(op))


def bucket(n: int) -> int:
    """Next power-of-two >= max(n, MIN_BUCKET)."""
    b = MIN_BUCKET
    while b < n:
        b <<= 1
    return b


def pad_rows(arr: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad axis 0 to n rows with `fill`."""
    if arr.shape[0] == n:
        return arr
    pad_shape = (n - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, dtype=arr.dtype)])


def pad_columns(
    cols: dict[str, np.ndarray],
    n: int,
    fills: dict[str, object] | None = None,
    default_fill=PAD_I32,
) -> dict[str, np.ndarray]:
    fills = fills or {}
    return {k: pad_rows(v, n, fills.get(k, default_fill)) for k, v in cols.items()}
