"""Tier B of the cache plane: a host-RAM compressed column-chunk pool
under the HBM staged cache (ops/stage).

When the staged-column LRU evicts an entry to stay under the HBM
budget, the padded device arrays are pulled back to host and parked
here instead of discarded -- the bytes already paid object-store IO,
decompression AND pad/assemble once. Entries are stored raw by
default and optionally recompressed through the block codec layer
(block/blockcodecs): a restage must beat the backend read + decode +
assemble it replaces, and without a native codec wheel the
compression round trip costs more than the RAM it saves. A later stage of the same
(block, column set, group range) decompresses and re-uploads straight
from the pool: no backend ranged read, no column decode, no
owner-offset assembly. The pool is per-process, which under PR-7
affinity placement means per cache domain -- the queries that staged an
entry are the ones routed back to the process holding its demotion.

Demotion happens OUTSIDE the stage LRU lock (stage.py collects victims
under the lock and drains them after release): device->host transfers
and compression are milliseconds, the lock protects microsecond
bookkeeping.

Knobs (config_registry): TEMPO_CHUNK_CACHE (kill switch; 0 restores
discard-on-evict exactly), TEMPO_CHUNK_CACHE_BUDGET (compressed-byte
pool bound), TEMPO_CHUNK_CACHE_MAX_ENTRY (per-entry raw-byte admission
cap), TEMPO_CHUNK_CACHE_MIN_REUSE (stagings of a key before its
demotion is worth host RAM), TEMPO_CHUNK_CACHE_CODEC
(lz4/snappy/zstd/none).
"""

from __future__ import annotations

import time as _time
from collections import OrderedDict
from dataclasses import dataclass

import numpy as np

from .. import config_registry as _cfg
from ..util.profiler import timed_lock


def enabled() -> bool:
    return _cfg.get_bool("TEMPO_CHUNK_CACHE")


def _budget() -> int:
    return max(0, _cfg.get_int("TEMPO_CHUNK_CACHE_BUDGET"))


def _max_entry() -> int:
    return max(0, _cfg.get_int("TEMPO_CHUNK_CACHE_MAX_ENTRY"))


def _min_reuse() -> int:
    return max(1, _cfg.get_int("TEMPO_CHUNK_CACHE_MIN_REUSE"))


# ---------------------------------------------------------------- codecs
def _codec_pair(name: str):
    """(compress(bytes) -> bytes, decompress(bytes, raw_len) -> bytes).
    raw_len travels out of band in the entry, matching the colio
    convention."""
    if name == "none":
        return (lambda d: d), (lambda d, n: d)
    if name == "zstd":
        from ..util import zstdshim

        return (lambda d: zstdshim.ZstdCompressor(3).compress(d),
                lambda d, n: zstdshim.ZstdDecompressor().decompress(
                    d, max_output_size=n))
    from ..block import blockcodecs

    if name == "snappy":
        return blockcodecs.snappy_compress, blockcodecs.snappy_decompress
    # default: lz4 -- the cheapest round trip in the codec layer
    return blockcodecs.lz4_compress, blockcodecs.lz4_decompress


def codec_name() -> str:
    name = (_cfg.get("TEMPO_CHUNK_CACHE_CODEC") or "none").lower()
    return name if name in ("lz4", "snappy", "zstd", "none") else "none"


@dataclass
class _Entry:
    """One demoted staged-cache entry: the padded columns' compressed
    bytes plus everything restage() needs to rebuild the StagedBlock
    bit-identically."""

    cols: list  # [(name, dtype_str, shape, comp_bytes, raw_len), ...]
    shape_meta: tuple  # (n_spans, n_traces, n_res, *_b, span_base)
    codec: str
    raw_bytes: int
    comp_bytes: int


# a cataloged hot lock, like stage_lru (TEMPO_LOCK_PROFILE arms timing)
_pool_lock = timed_lock("chunk_pool")
_pool: OrderedDict[tuple[str, tuple], _Entry] = OrderedDict()
_pool_bytes = 0
# (block_id, key) -> times stage_block built/looked for this entry; the
# bytesxreuse admission signal (entries staged once and never again are
# not worth host RAM when MIN_REUSE > 1)
_stage_counts: dict[tuple[str, tuple], int] = {}
_STAGE_COUNTS_MAX = 4096


def _tel():
    from ..util.kerneltel import TEL

    return TEL


def note_stage(block_id: str, key: tuple) -> None:
    """Record one staging of (block, key) -- the reuse signal demote
    admission checks."""
    if not enabled():
        return
    with _pool_lock:
        if len(_stage_counts) >= _STAGE_COUNTS_MAX and (
                block_id, key) not in _stage_counts:
            _stage_counts.clear()  # coarse reset; admission degrades soft
        _stage_counts[(block_id, key)] = _stage_counts.get(
            (block_id, key), 0) + 1


def _evict_over_budget_locked() -> None:
    global _pool_bytes
    budget = _budget()
    while _pool_bytes > budget and _pool:
        _, ent = _pool.popitem(last=False)
        _pool_bytes -= ent.comp_bytes
        _tel().chunk_cache_evictions.inc()
    _tel().chunk_cache_bytes.set(_pool_bytes)


def demote(block_id: str, key: tuple, staged) -> bool:
    """Compress an evicted StagedBlock's padded columns into the pool.
    Called by ops/stage AFTER releasing the stage LRU lock. Returns
    whether the entry was admitted."""
    global _pool_bytes
    if not enabled() or not block_id or not staged.cols:
        return False
    pk = (block_id, key)
    with _pool_lock:
        if pk in _pool:  # already demoted once; just re-rank it
            _pool.move_to_end(pk)
            return True
        reuse = _stage_counts.get(pk, 1)
    raw = sum(int(a.nbytes) for a in staged.cols.values())
    if raw > _max_entry() or reuse < _min_reuse():
        return False
    name = codec_name()
    comp_fn, _ = _codec_pair(name)
    cols = []
    comp_total = 0
    for cname, arr in staged.cols.items():
        # device -> host pull; contiguous bytes for the codec
        host = np.ascontiguousarray(np.asarray(arr))
        blob = comp_fn(host.tobytes())
        cols.append((cname, str(host.dtype), host.shape, blob, host.nbytes))
        comp_total += len(blob)
    ent = _Entry(
        cols=cols,
        shape_meta=(staged.n_spans, staged.n_traces, staged.n_res,
                    staged.n_spans_b, staged.n_traces_b, staged.n_res_b,
                    staged.span_base),
        codec=name, raw_bytes=raw, comp_bytes=comp_total,
    )
    with _pool_lock:
        if pk in _pool:
            _pool.move_to_end(pk)
            return True
        _pool[pk] = ent
        _pool_bytes += comp_total
        _tel().chunk_cache_demotions.inc()
        _evict_over_budget_locked()
    return True


def probe(block_id: str, key: tuple) -> bool:
    """Whether a restage of (block, key) would hit -- the plan-time
    check stream pipelines use to skip issuing backend ranged reads."""
    if not enabled():
        return False
    with _pool_lock:
        return (block_id, key) in _pool


def restage(block_id: str, key: tuple):
    """Rebuild the StagedBlock for (block, key) from the pool:
    decompress on host, one batched device upload. Returns None on a
    pool miss. Counts hits/misses and attaches a cache:chunk-hit span
    to the active self-trace."""
    if not enabled():
        return None
    tel = _tel()
    with _pool_lock:
        ent = _pool.get((block_id, key))
        if ent is not None:
            _pool.move_to_end((block_id, key))
    if ent is None:
        tel.chunk_cache_misses.inc()
        return None
    import jax

    from .stage import StagedBlock

    t0 = _time.time()
    _, dec_fn = _codec_pair(ent.codec)
    host = []
    for cname, dtype, shape, blob, raw_len in ent.cols:
        arr = np.frombuffer(dec_fn(blob, raw_len), dtype=dtype).reshape(shape)
        host.append((cname, arr))
    # ONE batched transfer, same as upload_stage: per-array device_puts
    # each pay a full link round trip
    devs = jax.device_put([a for _, a in host])
    (n_spans, n_traces, n_res, n_spans_b, n_traces_b, n_res_b,
     span_base) = ent.shape_meta
    staged = StagedBlock(
        n_spans=n_spans, n_traces=n_traces, n_res=n_res,
        n_spans_b=n_spans_b, n_traces_b=n_traces_b, n_res_b=n_res_b,
        span_base=span_base,
        cols={cname: dev for (cname, _), dev in zip(host, devs)},
    )
    tel.chunk_cache_hits.inc()
    tel.child_span("cache:chunk-hit", t0, _time.time(),
                   {"block": block_id[:8], "bytes": ent.raw_bytes,
                    "codec": ent.codec})
    return staged


def stats() -> dict:
    """Point-in-time pool view for /status/kernels."""
    tel = _tel()
    with _pool_lock:
        entries = len(_pool)
        comp = _pool_bytes
        raw = sum(e.raw_bytes for e in _pool.values())
    return {
        "enabled": enabled(),
        "codec": codec_name(),
        "entries": entries,
        "compressed_bytes": int(comp),
        "raw_bytes": int(raw),
        "budget_bytes": _budget(),
        "hits": int(tel.chunk_cache_hits.get()),
        "misses": int(tel.chunk_cache_misses.get()),
        "demotions": int(tel.chunk_cache_demotions.get()),
        "evictions": int(tel.chunk_cache_evictions.get()),
    }


def clear() -> None:
    """Drop everything (tests + budget reconfiguration)."""
    global _pool_bytes
    with _pool_lock:
        _pool.clear()
        _stage_counts.clear()
        _pool_bytes = 0
        _tel().chunk_cache_bytes.set(0)

