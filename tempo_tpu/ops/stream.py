"""Pipelined row-group streaming: overlap host IO with device compute.

The long-context story (SURVEY.md 5.7): a block's span axis is the
"sequence", row groups are its chunks. Like ring attention streams KV
blocks through device memory while the next block prefetches, the
streamed search pipeline stages row-group chunk N+1 (backend range
reads + decompression + padding) on a background thread while the
filter kernel evaluates chunk N on device -- the role of the
reference's prefetch iterators (vparquet/prefetch_iterator.go,
v2/iterator_prefetch.go), with the device as the consumer.

Chunks share one padded shape bucket, so every chunk reuses the same
compiled program (ops/filter's lru-cached jit).

Cross-chunk correctness: a trace's spans can straddle chunk boundaries,
so evaluating the FULL trace-level tree per chunk and OR-ing masks
would drop traces whose AND-of-tracify legs hit in different chunks.
Instead each trace-level LEAF (a tracify subtree or a trace-axis cond)
aggregates across chunks first -- tracify leaves OR their per-chunk
trace hits, trace-cond leaves are chunk-invariant -- and the boolean
skeleton combines the aggregated leaf vectors on host.
"""

from __future__ import annotations

import time as _time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from ..block.reader import BackendBlock
from .filter import Operands, eval_block, normalize_tree
from .stage import stage_block

DEFAULT_GROUPS_PER_CHUNK = 4

import os as _os

# sized for concurrent streamed searches (the frontend dispatches many
# jobs at once); each pipeline keeps at most one prefetch in flight
_prefetch_pool = ThreadPoolExecutor(
    max_workers=max(4, (_os.cpu_count() or 8) // 2), thread_name_prefix="stream-prefetch"
)


def _chunks(n: int, per: int) -> list[list[int]]:
    return [list(range(i, min(i + per, n))) for i in range(0, n, per)]


def _split_leaves(tree):
    """Trace-level tree -> (skeleton, leaves). Leaves are tracify
    subtrees or trace-cond nodes; skeleton nodes are ('and'|'or', ...)
    over ('leaf', j)."""
    leaves: list = []

    def walk(t):
        if t[0] in ("tracify", "cond"):
            leaves.append(t)
            return ("leaf", len(leaves) - 1)
        return (t[0],) + tuple(walk(ch) for ch in t[1:])

    return walk(tree), leaves


def eval_block_streamed(
    blk: BackendBlock,
    needed: list[str],
    tree_conds,
    operands: Operands,
    groups: list[int] | None = None,
    groups_per_chunk: int = DEFAULT_GROUPS_PER_CHUNK,
    return_device: bool = False,
):
    """Evaluate a condition tree over a block by streaming row-group
    chunks through the device. Returns (trace_mask (n_traces,),
    span_count (n_traces,), n_spans_seen) as numpy -- or, with
    return_device, (trace_mask_dev, counts_dev, n_spans_seen) as PADDED
    device arrays with no host sync at all: the caller's top-k selector
    (ops/select.py) does the single fetch."""
    tree, conds = tree_conds
    if tree is not None:
        tree = normalize_tree(tree, conds)
        skeleton, leaves = _split_leaves(tree)
        # union-of-span-subtrees tree for per-trace matched-span counts
        span_subs = [lf[1] for lf in leaves if lf[0] == "tracify"]
        if span_subs:
            count_tree = ("tracify", span_subs[0] if len(span_subs) == 1
                          else ("or",) + tuple(span_subs))
        else:
            count_tree = None
    else:
        skeleton, leaves, count_tree = None, [], None

    span_ax = blk.pack.axes.get("span")
    all_groups = groups if groups is not None else list(
        range(span_ax.n_groups if span_ax else 1)
    )
    chunk_groups = [[all_groups[i] for i in c]
                    for c in _chunks(len(all_groups), groups_per_chunk)]

    n_traces = blk.meta.total_traces
    # accumulate ON DEVICE: per-chunk results stay resident and fold with
    # async device ops; the host syncs exactly once at the end. Pulling
    # each chunk's mask back would cost a device->host round trip per
    # chunk, which dominates when the interconnect has high latency.
    leaf_hits: list = [None for _ in leaves]
    counts_dev = None
    n_spans_seen = 0

    def run_tree(t, staged):
        tm, sc = eval_block(
            (t, conds), staged.cols, operands,
            staged.n_spans, staged.n_traces,
            staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
            span_out=False,
        )
        return tm, sc  # device arrays, padded (n_traces_b,)

    from ..util.kerneltel import TEL

    TEL.record_routing("stream", "device", "chunked")
    t0_stream = _time.perf_counter()

    single_tracify = sum(1 for lf in leaves if lf[0] == "tracify") == 1
    # cache=False: the streamed path exists because staging the whole
    # block exceeds the device budget, so pinning each chunk in the staged
    # cache would be pure churn (per-block FIFO would evict before reuse)
    nxt = _prefetch_pool.submit(stage_block, blk, needed, chunk_groups[0], cache=False)
    try:
        for ci in range(len(chunk_groups)):
            staged = nxt.result()
            if ci + 1 < len(chunk_groups):
                nxt = _prefetch_pool.submit(
                    stage_block, blk, needed, chunk_groups[ci + 1], cache=False
                )
            if tree is None:
                tm, sc = run_tree(None, staged)
                counts_dev = sc if counts_dev is None else counts_dev + sc
            else:
                for j, leaf in enumerate(leaves):
                    if leaf[0] == "cond" and ci > 0:
                        continue  # trace-axis conds are chunk-invariant
                    tm, sc = run_tree(leaf, staged)
                    leaf_hits[j] = tm if leaf_hits[j] is None else leaf_hits[j] | tm
                    if single_tracify and leaf[0] == "tracify":
                        counts_dev = sc if counts_dev is None else counts_dev + sc
                if not single_tracify:
                    _, sc = run_tree(count_tree, staged)
                    counts_dev = sc if counts_dev is None else counts_dev + sc
            n_spans_seen += staged.n_spans
    finally:
        nxt.cancel()  # abandoned prefetch on error mustn't leak device work
    # whole-pipeline window (IO overlap included): the per-chunk filter
    # kernels already record their own launches/compiles via eval_block
    TEL.observe_device("stream", len(chunk_groups), t0_stream)

    if return_device:
        import jax.numpy as jnp

        if counts_dev is None:
            counts_dev = jnp.zeros(max(n_traces, 1), dtype=jnp.int32)
        nb = counts_dev.shape[0]
        valid = jnp.arange(nb, dtype=jnp.int32) < n_traces
        if tree is None:
            tm_dev = (counts_dev > 0) & valid
        else:
            def evd(sk):
                if sk[0] == "leaf":
                    h = leaf_hits[sk[1]]
                    return h if h is not None else jnp.zeros(nb, dtype=bool)
                vals = [evd(ch) for ch in sk[1:]]
                out = vals[0]
                for v in vals[1:]:
                    out = (out & v) if sk[0] == "and" else (out | v)
                return out

            tm_dev = evd(skeleton) & valid
        return tm_dev, counts_dev, n_spans_seen

    counts = (
        np.asarray(counts_dev)[:n_traces].astype(np.int64)
        if counts_dev is not None
        else np.zeros(n_traces, dtype=np.int64)
    )
    if tree is None:
        trace_mask = counts > 0
    else:
        hits_np = [
            np.asarray(h)[:n_traces] if h is not None else np.zeros(n_traces, bool)
            for h in leaf_hits
        ]

        def ev(sk):
            if sk[0] == "leaf":
                return hits_np[sk[1]]
            vals = [ev(ch) for ch in sk[1:]]
            out = vals[0]
            for v in vals[1:]:
                out = (out & v) if sk[0] == "and" else (out | v)
            return out

        trace_mask = ev(skeleton)
    return trace_mask, np.where(trace_mask, counts, 0), n_spans_seen
