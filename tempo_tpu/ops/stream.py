"""Cold-read streaming pipeline: overlap ranged IO, native decompress,
pad/assemble and device upload across the units of a scan.

The long-context story (SURVEY.md 5.7): a block's span axis is the
"sequence", row groups are its chunks. Like ring attention streams KV
blocks through device memory while the next block prefetches, the
pipeline keeps every stage of the cold path busy at once -- while unit
N's filter kernel runs on device, unit N+1 is uploading from the
double buffer, unit N+2 is decompressing on native threads, and unit
N+3's ranged reads are in flight. Units are row-group chunks of one
block (the streamed device eval) or whole cold blocks of one query
(the fused search/metrics host engines) -- the role of the reference's
prefetch iterators (vparquet/prefetch_iterator.go,
v2/iterator_prefetch.go), with the stages made explicit so each shows
up in kerneltel (tempo_stream_stage_seconds{stage}) and the overlap
ratio is measurable in /status/kernels.

Scheduling is budgeted, not best-effort:

  * TEMPO_STREAM_PREFETCH_DEPTH (default 3) bounds how many units run
    ahead of the consumer; depth 0 is the serial kill switch (same
    stages, inline -- the differential tests' oracle).
  * TEMPO_STREAM_MEM_BUDGET (default 256 MiB) gates admission on each
    unit's estimated host bytes (compressed fetch + decode output,
    known from footer metadata before any IO). Admission is strictly
    in unit order per pipeline and one unit always admits, so an
    oversized unit stalls its pipeline instead of deadlocking it --
    the compact_pipeline admission-gate shape on the read side.
  * TEMPO_STREAM_WORKERS sizes the shared stage executor (default
    max(4, cpu/2)). The pool is process-wide; fairness across
    concurrent pipelines comes from the per-pipeline depth bound and
    the byte gate, not from pool ownership -- this replaces the old
    module-global unbounded-fairness prefetch pool.
  * uploads are double-buffered IN ORDER: unit i uploads only once the
    consumer is within _UPLOAD_BUFFERS units of it, so at most two
    staged-but-unconsumed uploads hold device memory.

Cross-chunk correctness (the streamed device eval): a trace's spans can
straddle chunk boundaries, so evaluating the FULL trace-level tree per
chunk and OR-ing masks would drop traces whose AND-of-tracify legs hit
in different chunks. Instead each trace-level LEAF (a tracify subtree
or a trace-axis cond) aggregates across chunks first -- tracify leaves
OR their per-chunk trace hits, trace-cond leaves are chunk-invariant --
and the boolean skeleton combines the aggregated leaf vectors on host.
"""

from __future__ import annotations

import os
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

from ..block import schema as S
from ..block.reader import BackendBlock
from ..util.kerneltel import TEL
from .filter import Operands, eval_block, normalize_tree
from .stage import (
    assemble_stage,
    plan_stage,
    read_stage_columns,
    stage_fetch_wants,
    upload_stage,
)

DEFAULT_GROUPS_PER_CHUNK = 4
_UPLOAD_BUFFERS = 2  # staged-but-unconsumed uploads allowed (double buffer)

_DEFAULT_DEPTH = 3
_DEFAULT_MEM_BUDGET = 256 << 20


def _env_int(name: str, default: int) -> int:
    try:
        v = os.environ.get(name, "")
        return int(v) if v else default
    except ValueError:
        return default


def prefetch_depth() -> int:
    """Units the pipeline runs ahead of the consumer; 0 = serial."""
    return max(0, _env_int("TEMPO_STREAM_PREFETCH_DEPTH", _DEFAULT_DEPTH))


def mem_budget() -> int:
    return max(1, _env_int("TEMPO_STREAM_MEM_BUDGET", _DEFAULT_MEM_BUDGET))


_pool: ThreadPoolExecutor | None = None
_pool_lock = threading.Lock()


def _executor() -> ThreadPoolExecutor:
    """The shared stage executor, sized once (TEMPO_STREAM_WORKERS).
    Context-propagating (util/ctxpool): stage timings/spans recorded on
    pool threads keep the submitting query's ambient self-trace +
    affinity placement."""
    global _pool
    with _pool_lock:
        if _pool is None:
            from ..util.ctxpool import ContextThreadPool

            workers = _env_int("TEMPO_STREAM_WORKERS", 0)
            if workers <= 0:
                workers = max(4, (os.cpu_count() or 8) // 2)
            _pool = ContextThreadPool(
                max_workers=workers, thread_name_prefix="stream-stage")
        return _pool


class _ByteGate:
    """Process-wide admission budget over every stream pipeline's
    in-flight units. A unit holds its estimate from admission until its
    stages finish (fetched bytes + decode buffers are host RAM for
    exactly that window). Admission order within a pipeline is strictly
    unit order (_PipeState.wait_admit_turn), so a pipeline's later
    units can never hold budget while its head waits -- the classic
    inversion deadlock. A unit always admits when nothing is in flight,
    so one oversized unit stalls, never deadlocks."""

    def __init__(self):
        self._cv = threading.Condition()
        self._bytes = 0
        self._holders = 0
        self.peak_bytes = 0  # high-water mark (tests + /status)

    def acquire(self, n: int, cancelled: threading.Event | None) -> bool:
        with self._cv:
            while True:
                if cancelled is not None and cancelled.is_set():
                    return False
                if self._holders == 0 or self._bytes + n <= mem_budget():
                    self._bytes += n
                    self._holders += 1
                    if self._bytes > self.peak_bytes:
                        self.peak_bytes = self._bytes
                    TEL.stream_inflight(self._bytes)
                    return True
                # re-check on release notifications; the timeout only
                # guards against a lost cancellation wakeup
                self._cv.wait(0.05)

    def release(self, n: int) -> None:
        with self._cv:
            self._bytes -= n
            self._holders -= 1
            TEL.stream_inflight(self._bytes)
            self._cv.notify_all()

    def inflight_bytes(self) -> int:
        with self._cv:
            return self._bytes


_GATE = _ByteGate()


@dataclass
class StreamUnit:
    """One pipeline unit: a (block, columns, row-group slice) read.
    upload=True stages padded device columns (the streamed device eval);
    upload=False stops after fetch+decompress, leaving the columns
    cache-resident for a host engine (the cold fused-search path)."""

    blk: BackendBlock
    needed: list[str]
    groups: list[int] | None = None  # None = whole block
    upload: bool = True
    est_bytes: int = 0  # filled at plan time (admission gate)
    index: int = 0  # position in its pipeline (set by _run_unit; the
    # upload turnstile orders the double buffer by it)
    pool_hit: bool = False  # plan-time host chunk-pool probe hit: the
    # fetch/decompress/assemble stages are skipped (ops/chunkpool)


class _PipeState:
    """Per-pipeline coordination: ordered admission, ordered
    double-buffered upload, consumer progress, cancellation."""

    def __init__(self):
        self._cv = threading.Condition()
        self._admitted = 0  # units past the admission turnstile
        self._consumed = 0  # units the consumer is done with
        self.cancelled = threading.Event()

    def wait_admit_turn(self, i: int) -> bool:
        with self._cv:
            while not self.cancelled.is_set() and i != self._admitted:
                self._cv.wait(0.05)
            return not self.cancelled.is_set()

    def admit_done(self) -> None:
        with self._cv:
            self._admitted += 1
            self._cv.notify_all()

    def wait_upload_turn(self, i: int) -> bool:
        """Unit i may upload once the consumer is within
        _UPLOAD_BUFFERS units: device memory holds at most two staged
        uploads the filter hasn't consumed yet."""
        with self._cv:
            while (not self.cancelled.is_set()
                   and i >= self._consumed + _UPLOAD_BUFFERS):
                self._cv.wait(0.05)
            return not self.cancelled.is_set()

    def advance(self) -> None:
        with self._cv:
            self._consumed += 1
            self._cv.notify_all()

    def cancel(self) -> None:
        self.cancelled.set()
        with self._cv:
            self._cv.notify_all()


def _unit_groups(u: StreamUnit) -> list[int]:
    span_ax = u.blk.pack.axes.get(S.AX_SPAN)
    if u.groups is not None:
        return u.groups
    return list(range(span_ax.n_groups)) if span_ax else []


def _unit_pool_key(u: StreamUnit) -> tuple:
    """The (columns, groups) identity a stage_block caching of this
    unit would use -- ONE key shape shared with ops/stage so demotions
    from either path restage on the other."""
    return (tuple(u.needed),
            tuple(u.groups) if u.groups is not None else None)


def _plan_unit(u: StreamUnit):
    """(stage plan, column-fetch plan) for a unit -- footer metadata
    only, no IO; fills u.est_bytes for the admission gate. Upload units
    probe the host chunk pool (ops/chunkpool) first: a warm entry means
    no backend ranged read to plan and no admission bytes to hold."""
    if u.upload:
        plan = plan_stage(u.needed)
        block_id = getattr(u.blk.meta, "block_id", "") or ""
        if block_id:
            from . import chunkpool

            if chunkpool.probe(block_id, _unit_pool_key(u)):
                u.pool_hit = True
                u.est_bytes = 0
                return plan, None
        wants = stage_fetch_wants(u.blk, plan, u.groups)
    else:
        plan = None
        wants = [(n, None) for n in u.needed]
    cf = u.blk.pack.plan_fetch(wants)
    u.est_bytes = cf.est_bytes if cf is not None else 0
    return plan, cf


def _run_stages(u: StreamUnit, plan, cf, state: _PipeState | None):
    """fetch -> decompress -> assemble -> upload for one unit, with
    per-stage kerneltel timings. state=None runs without cancellation
    checks (the serial path)."""
    pack = u.blk.pack
    if u.upload and u.pool_hit:
        from . import chunkpool

        if state is not None and not state.wait_upload_turn(u.index):
            return None  # cancelled before the restage upload
        t0 = _time.perf_counter()
        staged = chunkpool.restage(u.blk.meta.block_id, _unit_pool_key(u))
        if staged is not None:
            TEL.record_stream_stage("upload", _time.perf_counter() - t0)
            return staged
        # evicted between plan and run: late-plan the cold fetch and
        # fall through to the normal stages (est_bytes stays 0 -- the
        # gate's one-always-admits rule bounds the raced unit)
        u.pool_hit = False
        cf = pack.plan_fetch(stage_fetch_wants(u.blk, plan, u.groups))
    t0 = _time.perf_counter()
    if cf is not None:
        pack.fetch_ranges(cf)
    TEL.record_stream_stage("fetch", _time.perf_counter() - t0)
    if state is not None and state.cancelled.is_set():
        return None
    t0 = _time.perf_counter()
    if cf is not None:
        pack.decode_fetched(cf)
    if not u.upload:
        TEL.record_stream_stage("decompress", _time.perf_counter() - t0)
        return True  # columns are cache-resident; host engines read them
    groups = _unit_groups(u)
    host, n_res = read_stage_columns(u.blk, plan, groups)
    TEL.record_stream_stage("decompress", _time.perf_counter() - t0)
    if state is not None and state.cancelled.is_set():
        return None
    t0 = _time.perf_counter()
    staged, padded, real_rows = assemble_stage(u.blk, plan, groups, host, n_res)
    TEL.record_stream_stage("assemble", _time.perf_counter() - t0)
    if state is not None and not state.wait_upload_turn(u.index):
        return None  # cancelled: no device work for abandoned units
    t0 = _time.perf_counter()
    upload_stage(u.blk, plan, staged, padded, real_rows)
    TEL.record_stream_stage("upload", _time.perf_counter() - t0)
    return staged


def _run_unit(u: StreamUnit, i: int, state: _PipeState):
    """One unit through admission + stages on a pool worker."""
    u.index = i
    if not state.wait_admit_turn(i):
        TEL.record_stream_unit("cancelled")
        return None
    ok = False
    try:
        plan, cf = _plan_unit(u)
        ok = _GATE.acquire(u.est_bytes, state.cancelled)
    except BaseException:
        TEL.record_stream_unit("error")
        raise
    finally:
        # unblock the next unit's turnstile on EVERY exit -- a planning
        # error here must fail this unit, not stall the whole pipeline
        # (HostPrefetch callers wait() with no timeout)
        state.admit_done()
    if not ok:
        TEL.record_stream_unit("cancelled")
        return None
    try:
        out = _run_stages(u, plan, cf, state)
        TEL.record_stream_unit(
            "cancelled" if state.cancelled.is_set() and out is None else "ok")
        return out
    except BaseException:
        TEL.record_stream_unit("error")
        raise
    finally:
        _GATE.release(u.est_bytes)


def stream_staged(units: list[StreamUnit], depth: int | None = None):
    """THE pipelined iterator: yields (unit, result) strictly in unit
    order while later units' stages run ahead. result is a StagedBlock
    for upload units, True for host units (their columns are left
    cache-resident). Results are bit-identical to running the same
    units serially -- the pipeline reorders WORK, never data.

    On error or early close, every in-flight future is cancelled or
    drained and admission bytes return to the gate: no leaked device
    work, no leaked budget."""
    if depth is None:
        depth = prefetch_depth()
    t_run = _time.perf_counter()
    if depth <= 0 or len(units) <= 1:
        # serial kill switch / degenerate pipeline: same stages, inline
        try:
            for u in units:
                plan, cf = _plan_unit(u)
                try:
                    out = _run_stages(u, plan, cf, None)
                except BaseException:
                    TEL.record_stream_unit("error")
                    raise
                TEL.record_stream_unit("ok")
                yield u, out
        finally:
            TEL.record_stream_run(_time.perf_counter() - t_run)
        return
    state = _PipeState()
    pool = _executor()
    futures = []

    def submit(i: int) -> None:
        futures.append(pool.submit(_run_unit, units[i], i, state))

    try:
        for i in range(min(depth + 1, len(units))):
            submit(i)
        for i in range(len(units)):
            res = futures[i].result()
            yield units[i], res
            state.advance()  # consumer done with unit i
            nxt = i + depth + 1
            if nxt < len(units):
                submit(nxt)
    finally:
        state.cancel()
        for f in futures:
            f.cancel()
        for f in futures:
            if not f.cancelled():
                try:
                    f.exception()  # drain started futures; nothing leaks
                except BaseException:  # noqa: BLE001 - already surfaced
                    pass
        TEL.record_stream_run(_time.perf_counter() - t_run)


class HostPrefetch:
    """Handle over a host-flavor pipeline run (upload=False units): the
    cold blocks' fetch+decompress stages run ahead on the stream
    executor while the caller's host engines evaluate blocks as their
    columns land. wait(blk) returns True once that block's columns are
    cache-resident, False if the unit errored or was cancelled first
    (callers then read the normal way, which surfaces any real error
    itself). Host units never touch the device and never wait on the
    consumer, so every unit is submitted up front -- the admission
    turnstile + byte gate bound the actual in-flight work."""

    def __init__(self, items: list[tuple[BackendBlock, list[str]]]):
        self._state = _PipeState()
        self._lock = threading.Lock()
        self._done: dict[int, threading.Event] = {}
        self._ok: dict[int, bool] = {}
        self._t0 = _time.perf_counter()
        self._futures: list = []
        self._remaining = 0
        if prefetch_depth() <= 0:
            # serial kill switch: every wait() misses, so callers run
            # their own inline reads -- the differential tests' oracle
            return
        units = []
        for blk, names in items:
            if id(blk) in self._done:
                continue
            units.append(StreamUnit(blk, list(names), None, upload=False))
            self._done[id(blk)] = threading.Event()
            self._ok[id(blk)] = False
        self._remaining = len(units)
        pool = _executor()
        self._futures = [pool.submit(self._run, u, i)
                         for i, u in enumerate(units)]

    def _run(self, u: StreamUnit, i: int) -> None:
        ok = False
        try:
            ok = _run_unit(u, i, self._state) is not None
        except BaseException:  # noqa: BLE001 - the caller's own read re-raises
            ok = False
        finally:
            self._ok[id(u.blk)] = ok
            self._done[id(u.blk)].set()
            with self._lock:
                self._remaining -= 1
                last = self._remaining == 0
            if last:
                TEL.record_stream_run(_time.perf_counter() - self._t0)

    def wait(self, blk: BackendBlock, timeout: float | None = None) -> bool:
        ev = self._done.get(id(blk))
        if ev is None:
            return False
        ev.wait(timeout)
        return self._ok.get(id(blk), False)

    def close(self) -> None:
        """Cancel outstanding work (idempotent); never strands a
        waiter."""
        self._state.cancel()
        cancelled = sum(1 for f in self._futures if f.cancel())
        self._futures = []
        for ev in self._done.values():
            ev.set()
        if cancelled:
            # queued units whose _run will never execute still owe
            # their _remaining decrement, else the run is never
            # recorded and overlap ratio drifts up after errored runs
            with self._lock:
                self._remaining -= cancelled
                last = self._remaining == 0
            if last:
                TEL.record_stream_run(_time.perf_counter() - self._t0)


def staged_warm(blk: BackendBlock, names: list[str]) -> None:
    """Single-unit inline form of the pipeline's fetch+decompress
    stages: one coalesced ranged read + one threaded decode into the
    pack's caches, with the stage timings recorded (colio._run_plan).
    The cold path of callers that handle one block at a time (per-block
    search shards, the metrics executor)."""
    blk.pack.warm_columns(names)


def _chunks(n: int, per: int) -> list[list[int]]:
    return [list(range(i, min(i + per, n))) for i in range(0, n, per)]


def _split_leaves(tree):
    """Trace-level tree -> (skeleton, leaves). Leaves are tracify
    subtrees or trace-cond nodes; skeleton nodes are ('and'|'or', ...)
    over ('leaf', j)."""
    leaves: list = []

    def walk(t):
        if t[0] in ("tracify", "cond"):
            leaves.append(t)
            return ("leaf", len(leaves) - 1)
        return (t[0],) + tuple(walk(ch) for ch in t[1:])

    return walk(tree), leaves


def eval_block_streamed(
    blk: BackendBlock,
    needed: list[str],
    tree_conds,
    operands: Operands,
    groups: list[int] | None = None,
    groups_per_chunk: int = DEFAULT_GROUPS_PER_CHUNK,
    return_device: bool = False,
):
    """Evaluate a condition tree over a block by streaming row-group
    chunks through the device pipeline. Returns (trace_mask (n_traces,),
    span_count (n_traces,), n_spans_seen) as numpy -- or, with
    return_device, (trace_mask_dev, counts_dev, n_spans_seen) as PADDED
    device arrays with no host sync at all: the caller's top-k selector
    (ops/select.py) does the single fetch."""
    tree, conds = tree_conds
    if tree is not None:
        tree = normalize_tree(tree, conds)
        skeleton, leaves = _split_leaves(tree)
        # union-of-span-subtrees tree for per-trace matched-span counts
        span_subs = [lf[1] for lf in leaves if lf[0] == "tracify"]
        if span_subs:
            count_tree = ("tracify", span_subs[0] if len(span_subs) == 1
                          else ("or",) + tuple(span_subs))
        else:
            count_tree = None
    else:
        skeleton, leaves, count_tree = None, [], None

    span_ax = blk.pack.axes.get("span")
    all_groups = groups if groups is not None else list(
        range(span_ax.n_groups if span_ax else 1)
    )
    chunk_groups = [[all_groups[i] for i in c]
                    for c in _chunks(len(all_groups), groups_per_chunk)]

    n_traces = blk.meta.total_traces
    # accumulate ON DEVICE: per-chunk results stay resident and fold with
    # async device ops; the host syncs exactly once at the end. Pulling
    # each chunk's mask back would cost a device->host round trip per
    # chunk, which dominates when the interconnect has high latency.
    leaf_hits: list = [None for _ in leaves]
    counts_dev = None
    n_spans_seen = 0

    def run_tree(t, staged):
        tm, sc = eval_block(
            (t, conds), staged.cols, operands,
            staged.n_spans, staged.n_traces,
            staged.n_spans_b, staged.n_res_b, staged.n_traces_b,
            span_out=False,
        )
        return tm, sc  # device arrays, padded (n_traces_b,)

    TEL.record_routing("stream", "device", "chunked")
    t0_stream = _time.perf_counter()

    single_tracify = sum(1 for lf in leaves if lf[0] == "tracify") == 1
    # the streamed path exists because staging the whole block exceeds
    # the device budget, so chunks never enter the staged cache (per-
    # block FIFO would evict before reuse); the pipeline's own double
    # buffer bounds device memory instead
    units = [StreamUnit(blk, needed, cg, upload=True) for cg in chunk_groups]
    it = stream_staged(units)
    try:
        for ci, (_unit, staged) in enumerate(it):
            if tree is None:
                tm, sc = run_tree(None, staged)
                counts_dev = sc if counts_dev is None else counts_dev + sc
            else:
                for j, leaf in enumerate(leaves):
                    if leaf[0] == "cond" and ci > 0:
                        continue  # trace-axis conds are chunk-invariant
                    tm, sc = run_tree(leaf, staged)
                    leaf_hits[j] = tm if leaf_hits[j] is None else leaf_hits[j] | tm
                    if single_tracify and leaf[0] == "tracify":
                        counts_dev = sc if counts_dev is None else counts_dev + sc
                if not single_tracify:
                    _, sc = run_tree(count_tree, staged)
                    counts_dev = sc if counts_dev is None else counts_dev + sc
            n_spans_seen += staged.n_spans
    finally:
        it.close()  # abandoned prefetch on error mustn't leak device work
    # whole-pipeline window (IO overlap included): the per-chunk filter
    # kernels already record their own launches/compiles via eval_block
    TEL.observe_device("stream", len(chunk_groups), t0_stream)

    if return_device:
        import jax.numpy as jnp

        if counts_dev is None:
            counts_dev = jnp.zeros(max(n_traces, 1), dtype=jnp.int32)
        nb = counts_dev.shape[0]
        valid = jnp.arange(nb, dtype=jnp.int32) < n_traces
        if tree is None:
            tm_dev = (counts_dev > 0) & valid
        else:
            def evd(sk):
                if sk[0] == "leaf":
                    h = leaf_hits[sk[1]]
                    return h if h is not None else jnp.zeros(nb, dtype=bool)
                vals = [evd(ch) for ch in sk[1:]]
                out = vals[0]
                for v in vals[1:]:
                    out = (out & v) if sk[0] == "and" else (out | v)
                return out

            tm_dev = evd(skeleton) & valid
        return tm_dev, counts_dev, n_spans_seen

    counts = (
        np.asarray(counts_dev)[:n_traces].astype(np.int64)
        if counts_dev is not None
        else np.zeros(n_traces, dtype=np.int64)
    )
    if tree is None:
        trace_mask = counts > 0
    else:
        hits_np = [
            np.asarray(h)[:n_traces] if h is not None else np.zeros(n_traces, bool)
            for h in leaf_hits
        ]

        def ev(sk):
            if sk[0] == "leaf":
                return hits_np[sk[1]]
            vals = [ev(ch) for ch in sk[1:]]
            out = vals[0]
            for v in vals[1:]:
                out = (out & v) if sk[0] == "and" else (out | v)
            return out

        trace_mask = ev(skeleton)
    return trace_mask, np.where(trace_mask, counts, 0), n_spans_seen
